"""The fused scan: N analyzers, ONE compiled XLA computation per pass.

This is the TPU-native analogue of the reference's scan-sharing optimizer
(reference: runners/AnalysisRunner.scala:279-326 — all scan-shareable
analyzers run in a single `df.agg(...)` with offset arithmetic). Here the
"offsets" are pytree structure: every analyzer contributes a device_reduce
over a shared, deduplicated set of input arrays, XLA CSE merges the common
subexpressions (masks, counts), and one program per batch produces every
partial state at once.

Cross-batch folding happens host-side in float64 via the same merge_agg
formulas (numpy namespace) — the driver-side semigroup fold, exactly the
role the reference's `State.sum` plays after Catalyst partial aggregation.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu import observe
from deequ_tpu.analyzers.base import ScanShareableAnalyzer
from deequ_tpu.analyzers.states import State
from deequ_tpu.core.controller import RunCancelled, StallWatchdog
from deequ_tpu.data.table import Table
from deequ_tpu.ops import pipeline, runtime

DEFAULT_BATCH_SIZE = 1 << 22  # 4M rows: < 2^24 so f32 counts stay exact

_FUSED_CACHE: Dict[Any, Any] = {}
_FUSED_CACHE_MAX = 256  # insertion-order eviction; bounds memory on
# long heterogeneous streams (layouts are sticky per pass, so steady
# state is 1-2 entries per analyzer set)
_FUSED_CACHE_LOCK = threading.Lock()


def _pad_size(n: int, batch_size: int) -> int:
    """Round up to a power of two (min 8): few compiled shapes, no
    per-tail recompilation. Always a multiple of 8 so bitpacked masks
    (1 bit/row) decode to exactly `padded` rows. Delegates to
    runtime.wire_pad_size — the decode-to-wire workers size their
    pre-packed rows with the same function, so the two can never
    disagree on a batch's padded length."""
    return runtime.wire_pad_size(n, batch_size)


def _pack_outputs(tree):
    """Flatten a pytree of device arrays into ONE 1-D array.

    Every aggregate output is fixed-size (scalars, HLL registers, quantile
    samples), but on a tunneled device each fetched array pays a full
    round-trip (~75ms measured) — ~90 leaves dominated the profiler
    wall-clock. Everything is cast to the compute float dtype for the
    single transfer: registers (≤ 63), class/level codes, and per-batch
    counts (≤ 2^24 rows/batch) are all exactly representable in float32.
    Returns (packed_array, meta) where meta unpacks host-side.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs = [(str(leaf.dtype), tuple(leaf.shape)) for leaf in leaves]
    if not leaves:
        return jnp.zeros(0, dtype=runtime.compute_dtype()), (treedef, specs)
    dt = runtime.compute_dtype()
    packed = jnp.concatenate([jnp.ravel(leaf).astype(dt) for leaf in leaves])
    return packed, (treedef, specs)


def unpack_outputs(packed: np.ndarray, meta):
    treedef, specs = meta
    buf = np.asarray(packed).reshape(-1)
    leaves: List[Any] = []
    off = 0
    for dtype_name, shape in specs:
        n = int(np.prod(shape)) if shape else 1
        leaves.append(buf[off : off + n].astype(dtype_name).reshape(shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)


def plan_shape_key(
    analyzers: Sequence[ScanShareableAnalyzer],
    assisted: Sequence[ScanShareableAnalyzer] = (),
    layout: Any = None,
) -> Tuple[Any, ...]:
    """The compiled-plan cache key: the plan-*shape* component of
    `repository.states.plan_signature` (analyzer reprs in pass order)
    plus the wire layout and the x64 flag — everything that changes the
    traced program. Two tenants whose suites reduce to the same shape
    share one jitted fused fn, so the jit/fuse cost is paid once per
    shape fleet-wide."""
    return (
        tuple(repr(a) for a in analyzers),
        tuple(repr(a) for a in assisted),
        layout,
        bool(jax.config.jax_enable_x64),
    )


def get_fused_fn(
    analyzers: Sequence[ScanShareableAnalyzer],
    assisted: Sequence[ScanShareableAnalyzer] = (),
    layout: Any = None,
):
    """Compiled fused pass over packed inputs.

    `layout` maps each packed input buffer to its named rows:
    tuple of (dtype_name, (key, ...)); buffer `dtype_name` is a stacked
    (k, padded) array whose row i is input `key_i`. Returns (fn, meta_box);
    meta_box['meta'] (filled at trace time) drives unpack_outputs.
    """
    key = plan_shape_key(analyzers, assisted, layout)
    with _FUSED_CACHE_LOCK:
        cached = _FUSED_CACHE.get(key)
    runtime.record_plan_cache(cached is not None)
    if cached is None:
        meta_box: Dict[str, Any] = {}
        if layout is None:
            groups, const_keys, padded = None, (), 0
        else:
            groups, const_keys, padded = layout

        def fused(packed_inputs):
            if groups is None:
                inputs = packed_inputs
            else:
                # Unpack the wire format (see _run_pass): per-group 1-D
                # buffers (1-D H2D transfers avoid the host-side relayout
                # a 2-D put pays on this platform); bool masks arrive
                # bitpacked (1 bit/row) and all-true masks aren't
                # transferred at all — they're synthesized from the row
                # count. Decoding is a few VPU ops: compute is ~free next
                # to tunnel bytes.
                inputs = {}
                for group_name, entries in groups:
                    rows = packed_inputs[group_name].reshape(len(entries), -1)
                    for i, (in_key, kind) in enumerate(entries):
                        row = rows[i]
                        if kind == "bits":
                            shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
                            bits = (row[:, None] >> shifts[None, :]) & jnp.uint8(1)
                            inputs[in_key] = bits.reshape(-1).astype(jnp.bool_)
                        elif kind == "ival":
                            # decode-to-wire narrowed int row for a num:
                            # key: widen to the compute dtype (the planner
                            # pinned a width whose every value is exact in
                            # float64, so this equals the f64 row the
                            # Column path would have shipped)
                            inputs[in_key] = row.astype(runtime.compute_dtype())
                        elif kind == "int" and row.dtype.itemsize < 4:
                            # widen wire-narrowed ints; int32/int64 as-is
                            inputs[in_key] = row.astype(jnp.int32)
                        else:
                            inputs[in_key] = row
                if const_keys:
                    n = packed_inputs["__nrows"][0]
                    all_rows = jnp.arange(padded, dtype=jnp.int32) < n
                    for in_key in const_keys:
                        inputs[in_key] = all_rows
            # trace-time marker: device-assisted members may use single-
            # device-only strategies (e.g. the pallas hist16 radix-select,
            # whose host finisher needs this batch's host inputs — not
            # available per-shard in the mesh pass)
            inputs["__single_device"] = True
            out = (
                tuple(a.device_reduce(inputs, jnp) for a in analyzers),
                tuple(a.device_batch(inputs, jnp) for a in assisted),
            )
            packed_out, meta = _pack_outputs(out)
            meta_box["meta"] = meta
            return packed_out

        cached = (jax.jit(fused), meta_box)
        with _FUSED_CACHE_LOCK:
            # two threads may have built concurrently: first insert wins
            # so both use the same meta_box the traced program fills
            cached = _FUSED_CACHE.setdefault(key, cached)
            while len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
                _FUSED_CACHE.pop(next(iter(_FUSED_CACHE)))
    return cached


def resolve_shift(key: str, arr: np.ndarray, sticky, lookup) -> float:
    """Scan-constant pre-centering shift for a num: wire key. Picked
    from the first VALID row (null slots are 0.0-filled and would
    otherwise silently disable the centering); recorded sticky so every
    batch of the pass ships the same shift."""
    shift_key = f"shift:{key}"
    shift = sticky.get(shift_key)
    if shift is None:
        shift = 0.0
        valid = lookup(f"valid:{key[len('num:'):]}") if key.startswith("num:") else None
        if valid is not None:
            valid = np.asarray(valid, dtype=bool)
            first = np.flatnonzero(valid)[:1]
            if first.size:
                candidate = float(arr[int(first[0])])
                if np.isfinite(candidate):
                    shift = candidate
        else:
            finite = arr[np.isfinite(arr)]
            if finite.size:
                shift = float(finite[0])
        sticky[shift_key] = shift
    return shift


def wire_shifts(sticky) -> Dict[str, float]:
    """The f32 wire's per-column pre-centering shifts recorded by
    pack_batch_inputs, keyed by input key (empty on the f64 wire)."""
    return {
        key[len("shift:"):]: value
        for key, value in sticky.items()
        if key.startswith("shift:") and value != 0.0
    }


def pack_batch_inputs(
    built_items, padded: int, dtype, sticky=None, num_rows=None, prepacked=None
):
    """Build the minimal wire format for one batch.

    The tunnel to the device moves ~10MB/s (measured; a real TPU host moves
    GB/s over PCIe, but the byte-economy is the right design either way):
      * bool masks  -> bitpacked, 1 bit/row
      * all-true masks (no filter, null-free column) -> NOT transferred;
        synthesized on device from the row count
      * integers    -> range-downcast to int8/int16 where exact
      * floats      -> the compute dtype
    Same-format arrays are concatenated into ONE flat 1-D buffer per group
    so each put streams at bandwidth instead of paying per-array latency.

    `prepacked` maps input keys to runtime.WireRows the decode-to-wire
    workers already emitted in final wire form (the batch Table's
    ``wire_rows``): their padded buffers splice into the group buffers
    verbatim — no packbits, no narrowing, no shift math here. A
    prepacked key's built array may be None (the Column was never
    materialized). Sticky pinning follows the same rules as the packed
    route, so fused and fallback batches of one pass converge on the
    same layout.

    Returns (packed_inputs, layout); `layout` is hashable and keys the
    compiled program (groups, const_keys, padded). `sticky` (a dict the
    caller keeps for the life of one pass) pins each key's wire format
    across batches — a key only ever moves toward the wider/general form
    (const->bits, narrow int->wider int), bounding recompiles at 2 per key
    instead of one per distinct batch data range.
    """
    if sticky is None:
        sticky = {}
    if prepacked is None:
        prepacked = {}
    _built_map = {k: a for k, a in built_items}

    def _built_lookup(key: str):
        return _built_map.get(key)

    entries_by_group: Dict[tuple, List[tuple]] = {}
    const_keys: List[str] = []
    for key, arr in built_items:
        wire_row = prepacked.get(key)
        if wire_row is not None:
            if wire_row.kind == "bits":
                # same elision/pinning ladder as the bool branch below:
                # all-valid rows elide to const until any batch has an
                # invalid row, then the key is bits for the pass
                if wire_row.all_valid and sticky.get(key, "const") == "const":
                    sticky[key] = "const"
                    const_keys.append(key)
                    continue
                sticky[key] = "bits"
                entries_by_group.setdefault(("uint8", "bits"), []).append(
                    (key, "bits", wire_row.arr)
                )
            elif wire_row.kind == "ival":
                entries_by_group.setdefault(
                    (wire_row.arr.dtype.name, "ival"), []
                ).append((key, "ival", wire_row.arr))
            else:  # "val": compute-dtype row, shift already applied
                sticky.setdefault(f"shift:{key}", wire_row.shift)
                entries_by_group.setdefault(
                    (np.dtype(dtype).name, "val"), []
                ).append((key, "val", wire_row.arr))
            continue
        if num_rows is None:
            num_rows = len(arr)
        if arr.dtype == np.bool_:
            if arr.all() and sticky.get(key, "const") == "const":
                sticky[key] = "const"
                const_keys.append(key)
                continue
            sticky[key] = "bits"
            bits = np.zeros(padded // 8, dtype=np.uint8)
            packed_bits = np.packbits(arr)
            bits[: len(packed_bits)] = packed_bits
            entries_by_group.setdefault(("uint8", "bits"), []).append(
                (key, "bits", bits)
            )
        elif np.issubdtype(arr.dtype, np.integer):
            arr = runtime.narrow_int_wire(arr, key, sticky)
            entries_by_group.setdefault((arr.dtype.name, "int"), []).append(
                (key, "int", arr)
            )
        else:
            if np.dtype(dtype) == np.float32 and key.startswith("num:"):
                # pre-center before the f32 cast: clustered data (mean
                # ~1e7, variance ~1e-2) would otherwise lose its entire
                # variance signal to f32 quantization ON THE WIRE. The
                # shift is scan-constant (sticky) so cross-batch merges
                # stay valid; analyzers undo it via unshift_agg/_batch.
                shift = resolve_shift(key, arr, sticky, _built_lookup)
                if shift != 0.0:
                    arr = np.asarray(arr, dtype=np.float64) - shift
            arr = arr.astype(dtype, copy=False)
            entries_by_group.setdefault((np.dtype(dtype).name, "val"), []).append(
                (key, "val", arr)
            )

    packed_inputs: Dict[str, Any] = {}
    groups = []
    for (dtype_name, kind), entries in sorted(entries_by_group.items()):
        group_name = f"{dtype_name}:{kind}"
        row_len = padded // 8 if kind == "bits" else padded
        buf = np.zeros(len(entries) * row_len, dtype=dtype_name)
        for i, (_key, _kind, arr) in enumerate(entries):
            buf[i * row_len : i * row_len + len(arr)] = arr
        packed_inputs[group_name] = jnp.asarray(buf)
        groups.append((group_name, tuple((e[0], e[1]) for e in entries)))
    if const_keys:
        packed_inputs["__nrows"] = jnp.asarray(
            np.array([num_rows or 0], dtype=np.int32)
        )
    layout = (tuple(groups), tuple(sorted(const_keys)), padded)
    return packed_inputs, layout


# -- pure plan construction ---------------------------------------------------
#
# Everything the pass decides BEFORE it sees a row — member placement,
# the deduplicated input-spec set, family-kernel job identity and
# grouping — lives in the pure functions below. `FusedScanPass.run`,
# `DistributedScanPass._run`, and `_precompute_family_kernels` consume
# them at runtime; the static cost analyzer (deequ_tpu/lint/cost.py)
# calls the SAME functions so its predictions cannot drift from the
# planner (the trace-differential suite pins this).


@dataclass
class ScanMemberPlan:
    """Data-free partition of one scan pass's members by placement.

    Index lists refer to positions in the analyzer sequence handed to
    `plan_scan_members`; an index appears in exactly one of the four
    lists or in `spec_errors` (spec construction failed — that analyzer
    fails alone, not the pass)."""

    mode: str
    merge_idx: List[int] = field(default_factory=list)
    assisted_idx: List[int] = field(default_factory=list)
    host_idx: List[int] = field(default_factory=list)
    host_assisted_idx: List[int] = field(default_factory=list)
    specs: Dict[str, Any] = field(default_factory=dict)
    device_keys: set = field(default_factory=set)
    # device keys consumed by device-ASSISTED members: their host
    # finishers may re-read the built host arrays (fold.submit's
    # host_ctx), so these keys are not packed-only and the decode-to-wire
    # planner must keep their columns on the Column path
    assisted_keys: set = field(default_factory=set)
    host_keys: Dict[int, List[str]] = field(default_factory=dict)
    spec_errors: Dict[int, BaseException] = field(default_factory=dict)

    @property
    def packed_only_keys(self) -> set:
        """Device keys whose ONLY consumers are merge members' compiled
        reduces — the keys that live purely on the packed wire. The
        decode-to-wire planner may fuse a column exactly when every one
        of its consumer keys is in this set."""
        host = set()
        for keys in self.host_keys.values():
            host.update(keys)
        return self.device_keys - self.assisted_keys - host

    @property
    def device_member_count(self) -> int:
        return len(self.merge_idx) + len(self.assisted_idx)

    @property
    def host_member_count(self) -> int:
        return len(self.host_idx) + len(self.host_assisted_idx)

    @property
    def any_members(self) -> bool:
        return bool(
            self.merge_idx
            or self.assisted_idx
            or self.host_idx
            or self.host_assisted_idx
        )


def plan_scan_members(analyzers: Sequence[Any], mode: Optional[str] = None) -> ScanMemberPlan:
    """Partition a scan's members by placement — pure and data-free.

    Placement (runtime.placement_mode): on a slow device link, discrete
    analyzers (mask/code-only inputs) — or, below the bandwidth floor,
    EVERY analyzer — fold on the host inside the SAME logical scan
    instead of shipping rows; `host_only` device-assisted members
    (strings, dict codes) never ship regardless of placement."""
    if mode is None:
        mode = runtime.placement_mode()
    plan = ScanMemberPlan(mode=mode)
    host_all = mode == "host-all"
    host_discrete = host_all or mode == "host-discrete"
    for i, analyzer in enumerate(analyzers):
        try:
            analyzer_specs = analyzer.input_specs()
        except Exception as e:  # noqa: BLE001
            plan.spec_errors[i] = e
            continue
        if getattr(analyzer, "device_assisted", False):
            if host_all or getattr(analyzer, "host_only", False):
                plan.host_assisted_idx.append(i)
                plan.host_keys[i] = [s.key for s in analyzer_specs]
            else:
                plan.assisted_idx.append(i)
                plan.device_keys.update(s.key for s in analyzer_specs)
                plan.assisted_keys.update(s.key for s in analyzer_specs)
        elif host_all or (
            host_discrete and getattr(analyzer, "discrete_inputs", False)
        ):
            plan.host_idx.append(i)
            plan.host_keys[i] = [s.key for s in analyzer_specs]
        else:
            plan.merge_idx.append(i)
            plan.device_keys.update(s.key for s in analyzer_specs)
        for spec in analyzer_specs:
            plan.specs.setdefault(spec.key, spec)
    return plan


def build_union_plan(
    plans: Sequence[Sequence[Any]],
) -> Tuple[List[Any], List[List[int]]]:
    """Union-plan builder for fleet-level scan sharing: merge several
    suites' analyzer lists into ONE superset fused scan — pure and
    data-free.

    Analyzers deduplicate by engine identity ((type, repr), the same
    equality the runner and the state-cache signature use), preserving
    first-appearance order, so the union's pass order is deterministic
    in submission order. Returns ``(union, memberships)``:
    ``union`` is the superset analyzer list, ``memberships[i]`` indexes
    plan i's (deduplicated, order-preserved) analyzers into ``union``.
    Each member plan's states fan back out by selecting its rows of the
    union's results — bit-identical to a solo run, because per-analyzer
    fold states are independent of which other members ride the pass
    (the multi-family kernels are proven batched-vs-solo identical and
    partition states merge over the semigroup).

    Equivalent-but-differently-spelled where clauses deliberately stay
    separate members: each suite's states then fold under its own
    spelling, keeping the fan-out trivially exact (the prover records
    such pairs as CONTAINED_WITH_RESIDUAL when asked directly)."""
    union: List[Any] = []
    index: Dict[Any, int] = {}
    memberships: List[List[int]] = []
    for plan in plans:
        rows: List[int] = []
        seen: set = set()
        for analyzer in plan:
            if analyzer in seen:
                continue
            seen.add(analyzer)
            pos = index.get(analyzer)
            if pos is None:
                pos = len(union)
                index[analyzer] = pos
                union.append(analyzer)
            rows.append(pos)
        memberships.append(rows)
    return union, memberships


@dataclass(frozen=True)
class FamilyJobPlan:
    """One planned family-kernel job: the (column, where) family whose
    fused moments + decimated quantile sample (+ HLL registers when an
    ApproxCountDistinct on the same family consumes them) come out of a
    single C traversal. Identity is the memo key `qkey`."""

    column: str
    where: Optional[str]
    wkey: str
    cap: int
    want_regs: bool

    @property
    def qkey(self) -> str:
        return f"__qsample:{self.column}:{self.wkey}:{self.cap}"

    @property
    def mkey(self) -> str:
        return f"__moments:{self.column}:{self.wkey}"

    @property
    def rkey(self) -> str:
        return f"__hllregs:{self.column}:{self.wkey}"


def family_group_key(wkey: str, cap: int) -> Tuple[str, int]:
    """Grouping key for batching family jobs into ONE multi-column
    native traversal: same where mask, same sample cap. (All jobs of one
    batch share the row count, so this is the full runtime key too.)"""
    return (wkey, cap)


def plan_family_jobs(
    host_assisted_members: Sequence[Any],
    host_members: Sequence[Any] = (),
) -> List[FamilyJobPlan]:
    """Plan the family-kernel jobs a host fold would run — pure and
    data-free. One job per distinct (column, where, cap) family across
    the host-assisted members (quantile sketches); `want_regs` marks
    families whose HLL registers a host-folded ApproxCountDistinct on
    the same (column, where) will consume."""
    from deequ_tpu.analyzers.base import where_key

    acd_families = {
        (getattr(member, "column", None), where_key(getattr(member, "where", None)))
        for member in host_members
        if getattr(member, "name", "") == "ApproxCountDistinct"
    }
    jobs: List[FamilyJobPlan] = []
    seen: set = set()
    for member in host_assisted_members:
        sample_size = getattr(member, "_sample_size", None)
        column = getattr(member, "column", None)
        if sample_size is None or column is None:
            continue
        where = getattr(member, "where", None)
        wkey = where_key(where)
        job = FamilyJobPlan(
            column=column,
            where=where,
            wkey=wkey,
            cap=int(sample_size()),
            want_regs=(column, wkey) in acd_families,
        )
        if job.qkey in seen:
            continue
        seen.add(job.qkey)
        jobs.append(job)
    return jobs


def group_family_jobs(
    jobs: Sequence[FamilyJobPlan],
) -> List[Tuple[Tuple[str, int], List[FamilyJobPlan]]]:
    """Group planned family jobs by `family_group_key` — each group is
    one (possibly multi-column batched) native kernel dispatch per
    batch. Order: first appearance, matching the runtime dispatch."""
    groups: Dict[Tuple[str, int], List[FamilyJobPlan]] = {}
    for job in jobs:
        groups.setdefault(family_group_key(job.wkey, job.cap), []).append(job)
    return list(groups.items())


class AnalyzerRunResult:
    """Outcome of one analyzer in a pass: a state (possibly None = empty)
    or an error."""

    def __init__(
        self,
        analyzer: ScanShareableAnalyzer,
        state: Optional[State] = None,
        error: Optional[BaseException] = None,
    ):
        self.analyzer = analyzer
        self.state = state
        self.error = error

    def state_or_raise(self) -> Optional[State]:
        if self.error is not None:
            raise self.error
        return self.state


def _merge_partition_results(
    a: AnalyzerRunResult, b: AnalyzerRunResult
) -> AnalyzerRunResult:
    """Semigroup merge of one analyzer's outcome across two partitions:
    errors win (a failing analyzer fails for the dataset, matching the
    single-pass contract), a None state is the identity (an empty
    partition contributes nothing), and a failing merge becomes that
    analyzer's error, never the pass's."""
    if a.error is not None:
        return a
    if b.error is not None:
        return b
    if a.state is None:
        return AnalyzerRunResult(a.analyzer, state=b.state)
    if b.state is None:
        return a
    try:
        return AnalyzerRunResult(a.analyzer, state=a.state.merge(b.state))
    except Exception as e:  # noqa: BLE001
        return AnalyzerRunResult(a.analyzer, error=e)


def scan_partition(
    analyzers,
    partition,
    *,
    batch_size=None,
    forensics=None,
    controller=None,
):
    """Fold ONE partition to per-analyzer results through the normal
    single-source fused path (native reader read-ahead, decode->wire
    fusion, backpressured pipeline — everything a whole-dataset scan
    uses). This is the one sub-scan both `_run_partitioned` and the
    sharded scan (parallel/multihost.py) call, which is what makes a
    shard's per-partition states byte-identical to a solo run's: same
    analyzer list, same batch sizing, same fold — same bits."""
    sub = FusedScanPass(
        analyzers, batch_size, forensics=forensics, controller=controller
    )
    return sub.run(partition.source())


def _to_f64(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, dtype=np.float64), tree
    )


def prune_table_columns(table, specs: Dict[str, Any]):
    """Column pruning for streaming sources: when every live input spec
    declares the columns it reads, restrict the scan to their union so
    the source only decodes what the pass consumes (the reference gets
    this from Spark's column pruning; here it's the difference between
    decoding 6 Parquet columns per pass and 3). In-memory Tables slice
    lazily and don't implement with_columns; unknown-read specs
    (columns=None) disable pruning for safety."""
    with_columns = getattr(table, "with_columns", None)
    if with_columns is None:
        return table
    needed: set = set()
    for spec in specs.values():
        if spec.columns is None:
            return table
        needed.update(spec.columns)
    if not needed:
        # e.g. a Size()-only pass: row counts need only the cheapest column
        names = getattr(table, "column_names", None)
        if not names:
            return table
        needed = {names[0]}
    return with_columns(sorted(needed))


def plan_row_group_prune(table, members):
    """Static row-group pruning for a parquet-backed scan: build a
    PrunePlan (lint/pushdown.py's three-valued interpreter) from the
    file's row-group statistics and the live members' where filters.
    None when the source has no statistics surface, the knob is off, or
    anything at all goes wrong — pruning is an optimization, never a
    failure mode. The decision itself is pure: the source is the only
    statistics reader."""
    if not runtime.pushdown_enabled():
        return None
    stats_fn = getattr(table, "row_group_stats", None)
    if stats_fn is None or getattr(table, "with_prune", None) is None:
        return None
    from deequ_tpu.lint.pushdown import build_prune_plan

    try:
        groups = stats_fn()
        if not groups:
            return None
        return build_prune_plan(
            [getattr(m, "where", None) for m in members],
            groups,
            dict(table.schema),
        )
    except Exception:  # noqa: BLE001
        return None


#: spec-key prefixes whose builds consume only the packed representation
#: of a dictionary-string column (codes + mask + uniques digest) — the
#: lazy per-row string gather provably never fires, so such columns are
#: safe for the native decode's lazy-values Column. An unknown prefix
#: routes the column to the host chain instead (conservative, never
#: wrong). Numeric/bool columns skip this check: their Columns are fully
#: materialized by both paths.
PACKED_SAFE_PREFIXES = frozenset(
    {
        "num", "valid", "where", "pred", "prednn", "match", "dtclass",
        "hll", "lcc_codes", "lcc_uniq", "optnum", "optnumv",
    }
)

#: per-row bytes of intermediate host materialization the fast path
#: avoids for one column: the fill_null'd arrow array copy (element
#: width) plus the bitmap→bool mask expansion (1 byte). Prediction-only
#: accounting for EXPLAIN/cost — never used for correctness.
_DECODE_TOKEN_BYTES = {
    "double": 8, "float": 4, "int8": 1, "int16": 2, "int32": 4,
    "int64": 8, "uint8": 1, "uint16": 2, "uint32": 4, "uint64": 8,
    "bool": 1, "dictionary<string,int32>": 4,
}


@dataclass(frozen=True)
class DecodePlan:
    """Static per-column decode routing for one parquet-backed scan:
    which columns take the buffer-level native fast path, which fall
    back to the host chain (with the reason, for EXPLAIN's DQ312), and
    the worker count the scan decodes with. Purely a perf/accounting
    decision — both routes emit bit-identical Columns.

    The wire_* fields carry the decode-to-wire verdict layered on top:
    columns (a subset of `fast`) whose every live consumer is
    packed-only decode STRAIGHT to wire buffers, and the rest of the
    wire candidates record why they stayed on the Column path (column,
    reason, offending consumer key — EXPLAIN's DQ313 caret)."""

    fast: Tuple[str, ...]
    fallbacks: Tuple[Tuple[str, str], ...]  # (column, reason)
    workers: int
    wire_fused: Tuple[str, ...] = ()
    wire_falloffs: Tuple[Tuple[str, str, str], ...] = ()  # (col, reason, key)
    wire_batch: int = 0
    wire_specs: Any = field(default=None, compare=False)  # col -> ColumnWireSpec
    # native-parquet-reader verdict layered on the fast set: columns
    # whose EVERY live column chunk the page decoder proves from footer
    # metadata (classify_reader_columns), the per-column fall-off
    # reasons (EXPLAIN's DQ315), and the non-pruned group count the
    # chunk counters scale by. reader_planned distinguishes "reader
    # planning ran and fused nothing" from "never planned" so the
    # drift pin sees 0 == 0 rather than a missing series.
    reader_cols: Tuple[str, ...] = ()
    reader_falloffs: Tuple[Tuple[str, str], ...] = ()  # (column, reason)
    reader_groups: int = 0
    reader_planned: bool = False
    # encoded-fold verdict layered on the reader set: columns whose
    # every live chunk is provably all-dictionary-coded AND whose every
    # consumer the run-fold memos can serve (classify_encfold_columns),
    # the per-column fall-off reasons (EXPLAIN's DQ325), and the
    # col -> EncFoldColSpec map the source ships to decode_unit.
    # enc_planned follows reader_planned's record-the-zeros contract.
    enc_cols: Tuple[str, ...] = ()
    enc_falloffs: Tuple[Tuple[str, str], ...] = ()  # (column, reason)
    enc_specs: Any = field(default=None, compare=False)
    enc_planned: bool = False

    @property
    def total(self) -> int:
        return len(self.fast) + len(self.fallbacks)


def classify_decode_columns(
    col_types: Dict[str, str], specs: Dict[str, Any]
) -> Tuple[List[str], List[Tuple[str, str]]]:
    """Pure eligibility split over a scan's columns. `col_types` is the
    source's decode_column_types() token map; `specs` the live input
    specs (their key prefixes prove which dictionary-string columns are
    consumed packed-only). Shared verbatim by the planner and the cost
    model so prediction and execution can never disagree."""
    from deequ_tpu.ops import native

    consumers: Dict[str, set] = {}
    for spec in specs.values():
        prefix = spec.key.split(":", 1)[0]
        for col in spec.columns or ():
            consumers.setdefault(col, set()).add(prefix)
    fast: List[str] = []
    fallbacks: List[Tuple[str, str]] = []
    for name in sorted(col_types):
        token = col_types[name]
        if token in native.DECODE_PRIMITIVES or token == "bool":
            fast.append(name)
        elif token == "dictionary<string,int32>":
            unsafe = sorted(consumers.get(name, ()) - PACKED_SAFE_PREFIXES)
            if unsafe:
                fallbacks.append(
                    (
                        name,
                        "host string values may be required by "
                        + ", ".join(unsafe),
                    )
                )
            else:
                fast.append(name)
        elif token in ("string", "large_string"):
            fallbacks.append((name, "plain string values are host objects"))
        elif token.startswith("timestamp"):
            fallbacks.append((name, "timestamp decode needs an arrow cast"))
        elif token.startswith("decimal"):
            fallbacks.append((name, "decimal values decode host-side"))
        else:
            fallbacks.append((name, f"no native kernel for {token}"))
    return fast, fallbacks


def decode_saved_bytes_per_row(plan: DecodePlan, col_types: Dict[str, str]) -> int:
    """Predicted bytes/row of intermediate materialization the fast
    columns skip (value copy + mask byte-expansion)."""
    return sum(
        _DECODE_TOKEN_BYTES.get(col_types.get(c, ""), 0) + 1 for c in plan.fast
    )


def classify_reader_columns(
    col_types: Dict[str, str],
    groups,
    codec_mask: int,
    skip_groups=frozenset(),
) -> Tuple[List[str], List[Tuple[str, str]], int]:
    """Pure native-parquet-reader eligibility split over a scan's
    fast-decode columns, proved statically from footer metadata alone.

    `col_types` maps the CANDIDATE columns (the decode plan's fast set —
    reader ⊆ fastpath by construction) to their decode tokens; `groups`
    are the source's row_group_stats() with chunk-layout fields
    (physical type, codec, page encodings, byte ranges, nesting);
    `codec_mask` is native.reader_codecs()'s loadable-decompressor
    bitmask; `skip_groups` replays the prune verdict so only chunks the
    scan will actually read are judged. A column qualifies only when
    EVERY live chunk does — one odd chunk falls the whole column back,
    with a reason naming the disqualifying encoding/codec (EXPLAIN's
    DQ315). Returns (reader_cols, falloffs, live_group_count). Shared
    verbatim by the planner and the cost model so prediction and
    execution can never disagree."""
    from deequ_tpu.ops import native

    live = [rg for rg in groups if rg.index not in skip_groups]
    reader: List[str] = []
    falloffs: List[Tuple[str, str]] = []
    if not live:
        return (
            reader,
            [(n, "every row group is pruned") for n in sorted(col_types)],
            0,
        )
    for name in sorted(col_types):
        token = col_types[name]
        spec = native.READER_TOKENS.get(token)
        if spec is None:
            falloffs.append((name, f"no native page decoder for {token}"))
            continue
        allowed_phys, _ = spec
        reason = None
        for rg in live:
            st = rg.columns.get(name)
            if (
                st is None
                or st.physical_type is None
                or st.codec is None
                or st.encodings is None
                or st.chunk_offset is None
                or st.chunk_bytes is None
                or st.num_values is None
                or st.max_def_level is None
                or st.max_rep_level is None
            ):
                reason = (
                    f"row group {rg.index} carries no chunk layout metadata"
                )
                break
            if st.physical_type not in allowed_phys:
                reason = (
                    f"physical type {st.physical_type} cannot back {token}"
                )
                break
            bit = native.READER_CODEC_MASK.get(st.codec)
            if bit is None:
                reason = f"codec {st.codec} has no native decompressor"
                break
            if not (codec_mask & bit):
                reason = f"codec {st.codec} library is not loadable here"
                break
            extra = sorted(set(st.encodings) - native.READER_ENCODINGS)
            if extra:
                reason = f"page encoding {extra[0]} has no native decoder"
                break
            if token == "bool" and (
                set(st.encodings) & {"PLAIN_DICTIONARY", "RLE_DICTIONARY"}
            ):
                reason = "dictionary-encoded boolean pages decode via arrow"
                break
            if st.max_rep_level != 0 or st.max_def_level > 1:
                reason = "nested or repeated values need the arrow reader"
                break
            if int(st.num_values) != int(rg.num_rows):
                reason = "chunk value count disagrees with the row group"
                break
        if reason is not None:
            falloffs.append((name, reason))
        else:
            reader.append(name)
    return reader, falloffs, len(live)


def reader_saved_alloc_bytes_per_row(
    reader_cols, col_types: Dict[str, str]
) -> int:
    """Predicted bytes/row of arrow materialization the native reader
    skips per fused column: the decoded arrow array (element width) plus
    its validity bitmap byte — the buffers pyarrow would have built just
    for the decode kernels to re-read. Prediction-only accounting for
    EXPLAIN/cost — never used for correctness."""
    return sum(
        _DECODE_TOKEN_BYTES.get(col_types.get(c, ""), 0) + 1
        for c in reader_cols
    )


#: analyzer families the encoded-fold planner may serve from run-fold
#: memos (ops/analyzers answering from the family/moments memo keys):
#: anything else on the column needs row-width values and falls it off.
_ENCFOLD_ANALYZERS = frozenset(
    {
        "Mean", "Sum", "Minimum", "Maximum", "StandardDeviation",
        "Completeness", "ApproxQuantile", "ApproxQuantiles",
        "ApproxCountDistinct",
    }
)

#: members whose family job publishes the full sketch memos
_ENCFOLD_SKETCH = frozenset(
    {"ApproxQuantile", "ApproxQuantiles", "ApproxCountDistinct"}
)

#: input-spec key prefixes the memo publication can stand in for
_ENCFOLD_KEY_PREFIXES = frozenset({"num", "valid", "hll"})


def classify_encfold_columns(
    col_types: Dict[str, str],
    analyzers,
    specs: Dict[str, Any],
    device_keys,
    groups,
    skip_groups=frozenset(),
    int_bounds=None,
):
    """Pure encoded-fold eligibility split over a scan's native-reader
    columns, proved statically — exactly like classify_reader_columns.

    `col_types` maps the CANDIDATE columns (the reader set — encoded
    fold ⊆ reader by construction) to their decode tokens; `analyzers`
    are the pass's live members; `specs` the deduplicated input specs
    (their key prefixes prove which consumers the memo publication can
    serve); `device_keys` the member plan's device-consumed key set (a
    device-packed column would expand its stub every batch — excluded);
    `groups` the row_group_stats with page-placement fields;
    `int_bounds` the statically pinned footer min/max per column. A
    column qualifies only when EVERY live chunk is provably
    all-dictionary-coded AND every consumer is memo-servable — one odd
    chunk or consumer falls the whole column back, with a reason naming
    the disqualifier (EXPLAIN's DQ325). Returns
    (col -> EncFoldColSpec, falloffs). Shared verbatim by the planner
    and the cost model so prediction and execution can never
    disagree."""
    from deequ_tpu.data import native_reader as nr
    from deequ_tpu.data.encfold import EncFoldColSpec

    live = [rg for rg in groups if rg.index not in skip_groups]
    if not live:
        return {}, [
            (n, "codec: every row group is pruned")
            for n in sorted(col_types)
        ]
    int_bounds = int_bounds or {}
    # per-column consumer views: spec key prefixes + exact keys (for the
    # device-placement exclusion), analyzer names
    prefixes: Dict[str, set] = {}
    keys_by_col: Dict[str, set] = {}
    for spec in specs.values():
        prefix = spec.key.split(":", 1)[0]
        for col in spec.columns or ():
            prefixes.setdefault(col, set()).add(prefix)
            keys_by_col.setdefault(col, set()).add(spec.key)
    names: Dict[str, set] = {}
    wheres: Dict[str, set] = {}
    for a in analyzers:
        try:
            a_cols = set()
            for s in a.input_specs():
                a_cols.update(s.columns or ())
                if s.columns is None:
                    # unknowable reads: the analyzer may touch anything
                    a_cols.update(col_types)
        except Exception:  # noqa: BLE001 - unknowable reads: consume all
            a_cols = set(col_types)
        for col in a_cols:
            names.setdefault(col, set()).add(a.name)
            if getattr(a, "where", None) is not None:
                wheres.setdefault(col, set()).add(a.name)
    enc: Dict[str, Any] = {}
    falloffs: List[Tuple[str, str]] = []
    for name in sorted(col_types):
        token = col_types[name]
        if token not in nr.ENCFOLD_TOKENS:
            falloffs.append(
                (name, f"dtype: no run-fold kernel for {token}")
            )
            continue
        consumers = names.get(name, set())
        bad = sorted(consumers - _ENCFOLD_ANALYZERS)
        if bad:
            falloffs.append(
                (name, f"analyzer: {bad[0]} needs row-width values")
            )
            continue
        filtered = sorted(wheres.get(name, ()))
        if filtered:
            falloffs.append(
                (
                    name,
                    f"analyzer: {filtered[0]} carries a where filter "
                    "(family memos publish unfiltered only)",
                )
            )
            continue
        extra = sorted(prefixes.get(name, set()) - _ENCFOLD_KEY_PREFIXES)
        if extra:
            falloffs.append(
                (name, f"analyzer: consumer {extra[0]}: needs row values")
            )
            continue
        if keys_by_col.get(name, set()) & set(device_keys):
            falloffs.append(
                (name, "analyzer: consumed by a device-placed member")
            )
            continue
        has_sketch = bool(consumers & _ENCFOLD_SKETCH)
        kind = "f64" if token in ("double", "float") else "i64"
        bounds = int_bounds.get(name)
        publish_moments = (
            kind == "i64"
            and "StandardDeviation" not in consumers
            and bounds is not None
            and -(1 << 31) < int(bounds[0])
            and int(bounds[1]) < (1 << 31)
        )
        if "StandardDeviation" in consumers and not has_sketch:
            falloffs.append(
                (
                    name,
                    "analyzer: StandardDeviation without a sketch "
                    "family needs the kernel's m2 stream",
                )
            )
            continue
        if not (has_sketch or publish_moments or
                prefixes.get(name, set()) <= {"valid"}):
            falloffs.append(
                (
                    name,
                    "dict-size: no memo-servable consumer (moments "
                    "bounds unproven and no sketch family)",
                )
            )
            continue
        reason = None
        for rg in live:
            st = rg.columns.get(name)
            if st is None:
                reason = (
                    f"codec: row group {rg.index} carries no chunk "
                    "layout metadata"
                )
                break
            if (
                getattr(st, "dictionary_page_offset", None) is None
                or getattr(st, "data_page_offset", None) is None
                or st.dictionary_page_offset >= st.data_page_offset
            ):
                reason = (
                    f"codec: chunk in row group {rg.index} has no "
                    "leading dictionary page"
                )
                break
            encs = set(st.encodings or ())
            if "RLE_DICTIONARY" in encs:
                # v2 footers list PLAIN unconditionally (the dictionary
                # page's own encoding): genuinely plain data pages fail
                # closed per chunk at decode (PQE_UNSUPPORTED)
                continue
            if "PLAIN_DICTIONARY" not in encs:
                reason = (
                    f"codec: chunk in row group {rg.index} is not "
                    "dictionary-coded"
                )
                break
            if "PLAIN" in encs:
                # v1 footers list PLAIN only when the writer actually
                # fell back to plain data pages mid-chunk
                reason = (
                    f"codec: chunk in row group {rg.index} fell back "
                    "to PLAIN data pages (dict-size overflow at write)"
                )
                break
        if reason is not None:
            falloffs.append((name, reason))
            continue
        enc[name] = EncFoldColSpec(
            column=name,
            token=token,
            kind=kind,
            publish_moments=publish_moments,
        )
    return enc, falloffs


#: integer arrow tokens the wire kernels take (uint64 deliberately
#: absent: the OFF path ships it through int64-wrap semantics the wire
#: kernels don't reproduce) and their type value bounds
_WIRE_INT_TOKEN_BOUNDS = {
    "int8": (-(1 << 7), (1 << 7) - 1),
    "int16": (-(1 << 15), (1 << 15) - 1),
    "int32": (-(1 << 31), (1 << 31) - 1),
    "int64": (-(1 << 63), (1 << 63) - 1),
    "uint8": (0, (1 << 8) - 1),
    "uint16": (0, (1 << 16) - 1),
    "uint32": (0, (1 << 32) - 1),
}

#: narrow wire dtypes an int column may pin to, narrowest first
_WIRE_NARROW_LADDER = (
    ("int8", -(1 << 7), (1 << 7) - 1),
    ("int16", -(1 << 15), (1 << 15) - 1),
    ("int32", -(1 << 31), (1 << 31) - 1),
)


def _pin_int_wire_width(token: str, bounds) -> Optional[str]:
    """The narrowest exact wire dtype for an int column, pinned
    STATICALLY for the whole pass: from the file's min/max statistics
    when every row group has them, else from the arrow type's value
    bounds. The range always widens to include 0 (the null fill the
    kernels write). None when nothing ≤ int32 holds the range — the
    column then ships as a float64 value row, which is what the Column
    path produces for every integer anyway."""
    lo, hi = _WIRE_INT_TOKEN_BOUNDS[token]
    if bounds is not None:
        lo, hi = bounds
    lo = min(int(lo), 0)
    hi = max(int(hi), 0)
    for name, dlo, dhi in _WIRE_NARROW_LADDER:
        if dlo <= lo and hi <= dhi:
            return name
    return None


def classify_wire_columns(
    col_types: Dict[str, str],
    specs: Dict[str, Any],
    packed_only_keys: set,
    dtype_name: str,
    int_bounds: Optional[Dict[str, Any]] = None,
):
    """Pure decode-to-wire eligibility split over a scan's columns.

    A column fuses iff its every live consumer key is `num:{col}` /
    `valid:{col}` AND in `packed_only_keys` (merge members' compiled
    reduces only — see ScanMemberPlan.packed_only_keys), its token has a
    wire kernel, and its wire value layout is statically known. Anything
    else stays on the Column path with a (column, reason, offending key)
    record for EXPLAIN's DQ313. `dtype_name` is the compute dtype
    ('float64'/'float32'); `int_bounds` maps columns to (min, max) file
    statistics (None/absent = no usable stats). Shared verbatim by the
    planner and the cost model so prediction and execution can never
    disagree."""
    from deequ_tpu.ops import native

    wire_specs: Dict[str, runtime.ColumnWireSpec] = {}
    falloffs: List[Tuple[str, str, str]] = []
    int_bounds = int_bounds or {}
    candidates = [
        name
        for name in sorted(col_types)
        if col_types[name] in ("double", "float", "bool")
        or col_types[name] in _WIRE_INT_TOKEN_BOUNDS
        or col_types[name] == "uint64"
    ]
    if not candidates:
        return wire_specs, falloffs
    unknown_reads = any(spec.columns is None for spec in specs.values())
    consumers: Dict[str, set] = {}
    for spec in specs.values():
        for col in spec.columns or ():
            consumers.setdefault(col, set()).add(spec.key)
    for name in candidates:
        token = col_types[name]
        if unknown_reads:
            falloffs.append(
                (name, "an input spec reads unknown columns", "")
            )
            continue
        if token == "uint64":
            falloffs.append(
                (name, "uint64 int64-wrap semantics stay on the Column path", "")
            )
            continue
        keys = consumers.get(name, set())
        if not keys:
            falloffs.append((name, "no live consumer reads this column", ""))
            continue
        allowed = {f"num:{name}", f"valid:{name}"}
        bad = sorted(keys - allowed)
        if bad:
            falloffs.append(
                (name, f"consumer {bad[0]} needs the host Column", bad[0])
            )
            continue
        off_wire = sorted(keys - packed_only_keys)
        if off_wire:
            falloffs.append(
                (
                    name,
                    f"{off_wire[0]} is re-read off-wire by a host/assisted member",
                    off_wire[0],
                )
            )
            continue
        want_value = f"num:{name}" in keys
        want_valid = f"valid:{name}" in keys
        value_kind = ""
        value_dtype = ""
        needs_shift = False
        desc = "bits"
        if want_value:
            if token == "bool":
                falloffs.append(
                    (
                        name,
                        "bool numeric values build host-side (astype)",
                        f"num:{name}",
                    )
                )
                continue
            if token in ("double", "float"):
                value_kind = "val"
                value_dtype = dtype_name
                needs_shift = dtype_name == "float32"
                desc = "f32+shift" if needs_shift else "f64"
            elif dtype_name == "float32":
                # f32 wire ships ints as shifted f32 value rows, exactly
                # like the Column path's pack
                value_kind = "val"
                value_dtype = "float32"
                needs_shift = True
                desc = "f32+shift"
            else:
                narrow = _pin_int_wire_width(token, int_bounds.get(name))
                if narrow is None:
                    value_kind = "val"
                    value_dtype = "float64"
                    desc = "f64"
                else:
                    value_kind = "ival"
                    value_dtype = narrow
                    desc = narrow.replace("int", "i")
            if not native.wire_supported(token, value_dtype):
                falloffs.append(
                    (name, f"no wire kernel for {token}->{value_dtype}", "")
                )
                continue
        wire_specs[name] = runtime.ColumnWireSpec(
            column=name,
            token=token,
            want_value=want_value,
            want_valid=want_valid,
            value_kind=value_kind,
            value_dtype=value_dtype,
            needs_shift=needs_shift,
            desc=desc,
        )
    return wire_specs, falloffs


def wire_saved_pack_bytes_per_row(wire_specs: Dict[str, Any]) -> int:
    """Predicted bytes/row of host pack work the fused columns skip: the
    full-width value array pack re-reads plus the uint8 mask packbits
    re-reads, per column. Prediction-only accounting for EXPLAIN/cost."""
    saved = 0
    for spec in wire_specs.values():
        if spec.want_value:
            saved += 8  # the f64 numeric_values array pack re-reads
        if spec.want_valid:
            saved += 1  # the uint8 mask packbits re-reads
    return saved


def wire_int_bounds(table, columns) -> Dict[str, Any]:
    """Per-column (min, max) from the file's row-group statistics, for
    the wire planner's static narrow-int pinning. A column appears only
    when EVERY row group has usable min/max — a single missing stat
    falls the column back to its type bounds (wider, never wrong).
    Empty on any error: bounds are an optimization input."""
    stats_fn = getattr(table, "row_group_stats", None)
    if stats_fn is None or not columns:
        return {}
    try:
        groups = stats_fn()
    except Exception:  # noqa: BLE001
        return {}
    return wire_int_bounds_from_groups(groups, columns)


def wire_int_bounds_from_groups(groups, columns) -> Dict[str, Any]:
    """Same pinning input computed from already-loaded row-group stats —
    the cost model replays the wire verdict from its `row_groups`
    argument without a live source handle."""
    if not groups:
        return {}
    bounds: Dict[str, Any] = {}
    for name in columns:
        lo = hi = None
        for rg in groups:
            st = rg.columns.get(name)
            if st is None or st.min_value is None or st.max_value is None:
                lo = None
                break
            try:
                g_lo, g_hi = int(st.min_value), int(st.max_value)
            except (TypeError, ValueError):
                lo = None
                break
            lo = g_lo if lo is None else min(lo, g_lo)
            hi = g_hi if hi is None else max(hi, g_hi)
        if lo is not None and hi is not None:
            bounds[name] = (lo, hi)
    return bounds


def plan_decode_fastpath(
    table,
    specs: Dict[str, Any],
    member_plan=None,
    batch_size: int = 0,
    analyzers=None,
):
    """Build a DecodePlan for a parquet-backed scan, or None when the
    knob is off, the source has no decode-planning surface, the native
    library is unavailable, or anything at all goes wrong — the fast
    path is an optimization, never a failure mode. Call AFTER column
    pruning so only surviving columns are classified.

    With `member_plan` (the pass's ScanMemberPlan) and `batch_size`, the
    plan layers the decode-to-wire verdict on top: fast columns whose
    every consumer is packed-only get a ColumnWireSpec and skip the
    Column intermediate entirely (DEEQU_TPU_WIRE_FUSED gates this
    independently of the fast path)."""
    if not runtime.decode_fastpath_enabled():
        return None
    types_fn = getattr(table, "decode_column_types", None)
    if types_fn is None or getattr(table, "with_decode_fastpath", None) is None:
        return None
    from deequ_tpu.ops import native

    if not native.available():
        return None
    try:
        col_types = types_fn()
        if not col_types:
            return None
        fast, fallbacks = classify_decode_columns(col_types, specs)
        wire_specs: Dict[str, Any] = {}
        wire_falloffs: List[Tuple[str, str, str]] = []
        if (
            member_plan is not None
            and batch_size > 0
            and runtime.wire_fused_enabled()
            and getattr(table, "with_wire_fusion", None) is not None
        ):
            fast_types = {c: col_types[c] for c in fast}
            dtype_name = np.dtype(runtime.compute_dtype()).name
            wire_specs, wire_falloffs = classify_wire_columns(
                fast_types,
                specs,
                member_plan.packed_only_keys,
                dtype_name,
                int_bounds=wire_int_bounds(table, sorted(fast_types)),
            )
        reader_cols: Tuple[str, ...] = ()
        reader_falloffs: Tuple[Tuple[str, str], ...] = ()
        reader_groups = 0
        reader_planned = False
        enc_cols: Tuple[str, ...] = ()
        enc_falloffs: Tuple[Tuple[str, str], ...] = ()
        enc_specs = None
        enc_planned = False
        if (
            runtime.native_reader_enabled()
            and getattr(table, "with_native_reader", None) is not None
            and getattr(table, "row_group_stats", None) is not None
        ):
            # reader planning is best-effort on top of the fast-path
            # verdict: a stats failure here must not cost the fast set
            try:
                codec_mask = native.reader_codecs()
                groups = table.row_group_stats()
                if groups and codec_mask:
                    skip = (
                        getattr(table, "prune_groups", None) or frozenset()
                    )
                    r_cols, r_falloffs, reader_groups = (
                        classify_reader_columns(
                            {c: col_types[c] for c in fast},
                            groups,
                            codec_mask,
                            skip,
                        )
                    )
                    reader_cols = tuple(r_cols)
                    reader_falloffs = tuple(r_falloffs)
                    reader_planned = True
                    # encoded-fold verdict layered on the reader set:
                    # needs the live analyzers (consumer proofs) and
                    # the encoded-fold source surface. Best-effort like
                    # reader planning — a failure here must not cost
                    # the reader set.
                    if (
                        reader_cols
                        and analyzers is not None
                        and member_plan is not None
                        and runtime.encoded_fold_enabled()
                        and getattr(table, "with_encoded_fold", None)
                        is not None
                    ):
                        e_specs, e_falloffs = classify_encfold_columns(
                            {c: col_types[c] for c in reader_cols},
                            analyzers,
                            specs,
                            member_plan.device_keys,
                            groups,
                            skip,
                            int_bounds=wire_int_bounds_from_groups(
                                groups, sorted(reader_cols)
                            ),
                        )
                        enc_cols = tuple(sorted(e_specs))
                        enc_falloffs = tuple(e_falloffs)
                        enc_specs = e_specs or None
                        enc_planned = True
            except Exception:  # noqa: BLE001
                reader_cols = ()
                reader_falloffs = ()
                reader_groups = 0
                reader_planned = False
                enc_cols = ()
                enc_falloffs = ()
                enc_specs = None
                enc_planned = False
        return DecodePlan(
            fast=tuple(fast),
            fallbacks=tuple(fallbacks),
            workers=runtime.decode_workers(),
            wire_fused=tuple(sorted(wire_specs)),
            wire_falloffs=tuple(wire_falloffs),
            wire_batch=int(batch_size),
            wire_specs=wire_specs or None,
            reader_cols=reader_cols,
            reader_falloffs=reader_falloffs,
            reader_groups=reader_groups,
            reader_planned=reader_planned,
            enc_cols=enc_cols,
            enc_falloffs=enc_falloffs,
            enc_specs=enc_specs,
            enc_planned=enc_planned,
        )
    except Exception:  # noqa: BLE001
        return None


def apply_decode_plan(table, plan: DecodePlan):
    """Act on a DecodePlan: record the `decode_fastpath` span + counters
    (the trace side of cost_drift's zero-drift pins and the
    engine.decode_fastpath_ratio / engine.wire_fused_ratio telemetry
    series), then view the source with the fast set — and, when the
    wire verdict fused columns, the WireFusionPlan — attached."""
    with observe.span(
        "decode_fastpath",
        cat="plan",
        cols_total=plan.total,
        cols_fast=len(plan.fast),
        cols_fallback=len(plan.fallbacks),
        cols_wire_fused=len(plan.wire_fused),
        cols_reader=len(plan.reader_cols),
        reader_groups=plan.reader_groups,
        cols_encfold=len(plan.enc_cols),
        workers=plan.workers,
    ):
        pass
    runtime.record_decode_fastpath(len(plan.fast), plan.total, plan.workers)
    if plan.wire_batch > 0:
        # wire planning ran (single-engine pass with a member plan):
        # record the verdict even when it fused nothing, so the drift
        # pin sees 0 predicted == 0 observed rather than a missing series
        runtime.record_wire_fused(len(plan.wire_fused), plan.total)
    if plan.reader_planned:
        # same record-the-zeros contract for the reader chunk counters:
        # chunk counts are STATIC (columns × non-pruned groups), the
        # trace side of cost_drift's reader_chunks_native pin
        native_chunks = len(plan.reader_cols) * plan.reader_groups
        total_chunks = plan.total * plan.reader_groups
        runtime.record_reader_chunks(
            native_chunks, total_chunks - native_chunks, total_chunks
        )
    if plan.enc_planned:
        # record-the-zeros contract for the encoded-fold column verdict
        # (the STATIC half — per-unit run/fallback counters come from
        # decode_unit): the trace side of cost_drift's encfold_columns
        # pin sees 0 predicted == 0 observed rather than a missing series
        runtime.record_encfold_plan(len(plan.enc_cols), plan.total)
    if plan.fast:
        table = table.with_decode_fastpath(plan.fast)
    if plan.wire_specs:
        with_wire = getattr(table, "with_wire_fusion", None)
        if with_wire is not None:
            table = with_wire(
                runtime.WireFusionPlan(plan.wire_specs, plan.wire_batch)
            )
    if plan.reader_cols:
        with_reader = getattr(table, "with_native_reader", None)
        if with_reader is not None:
            table = with_reader(plan.reader_cols)
    if plan.enc_specs:
        with_enc = getattr(table, "with_encoded_fold", None)
        if with_enc is not None:
            table = with_enc(plan.enc_specs)
    return table


def apply_prune_plan(table, prune, specs: Dict[str, Any]):
    """Act on a PrunePlan: swap every proven-all-true where's mask spec
    for a constant (the filter's columns then fall out of column
    pruning and the all-true mask elides on the wire), then view the
    source without its proven-all-false groups. The `prune` span and
    rg_* counters record what happened for the trace differential
    against EXPLAIN's prediction."""
    from deequ_tpu.analyzers.base import InputSpec, _all_true, where_key

    elided = 0
    for text in prune.elided_wheres():
        key = where_key(text)
        if key in specs:
            specs[key] = InputSpec(
                key=key,
                build=lambda t: _all_true(t.num_rows),
                columns=(),
            )
            elided += 1
    with observe.span(
        "prune",
        cat="plan",
        groups_total=prune.total_groups,
        groups_skipped=prune.skipped_groups,
        rows_skipped=prune.skipped_rows,
        wheres_elided=elided,
    ):
        pass
    runtime.record_pruned_groups(prune.skipped_groups, prune.total_groups)
    if prune.skip:
        table = table.with_prune(prune.skip)
    return table


class HostInputs(dict):
    """Per-batch input map for host-folded members. Host-only keys build
    LAZILY on first access: a member that answers from a pre-pass memo
    (e.g. ApproxCountDistinct reading fused-family HLL registers) never
    pays for the inputs it skipped. Build failures are remembered and
    re-raised on every access, so they fail exactly the members that
    consume the key — the same isolation contract as the eager path."""

    def __init__(self, specs: Dict[str, Any], batch):
        super().__init__()
        self._specs = specs
        self.batch = batch
        self.build_errors: Dict[str, BaseException] = {}

    def materialize(self, key: str) -> None:
        try:
            self[key]
        except Exception:  # noqa: BLE001 - recorded in build_errors
            pass

    def __missing__(self, key):
        err = self.build_errors.get(key)
        if err is not None:
            raise err
        spec = self._specs.get(key)
        if spec is None:
            raise KeyError(key)
        try:
            value = np.asarray(spec.build(self.batch))
        except Exception as e:  # noqa: BLE001
            self.build_errors[key] = e
            raise
        self[key] = value
        return value

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            if key in self.build_errors:
                raise
            return default


def fold_host_batch(
    built: Dict[str, np.ndarray],
    build_errors: Dict[str, BaseException],
    host_members,
    host_assisted,
    host_member_keys,
    host_aggs: Dict[int, Any],
    host_assisted_states: Dict[int, Any],
    host_errors: Dict[int, BaseException],
    batch=None,
    streaming: bool = False,
    family_memo: Optional[Dict] = None,
    precomputed: bool = False,
) -> None:
    """One batch's host-placed fold, shared by FusedScanPass and
    DistributedScanPass: merge members run their xp-generic reduce with
    numpy; assisted members (sketches) run the SAME per-batch computation
    the device would (sort+decimate) and fold via host_consume. A failed
    input fails only the members that need it. `family_memo` is a dict
    the caller keeps alive for the whole scan: cross-batch facts (e.g.
    which columns miss the counts shortcut) persist across batches.
    `precomputed=True` skips the family-kernel precompute — the stream
    pipeline's prep stage (ops/pipeline.py) already ran it off the fold
    stage's critical path and its memos sit in `built`."""
    if not precomputed:
        _precompute_family_kernels(
            built,
            host_assisted,
            batch,
            host_members=host_members,
            host_errors=host_errors,
            streaming=streaming,
            family_memo=family_memo,
        )
    # assisted members fold FIRST: some publish per-batch memos that
    # merge members answer from (e.g. _LowCardCounts' dictionary
    # presence serving ApproxCountDistinct)
    for i, member in host_assisted:
        if i in host_errors:
            continue
        try:
            for key in host_member_keys[i]:
                if key in build_errors:
                    raise build_errors[key]
            out = member.device_batch(built, np)
            host_assisted_states[i] = member.host_consume(
                host_assisted_states.get(i), out
            )
        except Exception as e:  # noqa: BLE001
            host_errors[i] = e
    for i, member in host_members:
        if i in host_errors:
            continue
        try:
            for key in host_member_keys[i]:
                if key in build_errors:
                    raise build_errors[key]
            agg = _to_f64(member.device_reduce(built, np))
            prev = host_aggs.get(i)
            host_aggs[i] = agg if prev is None else member.merge_agg(prev, agg, np)
        except Exception as e:  # noqa: BLE001
            host_errors[i] = e


_FAMILY_POOL = None


def _family_pool():
    """Process-wide worker pool for family kernels (created once: the C
    kernels' thread-local arenas stay warm and bounded per thread)."""
    global _FAMILY_POOL
    if _FAMILY_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _FAMILY_POOL = ThreadPoolExecutor(
            max_workers=min(8, os.cpu_count() or 1),
            thread_name_prefix="deequ-family",
        )
    return _FAMILY_POOL


def _family_hll_mode(batch, column: str):
    """(hll_mode, hashvals) for folding the column's HLL++ register
    update into the family kernel — matching canonical_int64's identity
    rules exactly (ops/sketches/hll.py): floats hash their f64 bit
    pattern (mode 1); ints and bools hash the canonical int64 VALUE
    (mode 2, via the original backing array — no float roundtrip).
    (0, None) when the identity can't be reproduced in-kernel.

    Only STREAMING scans fold HLL this way — the CALLER gates on its
    streaming flag (`_precompute_family_kernels`: `want_regs and
    streaming`): in-memory tables amortize the hash+pack across runs
    through the per-column encode cache, which is cheaper than
    re-hashing inside every family-kernel call — a stream's batches are
    fresh columns with nothing to amortize."""
    from deequ_tpu.data.table import ColumnType

    if batch is None:
        return 0, None
    try:
        col = batch.column(column)
    except Exception:  # noqa: BLE001
        return 0, None
    if col.ctype == ColumnType.DOUBLE and col.values.dtype == np.float64:
        return 1, None
    if col.ctype == ColumnType.LONG and col.values.dtype == np.int64:
        return 2, col.values
    if col.ctype == ColumnType.BOOLEAN and col.values.dtype == np.bool_:
        return 2, col.values.astype(np.int64)
    return 0, None


def _counts_family_shortcut(
    built, batch, column, where, wkey, cap, want_regs, qkey, mkey, rkey
) -> bool:
    """Try the counts-based family path (ops/counts_family) for a
    low-range integer column: ONE windowed count pass replaces the
    select kernel's two, and the family memos (moments, decimated
    sample, HLL registers) derive from the counts table in O(#bins).
    Returns True when the memos were published (the select job is then
    skipped); False falls through to the regular kernel. Never touches
    `num:{column}` — on success the f64 view is never built at all."""
    from deequ_tpu.data.table import ColumnType
    from deequ_tpu.ops import counts_family

    if batch is None:
        return False
    try:
        col = batch.column(column)
    except Exception:  # noqa: BLE001 - missing column: let the member fail
        return False
    if col.ctype not in (ColumnType.LONG, ColumnType.DOUBLE):
        return False
    values = np.asarray(col.values)
    is_long = col.ctype == ColumnType.LONG
    if values.dtype != (np.int64 if is_long else np.float64):
        return False
    try:
        valid = np.asarray(built[f"valid:{column}"])
        warr = None if where is None else np.asarray(built[wkey])
    except Exception:  # noqa: BLE001 - input build failure: regular path
        return False
    if valid.dtype != np.bool_ or len(valid) != len(values):
        return False
    if warr is not None and (
        warr.dtype != np.bool_ or len(warr) != len(values)
    ):
        return False
    derived = None
    if is_long:
        # dense window first (cheapest); sparse wide-range ints fall
        # through to the hash counter
        res = counts_family.counts_for_column(values, valid, warr)
        if res is not None:
            counts, lo, _n_valid, n_where = res
            derived = counts_family.family_from_counts(
                counts, lo, cap, n_where, want_regs
            )
    if derived is None:
        n_v = len(values)
        if n_v > 262144:
            # sample pre-check before the ~262k-slot hash probe: a
            # strided 4096-row sample that is nearly all-distinct
            # (>4000; a 65536-distinct population — the counter's
            # bound — expects ~3969) implies the full column is far
            # beyond the bound and the probe is guaranteed to abort.
            # A wrong skip only costs the shortcut, never correctness.
            sample = values[:: n_v // 4096][:4096]
            if np.unique(sample).size > 4000:
                return False
        hres = counts_family.hash_counts_for_column(values, valid, warr)
        if hres is None:
            return False
        keys, counts, _n_valid, n_where = hres
        derived = counts_family.family_from_hash_counts(
            keys, counts, "i64" if is_long else "f64", cap, n_where,
            want_regs,
        )
    mom, sample, n_valid, level, regs = derived
    built[qkey] = {
        "sample": sample,
        "n": np.asarray([n_valid], dtype=np.float64),
        "level": np.asarray([level], dtype=np.int32),
    }
    if regs is not None:
        built[rkey] = regs
    if mkey not in built:
        built[mkey] = {
            "count": float(mom[0]),
            "sum": float(mom[1]),
            "min": float(mom[2]),
            "max": float(mom[3]),
            "m2": float(mom[4]),
            "n_where": float(mom[5]),
            "n_rows": float(len(values)),
        }
    return True


def _precompute_family_kernels(
    built: Dict[str, np.ndarray],
    host_assisted,
    batch=None,
    host_members=(),
    host_errors=(),
    streaming: bool = False,
    family_memo: Optional[Dict] = None,
) -> None:
    """Host-fold scan sharing ACROSS analyzer kinds: when a quantile
    sketch rides the pass, one combined C traversal produces the
    (column, where) family's fused moments (consumed by
    Mean/Min/Max/Sum/StdDev via their `_moments` memo), the sketch's
    decimated sample, AND the column's HLL++ registers (consumed by
    ApproxCountDistinct, whose hash inputs then never get built at all
    under the lazy HostInputs map) — two passes over the column instead
    of the seven that separate kernels would pay. Low-range INTEGER
    columns skip even those two passes: one windowed count pass derives
    the whole family from the value distribution (ops/counts_family).
    Results land in the per-batch memo keys the members already read;
    any failure simply leaves the memos unset and each member computes
    on its own.

    `family_memo` (optional, scoped to ONE scan/stream by the caller)
    carries cross-batch facts: a column that failed the counts shortcut
    once (high-cardinality, wrong dtype) fails it for every batch of the
    stream, so the probe is skipped after the first miss.

    Same-(where, cap) families are batched into ONE multi-column native
    traversal (masked_moments_select_multi) — the across-column leg of
    scan sharing. `DEEQU_TPU_NO_MULTI_FAMILY=1` forces the per-column
    kernel (the batched path is bit-identical; the toggle exists for
    parity testing and triage).

    Job identity and grouping come from the PURE planner
    (`plan_family_jobs`/`group_family_jobs`) — the static cost analyzer
    calls the same functions; this body only adds the data-dependent
    parts (counts shortcut, array builds, kernel dispatch)."""
    from deequ_tpu.ops import counts_family, native

    # dead members don't pay their family kernel; HLL piggybacking is
    # only worth the per-row hash when a live host-folded
    # ApproxCountDistinct on the same (column, where) will consume it
    planned = plan_family_jobs(
        [member for i, member in host_assisted if i not in host_errors],
        host_members=[
            member for i, member in host_members if i not in host_errors
        ],
    )
    counts_ok = counts_family.enabled()
    # encoded-fold publication: batches decoded through the run-fold
    # path carry per-column value multisets (table.encfold payloads) —
    # publishing their family memos HERE pre-empts both the counts
    # shortcut and the select kernel below (a published qkey skips the
    # job), deriving through the same counts_family code the row path's
    # shortcut uses. Declining is always safe: the memo stays unset and
    # the job runs against the stub's expanded rows, bit-identical.
    enc = getattr(batch, "encfold", None) if batch is not None else None
    if enc and counts_ok:
        try:
            from deequ_tpu.data import encfold as _encfold

            _encfold.publish_memos(built, enc, planned)
        except Exception:  # noqa: BLE001 - memos stay unset, jobs run
            pass
    jobs = []
    for pj in planned:
        column, where, wkey = pj.column, pj.where, pj.wkey
        cap, want_regs = pj.cap, pj.want_regs
        qkey, mkey, rkey = pj.qkey, pj.mkey, pj.rkey
        if qkey in built:
            continue
        miss_key = ("counts_miss", column, wkey)
        if family_memo is not None and miss_key in family_memo:
            shortcut = False  # known miss: same column, same stream
        else:
            try:
                shortcut = counts_ok and _counts_family_shortcut(
                    built, batch, column, where, wkey, cap, want_regs,
                    qkey, mkey, rkey,
                )
            except Exception:  # noqa: BLE001 - memo stays unset, select runs
                shortcut = False
            if (
                not shortcut
                and counts_ok
                and batch is not None
                and family_memo is not None
            ):
                # the miss reasons (dtype, cardinality beyond the hash
                # counter) are column properties, stable across a
                # stream's batches — don't re-probe ~262k rows per batch
                family_memo[miss_key] = True
        if shortcut:
            continue
        try:
            x = np.asarray(built[f"num:{column}"])
            valid = np.asarray(built[f"valid:{column}"])
            warr = None if where is None else np.asarray(built[wkey])
            if valid.dtype != np.bool_ or (
                warr is not None and warr.dtype != np.bool_
            ):
                continue
        except Exception:  # noqa: BLE001 - memo stays unset, members recompute
            continue
        if valid.all():
            # all-valid elision: identical results, and it unlocks the
            # kernels' unmasked fast paths (branchless key transform,
            # quad-interleaved accumulation in the batched kernel)
            valid = None
        if want_regs and streaming:
            hll_mode, hashvals = _family_hll_mode(batch, column)
        else:
            hll_mode, hashvals = 0, None
        jobs.append(
            (qkey, mkey, rkey, x, valid, warr, cap, hll_mode, hashvals, wkey, column)
        )

    if not jobs:
        return

    def run_one(job):
        qkey, mkey, rkey, x, valid, warr, cap, hll_mode, hashvals, _w, _col = job
        try:
            return (
                native.masked_moments_select(
                    x, valid, warr, cap, hll_mode=hll_mode, hashvals=hashvals
                ),
                len(x),
            )
        except Exception:  # noqa: BLE001
            return None, len(x)

    # batch same-(where, cap) families into one traversal (all jobs of
    # one batch share the row count — `family_group_key` is the full
    # grouping decision); singleton groups keep the solo kernel (same
    # machinery, no batching overhead to amortize)
    no_multi = os.environ.get("DEEQU_TPU_NO_MULTI_FAMILY", "") not in ("", "0")
    group_map: Dict[Any, list] = {}
    for idx, job in enumerate(jobs):
        group_map.setdefault(family_group_key(job[9], job[6]), []).append(idx)
    groups = list(group_map.values())

    # worker-pool threads adopt the dispatching thread's trace context so
    # family spans stay under this scan's subtree (no-op when untraced)
    trace_tracer = observe.current_tracer()
    trace_parent = observe.current_span()

    def run_group(idxs):
        job0 = jobs[idxs[0]]
        with observe.attached(trace_tracer, trace_parent), observe.span(
            "family_kernel",
            cat="dispatch",
            where=str(job0[9]),
            cap=int(job0[6]),
            rows=len(job0[3]),
            dtype=str(job0[3].dtype),
            columns=len(idxs),
            cols=",".join(jobs[i][10] for i in idxs),
            batched=len(idxs) > 1 and not no_multi,
        ):
            if len(idxs) > 1 and not no_multi:
                g = [jobs[i] for i in idxs]
                try:
                    outs = native.masked_moments_select_multi(
                        [(j[3], j[4], j[7], j[8]) for j in g], g[0][5], g[0][6]
                    )
                except Exception:  # noqa: BLE001
                    outs = None
                if outs is not None:
                    return [(res, len(j[3])) for j, res in zip(g, outs)]
                # batched kernel unavailable/failed: per-column fallback
            return [run_one(jobs[i]) for i in idxs]

    if len(groups) > 1 and (os.cpu_count() or 1) > 1:
        # the C kernel releases the GIL: independent family groups run
        # concurrently on multicore hosts (a no-op gain on 1-core boxes).
        # ONE long-lived pool: the kernel keeps grow-only thread-local
        # arenas, so short-lived per-batch threads would leak them.
        group_outs = list(_family_pool().map(run_group, groups))
    else:
        group_outs = [run_group(g) for g in groups]
    outcomes: list = [None] * len(jobs)
    for idxs, outs in zip(groups, group_outs):
        for idx, out in zip(idxs, outs):
            outcomes[idx] = out

    for (qkey, mkey, rkey, *_rest), (res, n_rows) in zip(jobs, outcomes):
        if res is None:
            continue
        mom, sample, n_valid, level, regs = res
        built[qkey] = {
            "sample": sample,
            "n": np.asarray([n_valid], dtype=np.float64),
            "level": np.asarray([level], dtype=np.int32),
        }
        if regs is not None:
            built[rkey] = regs
        if mkey not in built:
            built[mkey] = {
                "count": float(mom[0]),
                "sum": float(mom[1]),
                "min": float(mom[2]),
                "max": float(mom[3]),
                "m2": float(mom[4]),
                "n_where": float(mom[5]),
                "n_rows": float(n_rows),
            }


def materialize_host_results(
    host_members,
    host_assisted,
    host_aggs: Dict[int, Any],
    host_assisted_states: Dict[int, Any],
    host_errors: Dict[int, BaseException],
) -> Dict[int, "AnalyzerRunResult"]:
    results: Dict[int, AnalyzerRunResult] = {}
    for i, member in host_members:
        if i in host_errors:
            results[i] = AnalyzerRunResult(member, error=host_errors[i])
        else:
            try:
                results[i] = AnalyzerRunResult(
                    member, state=member.state_from_aggregates(host_aggs.get(i))
                )
            except Exception as e:  # noqa: BLE001
                results[i] = AnalyzerRunResult(member, error=e)
    for i, member in host_assisted:
        if i in host_errors:
            results[i] = AnalyzerRunResult(member, error=host_errors[i])
        else:
            results[i] = AnalyzerRunResult(member, state=host_assisted_states.get(i))
    return results


class PipelinedAggFold:
    """Cross-batch host fold that overlaps device compute with host work:
    each submitted batch output starts an async D2H copy, and the
    PREVIOUS batch (whose copy has had a full batch of device time to
    land) is fetched and folded. Avoids paying the device round-trip
    latency per batch — on a tunneled device that latency (~20ms) would
    otherwise dominate small folds.

    Two kinds of outputs per batch: merge-analyzers' aggregates fold in
    float64 via merge_agg; assisted-analyzers' per-batch artifacts are
    handed to host_consume, once per device shard (`n_dev` shards are
    gathered along leaf axis 0 by the mesh pass)."""

    def __init__(
        self,
        analyzers: Sequence[ScanShareableAnalyzer],
        assisted: Sequence[ScanShareableAnalyzer] = (),
        n_dev: int = 1,
        sticky=None,
    ):
        self.analyzers = list(analyzers)
        self.assisted = list(assisted)
        self.n_dev = n_dev
        self.sticky = sticky if sticky is not None else {}
        self._total: Optional[List[Any]] = None
        self._assisted_states: List[Any] = [None] * len(self.assisted)
        self._pending = None

    def submit(self, device_out, meta_box=None, host_ctx=None) -> None:
        jax.tree_util.tree_map(lambda x: x.copy_to_host_async(), device_out)
        if self._pending is not None:
            self._fold(self._pending)
        # host_ctx (the batch's built inputs + wire shifts) stays alive
        # until this batch folds: device-assisted members whose output is
        # a summary (hist16) finish against the host-resident columns
        self._pending = (device_out, meta_box, host_ctx)

    def _fold(self, pending) -> None:
        device_out, meta_box, host_ctx = pending
        with observe.span("transfer", cat="transfer") as transfer_sp:
            fetched = jax.device_get(device_out)
            if transfer_sp:
                transfer_sp.set(
                    bytes=int(
                        sum(
                            int(getattr(leaf, "nbytes", 0))
                            for leaf in jax.tree_util.tree_leaves(fetched)
                        )
                    )
                )
        with observe.span("merge", cat="merge"):
            if meta_box is not None:
                merge_out, assisted_out = unpack_outputs(
                    fetched, meta_box["meta"]
                )
            else:
                merge_out, assisted_out = fetched
            batch_aggs = [_to_f64(t) for t in merge_out]
            if self._total is None:
                self._total = batch_aggs
            elif batch_aggs:
                self._total = [
                    a.merge_agg(t, b, np)
                    for a, t, b in zip(self.analyzers, self._total, batch_aggs)
                ]
            shifts = wire_shifts(self.sticky)
            for i, (analyzer, out) in enumerate(
                zip(self.assisted, assisted_out)
            ):
                for d in range(self.n_dev):
                    shard = jax.tree_util.tree_map(
                        lambda x, d=d: np.asarray(x).reshape(self.n_dev, -1)[d],
                        out,
                    )
                    if host_ctx is not None and self.n_dev == 1:
                        shard = analyzer.host_finish_batch(
                            shard, host_ctx, shifts
                        )
                    if shifts:
                        shard = analyzer.unshift_batch(shard, shifts)
                    self._assisted_states[i] = analyzer.host_consume(
                        self._assisted_states[i], shard
                    )

    def finish(self):
        if self._pending is not None:
            self._fold(self._pending)
            self._pending = None
        return (
            self._total if self._total is not None else []
        ), self._assisted_states


class FusedScanPass:
    """Runs a set of scan-shareable analyzers in one device pass."""

    def __init__(
        self,
        analyzers: Sequence[ScanShareableAnalyzer],
        batch_size: Optional[int] = None,
        state_cache=None,
        forensics=None,
        controller=None,
    ):
        self.analyzers = list(analyzers)
        # None = unset: the pass may widen the default for pure-host
        # in-memory folds; an EXPLICIT size (even one equal to the
        # default) is always honored as a memory bound
        self._batch_size_explicit = batch_size is not None
        self.batch_size = (
            batch_size if batch_size is not None else DEFAULT_BATCH_SIZE
        )
        # repository/states.StateCacheContext (or None): lets a
        # partitioned run swap a partition's scan for a state load
        self._state_cache = state_cache
        # observe/forensics.ForensicsCapture (or None, the default):
        # row-level violation capture + provenance notes. The off path
        # is one falsy check per batch — provably inert
        self._forensics = forensics
        # core/controller.RunController (or None, the default): the
        # cooperative cancel/deadline token honored at batch granularity
        # — the off path is one `is not None` check per batch
        self._controller = controller

    def run(self, table: Table) -> List[AnalyzerRunResult]:
        if getattr(table, "partitions", None) is not None:
            # partitioned dataset: fold per partition, merge states in
            # deterministic partition order — the shape that makes the
            # state cache a pure scan-for-load swap (bit-identical)
            return self._run_partitioned(table)
        return self._run_single(table)

    def _run_single(self, table: Table) -> List[AnalyzerRunResult]:
        # 1. plan: member placement + deduplicated input specs via the
        #    pure planner (an analyzer whose spec construction fails —
        #    e.g. unparseable predicate — fails alone, not the pass)
        results: Dict[int, AnalyzerRunResult] = {}
        with observe.span(
            "plan_fuse", cat="plan", analyzers=len(self.analyzers)
        ) as plan_sp:
            plan = plan_scan_members(self.analyzers)
            for i, err in plan.spec_errors.items():
                results[i] = AnalyzerRunResult(self.analyzers[i], error=err)
            plan_sp.set(
                placement=plan.mode,
                input_keys=len(plan.specs),
                device_members=plan.device_member_count,
                host_members=plan.host_member_count,
            )
        merge_idx = plan.merge_idx
        assisted_idx = plan.assisted_idx
        host_idx = plan.host_idx
        host_assisted_idx = plan.host_assisted_idx
        specs = plan.specs
        device_keys = plan.device_keys
        host_keys = plan.host_keys

        if plan.any_members:
            live_idx = merge_idx + assisted_idx + host_idx + host_assisted_idx
            prune = plan_row_group_prune(
                table, [self.analyzers[i] for i in live_idx]
            )
            if prune is not None:
                # spec elision must precede column pruning so a
                # constant-mask where's filter columns drop out of decode
                table = apply_prune_plan(table, prune, specs)
            table = prune_table_columns(table, specs)
            if self._forensics is not None:
                # coordinate map + prune provenance come from the PRUNED
                # source: scan offsets then map to surviving row groups
                self._forensics.note_table(table)
            # decode routing comes last: it classifies exactly the
            # columns that survived pruning (with_columns returns a new
            # source, so the fast set must attach to the final view)
            decode_plan = plan_decode_fastpath(
                table,
                specs,
                member_plan=plan,
                batch_size=self.batch_size,
                analyzers=[self.analyzers[i] for i in live_idx],
            )
            if decode_plan is not None:
                table = apply_decode_plan(table, decode_plan)
                if self._forensics is not None:
                    self._forensics.note_decode_plan(decode_plan)
            merge_analyzers = [self.analyzers[i] for i in merge_idx]
            assisted = [self.analyzers[i] for i in assisted_idx]
            host_members = [(i, self.analyzers[i]) for i in host_idx]
            host_assisted = [(i, self.analyzers[i]) for i in host_assisted_idx]
            try:
                with observe.span(
                    "fused_scan", cat="scan", analyzers=len(self.analyzers)
                ):
                    aggs, assisted_states, host_results, device_error = (
                        self._run_pass(
                            table, merge_analyzers, specs, assisted,
                            device_keys, host_members, host_keys, host_assisted,
                        )
                    )
                results.update(host_results)  # host outcomes stand on their own
                if device_error is not None:
                    # a runtime failure of the shared device program fails
                    # every analyzer IN that program; host-folded members
                    # keep their own outcomes
                    # (reference: AnalysisRunner.scala:310-313)
                    for i in merge_idx + assisted_idx:
                        results[i] = AnalyzerRunResult(
                            self.analyzers[i], error=device_error
                        )
                else:
                    for i, analyzer, agg in zip(merge_idx, merge_analyzers, aggs):
                        try:
                            results[i] = AnalyzerRunResult(
                                analyzer, state=analyzer.state_from_aggregates(agg)
                            )
                        except Exception as e:  # noqa: BLE001
                            results[i] = AnalyzerRunResult(analyzer, error=e)
                    for i, analyzer, state in zip(
                        assisted_idx, assisted, assisted_states
                    ):
                        results[i] = AnalyzerRunResult(analyzer, state=state)
            except RunCancelled:
                # deliberate early exit, not an analyzer failure: the
                # caller resumes from committed partition states
                raise
            except Exception as e:  # noqa: BLE001
                for i in merge_idx + assisted_idx + host_idx + host_assisted_idx:
                    results.setdefault(i, AnalyzerRunResult(self.analyzers[i], error=e))

        return [results[i] for i in range(len(self.analyzers))]

    def _run_partitioned(self, source) -> List[AnalyzerRunResult]:
        """Cached-vs-scan split over a partitioned source: for every
        partition in deterministic order, either load its analyzer
        states from the attached state cache (fingerprint + plan
        signature hit) or scan just that partition through the normal
        single-source path and publish its states; then merge partition
        states through the `State.merge` semigroup IN PARTITION ORDER.
        Cache on, off, or absent all fold and merge identically — only
        where a partition's states come from differs — so results are
        bit-identical to a full rescan by construction."""
        parts = list(source.partitions())
        cache = (
            self._state_cache
            if self._state_cache is not None and runtime.state_cache_enabled()
            else None
        )
        signature = None
        cap = self._forensics
        if cache is not None or cap is not None:
            from deequ_tpu.repository.states import plan_signature

            batch_rows = getattr(source, "batch_rows", None)
            signature = plan_signature(
                self.analyzers,
                placement=runtime.placement_mode(),
                compute_dtype=np.dtype(runtime.compute_dtype()).name,
                batch_size=(
                    self.batch_size if self._batch_size_explicit else None
                ),
                batch_rows=int(batch_rows) if batch_rows else None,
                variant=runtime.fold_signature_variant(),
            )
        if cap is not None:
            cap.note_plan_signature(signature)
        merged: Optional[List[AnalyzerRunResult]] = None
        cached_n = 0
        scanned_n = 0
        ctl = self._controller
        for part in parts:
            if ctl is not None:
                # partition boundaries are the resume points: every
                # partition finished before this check committed its
                # states above, so a cancel here loses no work
                ctl.check(
                    where=f"partition {part.name}",
                    progress={
                        "partitions_done": cached_n + scanned_n,
                        "partitions_total": len(parts),
                        "partitions_cached": cached_n,
                    },
                    boundary=True,
                )
            results: Optional[List[AnalyzerRunResult]] = None
            if cache is not None:
                sp = observe.span(
                    "state_cache", cat="cache", op="load", partition=part.name
                )
                with sp:
                    states = cache.repository.load_states(
                        cache.dataset, part.fingerprint, signature,
                        self.analyzers,
                    )
                    if sp:
                        sp.set(hit=states is not None)
                if states is not None:
                    results = [
                        AnalyzerRunResult(a, state=s)
                        for a, s in zip(self.analyzers, states)
                    ]
                    cached_n += 1
                    if cap is not None:
                        cap.note_partition(part.name, part.fingerprint, "cache")
            if results is None:
                results = scan_partition(
                    self.analyzers,
                    part,
                    batch_size=(
                        self.batch_size if self._batch_size_explicit else None
                    ),
                    forensics=(
                        cap.enter_partition(part.name, part.fingerprint)
                        if cap is not None
                        else None
                    ),
                    controller=ctl,
                )
                scanned_n += 1
                if cap is not None:
                    cap.note_partition(part.name, part.fingerprint, "scan")
                if cache is not None and all(r.error is None for r in results):
                    with observe.span(
                        "state_cache", cat="cache", op="save",
                        partition=part.name,
                    ):
                        cache.repository.save_states(
                            cache.dataset, part.fingerprint, signature,
                            [(r.analyzer, r.state) for r in results],
                        )
            merged = (
                results
                if merged is None
                else [
                    _merge_partition_results(m, r)
                    for m, r in zip(merged, results)
                ]
            )
        runtime.record_state_cache(cached_n, scanned_n, len(parts))
        assert merged is not None  # constructor guarantees >= 1 partition
        return merged

    def _run_pass(
        self,
        table: Table,
        analyzers,
        specs,
        assisted=(),
        device_keys=None,
        host_members=(),
        host_member_keys=None,
        host_assisted=(),
    ):
        dtype = runtime.compute_dtype()
        use_device = bool(analyzers or assisted)
        if (
            use_device
            and np.dtype(dtype) == np.float32
            and self.batch_size > runtime.MAX_F32_EXACT_COUNT_BATCH
        ):
            # only the packed f32 device transfer loses exactness; pure
            # host placement folds in float64 and takes any batch size
            raise ValueError(
                f"batch_size={self.batch_size} exceeds "
                f"{runtime.MAX_F32_EXACT_COUNT_BATCH} (2^24): per-batch "
                "counts would lose exactness in the float32 packed "
                "transfer. Use a smaller batch_size."
            )
        if device_keys is None:
            device_keys = set(specs)
        runtime.record_pass(
            "scan:"
            + ",".join(
                a.name
                for a in list(analyzers)
                + list(assisted)
                + [m for _, m in host_members]
                + [m for _, m in host_assisted]
            )
        )

        sticky: Dict[str, Any] = {}
        fold = PipelinedAggFold(analyzers, assisted, sticky=sticky)
        device_spec_keys = sorted(device_keys)
        streaming = bool(getattr(table, "is_streaming", False))
        # decode-to-wire handshake: the source's attached WireFusionPlan
        # (None when not planned). After every pack the resolved sticky
        # shifts publish through it so decode workers can start fusing
        # shift-needing columns; a device death abandons the handshake.
        wire_plan = getattr(table, "wire_plan", None)

        # host fold state: per host member, (f64 aggregate, error)
        host_aggs: Dict[int, Any] = {}
        host_errors: Dict[int, BaseException] = {}
        device_error: Optional[BaseException] = None

        all_host = list(host_members) + list(host_assisted)
        if host_member_keys is None:
            host_member_keys = {
                i: [s.key for s in member.input_specs()] for i, member in all_host
            }
        host_assisted_states: Dict[int, Any] = {}
        family_memo: Dict[Any, Any] = {}  # cross-batch, one scan's scope
        scanned_rows = 0
        scanned_batches = 0
        batch_size = self.batch_size
        if (
            not use_device
            and not streaming
            and not self._batch_size_explicit
        ):
            # pure host fold over an in-memory table with no explicit
            # batch size (explicit sizes are memory bounds and always
            # honored): the 4M default exists for the f32 DEVICE wire
            # (2^24 count exactness) and for stream memory bounds —
            # neither applies, and one batch saves the per-batch
            # machinery and sketch folds. Capped at ~16M rows so
            # worst-case kernel scratch stays bounded.
            batch_size = max(batch_size, min(table.num_rows, 1 << 24))
        hb_total_rows: Optional[int] = None
        try:
            raw_rows = getattr(table, "num_rows", None)
            if raw_rows is not None:
                hb_total_rows = int(raw_rows)
        except (TypeError, ValueError):
            hb_total_rows = None
        # a streaming source caps its own batches at `batch_rows`
        # (data/source.py uses min(batch_size, batch_rows)), so the
        # batch-count prediction must apply the same cap
        hb_batch = batch_size
        try:
            raw_cap = getattr(table, "batch_rows", None)
            if streaming and raw_cap:
                hb_batch = min(hb_batch, int(raw_cap))
        except (TypeError, ValueError):
            pass
        progress = observe.heartbeat.start(
            runtime.heartbeat_s(),
            total_rows=hb_total_rows,
            predicted_batches=(
                None
                if hb_total_rows is None
                else max(1, -(-hb_total_rows // hb_batch))
            ),
            name="fused_scan",
        )
        ctl = self._controller
        watchdog = None
        if ctl is not None:
            wd_s = runtime.stall_watchdog_s()
            if wd_s > 0:
                # per-stage forensics on stall: the live heartbeat
                # snapshot (bottleneck/occupancy/readahead) when the
                # heartbeat runs, else deequ-* thread stacks
                watchdog = StallWatchdog(
                    ctl, wd_s, snapshot_fn=progress.snapshot
                ).start()
        try:
            if streaming and runtime.pipeline_enabled():
                scanned_rows, scanned_batches, device_error = self._scan_pipelined(
                    table, batch_size, analyzers, assisted, specs,
                    device_spec_keys, use_device, dtype, sticky, fold,
                    host_members, host_assisted, host_member_keys,
                    host_aggs, host_assisted_states, host_errors, family_memo,
                    progress=progress,
                )
            else:
                for batch in table.batches(batch_size):
                    if ctl is not None:
                        ctl.check(
                            where="fused_scan batch",
                            progress={
                                "batches": scanned_batches,
                                "rows": scanned_rows,
                            },
                        )
                    # per-key builds with error capture: a failing input (e.g.
                    # a predicate over a missing column) fails only the
                    # analyzers that need it — host members individually, the
                    # device group as a whole (reference:
                    # AnalysisRunner.scala:310-313). Only keys with a
                    # still-live consumer are built at all.
                    live_keys: set = set()
                    if use_device and device_error is None:
                        live_keys.update(device_spec_keys)
                    for i, _member in all_host:
                        if i not in host_errors:
                            live_keys.update(host_member_keys[i])
                    device_live = use_device and device_error is None
                    host_live = any(i not in host_errors for i, _m in all_host)
                    if not device_live and not host_live:
                        break  # everything already failed; stop scanning
                    # device keys build eagerly (the shared program needs them
                    # packed); host-only keys build lazily on member access.
                    # Keys the decode workers already emitted in wire form
                    # (batch.wire_rows) skip the build entirely.
                    built = HostInputs(specs, batch)
                    build_errors = built.build_errors
                    wire_rows = getattr(batch, "wire_rows", None) or {}
                    if device_live:
                        for key in device_spec_keys:
                            if key not in wire_rows:
                                built.materialize(key)
                    if use_device and device_error is None:
                        try:
                            with observe.span(
                                "dispatch", cat="dispatch", rows=batch.num_rows
                            ) as dispatch_sp:
                                for key in device_spec_keys:
                                    if key in build_errors:
                                        raise build_errors[key]
                                padded = _pad_size(batch.num_rows, self.batch_size)
                                packed_inputs, layout = pack_batch_inputs(
                                    [
                                        (k, None if k in wire_rows else built[k])
                                        for k in device_spec_keys
                                    ],
                                    padded, dtype, sticky, num_rows=batch.num_rows,
                                    prepacked=wire_rows,
                                )
                                if wire_plan is not None:
                                    wire_plan.publish_shifts(
                                        {
                                            k: float(
                                                sticky.get(f"shift:{k}", 0.0)
                                            )
                                            for k in wire_plan.shift_keys
                                        }
                                    )
                                if dispatch_sp:
                                    dispatch_sp.set(
                                        wire_bytes=int(
                                            sum(
                                                int(getattr(v, "nbytes", 0))
                                                for v in packed_inputs.values()
                                            )
                                        )
                                    )
                                fused, meta_box = get_fused_fn(
                                    analyzers, assisted, layout
                                )
                                runtime.record_launch()
                                # async dispatch: the device crunches this
                                # batch while the host folds the previous
                                # batch (and the host members below)
                                fold.submit(
                                    fused(packed_inputs), meta_box, host_ctx=built
                                )
                        except Exception as e:  # noqa: BLE001
                            device_error = e
                            if wire_plan is not None:
                                wire_plan.abandon_shifts()
                    with observe.span("host_fold", cat="host", rows=batch.num_rows):
                        fold_host_batch(
                            built, build_errors, host_members, host_assisted,
                            host_member_keys, host_aggs, host_assisted_states,
                            host_errors, batch=batch, streaming=streaming,
                            family_memo=family_memo,
                        )
                    if self._forensics is not None:
                        with observe.span(
                            "forensics_capture", cat="forensics",
                            rows=batch.num_rows,
                        ):
                            self._forensics.capture_batch(batch, scanned_rows)
                    scanned_rows += batch.num_rows
                    scanned_batches += 1
                    if ctl is not None:
                        ctl.beat()
                    progress.advance(batch.num_rows)
        finally:
            if watchdog is not None:
                watchdog.stop()
            progress.finish()

        observe.annotate(rows=scanned_rows, batches=scanned_batches)
        aggs, assisted_states = [], []
        if device_error is None:
            try:
                # the final device_get lives here: an execution/transfer
                # failure surfaces now and must not erase host outcomes
                aggs, assisted_states = fold.finish()
                shifts = wire_shifts(sticky)
                if shifts:
                    aggs = [
                        a.unshift_agg(agg, shifts)
                        for a, agg in zip(analyzers, aggs)
                    ]
            except Exception as e:  # noqa: BLE001
                device_error = e
        host_results = materialize_host_results(
            host_members, host_assisted, host_aggs, host_assisted_states, host_errors
        )
        return aggs, assisted_states, host_results, device_error

    def _scan_pipelined(
        self,
        table,
        batch_size,
        analyzers,
        assisted,
        specs,
        device_spec_keys,
        use_device,
        dtype,
        sticky,
        fold,
        host_members,
        host_assisted,
        host_member_keys,
        host_aggs,
        host_assisted_states,
        host_errors,
        family_memo,
        progress=observe.heartbeat.NOOP_PROGRESS,
    ):
        """The pipelined streaming consumer loop (`DEEQU_TPU_PIPELINE`):
        per-batch prep — eager device-key builds, wire packing with its
        H2D put, family kernels — runs on a dedicated stage thread
        (ops/pipeline.py) ahead of this, the fold stage, which keeps
        every state mutation (`fold.submit` merges, `fold_host_batch`)
        in batch order on one thread. Fold order, fold inputs, and the
        single-threaded sticky-dict mutation are exactly the serial
        path's, so metrics are bit-identical; only WHERE the prep work
        runs changes. Liveness feedback to the prep stage (a failed
        device program, dead host members) lags by the queue depth —
        in-flight batches may prep work the fold stage then ignores."""
        all_host = list(host_members) + list(host_assisted)
        # prep-visible mirror of device_error: set either by a pack
        # failure on the prep thread or a dispatch/runtime failure here,
        # so in-flight batches stop paying for device packing
        device_down = threading.Event()
        wire_plan = getattr(table, "wire_plan", None)

        def _prep(batch):
            built = HostInputs(specs, batch)
            packed_inputs = layout = device_exc = None
            wire_rows = getattr(batch, "wire_rows", None) or {}
            if use_device and not device_down.is_set():
                if wire_plan is not None:
                    # opens the decode workers' shift_for wait window:
                    # from here a publish is imminent, so overlapped
                    # batches briefly wait instead of falling back
                    wire_plan.mark_pack_started()
                for key in device_spec_keys:
                    if key not in wire_rows:
                        built.materialize(key)
                try:
                    with observe.span(
                        "dispatch",
                        cat="dispatch",
                        rows=batch.num_rows,
                        wire_fuse=len(wire_rows),
                    ) as dispatch_sp:
                        for key in device_spec_keys:
                            if key in built.build_errors:
                                raise built.build_errors[key]
                        padded = _pad_size(batch.num_rows, self.batch_size)
                        # the H2D put happens HERE (jnp.asarray inside):
                        # batch N+1's wire lands device-side while the
                        # fold stage still runs batch N. Keys in
                        # batch.wire_rows splice in the decode workers'
                        # pre-packed buffers instead of packing here.
                        packed_inputs, layout = pack_batch_inputs(
                            [
                                (k, None if k in wire_rows else built[k])
                                for k in device_spec_keys
                            ],
                            padded, dtype, sticky, num_rows=batch.num_rows,
                            prepacked=wire_rows,
                        )
                        if wire_plan is not None:
                            # single prep thread: sticky shifts are final
                            # after this batch's pack — open the decode
                            # workers' shift gate
                            wire_plan.publish_shifts(
                                {
                                    k: float(sticky.get(f"shift:{k}", 0.0))
                                    for k in wire_plan.shift_keys
                                }
                            )
                        if dispatch_sp:
                            dispatch_sp.set(
                                wire_bytes=int(
                                    sum(
                                        int(getattr(v, "nbytes", 0))
                                        for v in packed_inputs.values()
                                    )
                                )
                            )
                except Exception as e:  # noqa: BLE001
                    device_exc = e
                    packed_inputs = layout = None
                    device_down.set()
                    if wire_plan is not None:
                        wire_plan.abandon_shifts()
            if any(i not in host_errors for i, _m in all_host):
                with observe.span(
                    "host_prep", cat="host", rows=batch.num_rows
                ):
                    _precompute_family_kernels(
                        built, host_assisted, batch,
                        host_members=host_members, host_errors=host_errors,
                        streaming=True, family_memo=family_memo,
                    )
            return batch, built, packed_inputs, layout, device_exc

        scanned_rows = 0
        scanned_batches = 0
        device_error: Optional[BaseException] = None
        ctl = self._controller
        items = pipeline.staged(
            table.batches(batch_size), _prep, name="prep", progress=progress
        )
        with contextlib.closing(items):
            with observe.span(
                "pipe_stage", cat="pipeline", stage="fold"
            ) as stage_sp:
                for item in items:
                    if ctl is not None:
                        # raising here unwinds through closing(items):
                        # the same shutdown contract an exhausted scan
                        # uses joins every stage thread and fd
                        ctl.check(
                            where="pipelined fold batch",
                            progress={
                                "batches": scanned_batches,
                                "rows": scanned_rows,
                            },
                        )
                    batch, built, packed_inputs, layout, device_exc = item
                    device_live = use_device and device_error is None
                    host_live = any(i not in host_errors for i, _m in all_host)
                    if not device_live and not host_live:
                        break  # everything already failed; stop scanning
                    with progress.timed("fold"), observe.span(
                        "pipe_item", cat="pipeline", stage="fold",
                        rows=batch.num_rows,
                    ):
                        if device_live:
                            if device_exc is not None:
                                device_error = device_exc
                            elif packed_inputs is not None:
                                try:
                                    fused, meta_box = get_fused_fn(
                                        analyzers, assisted, layout
                                    )
                                    runtime.record_launch()
                                    # async dispatch; submit folds the
                                    # PREVIOUS batch (async D2H landed)
                                    # while the device crunches this one
                                    fold.submit(
                                        fused(packed_inputs), meta_box,
                                        host_ctx=built,
                                    )
                                except Exception as e:  # noqa: BLE001
                                    device_error = e
                            if device_error is not None:
                                device_down.set()
                                if wire_plan is not None:
                                    wire_plan.abandon_shifts()
                        with observe.span(
                            "host_fold", cat="host", rows=batch.num_rows
                        ):
                            fold_host_batch(
                                built, built.build_errors, host_members,
                                host_assisted, host_member_keys, host_aggs,
                                host_assisted_states, host_errors,
                                batch=batch, streaming=True,
                                family_memo=family_memo, precomputed=True,
                            )
                        if self._forensics is not None:
                            with observe.span(
                                "forensics_capture", cat="forensics",
                                rows=batch.num_rows,
                            ):
                                self._forensics.capture_batch(
                                    batch, scanned_rows
                                )
                    scanned_rows += batch.num_rows
                    scanned_batches += 1
                    if ctl is not None:
                        ctl.beat()
                    progress.advance(batch.num_rows)
                if stage_sp:
                    stage_sp.set(items=scanned_batches)
        return scanned_rows, scanned_batches, device_error

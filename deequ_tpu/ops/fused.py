"""The fused scan: N analyzers, ONE compiled XLA computation per pass.

This is the TPU-native analogue of the reference's scan-sharing optimizer
(reference: runners/AnalysisRunner.scala:279-326 — all scan-shareable
analyzers run in a single `df.agg(...)` with offset arithmetic). Here the
"offsets" are pytree structure: every analyzer contributes a device_reduce
over a shared, deduplicated set of input arrays, XLA CSE merges the common
subexpressions (masks, counts), and one program per batch produces every
partial state at once.

Cross-batch folding happens host-side in float64 via the same merge_agg
formulas (numpy namespace) — the driver-side semigroup fold, exactly the
role the reference's `State.sum` plays after Catalyst partial aggregation.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import ScanShareableAnalyzer
from deequ_tpu.analyzers.states import State
from deequ_tpu.data.table import Table
from deequ_tpu.ops import runtime

DEFAULT_BATCH_SIZE = 1 << 22  # 4M rows: < 2^24 so f32 counts stay exact

_FUSED_CACHE: Dict[Any, Any] = {}


def _pad_size(n: int, batch_size: int) -> int:
    """Round up to a power of two (min 8): few compiled shapes, no
    per-tail recompilation."""
    size = 8
    while size < n:
        size *= 2
    return min(size, max(batch_size, 8))


def get_fused_fn(
    analyzers: Sequence[ScanShareableAnalyzer],
    assisted: Sequence[ScanShareableAnalyzer] = (),
):
    key = (
        tuple(repr(a) for a in analyzers),
        tuple(repr(a) for a in assisted),
        bool(jax.config.jax_enable_x64),
    )
    fn = _FUSED_CACHE.get(key)
    if fn is None:

        def fused(inputs):
            return (
                tuple(a.device_reduce(inputs, jnp) for a in analyzers),
                tuple(a.device_batch(inputs, jnp) for a in assisted),
            )

        fn = jax.jit(fused)
        _FUSED_CACHE[key] = fn
    return fn


class AnalyzerRunResult:
    """Outcome of one analyzer in a pass: a state (possibly None = empty)
    or an error."""

    def __init__(
        self,
        analyzer: ScanShareableAnalyzer,
        state: Optional[State] = None,
        error: Optional[BaseException] = None,
    ):
        self.analyzer = analyzer
        self.state = state
        self.error = error

    def state_or_raise(self) -> Optional[State]:
        if self.error is not None:
            raise self.error
        return self.state


def _to_f64(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, dtype=np.float64), tree
    )


class PipelinedAggFold:
    """Cross-batch host fold that overlaps device compute with host work:
    each submitted batch output starts an async D2H copy, and the
    PREVIOUS batch (whose copy has had a full batch of device time to
    land) is fetched and folded. Avoids paying the device round-trip
    latency per batch — on a tunneled device that latency (~20ms) would
    otherwise dominate small folds.

    Two kinds of outputs per batch: merge-analyzers' aggregates fold in
    float64 via merge_agg; assisted-analyzers' per-batch artifacts are
    handed to host_consume, once per device shard (`n_dev` shards are
    gathered along leaf axis 0 by the mesh pass)."""

    def __init__(
        self,
        analyzers: Sequence[ScanShareableAnalyzer],
        assisted: Sequence[ScanShareableAnalyzer] = (),
        n_dev: int = 1,
    ):
        self.analyzers = list(analyzers)
        self.assisted = list(assisted)
        self.n_dev = n_dev
        self._total: Optional[List[Any]] = None
        self._assisted_states: List[Any] = [None] * len(self.assisted)
        self._pending = None

    def submit(self, device_out) -> None:
        jax.tree_util.tree_map(lambda x: x.copy_to_host_async(), device_out)
        if self._pending is not None:
            self._fold(self._pending)
        self._pending = device_out

    def _fold(self, device_out) -> None:
        merge_out, assisted_out = jax.device_get(device_out)
        batch_aggs = [_to_f64(t) for t in merge_out]
        if self._total is None:
            self._total = batch_aggs
        elif batch_aggs:
            self._total = [
                a.merge_agg(t, b, np)
                for a, t, b in zip(self.analyzers, self._total, batch_aggs)
            ]
        for i, (analyzer, out) in enumerate(zip(self.assisted, assisted_out)):
            for d in range(self.n_dev):
                shard = jax.tree_util.tree_map(
                    lambda x, d=d: np.asarray(x).reshape(self.n_dev, -1)[d], out
                )
                self._assisted_states[i] = analyzer.host_consume(
                    self._assisted_states[i], shard
                )

    def finish(self):
        if self._pending is not None:
            self._fold(self._pending)
            self._pending = None
        return (
            self._total if self._total is not None else []
        ), self._assisted_states


class FusedScanPass:
    """Runs a set of scan-shareable analyzers in one device pass."""

    def __init__(
        self,
        analyzers: Sequence[ScanShareableAnalyzer],
        batch_size: int = DEFAULT_BATCH_SIZE,
    ):
        self.analyzers = list(analyzers)
        self.batch_size = batch_size

    def run(self, table: Table) -> List[AnalyzerRunResult]:
        # 1. collect input specs; an analyzer whose spec construction fails
        #    (e.g. unparseable predicate) fails alone, not the pass
        merge_idx: List[int] = []
        assisted_idx: List[int] = []
        results: Dict[int, AnalyzerRunResult] = {}
        specs: Dict[str, Any] = {}
        for i, analyzer in enumerate(self.analyzers):
            try:
                analyzer_specs = analyzer.input_specs()
            except Exception as e:  # noqa: BLE001
                results[i] = AnalyzerRunResult(analyzer, error=e)
                continue
            if getattr(analyzer, "device_assisted", False):
                assisted_idx.append(i)
            else:
                merge_idx.append(i)
            for spec in analyzer_specs:
                specs.setdefault(spec.key, spec)

        if merge_idx or assisted_idx:
            merge_analyzers = [self.analyzers[i] for i in merge_idx]
            assisted = [self.analyzers[i] for i in assisted_idx]
            try:
                aggs, assisted_states = self._run_pass(
                    table, merge_analyzers, specs, assisted
                )
                for i, analyzer, agg in zip(merge_idx, merge_analyzers, aggs):
                    results[i] = AnalyzerRunResult(
                        analyzer, state=analyzer.state_from_aggregates(agg)
                    )
                for i, analyzer, state in zip(assisted_idx, assisted, assisted_states):
                    results[i] = AnalyzerRunResult(analyzer, state=state)
            except Exception as e:  # noqa: BLE001
                # a runtime failure of the shared pass fails every analyzer in
                # it (reference: AnalysisRunner.scala:310-313)
                for i in merge_idx + assisted_idx:
                    results[i] = AnalyzerRunResult(self.analyzers[i], error=e)

        return [results[i] for i in range(len(self.analyzers))]

    def _run_pass(self, table: Table, analyzers, specs, assisted=()):
        fused = get_fused_fn(analyzers, assisted)
        dtype = runtime.compute_dtype()
        runtime.record_pass(
            "scan:" + ",".join(a.name for a in list(analyzers) + list(assisted))
        )

        fold = PipelinedAggFold(analyzers, assisted)

        for batch in table.batches(self.batch_size):
            padded = _pad_size(batch.num_rows, self.batch_size)
            inputs: Dict[str, jnp.ndarray] = {}
            for key, spec in specs.items():
                arr = spec.build(batch)
                arr = runtime.pad_to(np.asarray(arr), padded)
                if arr.dtype == np.bool_ or np.issubdtype(arr.dtype, np.integer):
                    inputs[key] = jnp.asarray(arr)
                else:
                    inputs[key] = jnp.asarray(arr.astype(dtype))
            runtime.record_launch()
            # async dispatch: the device crunches this batch while the
            # host folds the previous batch
            fold.submit(fused(inputs))
        return fold.finish()

"""Native host kernels, compiled on demand and loaded via ctypes.

Where the reference drops below Spark's public API into JVM Catalyst
kernels for its hot aggregation loops (reference: analyzers/catalyst/,
SURVEY.md §2.6), this package drops below numpy into C for the host-side
hot loops that are not single vectorized reductions — currently the
xxhash64+HLL pack stage. The build is a single `cc -O3 -shared` at first
use, cached beside the package; every entry point degrades gracefully to
the vectorized numpy implementation when no compiler is available, so the
framework never REQUIRES the native path (same spirit as the reference
running with codegen disabled).
"""

from __future__ import annotations

import ctypes
import functools
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

from deequ_tpu import observe


def _traced_kernel(fn):
    """Record one `native` span per kernel invocation (size of the
    first array argument as `n`). Disabled tracing costs one extra
    function call + the span() thread-local probe."""
    name = f"native:{fn.__name__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        kernel_sp = observe.span(name, cat="native")
        if not kernel_sp:
            return fn(*args, **kwargs)
        with kernel_sp:
            first = args[0] if args else None
            if hasattr(first, "__len__"):
                kernel_sp.set(n=len(first))
            return fn(*args, **kwargs)

    return wrapper

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
#: every C translation unit compiled into the one native library; the
#: cache digest covers all of them, so editing any source rebuilds
_SOURCES = (
    os.path.join(_PKG_DIR, "xxhash_hll.c"),
    os.path.join(_PKG_DIR, "decode.c"),
    os.path.join(_PKG_DIR, "parquet_read.c"),
    os.path.join(_PKG_DIR, "encfold.c"),
)
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def per_user_cache_dir() -> Optional[str]:
    """PER-USER 0700 cache dir — never a shared world-writable path, so
    no other user can plant files where we would read them. Shared by
    the native-library build and the placement cache. Overridable via
    DEEQU_TPU_CACHE_DIR (tests point it at a tmp dir)."""
    override = os.environ.get("DEEQU_TPU_CACHE_DIR")
    if override:
        try:
            os.makedirs(override, mode=0o700, exist_ok=True)
            return override
        except OSError:
            return None
    try:
        uid = os.getuid()
    except AttributeError:  # non-posix
        uid = "u"
    user_dir = os.path.join(tempfile.gettempdir(), f"deequ_tpu_native_{uid}")
    try:
        os.makedirs(user_dir, mode=0o700, exist_ok=True)
        if uid == "u" or os.stat(user_dir).st_uid == uid:
            return user_dir
    except OSError:
        pass
    return None


def _cache_dirs():
    """Candidate build dirs: the per-user cache first (keeps build
    artifacts out of the package tree — they used to accumulate as
    hash-named .so files next to the sources), then the package dir as
    the fallback for environments without a writable temp dir."""
    user_dir = per_user_cache_dir()
    if user_dir is not None:
        yield user_dir
    yield _PKG_DIR


def _prune_stale_builds(directory: str, keep_digest: str) -> None:
    """Remove cached `_deequ_native_*.so` files whose name does not start
    with the current source digest (sanitize variants of the current
    source share the digest prefix and survive). Best-effort: a cache
    dir shared with a concurrently-running older version just means the
    older process rebuilds on its next cold start."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    prefix = f"_deequ_native_{keep_digest}"
    for entry in entries:
        if (
            entry.startswith("_deequ_native_")
            and entry.endswith(".so")
            and not entry.startswith(prefix)
        ):
            try:
                os.unlink(os.path.join(directory, entry))
            except OSError:
                pass


def _sanitize_flags() -> list:
    """DEEQU_TPU_SANITIZE=address,undefined adds -fsanitize instrumentation
    to the native build (a debugging mode, not a production path: the
    resulting .so usually needs the sanitizer runtime LD_PRELOADed into
    the host python). DEEQU_TPU_SANITIZE=thread builds with ThreadSanitizer
    instead — the kernels release the GIL and run concurrently (the
    family worker pool, independent scan threads), so TSan is the mode
    that checks the C side's data-race freedom; it cannot be combined
    with address/leak sanitizers (a toolchain rule — the build would
    fail). Empty list when unset."""
    spec = os.environ.get("DEEQU_TPU_SANITIZE", "").strip()
    if not spec:
        return []
    sanitizers = ",".join(s.strip() for s in spec.split(",") if s.strip())
    if not sanitizers:
        return []
    return [f"-fsanitize={sanitizers}", "-g", "-fno-omit-frame-pointer"]


def _build_library() -> Optional[str]:
    """Compile the kernel; atomic tmp+rename so concurrent processes
    (the normal multihost case) never observe a half-written library.
    The output name embeds a hash of the C source, so different package
    versions sharing a cache dir never load each other's kernels; a
    sanitized build gets its own name so it never shadows (or is
    shadowed by) the plain one."""
    import hashlib

    h = hashlib.sha256()
    for source in _SOURCES:
        with open(source, "rb") as f:
            h.update(f.read())
    source_digest = h.hexdigest()[:16]
    digest = source_digest
    sanitize = _sanitize_flags()
    if sanitize:
        tag = hashlib.sha256(" ".join(sanitize).encode()).hexdigest()[:8]
        digest = f"{digest}_san{tag}"
    for directory in _cache_dirs():
        out = os.path.join(directory, f"_deequ_native_{digest}.so")
        if os.path.exists(out):
            return out
        for compiler in ("cc", "gcc", "clang"):
            tmp = None
            try:
                fd, tmp = tempfile.mkstemp(suffix=".so", dir=directory)
                os.close(fd)
                subprocess.run(
                    [compiler, "-O3", "-shared", "-fPIC"]
                    + sanitize
                    + list(_SOURCES)
                    # parquet_read.c dlopens the decompressors and guards
                    # codec init with pthread_once
                    + ["-o", tmp, "-ldl", "-lpthread"],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, out)
                _prune_stale_builds(directory, source_digest)
                return out
            except (OSError, subprocess.SubprocessError):
                if tmp is not None and os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                continue
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("DEEQU_TPU_NO_NATIVE"):
        return None
    path = _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.xxhash64_pack.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.xxhash64_pack.restype = None
        lib.hll_update_registers.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.hll_update_registers.restype = None
        lib.masked_moments.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.masked_moments.restype = None
        for name in ("bincount_i64", "bincount_i32", "bincount_i8"):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            fn.restype = None
        lib.hashcount_u64.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.hashcount_u64.restype = ctypes.c_int64
        lib.bincount_window_i64.argtypes = [
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.bincount_window_i64.restype = None
        lib.masked_select_decimate.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.masked_select_decimate.restype = ctypes.c_int
        lib.masked_moments_select.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.masked_moments_select.restype = ctypes.c_int
        lib.masked_moments_select_multi.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.masked_moments_select_multi.restype = ctypes.c_int
        # decode.c: buffer-level Arrow decode fast path. Value/bitmap
        # inputs arrive as raw addresses (c_void_p) so the wrapper can
        # pass pre-advanced pointers without dtype-specific casts.
        for name in (
            "decode_f64",
            "decode_f32",
            "decode_i8",
            "decode_i16",
            "decode_i32",
            "decode_i64",
            "decode_u8",
            "decode_u16",
            "decode_u32",
            "decode_u64",
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            fn.restype = ctypes.c_int64
        lib.decode_bool.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.decode_bool.restype = ctypes.c_int64
        lib.decode_dict_i32.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.decode_dict_i32.restype = ctypes.c_int64
        # decode-to-wire kernels: same raw-address convention as the
        # Column decode above, but the outputs are the WIRE buffers
        # (bitpacked MSB mask row + value row), written at a row/bit
        # offset inside the batch's preallocated padded buffers.
        lib.wire_valid_bits.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
        ]
        lib.wire_valid_bits.restype = ctypes.c_int64
        for name in (
            "wire_f64",
            "wire_f64_to_f32",
            "wire_f32_to_f64",
            "wire_f32",
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_double,
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
            ]
            fn.restype = ctypes.c_int64
        for name in (
            "wire_i8",
            "wire_i16",
            "wire_i32",
            "wire_i64",
            "wire_u8",
            "wire_u16",
            "wire_u32",
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.c_double,
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
            ]
            fn.restype = ctypes.c_int64
        # parquet_read.c: native column-chunk reader (page headers,
        # decompression, PLAIN/RLE-dict/RLE-bool decode into the same
        # Arrow buffer layout decode.c consumes).
        lib.pq_reader_codecs.argtypes = []
        lib.pq_reader_codecs.restype = ctypes.c_int
        lib.pq_decode_chunk.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pq_decode_chunk.restype = ctypes.c_int64
        lib.pq_decode_chunk_runs.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pq_decode_chunk_runs.restype = ctypes.c_int64
        # encfold.c: fold kernels over the encoded-run streams
        lib.encfold_code_counts.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.encfold_code_counts.restype = ctypes.c_int64
        lib.encfold_def_nulls.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.encfold_def_nulls.restype = ctypes.c_int64
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


@_traced_kernel
def xxhash64_pack(values: np.ndarray, valid: np.ndarray) -> Optional[np.ndarray]:
    """(idx << 6 | rank) int32 per row from canonical int64 values; None
    when the native library is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.int64)
    valid_u8 = np.ascontiguousarray(valid, dtype=np.uint8)
    packed = np.empty(len(values), dtype=np.int32)
    lib.xxhash64_pack(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        valid_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(values),
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return packed


def _u8_ptr(mask: Optional[np.ndarray]):
    """Zero-copy uint8 pointer for a bool mask; None stays None (=all)."""
    if mask is None:
        return None
    mask = np.ascontiguousarray(mask)
    if mask.dtype == np.bool_:
        mask = mask.view(np.uint8)
    elif mask.dtype != np.uint8:
        mask = mask.astype(np.uint8)
    # keep the array alive through the call via the returned pair
    return mask


@_traced_kernel
def masked_moments(
    x: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
) -> Optional[np.ndarray]:
    """One-pass fused moments for a (column, where) family:
    [count, sum, min, max, m2, n_where]; None when native is unavailable
    (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    valid = _u8_ptr(valid)
    where = _u8_ptr(where)
    out = np.empty(6, dtype=np.float64)
    lib.masked_moments(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if valid is not None
        else None,
        where.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if where is not None
        else None,
        len(x),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out


_HASHCOUNT_LOG2 = 17  # 131072 slots: load factor <= 0.5 at 65536 distinct
_HASHCOUNT_MAX_DISTINCT = 1 << 16


@_traced_kernel
def hashcount(
    keys_u64: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
    max_distinct: int = _HASHCOUNT_MAX_DISTINCT,
):
    """Distinct-value counts over raw 8-byte keys (float64 bit patterns
    or int64 values) in one open-addressing pass:
    (distinct_keys_u64, counts, n_valid, n_where), or None when native
    is unavailable OR the column exceeds max_distinct (the kernel aborts
    after scanning roughly enough rows to see that many distinct values;
    a skew guard additionally bails at 4*max_distinct scanned rows when
    the table is already 3/4 full, so heavy-tailed near-cap columns cost
    only a bounded prefix too)."""
    lib = _load()
    if lib is None:
        return None
    keys_u64 = np.ascontiguousarray(keys_u64)
    if keys_u64.dtype != np.uint64:
        keys_u64 = keys_u64.view(np.uint64)
    valid = _u8_ptr(valid)
    where = _u8_ptr(where)
    slots = 1 << _HASHCOUNT_LOG2
    table_keys = np.zeros(slots, dtype=np.uint64)
    table_counts = np.zeros(slots, dtype=np.int64)
    meta = np.zeros(2, dtype=np.int64)
    cap = int(min(max_distinct, _HASHCOUNT_MAX_DISTINCT))
    distinct = lib.hashcount_u64(
        keys_u64.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if valid is not None
        else None,
        where.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if where is not None
        else None,
        len(keys_u64),
        _HASHCOUNT_LOG2,
        cap,
        4 * cap,
        table_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        table_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if distinct < 0:
        return None
    occupied = table_counts > 0
    return (
        table_keys[occupied],
        table_counts[occupied],
        int(meta[0]),
        int(meta[1]),
    )


@_traced_kernel
def bincount_window(
    values: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
    lo: int,
    nbins: int,
):
    """Dense windowed value counts for an int64 column in one pass:
    (counts[nbins], n_valid_in_window, n_where), or None when the native
    library is unavailable OR any valid&where value fell outside
    [lo, lo + nbins) — the caller falls back to the select kernel.
    The abort is immediate in-kernel, so a wrong window guess costs only
    the scanned prefix."""
    lib = _load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.int64)
    valid = _u8_ptr(valid)
    where = _u8_ptr(where)
    counts = np.zeros(int(nbins), dtype=np.int64)
    meta = np.zeros(3, dtype=np.int64)
    lib.bincount_window_i64(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if valid is not None
        else None,
        where.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if where is not None
        else None,
        len(values),
        int(lo),
        int(nbins),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if meta[2]:
        return None
    return counts, int(meta[0]), int(meta[1])


@_traced_kernel
def bincount(
    codes: np.ndarray,
    nbins: int,
    base: int = 0,
    where: Optional[np.ndarray] = None,
) -> Optional[np.ndarray]:
    """counts[c + base] over in-range codes in one pass (no shifted-copy
    temp); None when native is unavailable. Accepts int8/int32/int64
    codes natively (other int dtypes are converted to int64)."""
    lib = _load()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes)
    if codes.dtype == np.int8:
        fn = lib.bincount_i8
    elif codes.dtype == np.int32:
        fn = lib.bincount_i32
    else:
        if codes.dtype != np.int64:
            codes = codes.astype(np.int64)
        fn = lib.bincount_i64
    where = _u8_ptr(where)
    out = np.zeros(nbins, dtype=np.int64)
    fn(
        codes.ctypes.data,
        where.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if where is not None
        else None,
        len(codes),
        base,
        nbins,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


@_traced_kernel
def masked_select_decimate(
    x: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
    cap: int,
):
    """The quantile sketch's per-batch heavy step: exactly
    ``sorted(x[valid & where])[stride//2::stride][:cap]`` (stride =
    2^ceil(log2(n_valid/cap))) via histogram-assisted selection — no full
    sort. Returns (samples_f64, n_valid, level), or None when native is
    unavailable (caller falls back to the numpy sort path)."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    valid = _u8_ptr(valid)
    where = _u8_ptr(where)
    samples = np.empty(max(int(cap), 1), dtype=np.float64)
    meta = np.zeros(3, dtype=np.int64)
    rc = lib.masked_select_decimate(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if valid is not None
        else None,
        where.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if where is not None
        else None,
        len(x),
        int(cap),
        samples.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        return None
    return samples[: int(meta[2])], int(meta[0]), int(meta[1])


@_traced_kernel
def masked_moments_select(
    x: np.ndarray,
    valid: Optional[np.ndarray],
    where: Optional[np.ndarray],
    cap: int,
    hll_mode: int = 0,
    hashvals: Optional[np.ndarray] = None,
):
    """Combined (column, where)-family kernel: the fused moments
    [count, sum, min, max, m2, n_where] AND the quantile sketch's
    decimated sample, in the same data traversals (two passes instead of
    the five that masked_moments + masked_select_decimate would pay).
    hll_mode folds the HLL++ register update into the same pass:
    1 = hash x's f64 bit pattern (float columns), 2 = hash the parallel
    canonical-int64 array `hashvals` (int/bool columns). Returns
    (moments6, samples_f64, n_valid, level, registers_or_None) or None."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    valid = _u8_ptr(valid)
    where = _u8_ptr(where)
    samples = np.empty(max(int(cap), 1), dtype=np.float64)
    meta = np.zeros(3, dtype=np.int64)
    mom = np.zeros(6, dtype=np.float64)
    regs = None
    regs_ptr = None
    hash_ptr = None
    if hll_mode == 2 and hashvals is not None:
        hashvals = np.ascontiguousarray(hashvals, dtype=np.int64)
        hash_ptr = hashvals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    elif hll_mode == 2:
        hll_mode = 0
    if hll_mode:
        regs = np.zeros(512, dtype=np.int32)
        regs_ptr = regs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    rc = lib.masked_moments_select(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if valid is not None
        else None,
        where.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if where is not None
        else None,
        len(x),
        int(cap),
        samples.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        meta.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        mom.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        hash_ptr,
        int(hll_mode),
        regs_ptr,
    )
    if rc != 0:
        return None
    return mom, samples[: int(meta[2])], int(meta[0]), int(meta[1]), regs


@_traced_kernel
def masked_moments_select_multi(
    columns,
    where: Optional[np.ndarray],
    cap: int,
):
    """Batched family kernel: one row-blocked native traversal computes
    the fused moments, decimated quantile sample, and optional HLL
    registers for K columns at once (scan sharing ACROSS columns — the
    per-column masked_moments_select pays K full passes).

    `columns` is a sequence of (x, valid_or_None, hll_mode, hashvals_or_None)
    tuples sharing one row count; `where` is the shared row mask for the
    whole group (grouping by where-mask is the caller's job). Returns a
    list of per-column (moments6, samples_f64, n_valid, level,
    registers_or_None) tuples — each bit-identical to what a solo
    masked_moments_select call would produce — or None when the native
    library is unavailable, the lengths disagree, or the kernel fails
    (caller falls back to per-column calls)."""
    lib = _load()
    if lib is None:
        return None
    k = len(columns)
    if k == 0:
        return []
    PD = ctypes.POINTER(ctypes.c_double)
    PU8 = ctypes.POINTER(ctypes.c_uint8)
    PI64 = ctypes.POINTER(ctypes.c_int64)
    xptrs = (PD * k)()
    vptrs = (PU8 * k)()
    hptrs = (PI64 * k)()
    modes = np.zeros(k, dtype=np.int32)
    keep = []  # pins converted arrays for the call's duration
    n = None
    any_hll = False
    for idx, (x, valid, hll_mode, hashvals) in enumerate(columns):
        x = np.ascontiguousarray(x, dtype=np.float64)
        if n is None:
            n = len(x)
        elif len(x) != n:
            return None
        keep.append(x)
        xptrs[idx] = x.ctypes.data_as(PD)
        v = _u8_ptr(valid)
        if v is not None:
            if len(v) != n:
                return None
            keep.append(v)
            vptrs[idx] = v.ctypes.data_as(PU8)
        if hll_mode == 2 and hashvals is not None:
            hv = np.ascontiguousarray(hashvals, dtype=np.int64)
            if len(hv) != n:
                return None
            keep.append(hv)
            hptrs[idx] = hv.ctypes.data_as(PI64)
        elif hll_mode == 2:
            hll_mode = 0
        modes[idx] = int(hll_mode)
        if hll_mode:
            any_hll = True
    where = _u8_ptr(where)
    if where is not None and len(where) != n:
        return None
    cap = max(int(cap), 1)
    samples = np.empty((k, cap), dtype=np.float64)
    meta = np.zeros((k, 3), dtype=np.int64)
    mom = np.zeros((k, 6), dtype=np.float64)
    regs = np.zeros((k, 512), dtype=np.int32) if any_hll else None
    rc = lib.masked_moments_select_multi(
        xptrs,
        vptrs,
        where.ctypes.data_as(PU8) if where is not None else None,
        n,
        k,
        cap,
        samples.ctypes.data_as(PD),
        meta.ctypes.data_as(PI64),
        mom.ctypes.data_as(PD),
        hptrs,
        modes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        regs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        if regs is not None
        else None,
    )
    del keep
    if rc != 0:
        return None
    out = []
    for idx in range(k):
        kept = int(meta[idx, 2])
        out.append(
            (
                mom[idx].copy(),
                samples[idx, :kept].copy(),
                int(meta[idx, 0]),
                int(meta[idx, 1]),
                regs[idx].copy() if regs is not None and modes[idx] else None,
            )
        )
    return out


#: arrow primitive type name -> (decode.c entry point, element bytes);
#: the planner (ops/fused.py) and Table.from_arrow both key off this to
#: decide fast-path eligibility, so the two can never disagree
DECODE_PRIMITIVES = {
    "double": ("decode_f64", 8),
    "float": ("decode_f32", 4),
    "int8": ("decode_i8", 1),
    "int16": ("decode_i16", 2),
    "int32": ("decode_i32", 4),
    "int64": ("decode_i64", 8),
    "uint8": ("decode_u8", 1),
    "uint16": ("decode_u16", 2),
    "uint32": ("decode_u32", 4),
    "uint64": ("decode_u64", 8),
}


@_traced_kernel
def decode_primitive(
    kind: str,
    values_addr: int,
    validity_addr: Optional[int],
    bit_offset: int,
    n: int,
    out_values: np.ndarray,
    out_valid: np.ndarray,
) -> Optional[int]:
    """One-pass Arrow-buffer decode of a numeric chunk into the engine's
    Column backing (neutral-fill values + bool mask; floats fold NaN into
    the mask). `values_addr` is pre-advanced to the chunk's first logical
    element; `validity_addr` is the raw bitmap buffer (row i's bit at
    bit_offset + i) or None for null-free chunks. Writes `n` rows into
    the (possibly offset) output views and returns the invalid-row
    count; None when the native library is unavailable."""
    lib = _load()
    if lib is None or kind not in DECODE_PRIMITIVES:
        return None
    fn = getattr(lib, DECODE_PRIMITIVES[kind][0])
    return int(
        fn(
            ctypes.c_void_p(values_addr),
            ctypes.c_void_p(validity_addr) if validity_addr else None,
            int(bit_offset),
            int(n),
            out_values.ctypes.data_as(ctypes.c_void_p),
            out_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
    )


@_traced_kernel
def decode_bool_bitmap(
    values_addr: int,
    value_bit_offset: int,
    validity_addr: Optional[int],
    valid_bit_offset: int,
    n: int,
    out_values: np.ndarray,
    out_valid: np.ndarray,
) -> Optional[int]:
    """Arrow boolean chunk (values ARE a bitmap) -> bool values + mask
    in one pass (null -> False). Returns the invalid-row count; None
    when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    return int(
        lib.decode_bool(
            ctypes.c_void_p(values_addr),
            int(value_bit_offset),
            ctypes.c_void_p(validity_addr) if validity_addr else None,
            int(valid_bit_offset),
            int(n),
            out_values.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
    )


@_traced_kernel
def decode_dict_codes(
    indices_addr: int,
    validity_addr: Optional[int],
    bit_offset: int,
    n: int,
    out_codes: np.ndarray,
    out_valid: np.ndarray,
) -> Optional[int]:
    """Dictionary-column int32 index buffer -> dict_encode codes
    (null -> -1) + mask in one pass. Returns the invalid-row count;
    None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    return int(
        lib.decode_dict_i32(
            ctypes.c_void_p(indices_addr),
            ctypes.c_void_p(validity_addr) if validity_addr else None,
            int(bit_offset),
            int(n),
            out_codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
    )


#: arrow float type -> wire-dtype-keyed decode-to-wire entry points
_WIRE_FLOAT_KERNELS = {
    ("double", "float64"): "wire_f64",
    ("double", "float32"): "wire_f64_to_f32",
    ("float", "float64"): "wire_f32_to_f64",
    ("float", "float32"): "wire_f32",
}

#: arrow int type -> decode-to-wire entry point (uint64 is deliberately
#: absent: its int64-path wrap semantics stay on the Column path)
_WIRE_INT_KERNELS = {
    "int8": "wire_i8",
    "int16": "wire_i16",
    "int32": "wire_i32",
    "int64": "wire_i64",
    "uint8": "wire_u8",
    "uint16": "wire_u16",
    "uint32": "wire_u32",
}

#: wire value dtype -> the int kernels' out_code selector
_WIRE_OUT_CODES = {
    "int8": 0,
    "int16": 1,
    "int32": 2,
    "float64": 3,
    "float32": 4,
}


def wire_supported(token: str, out_dtype_name: str) -> bool:
    """True when a decode-to-wire kernel exists for (arrow type token,
    wire value dtype). The planner keys eligibility off this so it can
    never approve a column the decoder cannot take."""
    if (token, out_dtype_name) in _WIRE_FLOAT_KERNELS:
        return True
    return token in _WIRE_INT_KERNELS and out_dtype_name in _WIRE_OUT_CODES


@_traced_kernel
def wire_valid_bits(
    validity_addr: Optional[int],
    bit_offset: int,
    n: int,
    out_bits: np.ndarray,
    out_bit_offset: int,
) -> Optional[int]:
    """Validity bitmap (LSB order) -> wire mask bits (np.packbits MSB
    order) OR-ed into the prezeroed padded row at `out_bit_offset`.
    Returns the invalid-row count; None when native is unavailable."""
    lib = _load()
    if lib is None:
        return None
    return int(
        lib.wire_valid_bits(
            ctypes.c_void_p(validity_addr) if validity_addr else None,
            int(bit_offset),
            int(n),
            out_bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            int(out_bit_offset),
        )
    )


@_traced_kernel
def wire_primitive(
    token: str,
    values_addr: int,
    validity_addr: Optional[int],
    bit_offset: int,
    n: int,
    shift: float,
    out_values: Optional[np.ndarray],
    out_bits: Optional[np.ndarray],
    out_bit_offset: int,
) -> Optional[int]:
    """One-pass Arrow-buffer decode of a numeric chunk STRAIGHT to the
    wire: value row in `out_values`' dtype (floats pre-centered by the
    sticky `shift`; ints range-checked against the pinned narrow width)
    plus MSB mask bits (validity AND NaN fold) OR-ed into `out_bits` at
    `out_bit_offset`. Either output may be None to skip it. Returns the
    invalid-row count, or None when the native library is unavailable,
    the (token, wire dtype) pair has no kernel, or a value overflowed
    the pinned narrow range (caller falls back to the Column path)."""
    lib = _load()
    if lib is None:
        return None
    out_dtype_name = out_values.dtype.name if out_values is not None else None
    bits_ptr = (
        out_bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        if out_bits is not None
        else None
    )
    vals_ptr = (
        out_values.ctypes.data_as(ctypes.c_void_p)
        if out_values is not None
        else None
    )
    validity_ptr = ctypes.c_void_p(validity_addr) if validity_addr else None
    if token in ("double", "float"):
        name = _WIRE_FLOAT_KERNELS.get((token, out_dtype_name or "float64"))
        if name is None:
            return None
        rc = getattr(lib, name)(
            ctypes.c_void_p(values_addr),
            validity_ptr,
            int(bit_offset),
            int(n),
            float(shift),
            vals_ptr,
            bits_ptr,
            int(out_bit_offset),
        )
    else:
        name = _WIRE_INT_KERNELS.get(token)
        code = _WIRE_OUT_CODES.get(out_dtype_name or "")
        if name is None or code is None:
            return None
        rc = getattr(lib, name)(
            ctypes.c_void_p(values_addr),
            validity_ptr,
            int(bit_offset),
            int(n),
            int(code),
            float(shift),
            vals_ptr,
            bits_ptr,
            int(out_bit_offset),
        )
    rc = int(rc)
    if rc < 0:
        return None
    return rc


#: arrow type token -> (allowed parquet physical types, engine numpy
#: dtype name). The reader planner (ops/fused.py:classify_reader_columns)
#: and the native reader dispatch both key off this map, so planner
#: verdict and runtime capability can never disagree. uint32 may be
#: stored as either INT64 (spec'd) or INT32 (writer-dependent); "bits"
#: marks booleans, whose out buffer is an LSB bitmap.
READER_TOKENS = {
    "double": (("DOUBLE",), "float64"),
    "float": (("FLOAT",), "float32"),
    "int8": (("INT32",), "int8"),
    "int16": (("INT32",), "int16"),
    "int32": (("INT32",), "int32"),
    "int64": (("INT64",), "int64"),
    "uint8": (("INT32",), "uint8"),
    "uint16": (("INT32",), "uint16"),
    "uint32": (("INT64", "INT32"), "uint32"),
    "uint64": (("INT64",), "uint64"),
    "bool": (("BOOLEAN",), "bits"),
}

#: parquet physical-type name -> format enum (parquet_read.c)
READER_PHYS_ENUM = {
    "BOOLEAN": 0,
    "INT32": 1,
    "INT64": 2,
    "FLOAT": 4,
    "DOUBLE": 5,
}

#: parquet codec name -> format enum (parquet_read.c)
READER_CODEC_ENUM = {"UNCOMPRESSED": 0, "SNAPPY": 1, "ZSTD": 6}

#: parquet codec name -> pq_reader_codecs() capability bit
READER_CODEC_MASK = {"UNCOMPRESSED": 1, "SNAPPY": 2, "ZSTD": 4}

#: page encodings the native reader decodes; anything else (BIT_PACKED,
#: DELTA_*, BYTE_STREAM_SPLIT) falls the column back to pyarrow
READER_ENCODINGS = frozenset(
    {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY"}
)


def reader_codecs() -> int:
    """Bitmask of decompression codecs the native reader can use
    (1=UNCOMPRESSED, 2=SNAPPY, 4=ZSTD — see READER_CODEC_MASK); 0 when
    the native library is unavailable. Snappy/zstd load lazily via
    dlopen, so the mask reflects what this host actually has."""
    lib = _load()
    if lib is None:
        return 0
    return int(lib.pq_reader_codecs())


@_traced_kernel
def read_chunk(
    chunk: np.ndarray,
    phys: int,
    codec: int,
    out_itemsize: int,
    max_def: int,
    num_values: int,
    out_values: np.ndarray,
    out_validity: Optional[np.ndarray],
) -> Optional[tuple]:
    """Decode one raw column-chunk byte range (dictionary page + data
    pages) into caller-zeroed Arrow-layout buffers: `out_values` gets
    contiguous engine-dtype values (LSB bitmap for booleans) with zeros
    at null slots, `out_validity` (LSB bitmap, required when max_def==1)
    gets its bits OR-set at non-null rows. Returns
    (null_count, pages, uncompressed_bytes) or None on any decode error
    — the caller falls back to pyarrow for that column, bit-identical."""
    lib = _load()
    if lib is None:
        return None
    info = np.zeros(3, dtype=np.int64)
    rc = lib.pq_decode_chunk(
        chunk.ctypes.data_as(ctypes.c_void_p),
        int(len(chunk)),
        int(phys),
        int(codec),
        int(out_itemsize),
        int(max_def),
        int(num_values),
        out_values.ctypes.data_as(ctypes.c_void_p),
        out_validity.ctypes.data_as(ctypes.c_void_p)
        if out_validity is not None
        else None,
        info.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    rc = int(rc)
    if rc < 0:
        return None
    return rc, int(info[0]), int(info[1])


#: dictionary-entry ceiling the encoded-fold mode accepts per chunk;
#: pq_decode_chunk_runs fails with PQE_SIZE above it, so a column whose
#: dictionary outgrew the bound falls back to the row-width path
ENCFOLD_DICT_CAP = 65536


@_traced_kernel
def read_chunk_runs(
    chunk: np.ndarray,
    phys: int,
    codec: int,
    max_def: int,
    num_values: int,
    cap_dict: int = ENCFOLD_DICT_CAP,
) -> Optional[tuple]:
    """Decode one raw column-chunk byte range into encoded-run streams
    instead of row-width buffers: coalesced (run_length, dict_code)
    value runs plus (run_length, present) definition-level runs, with
    the dictionary page's values in physical layout. Only fully
    dictionary-coded chunks qualify — a PLAIN data page (dictionary
    fallback mid-chunk), boolean column, oversized dictionary, or any
    corrupt structure returns None and the caller decodes the chunk at
    row width instead. Returns (dict_raw_bytes, run_len, run_code,
    def_len, def_val, null_count, pages, uncompressed_bytes,
    dict_count)."""
    lib = _load()
    if lib is None:
        return None
    item = {1: 4, 2: 8, 4: 4, 5: 8}.get(int(phys))
    if item is None:
        return None
    nv = int(num_values)
    cap_dict = int(cap_dict)
    out_dict = np.zeros(max(cap_dict, 1) * item, dtype=np.uint8)
    # coalescing bounds both streams by the footer row count
    run_len = np.empty(max(nv, 1), dtype=np.int64)
    run_code = np.empty(max(nv, 1), dtype=np.uint32)
    def_len = np.empty(max(nv, 1), dtype=np.int64)
    def_val = np.empty(max(nv, 1), dtype=np.uint8)
    info = np.zeros(5, dtype=np.int64)
    rc = lib.pq_decode_chunk_runs(
        chunk.ctypes.data_as(ctypes.c_void_p),
        int(len(chunk)),
        int(phys),
        int(codec),
        int(max_def),
        nv,
        out_dict.ctypes.data_as(ctypes.c_void_p),
        cap_dict,
        run_len.ctypes.data_as(ctypes.c_void_p),
        run_code.ctypes.data_as(ctypes.c_void_p),
        int(len(run_len)),
        def_len.ctypes.data_as(ctypes.c_void_p),
        def_val.ctypes.data_as(ctypes.c_void_p),
        int(len(def_len)),
        info.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    rc = int(rc)
    if rc < 0:
        return None
    n_runs, n_defs, dict_count = int(info[3]), int(info[4]), int(info[2])
    # copy the live prefixes so the full-size scratch is freed promptly
    return (
        out_dict[: dict_count * item].copy(),
        run_len[:n_runs].copy(),
        run_code[:n_runs].copy(),
        def_len[:n_defs].copy(),
        def_val[:n_defs].copy(),
        rc,
        int(info[0]),
        int(info[1]),
        dict_count,
    )


@_traced_kernel
def encfold_code_counts(
    run_len: np.ndarray, run_code: np.ndarray, dict_count: int
) -> Optional[np.ndarray]:
    """Weighted bincount of a coalesced (run_length, dict_code) stream:
    per-code occurrence counts, i.e. the slice's multiset over the
    dictionary. Returns None when the native library is unavailable or
    any run is corrupt (non-positive length, code out of range) — the
    caller fails closed to the row-width path, never to wrong values."""
    lib = _load()
    if lib is None:
        return None
    run_len = np.ascontiguousarray(run_len, dtype=np.int64)
    run_code = np.ascontiguousarray(run_code, dtype=np.uint32)
    dict_count = int(dict_count)
    counts = np.zeros(max(dict_count, 1), dtype=np.int64)
    rc = lib.encfold_code_counts(
        run_len.ctypes.data_as(ctypes.c_void_p),
        run_code.ctypes.data_as(ctypes.c_void_p),
        int(len(run_len)),
        dict_count,
        counts.ctypes.data_as(ctypes.c_void_p),
    )
    if int(rc) < 0:
        return None
    return counts[:dict_count]


@_traced_kernel
def encfold_def_nulls(
    def_len: np.ndarray, def_val: np.ndarray, expect_rows: int = -1
) -> Optional[int]:
    """Null count from coalesced definition-level runs, with no
    materialized validity mask. Returns None when the native library is
    unavailable or any run is corrupt (non-positive length, non-boolean
    def value, row-count mismatch against expect_rows when >= 0)."""
    lib = _load()
    if lib is None:
        return None
    def_len = np.ascontiguousarray(def_len, dtype=np.int64)
    def_val = np.ascontiguousarray(def_val, dtype=np.uint8)
    rc = lib.encfold_def_nulls(
        def_len.ctypes.data_as(ctypes.c_void_p),
        def_val.ctypes.data_as(ctypes.c_void_p),
        int(len(def_len)),
        int(expect_rows),
    )
    rc = int(rc)
    if rc < 0:
        return None
    return rc


@_traced_kernel
def hll_update_registers(
    packed: np.ndarray, where: Optional[np.ndarray], registers: np.ndarray
) -> bool:
    """In-place register scatter-max; False when native is unavailable."""
    lib = _load()
    if lib is None:
        return False
    packed = np.ascontiguousarray(packed, dtype=np.int32)
    where_ptr = (
        np.ascontiguousarray(where, dtype=np.uint8).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)
        )
        if where is not None
        else None
    )
    lib.hll_update_registers(
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        where_ptr,
        len(packed),
        registers.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return True

/* Buffer-level Arrow decode kernels: the fast path behind
 * Table.from_arrow for columns the planner proves need only packed
 * inputs (ops/fused.py:plan_decode_fastpath).
 *
 * Each kernel consumes the raw buffers of ONE contiguous Arrow chunk —
 * the values buffer, the validity BITMAP (LSB bit order, never a
 * byte-expanded bool array), and for dictionary columns the int32 index
 * buffer — and emits the engine's Column backing: values with the
 * neutral fill in null slots (0 / 0.0 / false / -1 for dict codes; the
 * data/table.py Column contract) plus a uint8 0/1 mask.
 *
 * The Python chain these replace (Table.from_arrow fallback) is
 * fill_null(fill) -> to_numpy -> astype -> NaN fold: 3-4 passes and as
 * many intermediate buffers per column.  Here the shape is two tight
 * passes built to auto-vectorize: expand the validity bitmap into the
 * output mask ONCE (byte-at-a-time, popcount for the invalid total),
 * then a branchless blend over the values.  Per-element bit extraction
 * inside the value loop — the obvious one-pass shape — defeats SIMD
 * and reloads the bitmap byte every iteration; measured, the two-pass
 * form is several times faster.  All pointers are restrict-qualified:
 * the buffers come from disjoint Arrow and numpy allocations.
 *
 * Offsets/slices: `values` arrives pre-advanced to the chunk's first
 * logical element; `validity` is the ORIGINAL bitmap buffer with
 * `bit_offset` the chunk's Arrow offset, so row i's bit sits at
 * absolute position (bit_offset + i).  validity == NULL means
 * null-free.  Loops are bounded by n, so bitmap tail bits past the
 * last row are never read.  Each kernel returns the number of INVALID
 * rows (callers skip mask work when it is zero).
 */

#include <math.h>
#include <stdint.h>
#include <stddef.h>
#include <string.h>

static inline int bit_at(const uint8_t *bits, int64_t pos) {
    return (bits[pos >> 3] >> (pos & 7)) & 1;
}

/* Bitmap -> uint8 0/1 mask. Head/tail rows handle a non-byte-aligned
 * bit_offset (sliced chunks); the body expands one bitmap byte into
 * eight mask bytes per iteration. Returns the number of ZERO bits. */
static int64_t expand_validity(const uint8_t *restrict validity,
                               int64_t bit_offset, int64_t n,
                               uint8_t *restrict out_valid) {
    int64_t invalid = 0;
    int64_t i = 0;
    while (i < n && ((bit_offset + i) & 7) != 0) {
        uint8_t ok = (uint8_t)bit_at(validity, bit_offset + i);
        out_valid[i] = ok;
        invalid += !ok;
        i++;
    }
    const uint8_t *bytes = validity + ((bit_offset + i) >> 3);
    int64_t nb = (n - i) >> 3;
    for (int64_t b = 0; b < nb; b++) {
        uint8_t byte = bytes[b];
        uint8_t *out = out_valid + i + b * 8;
        for (int j = 0; j < 8; j++) out[j] = (uint8_t)((byte >> j) & 1);
        invalid += 8 - __builtin_popcount(byte);
    }
    i += nb * 8;
    for (; i < n; i++) {
        uint8_t ok = (uint8_t)bit_at(validity, bit_offset + i);
        out_valid[i] = ok;
        invalid += !ok;
    }
    return invalid;
}

/* float64: NaN == NULL under this engine, so validity folds the NaN
 * mask in the same kernel (table.py from_arrow: valid &= ~isnan). */
int64_t decode_f64(const double *restrict values,
                   const uint8_t *restrict validity,
                   int64_t bit_offset, int64_t n,
                   double *restrict out_values,
                   uint8_t *restrict out_valid) {
    int64_t invalid = 0;
    if (validity) {
        invalid = expand_validity(validity, bit_offset, n, out_valid);
        for (int64_t i = 0; i < n; i++) {
            double v = out_valid[i] ? values[i] : 0.0;
            uint8_t nan = (uint8_t)(v != v); /* null slots are 0.0: never NaN */
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)(out_valid[i] & !nan);
            invalid += nan;
        }
    } else {
        for (int64_t i = 0; i < n; i++) {
            double v = values[i];
            uint8_t nan = (uint8_t)(v != v);
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)!nan;
            invalid += nan;
        }
    }
    return invalid;
}

/* float32 widens to the engine's float64 backing in the same pass. */
int64_t decode_f32(const float *restrict values,
                   const uint8_t *restrict validity,
                   int64_t bit_offset, int64_t n,
                   double *restrict out_values,
                   uint8_t *restrict out_valid) {
    int64_t invalid = 0;
    if (validity) {
        invalid = expand_validity(validity, bit_offset, n, out_valid);
        for (int64_t i = 0; i < n; i++) {
            double v = out_valid[i] ? (double)values[i] : 0.0;
            uint8_t nan = (uint8_t)(v != v);
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)(out_valid[i] & !nan);
            invalid += nan;
        }
    } else {
        for (int64_t i = 0; i < n; i++) {
            double v = (double)values[i];
            uint8_t nan = (uint8_t)(v != v);
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)!nan;
            invalid += nan;
        }
    }
    return invalid;
}

/* Integers widen to int64 (null -> 0). The uint64 > INT64_MAX wrap
 * matches numpy's astype(int64) C-cast semantics in the fallback. */
#define DECODE_INT(NAME, CTYPE)                                           \
int64_t NAME(const CTYPE *restrict values,                                \
             const uint8_t *restrict validity,                            \
             int64_t bit_offset, int64_t n,                               \
             int64_t *restrict out_values,                                \
             uint8_t *restrict out_valid) {                               \
    if (validity) {                                                       \
        int64_t invalid = expand_validity(validity, bit_offset, n,        \
                                          out_valid);                     \
        for (int64_t i = 0; i < n; i++)                                   \
            out_values[i] = out_valid[i] ? (int64_t)values[i] : 0;        \
        return invalid;                                                   \
    }                                                                     \
    for (int64_t i = 0; i < n; i++)                                       \
        out_values[i] = (int64_t)values[i];                               \
    memset(out_valid, 1, (size_t)n);                                      \
    return 0;                                                             \
}

DECODE_INT(decode_i8, int8_t)
DECODE_INT(decode_i16, int16_t)
DECODE_INT(decode_i32, int32_t)
DECODE_INT(decode_i64, int64_t)
DECODE_INT(decode_u8, uint8_t)
DECODE_INT(decode_u16, uint16_t)
DECODE_INT(decode_u32, uint32_t)
DECODE_INT(decode_u64, uint64_t)

/* Booleans: BOTH buffers are bitmaps, each with its own bit offset
 * (a sliced chunk shares buffers with its parent). null -> false.
 * Both bitmaps expand byte-wise; the value mask then ANDs the null
 * mask so null slots read false. */
int64_t decode_bool(const uint8_t *restrict value_bits,
                    int64_t value_bit_offset,
                    const uint8_t *restrict validity,
                    int64_t valid_bit_offset,
                    int64_t n, uint8_t *restrict out_values,
                    uint8_t *restrict out_valid) {
    expand_validity(value_bits, value_bit_offset, n, out_values);
    if (!validity) {
        memset(out_valid, 1, (size_t)n);
        return 0;
    }
    int64_t invalid = expand_validity(validity, valid_bit_offset, n,
                                      out_valid);
    for (int64_t i = 0; i < n; i++)
        out_values[i] = (uint8_t)(out_values[i] & out_valid[i]);
    return invalid;
}

/* Dictionary-encoded strings: int32 index buffer -> dict_encode codes
 * (null -> -1, the sentinel gather_with_null indexes) plus the mask.
 * The dictionary itself stays host-side (uniques via the fallback
 * helper); per-row strings remain lazy. */
int64_t decode_dict_i32(const int32_t *restrict indices,
                        const uint8_t *restrict validity,
                        int64_t bit_offset, int64_t n,
                        int32_t *restrict out_codes,
                        uint8_t *restrict out_valid) {
    if (validity) {
        int64_t invalid = expand_validity(validity, bit_offset, n,
                                          out_valid);
        for (int64_t i = 0; i < n; i++)
            out_codes[i] = out_valid[i] ? indices[i] : -1;
        return invalid;
    }
    memcpy(out_codes, indices, (size_t)n * sizeof(int32_t));
    memset(out_valid, 1, (size_t)n);
    return 0;
}

/* Buffer-level Arrow decode kernels: the fast path behind
 * Table.from_arrow for columns the planner proves need only packed
 * inputs (ops/fused.py:plan_decode_fastpath).
 *
 * Each kernel consumes the raw buffers of ONE contiguous Arrow chunk —
 * the values buffer, the validity BITMAP (LSB bit order, never a
 * byte-expanded bool array), and for dictionary columns the int32 index
 * buffer — and emits the engine's Column backing: values with the
 * neutral fill in null slots (0 / 0.0 / false / -1 for dict codes; the
 * data/table.py Column contract) plus a uint8 0/1 mask.
 *
 * The Python chain these replace (Table.from_arrow fallback) is
 * fill_null(fill) -> to_numpy -> astype -> NaN fold: 3-4 passes and as
 * many intermediate buffers per column.  Here the shape is two tight
 * passes built to auto-vectorize: expand the validity bitmap into the
 * output mask ONCE (byte-at-a-time, popcount for the invalid total),
 * then a branchless blend over the values.  Per-element bit extraction
 * inside the value loop — the obvious one-pass shape — defeats SIMD
 * and reloads the bitmap byte every iteration; measured, the two-pass
 * form is several times faster.  All pointers are restrict-qualified:
 * the buffers come from disjoint Arrow and numpy allocations.
 *
 * Offsets/slices: `values` arrives pre-advanced to the chunk's first
 * logical element; `validity` is the ORIGINAL bitmap buffer with
 * `bit_offset` the chunk's Arrow offset, so row i's bit sits at
 * absolute position (bit_offset + i).  validity == NULL means
 * null-free.  Loops are bounded by n, so bitmap tail bits past the
 * last row are never read.  Each kernel returns the number of INVALID
 * rows (callers skip mask work when it is zero).
 */

#include <math.h>
#include <stdint.h>
#include <stddef.h>
#include <string.h>

static inline int bit_at(const uint8_t *bits, int64_t pos) {
    return (bits[pos >> 3] >> (pos & 7)) & 1;
}

/* Bitmap -> uint8 0/1 mask. Head/tail rows handle a non-byte-aligned
 * bit_offset (sliced chunks); the body expands one bitmap byte into
 * eight mask bytes per iteration. Returns the number of ZERO bits. */
static int64_t expand_validity(const uint8_t *restrict validity,
                               int64_t bit_offset, int64_t n,
                               uint8_t *restrict out_valid) {
    int64_t invalid = 0;
    int64_t i = 0;
    while (i < n && ((bit_offset + i) & 7) != 0) {
        uint8_t ok = (uint8_t)bit_at(validity, bit_offset + i);
        out_valid[i] = ok;
        invalid += !ok;
        i++;
    }
    const uint8_t *bytes = validity + ((bit_offset + i) >> 3);
    int64_t nb = (n - i) >> 3;
    for (int64_t b = 0; b < nb; b++) {
        uint8_t byte = bytes[b];
        uint8_t *out = out_valid + i + b * 8;
        for (int j = 0; j < 8; j++) out[j] = (uint8_t)((byte >> j) & 1);
        invalid += 8 - __builtin_popcount(byte);
    }
    i += nb * 8;
    for (; i < n; i++) {
        uint8_t ok = (uint8_t)bit_at(validity, bit_offset + i);
        out_valid[i] = ok;
        invalid += !ok;
    }
    return invalid;
}

/* float64: NaN == NULL under this engine, so validity folds the NaN
 * mask in the same kernel (table.py from_arrow: valid &= ~isnan). */
int64_t decode_f64(const double *restrict values,
                   const uint8_t *restrict validity,
                   int64_t bit_offset, int64_t n,
                   double *restrict out_values,
                   uint8_t *restrict out_valid) {
    int64_t invalid = 0;
    if (validity) {
        invalid = expand_validity(validity, bit_offset, n, out_valid);
        for (int64_t i = 0; i < n; i++) {
            double v = out_valid[i] ? values[i] : 0.0;
            uint8_t nan = (uint8_t)(v != v); /* null slots are 0.0: never NaN */
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)(out_valid[i] & !nan);
            invalid += nan;
        }
    } else {
        for (int64_t i = 0; i < n; i++) {
            double v = values[i];
            uint8_t nan = (uint8_t)(v != v);
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)!nan;
            invalid += nan;
        }
    }
    return invalid;
}

/* float32 widens to the engine's float64 backing in the same pass. */
int64_t decode_f32(const float *restrict values,
                   const uint8_t *restrict validity,
                   int64_t bit_offset, int64_t n,
                   double *restrict out_values,
                   uint8_t *restrict out_valid) {
    int64_t invalid = 0;
    if (validity) {
        invalid = expand_validity(validity, bit_offset, n, out_valid);
        for (int64_t i = 0; i < n; i++) {
            double v = out_valid[i] ? (double)values[i] : 0.0;
            uint8_t nan = (uint8_t)(v != v);
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)(out_valid[i] & !nan);
            invalid += nan;
        }
    } else {
        for (int64_t i = 0; i < n; i++) {
            double v = (double)values[i];
            uint8_t nan = (uint8_t)(v != v);
            out_values[i] = nan ? 0.0 : v;
            out_valid[i] = (uint8_t)!nan;
            invalid += nan;
        }
    }
    return invalid;
}

/* Integers widen to int64 (null -> 0). The uint64 > INT64_MAX wrap
 * matches numpy's astype(int64) C-cast semantics in the fallback. */
#define DECODE_INT(NAME, CTYPE)                                           \
int64_t NAME(const CTYPE *restrict values,                                \
             const uint8_t *restrict validity,                            \
             int64_t bit_offset, int64_t n,                               \
             int64_t *restrict out_values,                                \
             uint8_t *restrict out_valid) {                               \
    if (validity) {                                                       \
        int64_t invalid = expand_validity(validity, bit_offset, n,        \
                                          out_valid);                     \
        for (int64_t i = 0; i < n; i++)                                   \
            out_values[i] = out_valid[i] ? (int64_t)values[i] : 0;        \
        return invalid;                                                   \
    }                                                                     \
    for (int64_t i = 0; i < n; i++)                                       \
        out_values[i] = (int64_t)values[i];                               \
    memset(out_valid, 1, (size_t)n);                                      \
    return 0;                                                             \
}

DECODE_INT(decode_i8, int8_t)
DECODE_INT(decode_i16, int16_t)
DECODE_INT(decode_i32, int32_t)
DECODE_INT(decode_i64, int64_t)
DECODE_INT(decode_u8, uint8_t)
DECODE_INT(decode_u16, uint16_t)
DECODE_INT(decode_u32, uint32_t)
DECODE_INT(decode_u64, uint64_t)

/* Booleans: BOTH buffers are bitmaps, each with its own bit offset
 * (a sliced chunk shares buffers with its parent). null -> false.
 * Both bitmaps expand byte-wise; the value mask then ANDs the null
 * mask so null slots read false. */
int64_t decode_bool(const uint8_t *restrict value_bits,
                    int64_t value_bit_offset,
                    const uint8_t *restrict validity,
                    int64_t valid_bit_offset,
                    int64_t n, uint8_t *restrict out_values,
                    uint8_t *restrict out_valid) {
    expand_validity(value_bits, value_bit_offset, n, out_values);
    if (!validity) {
        memset(out_valid, 1, (size_t)n);
        return 0;
    }
    int64_t invalid = expand_validity(validity, valid_bit_offset, n,
                                      out_valid);
    for (int64_t i = 0; i < n; i++)
        out_values[i] = (uint8_t)(out_values[i] & out_valid[i]);
    return invalid;
}

/* Dictionary-encoded strings: int32 index buffer -> dict_encode codes
 * (null -> -1, the sentinel gather_with_null indexes) plus the mask.
 * The dictionary itself stays host-side (uniques via the fallback
 * helper); per-row strings remain lazy. */
int64_t decode_dict_i32(const int32_t *restrict indices,
                        const uint8_t *restrict validity,
                        int64_t bit_offset, int64_t n,
                        int32_t *restrict out_codes,
                        uint8_t *restrict out_valid) {
    if (validity) {
        int64_t invalid = expand_validity(validity, bit_offset, n,
                                          out_valid);
        for (int64_t i = 0; i < n; i++)
            out_codes[i] = out_valid[i] ? indices[i] : -1;
        return invalid;
    }
    memcpy(out_codes, indices, (size_t)n * sizeof(int32_t));
    memset(out_valid, 1, (size_t)n);
    return 0;
}

/* ---- decode-to-wire kernels -------------------------------------------
 *
 * The kernels above emit the engine Column backing (values + uint8
 * mask); the prep stage then re-reads every element to build the wire
 * format (ops/fused.py:pack_batch_inputs — np.packbits masks, int
 * narrowing, f32 pre-centering).  For planner-proven packed-only
 * columns that Column intermediate is pure waste, so the kernels below
 * emit the WIRE buffers directly from the Arrow buffers:
 *
 *   * a bitpacked 1-bit/row mask in np.packbits order (MSB-first —
 *     Arrow validity bitmaps are LSB-first, so this is a bit-order
 *     recode), validity AND the float NaN fold in the same pass;
 *   * value rows in the compute dtype, pre-centered by the sticky
 *     scan-constant shift on the f32 wire;
 *   * narrowed int rows at a statically pinned width (parquet
 *     statistics), range-checked — a lying file aborts the kernel
 *     (return -1) and the caller falls back to the Column path.
 *
 * Wire buffers are PREZEROED by the caller (the padded tail must read
 * zero to match the pack path's zeroed group buffer), and the mask
 * writers only OR bits in, so concurrent per-chunk writers at disjoint
 * row ranges never clobber a shared boundary byte.  `out_bit_offset`
 * is the chunk's first row position inside the batch row, which lands
 * mid-byte whenever a row group ends off a multiple of 8.  Tiles reuse
 * expand_validity for the LSB head/tail handling it already has.
 */

#define WIRE_TILE 512

/* OR `ok` (0/1 per row) into out_bits at out_off, MSB-first within each
 * byte (np.packbits bitorder="big"). Head/tail handle a mid-byte start
 * and end; the body packs eight rows per output byte. */
static void wire_set_bits_msb(const uint8_t *restrict ok, int64_t n,
                              uint8_t *restrict out_bits, int64_t out_off) {
    int64_t i = 0;
    while (i < n && ((out_off + i) & 7) != 0) {
        if (ok[i])
            out_bits[(out_off + i) >> 3] |=
                (uint8_t)(1u << (7 - ((out_off + i) & 7)));
        i++;
    }
    uint8_t *bytes = out_bits + ((out_off + i) >> 3);
    int64_t nb = (n - i) >> 3;
    for (int64_t b = 0; b < nb; b++) {
        const uint8_t *src = ok + i + b * 8;
        uint8_t byte = 0;
        for (int j = 0; j < 8; j++) byte = (uint8_t)((byte << 1) | (src[j] & 1));
        bytes[b] |= byte;
    }
    i += nb * 8;
    for (; i < n; i++)
        if (ok[i])
            out_bits[(out_off + i) >> 3] |=
                (uint8_t)(1u << (7 - ((out_off + i) & 7)));
}

/* Validity bitmap (LSB) -> wire mask bits (MSB) with no value pass:
 * int/bool columns whose only packed consumer is the valid: mask.
 * validity == NULL means null-free (all bits set). */
int64_t wire_valid_bits(const uint8_t *restrict validity, int64_t bit_offset,
                        int64_t n, uint8_t *restrict out_bits,
                        int64_t out_bit_offset) {
    uint8_t tile[WIRE_TILE];
    int64_t invalid = 0;
    for (int64_t t = 0; t < n; t += WIRE_TILE) {
        int64_t m = n - t < WIRE_TILE ? n - t : WIRE_TILE;
        if (validity)
            invalid += expand_validity(validity, bit_offset + t, m, tile);
        else
            memset(tile, 1, (size_t)m);
        wire_set_bits_msb(tile, m, out_bits, out_bit_offset + t);
    }
    return invalid;
}

/* Float chunk -> wire value row + wire mask bits in one pass.  The
 * value math replicates pack_batch_inputs exactly: v_eff is the Column
 * backing (null/NaN -> 0.0), the shift subtraction happens in double,
 * and only then does the result narrow to the wire dtype — so the f32
 * wire's (float)(v_eff - shift) matches numpy's f64-subtract-then-
 * astype bit for bit.  out_values == NULL emits mask bits only
 * (valid:-only consumers still need the NaN fold); out_bits == NULL
 * emits values only. */
#define WIRE_FLOAT(NAME, INTYPE, OUTTYPE)                                  \
int64_t NAME(const INTYPE *restrict values,                                \
             const uint8_t *restrict validity,                             \
             int64_t bit_offset, int64_t n, double shift,                  \
             OUTTYPE *restrict out_values,                                 \
             uint8_t *restrict out_bits, int64_t out_bit_offset) {         \
    uint8_t tile[WIRE_TILE];                                               \
    int64_t invalid = 0;                                                   \
    for (int64_t t = 0; t < n; t += WIRE_TILE) {                           \
        int64_t m = n - t < WIRE_TILE ? n - t : WIRE_TILE;                 \
        if (validity)                                                      \
            invalid += expand_validity(validity, bit_offset + t, m, tile); \
        else                                                               \
            memset(tile, 1, (size_t)m);                                    \
        for (int64_t i = 0; i < m; i++) {                                  \
            double v = tile[i] ? (double)values[t + i] : 0.0;              \
            uint8_t nan = (uint8_t)(v != v); /* null slots never NaN */    \
            invalid += nan;                                                \
            tile[i] = (uint8_t)(tile[i] & !nan);                           \
            if (out_values)                                                \
                out_values[t + i] = (OUTTYPE)((nan ? 0.0 : v) - shift);    \
        }                                                                  \
        if (out_bits)                                                      \
            wire_set_bits_msb(tile, m, out_bits, out_bit_offset + t);      \
    }                                                                      \
    return invalid;                                                        \
}

WIRE_FLOAT(wire_f64, double, double)
WIRE_FLOAT(wire_f64_to_f32, double, float)
WIRE_FLOAT(wire_f32_to_f64, float, double)
WIRE_FLOAT(wire_f32, float, float)

/* Int chunk -> wire value row (+ mask bits).  out_code selects the
 * wire dtype: 0=int8 1=int16 2=int32 (range-checked, null fill 0 is
 * always in range) 3=float64 4=float32 (pre-centered by `shift`, the
 * f32 wire's path).  A value outside the pinned narrow range returns
 * -1 — the statically chosen width came from parquet statistics, so
 * this only fires on a lying file; the caller discards the partial
 * wire buffers and re-decodes the column through the Column path. */
#define WIRE_INT(NAME, CTYPE)                                              \
int64_t NAME(const CTYPE *restrict values,                                 \
             const uint8_t *restrict validity,                             \
             int64_t bit_offset, int64_t n, int out_code, double shift,    \
             void *restrict out_values,                                    \
             uint8_t *restrict out_bits, int64_t out_bit_offset) {         \
    uint8_t tile[WIRE_TILE];                                               \
    int64_t invalid = 0;                                                   \
    int8_t *o8 = (int8_t *)out_values;                                     \
    int16_t *o16 = (int16_t *)out_values;                                  \
    int32_t *o32 = (int32_t *)out_values;                                  \
    double *o64 = (double *)out_values;                                    \
    float *of = (float *)out_values;                                       \
    for (int64_t t = 0; t < n; t += WIRE_TILE) {                           \
        int64_t m = n - t < WIRE_TILE ? n - t : WIRE_TILE;                 \
        if (validity)                                                      \
            invalid += expand_validity(validity, bit_offset + t, m, tile); \
        else                                                               \
            memset(tile, 1, (size_t)m);                                    \
        if (out_values) switch (out_code) {                                \
        case 0:                                                            \
            for (int64_t i = 0; i < m; i++) {                              \
                int64_t v = tile[i] ? (int64_t)values[t + i] : 0;          \
                if (v < -128 || v > 127) return -1;                        \
                o8[t + i] = (int8_t)v;                                     \
            }                                                              \
            break;                                                         \
        case 1:                                                            \
            for (int64_t i = 0; i < m; i++) {                              \
                int64_t v = tile[i] ? (int64_t)values[t + i] : 0;          \
                if (v < -32768 || v > 32767) return -1;                    \
                o16[t + i] = (int16_t)v;                                   \
            }                                                              \
            break;                                                         \
        case 2:                                                            \
            for (int64_t i = 0; i < m; i++) {                              \
                int64_t v = tile[i] ? (int64_t)values[t + i] : 0;          \
                if (v < -2147483648LL || v > 2147483647LL) return -1;      \
                o32[t + i] = (int32_t)v;                                   \
            }                                                              \
            break;                                                         \
        case 3:                                                            \
            for (int64_t i = 0; i < m; i++)                                \
                o64[t + i] = tile[i] ? (double)values[t + i] : 0.0;        \
            break;                                                         \
        case 4:                                                            \
            for (int64_t i = 0; i < m; i++) {                              \
                double v = tile[i] ? (double)values[t + i] : 0.0;          \
                of[t + i] = (float)(v - shift);                            \
            }                                                              \
            break;                                                         \
        default:                                                           \
            return -1;                                                     \
        }                                                                  \
        if (out_bits)                                                      \
            wire_set_bits_msb(tile, m, out_bits, out_bit_offset + t);      \
    }                                                                      \
    return invalid;                                                        \
}

WIRE_INT(wire_i8, int8_t)
WIRE_INT(wire_i16, int16_t)
WIRE_INT(wire_i32, int32_t)
WIRE_INT(wire_i64, int64_t)
WIRE_INT(wire_u8, uint8_t)
WIRE_INT(wire_u16, uint16_t)
WIRE_INT(wire_u32, uint32_t)

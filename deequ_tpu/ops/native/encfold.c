/* Run-fold kernels: fold semigroup state over encoded streams.
 *
 * pq_decode_chunk_runs (parquet_read.c) turns a dictionary-coded
 * column chunk into coalesced (run_length, dict_code) value runs plus
 * (run_length, present) definition-level runs. The kernels here reduce
 * those streams without ever expanding to row width:
 *
 *   encfold_code_counts  (run, code) stream -> per-code occurrence
 *                        counts, i.e. the multiset of the chunk slice
 *                        as a weighted bincount over dictionary codes.
 *                        One code->value rollup at the end of the batch
 *                        (Python side, through the dictionary) then
 *                        feeds the exact counts-family derivation the
 *                        row path's counts fast path uses — which is
 *                        what keeps moments/min-max/Frequency/HLL/KLL
 *                        bit-identical by construction.
 *   encfold_def_nulls    (run, present) stream -> null count, with the
 *                        same fail-closed validation.
 *
 * Both kernels validate every run (positive length, in-range code,
 * boolean def value) and return -1 on the first violation so a corrupt
 * run stream can never fold into wrong values — the caller falls back
 * to the row-width path for the column.
 */

#include <stdint.h>

/* Weighted bincount over dictionary codes. out_counts must hold
 * dict_count zero-initialised slots. Returns the total value count
 * (sum of run lengths) or -1 if any run is corrupt (len <= 0 or code
 * out of dictionary range). */
int64_t encfold_code_counts(const int64_t *run_len, const uint32_t *run_code,
                            int64_t n_runs, int64_t dict_count,
                            int64_t *out_counts) {
    if (n_runs < 0 || dict_count < 0 || (n_runs > 0 && (!run_len || !run_code)))
        return -1;
    if (n_runs > 0 && !out_counts) return -1;
    int64_t total = 0;
    for (int64_t i = 0; i < n_runs; i++) {
        int64_t len = run_len[i];
        uint32_t code = run_code[i];
        if (len <= 0 || (int64_t)code >= dict_count) return -1;
        out_counts[code] += len;
        total += len;
    }
    return total;
}

/* Fold definition-level runs into a null count: rows with def_val 0 are
 * null, 1 present — no materialized validity mask. Returns the null
 * count, or -1 if any run is corrupt (len <= 0, non-boolean def value,
 * or the total row count disagrees with expect_rows). */
int64_t encfold_def_nulls(const int64_t *def_len, const uint8_t *def_val,
                          int64_t n_defs, int64_t expect_rows) {
    if (n_defs < 0 || (n_defs > 0 && (!def_len || !def_val))) return -1;
    int64_t nulls = 0;
    int64_t rows = 0;
    for (int64_t i = 0; i < n_defs; i++) {
        int64_t len = def_len[i];
        uint8_t v = def_val[i];
        if (len <= 0 || v > 1) return -1;
        if (!v) nulls += len;
        rows += len;
    }
    if (expect_rows >= 0 && rows != expect_rows) return -1;
    return nulls;
}

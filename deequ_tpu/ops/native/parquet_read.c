/* Native parquet column-chunk reader: page headers in, Arrow-layout
 * buffers out.
 *
 * pq_decode_chunk() walks one column chunk's byte range (dictionary
 * page + data pages), parses each Thrift-compact PageHeader,
 * decompresses the page body (snappy / zstd via dlopen — the container
 * ships runtime .so's but no dev symlinks), and decodes PLAIN,
 * RLE_DICTIONARY / PLAIN_DICTIONARY and RLE-boolean values into the
 * same buffer layout Arrow would hand decode.c: contiguous
 * little-endian values with zeros at null slots plus an LSB validity
 * bitmap. The existing decode and wire kernels then consume those
 * buffers unchanged, which is what makes the native path bit-identical
 * by construction.
 *
 * Scope is fail-closed: anything outside the proven shapes (nested
 * levels, BIT_PACKED def levels, unknown codecs, malformed headers,
 * out-of-range dictionary indices, row-count mismatches) returns a
 * negative error so the Python layer falls back to pyarrow for that
 * column. No input may crash this file — every read is bounds-checked
 * and fuzz + sanitizer drivers exercise the error paths.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <dlfcn.h>
#include <pthread.h>

/* ---- error codes (negative returns from pq_decode_chunk) ---- */
#define PQE_TRUNCATED (-1)   /* byte range ends mid-structure */
#define PQE_THRIFT (-2)      /* malformed compact-protocol header */
#define PQE_UNSUPPORTED (-3) /* page/encoding shape outside proven set */
#define PQE_CODEC (-4)       /* decompression failed or codec missing */
#define PQE_SIZE (-5)        /* size field implausible / overflow */
#define PQE_ALLOC (-6)       /* scratch allocation failed */
#define PQE_DICT (-7)        /* dictionary index out of range / absent */
#define PQE_ROWS (-8)        /* decoded row count != footer num_values */

/* ---- parquet enums (format spec values) ---- */
#define PT_BOOLEAN 0
#define PT_INT32 1
#define PT_INT64 2
#define PT_FLOAT 4
#define PT_DOUBLE 5

#define PAGE_DATA 0
#define PAGE_INDEX 1
#define PAGE_DICT 2
#define PAGE_DATA_V2 3

#define ENC_PLAIN 0
#define ENC_PLAIN_DICT 2
#define ENC_RLE 3
#define ENC_RLE_DICT 8

#define CODEC_NONE 0
#define CODEC_SNAPPY 1
#define CODEC_ZSTD 6

#define MAX_PAGE_BYTES ((int64_t)1 << 30)

/* ---- lazy-loaded decompressors ---- */

typedef int (*snappy_uncompress_fn)(const char *, size_t, char *, size_t *);
typedef int (*snappy_uncompressed_length_fn)(const char *, size_t, size_t *);
typedef size_t (*zstd_decompress_fn)(void *, size_t, const void *, size_t);
typedef unsigned (*zstd_iserror_fn)(size_t);

static snappy_uncompress_fn g_snappy_uncompress;
static snappy_uncompressed_length_fn g_snappy_len;
static zstd_decompress_fn g_zstd_decompress;
static zstd_iserror_fn g_zstd_iserror;
static int g_codec_mask; /* 1 = uncompressed, 2 = snappy, 4 = zstd */
static pthread_once_t g_codec_once = PTHREAD_ONCE_INIT;

static void codec_init(void) {
    g_codec_mask = 1;
    void *snappy = dlopen("libsnappy.so.1", RTLD_NOW | RTLD_GLOBAL);
    if (snappy) {
        g_snappy_uncompress =
            (snappy_uncompress_fn)dlsym(snappy, "snappy_uncompress");
        g_snappy_len = (snappy_uncompressed_length_fn)dlsym(
            snappy, "snappy_uncompressed_length");
        if (g_snappy_uncompress && g_snappy_len) g_codec_mask |= 2;
    }
    void *zstd = dlopen("libzstd.so.1", RTLD_NOW | RTLD_GLOBAL);
    if (zstd) {
        g_zstd_decompress = (zstd_decompress_fn)dlsym(zstd, "ZSTD_decompress");
        g_zstd_iserror = (zstd_iserror_fn)dlsym(zstd, "ZSTD_isError");
        if (g_zstd_decompress && g_zstd_iserror) g_codec_mask |= 4;
    }
}

int pq_reader_codecs(void) {
    pthread_once(&g_codec_once, codec_init);
    return g_codec_mask;
}

static int pq_decompress(int32_t codec, const uint8_t *src, int64_t src_len,
                         uint8_t *dst, int64_t dst_len) {
    pthread_once(&g_codec_once, codec_init);
    if (codec == CODEC_SNAPPY) {
        if (!(g_codec_mask & 2)) return PQE_CODEC;
        size_t out_len = 0;
        if (g_snappy_len((const char *)src, (size_t)src_len, &out_len) != 0)
            return PQE_CODEC;
        if ((int64_t)out_len != dst_len) return PQE_CODEC;
        if (g_snappy_uncompress((const char *)src, (size_t)src_len,
                                (char *)dst, &out_len) != 0)
            return PQE_CODEC;
        return 0;
    }
    if (codec == CODEC_ZSTD) {
        if (!(g_codec_mask & 4)) return PQE_CODEC;
        size_t rc = g_zstd_decompress(dst, (size_t)dst_len, src, (size_t)src_len);
        if (g_zstd_iserror(rc) || (int64_t)rc != dst_len) return PQE_CODEC;
        return 0;
    }
    return PQE_CODEC;
}

/* ---- Thrift compact protocol (read-only subset) ---- */

typedef struct {
    const uint8_t *p;
    const uint8_t *end;
    int err;
} tin_t;

static uint64_t t_uvarint(tin_t *t) {
    uint64_t v = 0;
    int shift = 0;
    while (t->p < t->end && shift < 64) {
        uint8_t b = *t->p++;
        v |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) return v;
        shift += 7;
    }
    t->err = 1;
    return 0;
}

static int64_t t_zigzag(tin_t *t) {
    uint64_t u = t_uvarint(t);
    return (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
}

static void t_skipn(tin_t *t, uint64_t n) {
    if ((uint64_t)(t->end - t->p) < n) {
        t->err = 1;
        t->p = t->end;
        return;
    }
    t->p += n;
}

/* Skip one value of the given compact element type. Bool-in-struct is
 * encoded in the field-type nibble (1/2, no payload); bool-in-container
 * is one byte per element — callers pass type 3 (BYTE) for those. */
static void t_skip_value(tin_t *t, int ctype, int depth) {
    if (t->err || depth > 8) {
        t->err = 1;
        return;
    }
    switch (ctype) {
        case 1: /* BOOL true (field form, no payload) */
        case 2: /* BOOL false */
            return;
        case 3: /* BYTE */
            t_skipn(t, 1);
            return;
        case 4: /* I16 */
        case 5: /* I32 */
        case 6: /* I64 */
            (void)t_zigzag(t);
            return;
        case 7: /* DOUBLE */
            t_skipn(t, 8);
            return;
        case 8: { /* BINARY / STRING */
            uint64_t len = t_uvarint(t);
            t_skipn(t, len);
            return;
        }
        case 9:   /* LIST */
        case 10: { /* SET */
            if (t->p >= t->end) {
                t->err = 1;
                return;
            }
            uint8_t hdr = *t->p++;
            uint64_t size = hdr >> 4;
            int etype = hdr & 0x0f;
            if (size == 15) size = t_uvarint(t);
            if (size > (uint64_t)(t->end - t->p)) {
                /* every element is >= 1 byte on the wire */
                t->err = 1;
                return;
            }
            if (etype == 1 || etype == 2) etype = 3; /* bools: 1 byte each */
            for (uint64_t i = 0; i < size && !t->err; i++)
                t_skip_value(t, etype, depth + 1);
            return;
        }
        case 11: { /* MAP */
            uint64_t size = t_uvarint(t);
            if (size == 0) return;
            if (t->p >= t->end || size > (uint64_t)(t->end - t->p)) {
                t->err = 1;
                return;
            }
            uint8_t kv = *t->p++;
            int ktype = (kv >> 4) & 0x0f;
            int vtype = kv & 0x0f;
            if (ktype == 1 || ktype == 2) ktype = 3;
            if (vtype == 1 || vtype == 2) vtype = 3;
            for (uint64_t i = 0; i < size && !t->err; i++) {
                t_skip_value(t, ktype, depth + 1);
                t_skip_value(t, vtype, depth + 1);
            }
            return;
        }
        case 12: { /* STRUCT: fields until STOP */
            int16_t last_fid = 0;
            for (;;) {
                if (t->p >= t->end) {
                    t->err = 1;
                    return;
                }
                uint8_t fb = *t->p++;
                if (fb == 0) return; /* STOP */
                int ftype = fb & 0x0f;
                int delta = (fb >> 4) & 0x0f;
                if (delta == 0)
                    last_fid = (int16_t)t_zigzag(t);
                else
                    last_fid = (int16_t)(last_fid + delta);
                t_skip_value(t, ftype, depth + 1);
                if (t->err) return;
            }
        }
        default:
            t->err = 1;
            return;
    }
}

/* Parsed PageHeader fields we care about. */
typedef struct {
    int32_t page_type;
    int64_t uncompressed_size;
    int64_t compressed_size;
    /* data page v1 */
    int64_t num_values;
    int32_t encoding;
    int32_t def_encoding;
    /* dictionary page */
    int64_t dict_num_values;
    int32_t dict_encoding;
    /* data page v2 */
    int64_t v2_num_values;
    int64_t v2_num_nulls;
    int64_t v2_num_rows;
    int32_t v2_encoding;
    int64_t v2_dl_len;
    int64_t v2_rl_len;
    int v2_is_compressed;
} page_header_t;

static void parse_data_page_header(tin_t *t, page_header_t *h) {
    int16_t last_fid = 0;
    for (;;) {
        if (t->p >= t->end) {
            t->err = 1;
            return;
        }
        uint8_t fb = *t->p++;
        if (fb == 0) return;
        int ftype = fb & 0x0f;
        int delta = (fb >> 4) & 0x0f;
        if (delta == 0)
            last_fid = (int16_t)t_zigzag(t);
        else
            last_fid = (int16_t)(last_fid + delta);
        if (last_fid == 1 && ftype == 5)
            h->num_values = t_zigzag(t);
        else if (last_fid == 2 && ftype == 5)
            h->encoding = (int32_t)t_zigzag(t);
        else if (last_fid == 3 && ftype == 5)
            h->def_encoding = (int32_t)t_zigzag(t);
        else
            t_skip_value(t, ftype, 0);
        if (t->err) return;
    }
}

static void parse_dict_page_header(tin_t *t, page_header_t *h) {
    int16_t last_fid = 0;
    for (;;) {
        if (t->p >= t->end) {
            t->err = 1;
            return;
        }
        uint8_t fb = *t->p++;
        if (fb == 0) return;
        int ftype = fb & 0x0f;
        int delta = (fb >> 4) & 0x0f;
        if (delta == 0)
            last_fid = (int16_t)t_zigzag(t);
        else
            last_fid = (int16_t)(last_fid + delta);
        if (last_fid == 1 && ftype == 5)
            h->dict_num_values = t_zigzag(t);
        else if (last_fid == 2 && ftype == 5)
            h->dict_encoding = (int32_t)t_zigzag(t);
        else
            t_skip_value(t, ftype, 0);
        if (t->err) return;
    }
}

static void parse_data_page_v2_header(tin_t *t, page_header_t *h) {
    int16_t last_fid = 0;
    h->v2_is_compressed = 1; /* spec default when field absent */
    for (;;) {
        if (t->p >= t->end) {
            t->err = 1;
            return;
        }
        uint8_t fb = *t->p++;
        if (fb == 0) return;
        int ftype = fb & 0x0f;
        int delta = (fb >> 4) & 0x0f;
        if (delta == 0)
            last_fid = (int16_t)t_zigzag(t);
        else
            last_fid = (int16_t)(last_fid + delta);
        if (last_fid == 1 && ftype == 5)
            h->v2_num_values = t_zigzag(t);
        else if (last_fid == 2 && ftype == 5)
            h->v2_num_nulls = t_zigzag(t);
        else if (last_fid == 3 && ftype == 5)
            h->v2_num_rows = t_zigzag(t);
        else if (last_fid == 4 && ftype == 5)
            h->v2_encoding = (int32_t)t_zigzag(t);
        else if (last_fid == 5 && ftype == 5)
            h->v2_dl_len = t_zigzag(t);
        else if (last_fid == 6 && ftype == 5)
            h->v2_rl_len = t_zigzag(t);
        else if (last_fid == 7 && (ftype == 1 || ftype == 2))
            h->v2_is_compressed = (ftype == 1);
        else
            t_skip_value(t, ftype, 0);
        if (t->err) return;
    }
}

/* Parse one PageHeader struct starting at t->p. Returns 0 or PQE_*. */
static int parse_page_header(tin_t *t, page_header_t *h) {
    memset(h, 0, sizeof(*h));
    h->page_type = -1;
    h->uncompressed_size = -1;
    h->compressed_size = -1;
    h->num_values = -1;
    h->encoding = -1;
    h->def_encoding = -1;
    h->dict_num_values = -1;
    h->dict_encoding = -1;
    h->v2_num_values = -1;
    h->v2_num_nulls = -1;
    h->v2_num_rows = -1;
    h->v2_encoding = -1;
    h->v2_dl_len = -1;
    h->v2_rl_len = -1;
    int16_t last_fid = 0;
    int saw_dph = 0, saw_dict = 0, saw_v2 = 0;
    for (;;) {
        if (t->p >= t->end) return PQE_TRUNCATED;
        uint8_t fb = *t->p++;
        if (fb == 0) break; /* STOP */
        int ftype = fb & 0x0f;
        int delta = (fb >> 4) & 0x0f;
        if (delta == 0)
            last_fid = (int16_t)t_zigzag(t);
        else
            last_fid = (int16_t)(last_fid + delta);
        if (t->err) return PQE_THRIFT;
        if (last_fid == 1 && ftype == 5)
            h->page_type = (int32_t)t_zigzag(t);
        else if (last_fid == 2 && ftype == 5)
            h->uncompressed_size = t_zigzag(t);
        else if (last_fid == 3 && ftype == 5)
            h->compressed_size = t_zigzag(t);
        else if (last_fid == 5 && ftype == 12) {
            parse_data_page_header(t, h);
            saw_dph = 1;
        } else if (last_fid == 7 && ftype == 12) {
            parse_dict_page_header(t, h);
            saw_dict = 1;
        } else if (last_fid == 8 && ftype == 12) {
            parse_data_page_v2_header(t, h);
            saw_v2 = 1;
        } else
            t_skip_value(t, ftype, 0);
        if (t->err) return PQE_THRIFT;
    }
    if (h->page_type < 0 || h->uncompressed_size < 0 || h->compressed_size < 0)
        return PQE_THRIFT;
    if (h->uncompressed_size > MAX_PAGE_BYTES || h->compressed_size > MAX_PAGE_BYTES)
        return PQE_SIZE;
    if (h->page_type == PAGE_DATA && !saw_dph) return PQE_THRIFT;
    if (h->page_type == PAGE_DICT && !saw_dict) return PQE_THRIFT;
    if (h->page_type == PAGE_DATA_V2 && !saw_v2) return PQE_THRIFT;
    return 0;
}

/* ---- RLE / bit-packed hybrid decoder ---- */

/* Read `bw` bits at bit position `pos` from `in[0..in_len)`, LSB-first.
 * Caller guarantees the group's bytes exist; this re-checks anyway. */
/* Unpack one bit-packed group of 8 bw-bit values through a sliding
 * 64-bit bit buffer (the buffer never holds more than 39 live bits:
 * at most bw-1 <= 31 leftovers plus one 8-bit refill). The caller
 * guarantees all bw bytes of the group are present. Returns the
 * advanced input pointer. */
static inline const uint8_t *unpack8(const uint8_t *p, int bw,
                                     uint32_t *out) {
    if (bw == 1) {
        uint8_t b = p[0];
        for (int i = 0; i < 8; i++) out[i] = (b >> i) & 1u;
        return p + 1;
    }
    if (bw == 8) {
        for (int i = 0; i < 8; i++) out[i] = p[i];
        return p + 8;
    }
    uint64_t acc = 0;
    int have = 0;
    uint32_t mask = bw >= 32 ? 0xFFFFFFFFu : ((1u << bw) - 1u);
    for (int i = 0; i < 8; i++) {
        while (have < bw) {
            acc |= (uint64_t)(*p++) << have;
            have += 8;
        }
        out[i] = (uint32_t)acc & mask;
        acc >>= bw;
        have -= bw;
    }
    return p;
}

/* Decode exactly `count` values from an RLE/bit-packed hybrid stream.
 * Returns bytes consumed, or PQE_* (<0). */
static int64_t hybrid_u32(const uint8_t *in, int64_t in_len, int bw,
                          int64_t count, uint32_t *out) {
    if (bw < 0 || bw > 32) return PQE_UNSUPPORTED;
    if (count == 0) return 0;
    if (bw == 0) {
        memset(out, 0, (size_t)count * sizeof(uint32_t));
        return 0;
    }
    tin_t t = {in, in + in_len, 0};
    int64_t got = 0;
    int vbytes = (bw + 7) >> 3;
    while (got < count) {
        uint64_t header = t_uvarint(&t);
        if (t.err) return PQE_TRUNCATED;
        if ((header & 1) == 0) {
            int64_t run = (int64_t)(header >> 1);
            if (run <= 0) return PQE_THRIFT;
            if ((uint64_t)(t.end - t.p) < (uint64_t)vbytes)
                return PQE_TRUNCATED;
            uint32_t v = 0;
            for (int i = 0; i < vbytes; i++) v |= (uint32_t)t.p[i] << (8 * i);
            t.p += vbytes;
            if (bw < 32) v &= (uint32_t)(((uint64_t)1 << bw) - 1);
            int64_t take = run < count - got ? run : count - got;
            for (int64_t i = 0; i < take; i++) out[got + i] = v;
            got += take;
        } else {
            int64_t groups = (int64_t)(header >> 1);
            if (groups <= 0) return PQE_THRIFT;
            /* bw >= 1 here, so every group consumes at least one input
             * byte; bounding groups by the remaining bytes before the
             * multiplications keeps nvals/nbytes from overflowing on
             * corrupt varint group counts (up to 2^62). */
            if (groups > (int64_t)(t.end - t.p)) return PQE_TRUNCATED;
            int64_t nvals = groups * 8;
            int64_t nbytes = groups * bw;
            if ((int64_t)(t.end - t.p) < nbytes) return PQE_TRUNCATED;
            int64_t take = nvals < count - got ? nvals : count - got;
            /* every declared group's bw bytes are inside nbytes, so the
             * group containing a partial tail is still fully readable */
            const uint8_t *gp = t.p;
            uint32_t *op = out + got;
            int64_t full = take >> 3;
            for (int64_t g = 0; g < full; g++, op += 8)
                gp = unpack8(gp, bw, op);
            int64_t rem = take & 7;
            if (rem > 0) {
                uint32_t tail[8];
                unpack8(gp, bw, tail);
                for (int64_t i = 0; i < rem; i++) op[i] = tail[i];
            }
            t.p += nbytes;
            got += take;
        }
    }
    return (int64_t)(t.p - in);
}

/* OR bitmap bits [start, stop) (LSB-first). The output bitmaps arrive
 * zeroed and pages never overlap rows, so whole bytes inside the run
 * can be filled outright. */
static inline void bits_fill(uint8_t *bm, int64_t start, int64_t stop) {
    if (start >= stop) return;
    int64_t first = start >> 3, last = (stop - 1) >> 3;
    uint8_t head = (uint8_t)(0xFFu << (start & 7));
    uint8_t tail = (uint8_t)(0xFFu >> (7 - (int)((stop - 1) & 7)));
    if (first == last) {
        bm[first] |= (uint8_t)(head & tail);
        return;
    }
    bm[first] |= head;
    if (last > first + 1)
        memset(bm + first + 1, 0xFF, (size_t)(last - first - 1));
    bm[last] |= tail;
}

/* ---- value stores ---- */

/* Store one source element (parquet physical layout, LE host) into the
 * output at the engine's item size. Truncating narrows go through
 * unsigned intermediates: well-defined modulo arithmetic that preserves
 * the low bits exactly as Arrow's cast-free reinterpretation does. */
static inline void store_cast(uint8_t *dst, const uint8_t *src, int32_t phys,
                              int32_t out_itemsize) {
    if (phys == PT_INT32) {
        uint32_t v;
        memcpy(&v, src, 4);
        if (out_itemsize == 4) {
            memcpy(dst, &v, 4);
        } else if (out_itemsize == 2) {
            uint16_t w = (uint16_t)v;
            memcpy(dst, &w, 2);
        } else {
            uint8_t b = (uint8_t)v;
            dst[0] = b;
        }
    } else if (phys == PT_INT64) {
        uint64_t v;
        memcpy(&v, src, 8);
        if (out_itemsize == 8) {
            memcpy(dst, &v, 8);
        } else {
            uint32_t w = (uint32_t)v;
            memcpy(dst, &w, 4);
        }
    } else if (phys == PT_DOUBLE) {
        memcpy(dst, src, 8);
    } else { /* PT_FLOAT */
        memcpy(dst, src, 4);
    }
}

static inline int phys_itemsize(int32_t phys) {
    switch (phys) {
        case PT_INT32:
        case PT_FLOAT:
            return 4;
        case PT_INT64:
        case PT_DOUBLE:
            return 8;
        default:
            return 0;
    }
}

/* ---- scratch buffer ---- */

typedef struct {
    uint8_t *p;
    int64_t cap;
} buf_t;

static int buf_reserve(buf_t *b, int64_t need) {
    if (need <= b->cap) return 0;
    int64_t cap = b->cap > 0 ? b->cap : 4096;
    while (cap < need) cap *= 2;
    uint8_t *np = (uint8_t *)realloc(b->p, (size_t)cap);
    if (!np) return PQE_ALLOC;
    b->p = np;
    b->cap = cap;
    return 0;
}

/* ---- per-chunk decode state ---- */

typedef struct {
    int32_t phys;
    int32_t out_itemsize;
    int32_t max_def;
    uint8_t *out_values;
    uint8_t *out_validity;
    int64_t row; /* rows emitted so far */
    /* dictionary (physical-layout values) */
    uint8_t *dict;
    int64_t dict_count;
    /* scratch */
    buf_t page;   /* decompressed page body */
    buf_t defs;   /* def levels as u32 */
    buf_t idx;    /* dictionary indices as u32 */
    int64_t bytes_uncompressed;
} chunk_state_t;

/* Decode the def-level block: fills st->defs.p as u32[nv], returns the
 * number of non-null values (def == max_def) or PQE_*. When max_def is
 * 0 there is no def block and all values are present. */
static int64_t decode_defs(chunk_state_t *st, const uint8_t *block,
                           int64_t block_len, int64_t nv) {
    int rc = buf_reserve(&st->defs, nv * (int64_t)sizeof(uint32_t));
    if (rc < 0) return rc;
    uint32_t *defs = (uint32_t *)st->defs.p;
    if (st->max_def == 0) {
        for (int64_t i = 0; i < nv; i++) defs[i] = 1;
        return nv;
    }
    int64_t used = hybrid_u32(block, block_len, 1, nv, defs);
    if (used < 0) return used;
    int64_t nn = 0;
    for (int64_t i = 0; i < nv; i++) {
        if (defs[i] > 1) return PQE_UNSUPPORTED; /* nested — not proven */
        nn += defs[i];
    }
    return nn;
}

/* OR the page's validity bits in run-sized strokes: consecutive
 * non-null rows become one bits_fill instead of a per-value
 * read-modify-write. */
static void fill_validity(chunk_state_t *st, int64_t nv, int64_t nn) {
    if (!st->out_validity || st->max_def == 0) return;
    if (nn == nv) {
        bits_fill(st->out_validity, st->row, st->row + nv);
        return;
    }
    const uint32_t *defs = (const uint32_t *)st->defs.p;
    int64_t i = 0;
    while (i < nv) {
        if (!defs[i]) {
            i++;
            continue;
        }
        int64_t j = i + 1;
        while (j < nv && defs[j]) j++;
        bits_fill(st->out_validity, st->row + i, st->row + j);
        i = j;
    }
}

/* Set validity bits and write values for one page.
 * `nn` non-null values arrive dense; defs spread them over nv rows.
 * Runs of consecutive non-nulls move as one memcpy (same-width) or a
 * branch-free store_cast loop (narrowing), not a per-value branch. */
static int decode_values_plain(chunk_state_t *st, const uint8_t *vals,
                              int64_t vals_len, int64_t nv, int64_t nn) {
    const uint32_t *defs = (const uint32_t *)st->defs.p;
    int src_size = phys_itemsize(st->phys);
    if (src_size == 0) return PQE_UNSUPPORTED;
    if (vals_len < nn * src_size) return PQE_TRUNCATED;
    uint8_t *out = st->out_values + st->row * st->out_itemsize;
    int same = src_size == st->out_itemsize;
    if (nn == nv && same) {
        memcpy(out, vals, (size_t)(nn * src_size));
    } else {
        int64_t i = 0, t = 0;
        while (i < nv) {
            if (nn != nv && !defs[i]) {
                i++;
                continue;
            }
            int64_t j = nn == nv ? nv : i + 1;
            while (j < nv && defs[j]) j++;
            if (same) {
                memcpy(out + i * src_size, vals + t * src_size,
                       (size_t)((j - i) * src_size));
            } else {
                for (int64_t k = i; k < j; k++)
                    store_cast(out + k * st->out_itemsize,
                               vals + (t + (k - i)) * src_size, st->phys,
                               st->out_itemsize);
            }
            t += j - i;
            i = j;
        }
    }
    fill_validity(st, nv, nn);
    return 0;
}

/* PLAIN boolean: non-null values LSB bit-packed; out is an LSB bitmap. */
static int decode_values_plain_bool(chunk_state_t *st, const uint8_t *vals,
                                    int64_t vals_len, int64_t nv, int64_t nn) {
    const uint32_t *defs = (const uint32_t *)st->defs.p;
    if (vals_len < (nn + 7) / 8) return PQE_TRUNCATED;
    int64_t t = 0;
    for (int64_t i = 0; i < nv; i++) {
        if (nn == nv || defs[i]) {
            if ((vals[t >> 3] >> (t & 7)) & 1) {
                int64_t bit = st->row + i;
                st->out_values[bit >> 3] |= (uint8_t)(1u << (bit & 7));
            }
            t++;
        }
    }
    fill_validity(st, nv, nn);
    return 0;
}

/* RLE boolean values (format 2.x): 4-byte LE length prefix + hybrid
 * stream at bit width 1, one value per non-null slot. */
static int decode_values_rle_bool(chunk_state_t *st, const uint8_t *vals,
                                  int64_t vals_len, int64_t nv, int64_t nn) {
    if (vals_len < 4) return PQE_TRUNCATED;
    uint32_t rle_len = (uint32_t)vals[0] | ((uint32_t)vals[1] << 8) |
                       ((uint32_t)vals[2] << 16) | ((uint32_t)vals[3] << 24);
    if ((int64_t)rle_len > vals_len - 4) return PQE_TRUNCATED;
    int rc = buf_reserve(&st->idx, nn * (int64_t)sizeof(uint32_t));
    if (rc < 0) return rc;
    uint32_t *bits = (uint32_t *)st->idx.p;
    int64_t used = hybrid_u32(vals + 4, (int64_t)rle_len, 1, nn, bits);
    if (used < 0) return (int)used;
    const uint32_t *defs = (const uint32_t *)st->defs.p;
    int64_t t = 0;
    for (int64_t i = 0; i < nv; i++) {
        if (nn == nv || defs[i]) {
            if (bits[t]) {
                int64_t bit = st->row + i;
                st->out_values[bit >> 3] |= (uint8_t)(1u << (bit & 7));
            }
            t++;
        }
    }
    fill_validity(st, nv, nn);
    return 0;
}

/* RLE_DICTIONARY / PLAIN_DICTIONARY data page: 1 bit-width byte +
 * hybrid indices, gathered through the dictionary page's values. */
static int decode_values_dict(chunk_state_t *st, const uint8_t *vals,
                              int64_t vals_len, int64_t nv, int64_t nn) {
    if (!st->dict) return PQE_DICT;
    if (vals_len < 1) return PQE_TRUNCATED;
    int bw = vals[0];
    if (bw > 32) return PQE_UNSUPPORTED;
    int rc = buf_reserve(&st->idx, (nn > 0 ? nn : 1) * (int64_t)sizeof(uint32_t));
    if (rc < 0) return rc;
    uint32_t *idx = (uint32_t *)st->idx.p;
    int64_t used = hybrid_u32(vals + 1, vals_len - 1, bw, nn, idx);
    if (used < 0) return (int)used;
    int src_size = phys_itemsize(st->phys);
    if (src_size == 0) return PQE_UNSUPPORTED;
    /* validate every index up front so the gather loops run unchecked */
    uint32_t maxk = 0;
    for (int64_t i = 0; i < nn; i++)
        if (idx[i] > maxk) maxk = idx[i];
    if (nn > 0 && (int64_t)maxk >= st->dict_count) return PQE_DICT;
    const uint32_t *defs = (const uint32_t *)st->defs.p;
    uint8_t *out = st->out_values + st->row * st->out_itemsize;
    int same = src_size == st->out_itemsize;
    int64_t i = 0, t = 0;
    while (i < nv) {
        if (nn != nv && !defs[i]) {
            i++;
            continue;
        }
        int64_t j = nn == nv ? nv : i + 1;
        while (j < nv && defs[j]) j++;
        int64_t run = j - i;
        if (same && src_size == 8) {
            uint8_t *o = out + i * 8;
            for (int64_t k = 0; k < run; k++)
                memcpy(o + k * 8, st->dict + (int64_t)idx[t + k] * 8, 8);
        } else if (same && src_size == 4) {
            uint8_t *o = out + i * 4;
            for (int64_t k = 0; k < run; k++)
                memcpy(o + k * 4, st->dict + (int64_t)idx[t + k] * 4, 4);
        } else {
            for (int64_t k = 0; k < run; k++)
                store_cast(out + (i + k) * st->out_itemsize,
                           st->dict + (int64_t)idx[t + k] * src_size,
                           st->phys, st->out_itemsize);
        }
        t += run;
        i = j;
    }
    fill_validity(st, nv, nn);
    return 0;
}

static int decode_page_values(chunk_state_t *st, int32_t encoding,
                              const uint8_t *vals, int64_t vals_len,
                              int64_t nv, int64_t nn) {
    if (st->phys == PT_BOOLEAN) {
        if (encoding == ENC_PLAIN)
            return decode_values_plain_bool(st, vals, vals_len, nv, nn);
        if (encoding == ENC_RLE)
            return decode_values_rle_bool(st, vals, vals_len, nv, nn);
        return PQE_UNSUPPORTED;
    }
    if (encoding == ENC_PLAIN)
        return decode_values_plain(st, vals, vals_len, nv, nn);
    if (encoding == ENC_RLE_DICT || encoding == ENC_PLAIN_DICT)
        return decode_values_dict(st, vals, vals_len, nv, nn);
    return PQE_UNSUPPORTED;
}

/* ---- entry point ----
 *
 * chunk/chunk_len: the column chunk's byte range (dict page first when
 * present, then data pages back to back).
 * phys: parquet physical type enum. codec: chunk compression codec.
 * out_itemsize: engine dtype width (booleans: out_values is a bitmap).
 * max_def: 0 (required) or 1 (optional). num_values: footer row count.
 * out_values/out_validity: caller-zeroed buffers (validity may be NULL
 * when max_def == 0). out_info: [0]=pages, [1]=uncompressed bytes,
 * [2]=dict entries.
 *
 * Returns the chunk null count (>= 0) or a negative PQE_* error.
 */
int64_t pq_decode_chunk(const uint8_t *chunk, int64_t chunk_len, int32_t phys,
                        int32_t codec, int32_t out_itemsize, int32_t max_def,
                        int64_t num_values, uint8_t *out_values,
                        uint8_t *out_validity, int64_t *out_info) {
    if (!chunk || chunk_len < 0 || !out_values || num_values < 0)
        return PQE_UNSUPPORTED;
    if (max_def < 0 || max_def > 1) return PQE_UNSUPPORTED;
    if (max_def == 1 && !out_validity) return PQE_UNSUPPORTED;
    if (codec != CODEC_NONE && codec != CODEC_SNAPPY && codec != CODEC_ZSTD)
        return PQE_CODEC;
    if (phys != PT_BOOLEAN && phys_itemsize(phys) == 0) return PQE_UNSUPPORTED;

    chunk_state_t st;
    memset(&st, 0, sizeof(st));
    st.phys = phys;
    st.out_itemsize = out_itemsize;
    st.max_def = max_def;
    st.out_values = out_values;
    st.out_validity = out_validity;

    int64_t pages = 0;
    int64_t nulls = 0;
    int64_t rc = 0;
    const uint8_t *p = chunk;
    const uint8_t *chunk_end = chunk + chunk_len;

    while (p < chunk_end && st.row < num_values) {
        tin_t t = {p, chunk_end, 0};
        page_header_t h;
        int hrc = parse_page_header(&t, &h);
        if (hrc < 0) {
            rc = hrc;
            goto done;
        }
        const uint8_t *body = t.p;
        if (chunk_end - body < h.compressed_size) {
            rc = PQE_TRUNCATED;
            goto done;
        }
        p = body + h.compressed_size;
        pages++;

        if (h.page_type == PAGE_INDEX) continue;

        if (h.page_type == PAGE_DICT) {
            if (st.dict) { /* second dictionary page: malformed */
                rc = PQE_DICT;
                goto done;
            }
            if (phys == PT_BOOLEAN ||
                (h.dict_encoding != ENC_PLAIN &&
                 h.dict_encoding != ENC_PLAIN_DICT)) {
                rc = PQE_UNSUPPORTED;
                goto done;
            }
            if (h.dict_num_values < 0) {
                rc = PQE_THRIFT;
                goto done;
            }
            int src_size = phys_itemsize(phys);
            /* divide instead of multiply: dict_num_values * src_size can
             * wrap past int64 on corrupt headers and slip under
             * uncompressed_size. uncompressed_size is already bounded to
             * [0, MAX_PAGE_BYTES] by parse_page_header, so this also caps
             * dict_num_values (and the malloc below) at MAX_PAGE_BYTES. */
            if (h.dict_num_values > h.uncompressed_size / src_size) {
                rc = PQE_SIZE;
                goto done;
            }
            const uint8_t *data;
            if (codec == CODEC_NONE) {
                if (h.compressed_size != h.uncompressed_size) {
                    rc = PQE_SIZE;
                    goto done;
                }
                data = body;
            } else {
                int brc = buf_reserve(&st.page, h.uncompressed_size);
                if (brc < 0) {
                    rc = brc;
                    goto done;
                }
                int drc = pq_decompress(codec, body, h.compressed_size,
                                        st.page.p, h.uncompressed_size);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                data = st.page.p;
            }
            st.dict_count = h.dict_num_values;
            if (st.dict_count > 0) {
                st.dict = (uint8_t *)malloc((size_t)(st.dict_count * src_size));
                if (!st.dict) {
                    rc = PQE_ALLOC;
                    goto done;
                }
                memcpy(st.dict, data, (size_t)(st.dict_count * src_size));
            }
            st.bytes_uncompressed += h.uncompressed_size;
            continue;
        }

        if (h.page_type == PAGE_DATA) {
            if (h.num_values < 0 || h.encoding < 0) {
                rc = PQE_THRIFT;
                goto done;
            }
            int64_t nv = h.num_values;
            if (st.row + nv > num_values) {
                rc = PQE_ROWS;
                goto done;
            }
            const uint8_t *data;
            if (codec == CODEC_NONE) {
                if (h.compressed_size != h.uncompressed_size) {
                    rc = PQE_SIZE;
                    goto done;
                }
                data = body;
            } else {
                int brc = buf_reserve(&st.page, h.uncompressed_size);
                if (brc < 0) {
                    rc = brc;
                    goto done;
                }
                int drc = pq_decompress(codec, body, h.compressed_size,
                                        st.page.p, h.uncompressed_size);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                data = st.page.p;
            }
            int64_t data_len = h.uncompressed_size;
            const uint8_t *vals = data;
            int64_t vals_len = data_len;
            if (max_def > 0) {
                if (h.def_encoding != ENC_RLE) {
                    rc = PQE_UNSUPPORTED;
                    goto done;
                }
                if (data_len < 4) {
                    rc = PQE_TRUNCATED;
                    goto done;
                }
                uint32_t dl = (uint32_t)data[0] | ((uint32_t)data[1] << 8) |
                              ((uint32_t)data[2] << 16) |
                              ((uint32_t)data[3] << 24);
                if ((int64_t)dl > data_len - 4) {
                    rc = PQE_TRUNCATED;
                    goto done;
                }
                int64_t nn = decode_defs(&st, data + 4, (int64_t)dl, nv);
                if (nn < 0) {
                    rc = nn;
                    goto done;
                }
                nulls += nv - nn;
                vals = data + 4 + dl;
                vals_len = data_len - 4 - (int64_t)dl;
                int vrc = decode_page_values(&st, h.encoding, vals, vals_len,
                                             nv, nn);
                if (vrc < 0) {
                    rc = vrc;
                    goto done;
                }
            } else {
                int64_t nn = decode_defs(&st, NULL, 0, nv);
                if (nn < 0) {
                    rc = nn;
                    goto done;
                }
                int vrc = decode_page_values(&st, h.encoding, vals, vals_len,
                                             nv, nn);
                if (vrc < 0) {
                    rc = vrc;
                    goto done;
                }
            }
            st.row += nv;
            st.bytes_uncompressed += h.uncompressed_size;
            continue;
        }

        if (h.page_type == PAGE_DATA_V2) {
            if (h.v2_num_values < 0 || h.v2_encoding < 0 || h.v2_dl_len < 0 ||
                h.v2_rl_len < 0) {
                rc = PQE_THRIFT;
                goto done;
            }
            if (h.v2_rl_len != 0) { /* repeated fields — not proven */
                rc = PQE_UNSUPPORTED;
                goto done;
            }
            int64_t nv = h.v2_num_values;
            if (st.row + nv > num_values) {
                rc = PQE_ROWS;
                goto done;
            }
            int64_t lvl_len = h.v2_dl_len;
            if (lvl_len > h.compressed_size || lvl_len > h.uncompressed_size) {
                rc = PQE_TRUNCATED;
                goto done;
            }
            /* v2: levels sit uncompressed at the front of the body with
             * no length prefix; only the values region is compressed. */
            int64_t nn;
            if (max_def > 0) {
                nn = decode_defs(&st, body, lvl_len, nv);
                if (nn < 0) {
                    rc = nn;
                    goto done;
                }
            } else {
                if (lvl_len != 0) {
                    rc = PQE_UNSUPPORTED;
                    goto done;
                }
                nn = decode_defs(&st, NULL, 0, nv);
                if (nn < 0) {
                    rc = nn;
                    goto done;
                }
            }
            nulls += nv - nn;
            const uint8_t *vsrc = body + lvl_len;
            int64_t vsrc_len = h.compressed_size - lvl_len;
            int64_t vdst_len = h.uncompressed_size - lvl_len;
            if (vdst_len < 0) {
                rc = PQE_SIZE;
                goto done;
            }
            const uint8_t *vals;
            if (h.v2_is_compressed && codec != CODEC_NONE) {
                int brc = buf_reserve(&st.page, vdst_len > 0 ? vdst_len : 1);
                if (brc < 0) {
                    rc = brc;
                    goto done;
                }
                int drc = pq_decompress(codec, vsrc, vsrc_len, st.page.p,
                                        vdst_len);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                vals = st.page.p;
            } else {
                if (vsrc_len != vdst_len) {
                    rc = PQE_SIZE;
                    goto done;
                }
                vals = vsrc;
            }
            int vrc = decode_page_values(&st, h.v2_encoding, vals, vdst_len,
                                         nv, nn);
            if (vrc < 0) {
                rc = vrc;
                goto done;
            }
            st.row += nv;
            st.bytes_uncompressed += h.uncompressed_size;
            continue;
        }

        /* unknown page type */
        rc = PQE_UNSUPPORTED;
        goto done;
    }

    if (st.row != num_values) {
        rc = PQE_ROWS;
        goto done;
    }
    rc = nulls;

done:
    if (out_info) {
        out_info[0] = pages;
        out_info[1] = st.bytes_uncompressed;
        out_info[2] = st.dict_count;
    }
    free(st.dict);
    free(st.page.p);
    free(st.defs.p);
    free(st.idx.p);
    return rc;
}

/* ---- encoded-run output mode ----
 *
 * pq_decode_chunk_runs() walks the same page structure but never
 * expands to row width: dictionary-coded value streams come out as
 * coalesced (run_length, dict_code) pairs and definition levels as
 * (run_length, present) pairs, straight off the RLE/bit-packed hybrid
 * stream. Scope is narrower than pq_decode_chunk on purpose — every
 * data page must be RLE_DICTIONARY/PLAIN_DICTIONARY (a PLAIN data page,
 * e.g. a dictionary fallback mid-chunk, fails closed with
 * PQE_UNSUPPORTED and the Python layer re-decodes at row width).
 * Adjacent equal codes coalesce across page boundaries, so n_runs never
 * exceeds the non-null value count and n_defs never exceeds num_values
 * — the caller sizes the output arrays from the footer row count.
 */

typedef struct {
    int64_t *run_len;  /* coalesced non-null value runs */
    uint32_t *run_code;
    int64_t cap_runs;
    int64_t n_runs;
    int64_t *def_len;  /* coalesced definition-level runs */
    uint8_t *def_val;  /* 0 = null rows, 1 = present rows */
    int64_t cap_defs;
    int64_t n_defs;
    int64_t nn;        /* non-null rows accumulated via defs_push */
} runs_out_t;

static int runs_push(runs_out_t *r, int64_t len, uint32_t code) {
    if (len <= 0) return PQE_THRIFT;
    if (r->n_runs > 0 && r->run_code[r->n_runs - 1] == code) {
        r->run_len[r->n_runs - 1] += len;
        return 0;
    }
    if (r->n_runs >= r->cap_runs) return PQE_SIZE;
    r->run_len[r->n_runs] = len;
    r->run_code[r->n_runs] = code;
    r->n_runs++;
    return 0;
}

static int defs_push(runs_out_t *r, int64_t len, uint32_t val) {
    if (len <= 0) return PQE_THRIFT;
    if (val) r->nn += len;
    if (r->n_defs > 0 && r->def_val[r->n_defs - 1] == (uint8_t)val) {
        r->def_len[r->n_defs - 1] += len;
        return 0;
    }
    if (r->n_defs >= r->cap_defs) return PQE_SIZE;
    r->def_len[r->n_defs] = len;
    r->def_val[r->n_defs] = (uint8_t)val;
    r->n_defs++;
    return 0;
}

/* Decode exactly `count` entries of an RLE/bit-packed hybrid stream as
 * runs. An RLE run becomes one push; bit-packed groups unpack through
 * the same unpack8 the row path uses and push per value (coalescing
 * absorbs repeats). Every value must be < `bound`: dict codes check
 * against the dictionary size (PQE_DICT), def levels against
 * max_def + 1 (PQE_UNSUPPORTED — nested schema, not proven). Returns
 * bytes consumed or PQE_*. */
static int64_t hybrid_to_runs(const uint8_t *in, int64_t in_len, int bw,
                              int64_t count, uint32_t bound, runs_out_t *r,
                              int to_defs) {
    if (bw < 0 || bw > 32) return PQE_UNSUPPORTED;
    if (count == 0) return 0;
    if (bw == 0) {
        if (bound == 0) return to_defs ? PQE_UNSUPPORTED : PQE_DICT;
        int rc = to_defs ? defs_push(r, count, 0) : runs_push(r, count, 0);
        if (rc < 0) return rc;
        return 0;
    }
    tin_t t = {in, in + in_len, 0};
    int64_t got = 0;
    int vbytes = (bw + 7) >> 3;
    while (got < count) {
        uint64_t header = t_uvarint(&t);
        if (t.err) return PQE_TRUNCATED;
        if ((header & 1) == 0) {
            int64_t run = (int64_t)(header >> 1);
            if (run <= 0) return PQE_THRIFT;
            if ((uint64_t)(t.end - t.p) < (uint64_t)vbytes)
                return PQE_TRUNCATED;
            uint32_t v = 0;
            for (int i = 0; i < vbytes; i++) v |= (uint32_t)t.p[i] << (8 * i);
            t.p += vbytes;
            if (bw < 32) v &= (uint32_t)(((uint64_t)1 << bw) - 1);
            if (v >= bound) return to_defs ? PQE_UNSUPPORTED : PQE_DICT;
            int64_t take = run < count - got ? run : count - got;
            int rc = to_defs ? defs_push(r, take, v) : runs_push(r, take, v);
            if (rc < 0) return rc;
            got += take;
        } else {
            int64_t groups = (int64_t)(header >> 1);
            if (groups <= 0) return PQE_THRIFT;
            /* same pre-multiplication bound as hybrid_u32: groups is a
             * raw varint and could overflow nvals/nbytes otherwise */
            if (groups > (int64_t)(t.end - t.p)) return PQE_TRUNCATED;
            int64_t nvals = groups * 8;
            int64_t nbytes = groups * bw;
            if ((int64_t)(t.end - t.p) < nbytes) return PQE_TRUNCATED;
            int64_t take = nvals < count - got ? nvals : count - got;
            const uint8_t *gp = t.p;
            int64_t done = 0;
            while (done < take) {
                uint32_t tmp[8];
                gp = unpack8(gp, bw, tmp);
                int64_t m = take - done < 8 ? take - done : 8;
                for (int64_t i = 0; i < m; i++) {
                    uint32_t v = tmp[i];
                    if (v >= bound)
                        return to_defs ? PQE_UNSUPPORTED : PQE_DICT;
                    int rc = to_defs ? defs_push(r, 1, v) : runs_push(r, 1, v);
                    if (rc < 0) return rc;
                }
                done += m;
            }
            t.p += nbytes;
            got += take;
        }
    }
    return (int64_t)(t.p - in);
}

/* Entry point for the encoded-run mode.
 *
 * chunk/chunk_len, phys, codec, max_def, num_values: as pq_decode_chunk
 * (booleans are out of scope — their pages are not dictionary-coded).
 * out_dict: caller buffer for cap_dict dictionary entries in PHYSICAL
 * layout (phys_itemsize bytes each; a dictionary larger than cap_dict
 * fails with PQE_SIZE so the planner's entry bound is enforced here).
 * run_len/run_code: caller buffers for cap_runs coalesced value runs.
 * def_len/def_val: caller buffers for cap_defs coalesced def runs.
 * out_info: [0]=pages, [1]=uncompressed bytes, [2]=dict entries,
 * [3]=n_runs, [4]=n_defs.
 *
 * Returns the chunk null count (>= 0) or a negative PQE_* error.
 */
int64_t pq_decode_chunk_runs(const uint8_t *chunk, int64_t chunk_len,
                             int32_t phys, int32_t codec, int32_t max_def,
                             int64_t num_values, uint8_t *out_dict,
                             int64_t cap_dict, int64_t *run_len,
                             uint32_t *run_code, int64_t cap_runs,
                             int64_t *def_len, uint8_t *def_val,
                             int64_t cap_defs, int64_t *out_info) {
    if (!chunk || chunk_len < 0 || num_values < 0 || !out_dict || !run_len ||
        !run_code || !def_len || !def_val || cap_dict < 0)
        return PQE_UNSUPPORTED;
    if (max_def < 0 || max_def > 1) return PQE_UNSUPPORTED;
    if (codec != CODEC_NONE && codec != CODEC_SNAPPY && codec != CODEC_ZSTD)
        return PQE_CODEC;
    int src_size = phys_itemsize(phys);
    if (src_size == 0) return PQE_UNSUPPORTED; /* incl. PT_BOOLEAN */

    runs_out_t r;
    memset(&r, 0, sizeof(r));
    r.run_len = run_len;
    r.run_code = run_code;
    r.cap_runs = cap_runs;
    r.def_len = def_len;
    r.def_val = def_val;
    r.cap_defs = cap_defs;

    buf_t page;
    memset(&page, 0, sizeof(page));
    int64_t dict_count = 0;
    int saw_dict = 0;
    int64_t pages = 0;
    int64_t bytes_uncompressed = 0;
    int64_t row = 0;
    int64_t nulls = 0;
    int64_t rc = 0;
    const uint8_t *p = chunk;
    const uint8_t *chunk_end = chunk + chunk_len;

    while (p < chunk_end && row < num_values) {
        tin_t t = {p, chunk_end, 0};
        page_header_t h;
        int hrc = parse_page_header(&t, &h);
        if (hrc < 0) {
            rc = hrc;
            goto done;
        }
        const uint8_t *body = t.p;
        if (chunk_end - body < h.compressed_size) {
            rc = PQE_TRUNCATED;
            goto done;
        }
        p = body + h.compressed_size;
        pages++;

        if (h.page_type == PAGE_INDEX) continue;

        if (h.page_type == PAGE_DICT) {
            if (saw_dict) {
                rc = PQE_DICT;
                goto done;
            }
            if (h.dict_encoding != ENC_PLAIN &&
                h.dict_encoding != ENC_PLAIN_DICT) {
                rc = PQE_UNSUPPORTED;
                goto done;
            }
            if (h.dict_num_values < 0) {
                rc = PQE_THRIFT;
                goto done;
            }
            /* same wrap-proof divide bound as the row path */
            if (h.dict_num_values > h.uncompressed_size / src_size) {
                rc = PQE_SIZE;
                goto done;
            }
            if (h.dict_num_values > cap_dict) {
                rc = PQE_SIZE; /* planner's dictionary-entry bound */
                goto done;
            }
            const uint8_t *data;
            if (codec == CODEC_NONE) {
                if (h.compressed_size != h.uncompressed_size) {
                    rc = PQE_SIZE;
                    goto done;
                }
                data = body;
            } else {
                int brc = buf_reserve(&page, h.uncompressed_size);
                if (brc < 0) {
                    rc = brc;
                    goto done;
                }
                int drc = pq_decompress(codec, body, h.compressed_size,
                                        page.p, h.uncompressed_size);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                data = page.p;
            }
            dict_count = h.dict_num_values;
            saw_dict = 1;
            if (dict_count > 0)
                memcpy(out_dict, data, (size_t)(dict_count * src_size));
            bytes_uncompressed += h.uncompressed_size;
            continue;
        }

        if (h.page_type == PAGE_DATA) {
            if (h.num_values < 0 || h.encoding < 0) {
                rc = PQE_THRIFT;
                goto done;
            }
            if (h.encoding != ENC_RLE_DICT && h.encoding != ENC_PLAIN_DICT) {
                rc = PQE_UNSUPPORTED; /* plain data page: fail closed */
                goto done;
            }
            if (dict_count <= 0) {
                rc = PQE_DICT;
                goto done;
            }
            int64_t nv = h.num_values;
            if (row + nv > num_values) {
                rc = PQE_ROWS;
                goto done;
            }
            const uint8_t *data;
            if (codec == CODEC_NONE) {
                if (h.compressed_size != h.uncompressed_size) {
                    rc = PQE_SIZE;
                    goto done;
                }
                data = body;
            } else {
                int brc = buf_reserve(&page, h.uncompressed_size);
                if (brc < 0) {
                    rc = brc;
                    goto done;
                }
                int drc = pq_decompress(codec, body, h.compressed_size,
                                        page.p, h.uncompressed_size);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                data = page.p;
            }
            int64_t data_len = h.uncompressed_size;
            const uint8_t *vals = data;
            int64_t vals_len = data_len;
            int64_t nn = nv;
            if (max_def > 0) {
                if (h.def_encoding != ENC_RLE) {
                    rc = PQE_UNSUPPORTED;
                    goto done;
                }
                if (data_len < 4) {
                    rc = PQE_TRUNCATED;
                    goto done;
                }
                uint32_t dl = (uint32_t)data[0] | ((uint32_t)data[1] << 8) |
                              ((uint32_t)data[2] << 16) |
                              ((uint32_t)data[3] << 24);
                if ((int64_t)dl > data_len - 4) {
                    rc = PQE_TRUNCATED;
                    goto done;
                }
                int64_t nn_before = r.nn;
                int64_t drc = hybrid_to_runs(data + 4, (int64_t)dl, 1, nv,
                                             (uint32_t)(max_def + 1), &r, 1);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                nn = r.nn - nn_before;
                vals = data + 4 + dl;
                vals_len = data_len - 4 - (int64_t)dl;
            } else {
                int drc = defs_push(&r, nv, 1);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
            }
            nulls += nv - nn;
            if (vals_len < 1) {
                rc = PQE_TRUNCATED;
                goto done;
            }
            int bw = vals[0];
            int64_t vrc = hybrid_to_runs(vals + 1, vals_len - 1, bw, nn,
                                         (uint32_t)dict_count, &r, 0);
            if (vrc < 0) {
                rc = vrc;
                goto done;
            }
            row += nv;
            bytes_uncompressed += h.uncompressed_size;
            continue;
        }

        if (h.page_type == PAGE_DATA_V2) {
            if (h.v2_num_values < 0 || h.v2_encoding < 0 || h.v2_dl_len < 0 ||
                h.v2_rl_len < 0) {
                rc = PQE_THRIFT;
                goto done;
            }
            if (h.v2_rl_len != 0) {
                rc = PQE_UNSUPPORTED;
                goto done;
            }
            if (h.v2_encoding != ENC_RLE_DICT &&
                h.v2_encoding != ENC_PLAIN_DICT) {
                rc = PQE_UNSUPPORTED;
                goto done;
            }
            if (dict_count <= 0) {
                rc = PQE_DICT;
                goto done;
            }
            int64_t nv = h.v2_num_values;
            if (row + nv > num_values) {
                rc = PQE_ROWS;
                goto done;
            }
            int64_t lvl_len = h.v2_dl_len;
            if (lvl_len > h.compressed_size || lvl_len > h.uncompressed_size) {
                rc = PQE_TRUNCATED;
                goto done;
            }
            int64_t nn = nv;
            if (max_def > 0) {
                int64_t nn_before = r.nn;
                int64_t drc = hybrid_to_runs(body, lvl_len, 1, nv,
                                             (uint32_t)(max_def + 1), &r, 1);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                nn = r.nn - nn_before;
            } else {
                if (lvl_len != 0) {
                    rc = PQE_UNSUPPORTED;
                    goto done;
                }
                int drc = defs_push(&r, nv, 1);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
            }
            nulls += nv - nn;
            const uint8_t *vsrc = body + lvl_len;
            int64_t vsrc_len = h.compressed_size - lvl_len;
            int64_t vdst_len = h.uncompressed_size - lvl_len;
            if (vdst_len < 0) {
                rc = PQE_SIZE;
                goto done;
            }
            const uint8_t *vals;
            if (h.v2_is_compressed && codec != CODEC_NONE) {
                int brc = buf_reserve(&page, vdst_len > 0 ? vdst_len : 1);
                if (brc < 0) {
                    rc = brc;
                    goto done;
                }
                int drc = pq_decompress(codec, vsrc, vsrc_len, page.p,
                                        vdst_len);
                if (drc < 0) {
                    rc = drc;
                    goto done;
                }
                vals = page.p;
            } else {
                if (vsrc_len != vdst_len) {
                    rc = PQE_SIZE;
                    goto done;
                }
                vals = vsrc;
            }
            if (vdst_len < 1) {
                rc = PQE_TRUNCATED;
                goto done;
            }
            int bw = vals[0];
            int64_t vrc = hybrid_to_runs(vals + 1, vdst_len - 1, bw, nn,
                                         (uint32_t)dict_count, &r, 0);
            if (vrc < 0) {
                rc = vrc;
                goto done;
            }
            row += nv;
            bytes_uncompressed += h.uncompressed_size;
            continue;
        }

        rc = PQE_UNSUPPORTED;
        goto done;
    }

    if (row != num_values) {
        rc = PQE_ROWS;
        goto done;
    }
    rc = nulls;

done:
    if (out_info) {
        out_info[0] = pages;
        out_info[1] = bytes_uncompressed;
        out_info[2] = dict_count;
        out_info[3] = r.n_runs;
        out_info[4] = r.n_defs;
    }
    free(page.p);
    return rc;
}

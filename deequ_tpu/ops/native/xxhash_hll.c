/* Native host kernels for the scan hot path.
 *
 * The placement engine folds discrete analyzers on the host when the
 * device link is slow (ops/runtime.py:placement_mode); the one host stage
 * that is not a single vectorized numpy reduction is HLL hashing: xxhash64
 * per row plus register index/rank extraction. numpy needs ~15 passes over
 * the buffer for that; this C loop does it in one pass at memory speed.
 *
 * Same semantics as the vectorized numpy path (ops/sketches/hll.py):
 * xxhash64 of the 8-byte value with seed 42, idx = top P bits, rank =
 * 1 + leading zeros of the remainder (capped for a 6-bit register) —
 * the same parameters as the reference kernel
 * (reference: catalyst/StatefulHyperloglogPlus.scala:86-155, p=9 from
 * RELATIVE_SD=0.05, 512 registers).
 */

#include <math.h>
#include <stdint.h>
#include <stddef.h>

#define P 9
#define SEED 42ULL

static const uint64_t PRIME1 = 0x9E3779B185EBCA87ULL;
static const uint64_t PRIME2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t PRIME3 = 0x165667B19E3779F9ULL;
static const uint64_t PRIME4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t PRIME5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t xxhash64_u64(uint64_t v) {
    uint64_t acc = v * PRIME2;
    acc = rotl64(acc, 31);
    acc *= PRIME1;
    acc ^= SEED + PRIME5 + 8ULL;
    acc = rotl64(acc, 27);
    acc *= PRIME1;
    acc += PRIME4;
    acc ^= acc >> 33;
    acc *= PRIME2;
    acc ^= acc >> 29;
    acc *= PRIME3;
    acc ^= acc >> 32;
    return acc;
}

/* packed[i] = (register_idx << 6) | rank for valid rows, 0 otherwise.
 * values: canonical 8-byte representation per row (int64 buffer). */
void xxhash64_pack(const int64_t *values, const uint8_t *valid, int64_t n,
                   int32_t *packed) {
    const int max_rank = 64 - P + 1;
    for (int64_t i = 0; i < n; i++) {
        if (!valid[i]) {
            packed[i] = 0;
            continue;
        }
        uint64_t h = xxhash64_u64((uint64_t)values[i]);
        int32_t idx = (int32_t)(h >> (64 - P));
        uint64_t rest = (h << P) | (1ULL << (P - 1));
        int rank = 1 + __builtin_clzll(rest);
        if (rank > max_rank) rank = max_rank;
        packed[i] = (idx << 6) | rank;
    }
}

/* register scatter-max over packed codes (the host fold of the HLL
 * reduce): regs must hold 1 << P int32 slots. where==NULL means all rows. */
void hll_update_registers(const int32_t *packed, const uint8_t *where,
                          int64_t n, int32_t *regs) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int32_t code = packed[i];
        int32_t idx = code >> 6;
        int32_t rank = code & 0x3F;
        if (rank > regs[idx]) regs[idx] = rank;
    }
}

/* Dense-code bincount: out[codes[i] + base]++ for in-range codes, one
 * pass with no shifted-copy temporary (numpy's bincount(codes + 1)
 * allocates an n-row temp and re-casts). The host fold of the group-by
 * count the reference runs as groupBy().agg(count)
 * (reference: GroupingAnalyzers.scala:67-72). where==NULL means all
 * rows; out must hold nbins slots (caller-zeroed). */
void bincount_i64(const int64_t *codes, const uint8_t *where, int64_t n,
                  int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Same for int32 codes (arrow dictionary indices stay int32 end-to-end:
 * upcasting 4M codes to int64 per batch costs a copy plus 2x bincount
 * read traffic). */
void bincount_i32(const int32_t *codes, const uint8_t *where, int64_t n,
                  int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = (int64_t)codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Same for narrow codes (type-class codes, int8 wire formats). */
void bincount_i8(const int8_t *codes, const uint8_t *where, int64_t n,
                 int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = (int64_t)codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Windowed dense value counting for integer columns: counts[v - lo]++
 * for rows passing the masks whose value lies in [lo, lo + nbins).
 * Returns via meta: [0] = count of valid&where rows in-window,
 * [1] = count of where rows (n when where == NULL), [2] = 1 when any
 * valid&where value fell OUTSIDE the window (the pass aborts
 * immediately: the caller falls back to the select kernel, so a
 * speculative window on a wide-range column costs only the prefix it
 * scanned). One such pass replaces a whole family-kernel radix select
 * for low-range integer columns (the counts table answers moments,
 * decimated quantile sample, HLL registers and value histogram in
 * O(nbins) — see ops/fused.py counts fast path). */
void bincount_window_i64(const int64_t *v, const uint8_t *valid,
                         const uint8_t *where, int64_t n, int64_t lo,
                         int64_t nbins, int64_t *counts, int64_t *meta) {
    int64_t count = 0, n_where = 0;
    meta[0] = 0;
    meta[1] = where ? 0 : n;
    meta[2] = 0;
    for (int64_t i = 0; i < n; i++) {
        if (where) {
            if (!where[i]) continue;
            n_where++;
        }
        if (valid && !valid[i]) continue;
        /* unsigned subtraction: defined wraparound even at int64 extremes */
        uint64_t idx = (uint64_t)v[i] - (uint64_t)lo;
        if (idx >= (uint64_t)nbins) {
            meta[2] = 1;
            return;
        }
        counts[idx]++;
        count++;
    }
    meta[0] = count;
    if (where) meta[1] = n_where;
}

/* Open-addressing distinct-value counter over raw 8-byte keys (float64
 * bit patterns or int64 values — the same canonical identity HLL
 * hashes). counts[slot]==0 marks an empty slot, so keys[] needs no
 * sentinel and ANY bit pattern (including +0.0 == all-zero bits) is a
 * valid key. Returns the number of distinct keys, or -1 the moment the
 * table would exceed max_distinct — a high-cardinality column aborts
 * after seeing ~max_distinct distinct values (typically a small prefix
 * of the data), so speculatively probing every column is cheap. The
 * caller allocates keys[1<<cap2_log] / counts[1<<cap2_log] zeroed;
 * choose 1<<cap2_log >= 2*max_distinct so the load factor stays <= 0.5.
 * A skew guard bounds the worst case (a column whose distinct count
 * sits just above the cap with the tail appearing late, e.g. Zipf):
 * once probe_rows rows are scanned, a table already 3/4 full aborts —
 * heavy-tailed near-cap columns bail after a bounded prefix instead of
 * scanning almost everything before the inevitable overflow. Columns
 * rejected by the guard merely fall back to the select kernel.
 * On success the counts table answers the whole numeric family in
 * O(#distinct) (ops/counts_family.py) — this extends the windowed
 * integer fast path to LOW-CARDINALITY FLOAT columns (discount/tax/
 * rate-style data) and sparse wide-range integers. */
int64_t hashcount_u64(const uint64_t *x, const uint8_t *valid,
                      const uint8_t *where, int64_t n, int64_t cap2_log,
                      int64_t max_distinct, int64_t probe_rows,
                      uint64_t *keys, int64_t *counts, int64_t *meta) {
    uint64_t mask = ((uint64_t)1 << cap2_log) - 1;
    int64_t distinct = 0, count = 0, n_where = 0;
    int64_t guard_distinct = max_distinct - (max_distinct >> 2);
    meta[0] = 0;
    meta[1] = where ? 0 : n;
    for (int64_t i = 0; i < n; i++) {
        if (probe_rows > 0 && i == probe_rows && distinct >= guard_distinct)
            return -1;
        if (where) {
            if (!where[i]) continue;
            n_where++;
        }
        if (valid && !valid[i]) continue;
        uint64_t k = x[i];
        uint64_t h = xxhash64_u64(k) & mask;
        for (;;) {
            if (counts[h] == 0) {
                if (distinct >= max_distinct) return -1;
                distinct++;
                keys[h] = k;
                counts[h] = 1;
                break;
            }
            if (keys[h] == k) {
                counts[h]++;
                break;
            }
            h = (h + 1) & mask;
        }
        count++;
    }
    meta[0] = count;
    if (where) meta[1] = n_where;
    return distinct;
}

/* Fused masked numeric moments: one data traversal feeds Mean, Sum,
 * Minimum, Maximum, StandardDeviation and the count of a whole
 * (column, where) family — the reductions the reference pushes into one
 * Catalyst pass (reference: runners/AnalysisRunner.scala:279-326) need
 * ~15 separate numpy passes host-side; this does two cache-friendly
 * passes (sum/min/max, then centered m2 at the batch mean — the same
 * centering the device kernel uses, StatefulStdDevPop semantics).
 *
 * valid/where may each be NULL (= all rows). Long-double accumulators
 * keep sequential summation within 1e-15 of numpy's pairwise sums.
 * out[6]: count, sum, min (+inf when empty), max (-inf), m2, n_where. */
void masked_moments(const double *x, const uint8_t *valid,
                    const uint8_t *where, int64_t n, double *out) {
    long double sum = 0.0L;
    int64_t count = 0, n_where = 0;
    double mn = (double)INFINITY, mx = -(double)INFINITY;
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        n_where++;
        if (valid && !valid[i]) continue;
        double v = x[i];
        sum += v;
        count++;
        if (v < mn) mn = v;
        if (v > mx) mx = v;
    }
    double avg = count > 0 ? (double)(sum / count) : 0.0;
    long double m2 = 0.0L;
    if (count > 0) {
        for (int64_t i = 0; i < n; i++) {
            if (valid && !valid[i]) continue;
            if (where && !where[i]) continue;
            double d = x[i] - avg;
            m2 += d * d;
        }
    }
    out[0] = (double)count;
    out[1] = (double)sum;
    out[2] = mn;
    out[3] = mx;
    out[4] = (double)m2;
    out[5] = where ? (double)n_where : (double)n;
}

/* ---------------------------------------------------------------------
 * Masked select-decimate: the per-batch heavy step of the quantile
 * sketch (analyzers/sketch.py device_batch). Computes EXACTLY
 *
 *     xm = sorted(x[valid & where]); xm[stride/2 :: stride][:cap]
 *     with stride = 2^level, level = ceil(log2(n_valid / cap))
 *
 * i.e. `cap` evenly spaced order statistics — WITHOUT sorting the whole
 * batch. The role this plays is the reference's per-partition quantile
 * digest update (reference: catalyst/StatefulApproxQuantile.scala:28).
 *
 * Method: map doubles to order-preserving uint64 keys and run an MSD
 * radix SELECT: histogram the keys on the most significant varying bits
 * (16 at the top level, 8 below), locate each wanted rank's bucket via
 * prefix sums, then gather and recurse ONLY into buckets that own a
 * wanted rank. Buckets whose min==max key are constant and resolve
 * without gathering (low-cardinality columns stay O(n)); segments
 * below 48 keys use insertion sort. IEEE exponent clustering (the case
 * that defeats single-level top-bit bucketing) just recurses one level
 * deeper into the mantissa bits.
 *
 * All large buffers come from a THREAD-LOCAL grow-only arena: repeated
 * calls (one per column per batch) reuse warm pages instead of paying
 * ~8k page faults per fresh 32MB malloc (measured: that was half the
 * kernel's wall time). Bounded by the largest batch ever processed per
 * thread.
 *
 * Determinism: key order equals IEEE total order on doubles (with -0.0
 * before +0.0 and NaN last; equal doubles are interchangeable in the
 * decimated sample, so the result matches the numpy sort path).
 *
 * Returns 0 on success (meta = [n_valid, level, kept], samples[kept]
 * filled), 1 on allocation failure (caller falls back to numpy). */

#include <stdlib.h>
#include <string.h>

#define SD_MAX_DEPTH 16
#define SD_TOP_BUCKETS 16384

/* arena slots: 0 = keys, 1 = top-level tables, 2+d = scratch at depth d */
/* slots: 0 = keys/gather scratch, 1 = top tables, 2+d = recursion
 * scratch at depth d, 18..23 = entry-point planning tables,
 * 24..27 = multi-column batch state (masked_moments_select_multi) */
#define SD_SLOT_MC_COLS (2 + SD_MAX_DEPTH + 6)
#define SD_SLOT_MC_TOPS (SD_SLOT_MC_COLS + 1)
#define SD_SLOT_MC_SUBIDX (SD_SLOT_MC_COLS + 2)
#define SD_SLOT_MC_PLANS (SD_SLOT_MC_COLS + 3)
#define SD_SLOT_MC_SUBHIST (SD_SLOT_MC_COLS + 4)
#define SD_SLOT_MC_SUBFILL (SD_SLOT_MC_COLS + 5)
#define SD_SLOT_MC_DIRECT (SD_SLOT_MC_COLS + 6)
#define SD_ARENA_SLOTS (2 + SD_MAX_DEPTH + 6 + 7)
static __thread struct { void *p; size_t cap; } sd_arena[SD_ARENA_SLOTS];

static void *sd_get(int slot, size_t bytes) {
    if (sd_arena[slot].cap < bytes) {
        free(sd_arena[slot].p);
        size_t ncap = bytes + bytes / 2 + 64;
        sd_arena[slot].p = malloc(ncap);
        sd_arena[slot].cap = sd_arena[slot].p ? ncap : 0;
    }
    return sd_arena[slot].p;
}

static inline uint64_t f64_key(double v) {
    uint64_t u;
    memcpy(&u, &v, 8);
    return (u >> 63) ? ~u : (u | 0x8000000000000000ULL);
}

static inline double key_f64(uint64_t k) {
    uint64_t u = (k >> 63) ? (k & 0x7FFFFFFFFFFFFFFFULL) : ~k;
    double v;
    memcpy(&v, &u, 8);
    return v;
}

static void ins_sort_u64(uint64_t *a, int64_t n) {
    for (int64_t i = 1; i < n; i++) {
        uint64_t v = a[i];
        int64_t j = i - 1;
        while (j >= 0 && a[j] > v) {
            a[j + 1] = a[j];
            j--;
        }
        a[j + 1] = v;
    }
}

/* Resolve wanted ranks r_j = roff + j*step (j in [j0, j1), all with
 * 0 <= r_j < m) against the UNSORTED keys[0..m) whose min/max are
 * kmin/kmax. Writes samples[j]. May permute keys. */
static int resolve_segment(uint64_t *keys, int64_t m, uint64_t kmin,
                           uint64_t kmax, int64_t roff, int64_t step,
                           int64_t j0, int64_t j1, double *samples,
                           int depth) {
    if (j0 >= j1) return 0;
    if (kmin == kmax) {
        double v = key_f64(kmin);
        for (int64_t j = j0; j < j1; j++) samples[j] = v;
        return 0;
    }
    if (m <= 48 || depth + 1 >= SD_MAX_DEPTH) {
        ins_sort_u64(keys, m);
        for (int64_t j = j0; j < j1; j++)
            samples[j] = key_f64(keys[roff + j * step]);
        return 0;
    }

    int width = depth == 0 ? 16 : 8;
    int hb = 63 - __builtin_clzll(kmin ^ kmax);
    int shift = hb + 1 - width;
    if (shift < 0) shift = 0;
    uint64_t base = kmin >> shift;
    int64_t nbuckets = (int64_t)((kmax >> shift) - base) + 1;

    /* tables: stack at depth >= 1 (<= 256 buckets), arena at the top */
    uint32_t hist_stack[256];
    uint64_t bmin_stack[256], bmax_stack[256];
    int64_t cstart_stack[256], cfill_stack[256];
    uint32_t *hist;
    uint64_t *bmin, *bmax;
    int64_t *cstart, *cfill;
    if (nbuckets <= 256) {
        hist = hist_stack;
        bmin = bmin_stack;
        bmax = bmax_stack;
        cstart = cstart_stack;
        cfill = cfill_stack;
    } else {
        char *tables = (char *)sd_get(
            1, (size_t)nbuckets * (4 + 8 + 8 + 8 + 8));
        if (!tables) return 1;
        hist = (uint32_t *)tables;
        bmin = (uint64_t *)(tables + (size_t)nbuckets * 4);
        bmax = bmin + nbuckets;
        cstart = (int64_t *)(bmax + nbuckets);
        cfill = cstart + nbuckets;
    }
    memset(hist, 0, (size_t)nbuckets * 4);
    memset(bmin, 0xFF, (size_t)nbuckets * 8);
    memset(bmax, 0x00, (size_t)nbuckets * 8);

    for (int64_t i = 0; i < m; i++) {
        uint64_t k = keys[i];
        int64_t b = (int64_t)((k >> shift) - base);
        hist[b]++;
        if (k < bmin[b]) bmin[b] = k;
        if (k > bmax[b]) bmax[b] = k;
    }

    /* walk buckets in key order; resolve constant ones, mark the rest */
    int64_t collect_total = 0;
    {
        int64_t rank0 = 0;
        for (int64_t b = 0; b < nbuckets; b++) {
            int64_t c = (int64_t)hist[b];
            cstart[b] = -1;
            if (c > 0) {
                int64_t jlo =
                    (roff + j0 * step < rank0)
                        ? j0 + (rank0 - roff - j0 * step + step - 1) / step
                        : j0;
                if (jlo < j1 && roff + jlo * step < rank0 + c) {
                    if (bmin[b] == bmax[b]) {
                        double v = key_f64(bmin[b]);
                        for (int64_t j = jlo;
                             j < j1 && roff + j * step < rank0 + c; j++)
                            samples[j] = v;
                    } else {
                        cstart[b] = collect_total;
                        collect_total += c;
                    }
                }
                rank0 += c;
            }
        }
    }

    int rc = 0;
    if (collect_total > 0) {
        uint64_t *scratch =
            (uint64_t *)sd_get(2 + depth, (size_t)collect_total * 8);
        if (!scratch) return 1;
        memcpy(cfill, cstart, (size_t)nbuckets * 8);
        for (int64_t i = 0; i < m; i++) {
            uint64_t k = keys[i];
            int64_t b = (int64_t)((k >> shift) - base);
            if (cstart[b] >= 0) scratch[cfill[b]++] = k;
        }
        int64_t rank0 = 0;
        for (int64_t b = 0; b < nbuckets && rc == 0; b++) {
            int64_t c = (int64_t)hist[b];
            if (c > 0) {
                if (cstart[b] >= 0) {
                    int64_t jlo =
                        (roff + j0 * step < rank0)
                            ? j0 + (rank0 - roff - j0 * step + step - 1) / step
                            : j0;
                    int64_t jhi = jlo;
                    while (jhi < j1 && roff + jhi * step < rank0 + c) jhi++;
                    /* shift == 0 with bmin != bmax is impossible (the
                     * bucket id is then the full key), so recursion
                     * always has bits left to split on */
                    rc = resolve_segment(scratch + cstart[b], c, bmin[b],
                                         bmax[b], roff - rank0, step, jlo,
                                         jhi, samples, depth + 1);
                }
                rank0 += c;
            }
        }
    }
    return rc;
}

/* Entry point. Three direct masked passes over x (no key-buffer
 * materialization for the common case):
 *   P1: fixed 16-bit-prefix histogram + per-bucket min/max key
 *   P2: 8-bit count-only sub-histograms for buckets owning wanted ranks
 *   P3: gather only the sub-buckets owning wanted ranks
 * then resolve each gathered sub-bucket with resolve_segment (insertion
 * sort when tiny, recursion when an adversarial distribution concentrates
 * a sub-bucket). Constant buckets short-circuit at both levels. The rare
 * all-keys-share-top-16-bits case compacts keys and uses the adaptive
 * recursive path directly. */

#define SD_TOP_SHIFT 50
#define SD_SUB_BITS 8
#define SD_SUB_W (1 << SD_SUB_BITS)

static inline int sd_masked_out(const uint8_t *valid, const uint8_t *where,
                                int64_t i) {
    return (valid && !valid[i]) || (where && !where[i]);
}

/* core: select-decimate, optionally accumulating the masked-moments
 * family outputs [count, sum, min, max, m2, n_where] into mom (NULL =
 * skip) — the moments ride P1/P2's traversals instead of paying their
 * own two passes (ops/native masked_moments). hll_mode additionally
 * folds the HLL++ register update into P1 (the reference's
 * StatefulHyperloglogPlus per-row loop): 0 = off, 1 = hash the f64 bit
 * pattern of x[i] (float columns' canonical identity), 2 = hash
 * hashvals[i] (caller-supplied canonical int64 per row — int/bool
 * columns, whose identity is the integer value, not the float bits).
 * regs must hold 1 << P int32 slots (caller-zeroed). */
/* P1 bucket record: one 24-byte struct per bucket (single cache line
 * per update); 14-bit top level keeps the whole table L2-resident. */
typedef struct {
    uint64_t mn, mx;
    uint32_t cnt, pad;
} SdTop;

/* per planned bucket: its gather area offset (sizes known from P1).
 * subofs/subw serve only the multi-column kernel's adaptive sub level
 * (unused by sd_core). */
typedef struct {
    int64_t rank0, jlo, jhi, gofs, fill;
    uint64_t kmin, kmax;
    int64_t subofs;
    int32_t subw, pad;
} SdPlan;

static int sd_core(const double *x, const uint8_t *valid,
                   const uint8_t *where, int64_t n, int64_t cap,
                   double *samples, int64_t *meta, double *mom,
                   const int64_t *hashvals, int hll_mode, int32_t *regs) {
    if (cap <= 0) return 1;

    /* ---- P1: top histogram + per-bucket min/max + global min/max ---- */
    SdTop *top = (SdTop *)sd_get(1, (size_t)SD_TOP_BUCKETS * sizeof(SdTop));
    if (!top) return 1;
    for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
        top[b].mn = ~0ULL;
        top[b].mx = 0ULL;
        top[b].cnt = 0;
    }

    int64_t m = 0, n_where = 0;
    uint64_t kmin = ~0ULL, kmax = 0ULL;
    /* block accumulation: the inner 2048-element partial runs in SSE
     * doubles (an x87 long-double add per row serializes the loop); the
     * outer fold stays long double, so total error ~ pairwise-summation
     * class, comfortably inside the 1e-12 parity tests */
    long double sum = 0.0L;
    double bsum = 0.0;
    int bn = 0;
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        n_where++;
        if (valid && !valid[i]) continue;
        uint64_t k = f64_key(x[i]);
        SdTop *t = &top[k >> SD_TOP_SHIFT];
        m++;
        t->cnt++;
        if (k < t->mn) t->mn = k;
        if (k > t->mx) t->mx = k;
        if (k < kmin) kmin = k;
        if (k > kmax) kmax = k;
        if (mom) {
            bsum += x[i];
            if (++bn == 2048) {
                sum += bsum;
                bsum = 0.0;
                bn = 0;
            }
        }
        if (hll_mode) {
            uint64_t canon;
            if (hll_mode == 1) {
                memcpy(&canon, &x[i], 8);
            } else {
                canon = (uint64_t)hashvals[i];
            }
            uint64_t h = xxhash64_u64(canon);
            int32_t idx = (int32_t)(h >> (64 - P));
            uint64_t rest = (h << P) | (1ULL << (P - 1));
            int rank = 1 + __builtin_clzll(rest);
            if (rank > 64 - P + 1) rank = 64 - P + 1;
            if (rank > regs[idx]) regs[idx] = rank;
        }
    }
    if (mom) {
        sum += bsum;
        mom[0] = (double)m;
        mom[1] = (double)sum;
        mom[2] = m > 0 ? key_f64(kmin) : (double)INFINITY;
        mom[3] = m > 0 ? key_f64(kmax) : -(double)INFINITY;
        mom[4] = 0.0; /* m2 filled below */
        mom[5] = where ? (double)n_where : (double)n;
    }
    meta[0] = m;
    meta[1] = 0;
    meta[2] = 0;
    if (m == 0) return 0;

    int level = 0;
    while (((int64_t)cap << level) < m) level++;
    int64_t stride = 1LL << level;
    int64_t offset = stride / 2;
    int64_t kept = (m - offset + stride - 1) / stride;
    if (kept < 0) kept = 0;
    meta[1] = level;
    meta[2] = kept;
    if (kept == 0) return 0;

    if (kmin == kmax) {
        double v = key_f64(kmin);
        for (int64_t j = 0; j < kept; j++) samples[j] = v;
        return 0;
    }
    if ((kmin >> SD_TOP_SHIFT) == (kmax >> SD_TOP_SHIFT)) {
        /* all keys share the top 16 bits: compact and go adaptive */
        uint64_t *keys = (uint64_t *)sd_get(0, (size_t)m * 8);
        if (!keys) return 1;
        int64_t w = 0;
        for (int64_t i = 0; i < n; i++) {
            if (sd_masked_out(valid, where, i)) continue;
            keys[w++] = f64_key(x[i]);
        }
        if (mom) {
            long double m2 = 0.0L;
            double avg = mom[1] / (double)m;
            for (int64_t i = 0; i < m; i++) {
                double d = key_f64(keys[i]) - avg;
                m2 += d * d;
            }
            mom[4] = (double)m2;
        }
        return resolve_segment(keys, m, kmin, kmax, offset, stride, 0, kept,
                               samples, 0);
    }

    /* ---- walk top buckets: resolve constant ones, plan the rest ----- */
    int32_t *subidx = (int32_t *)sd_get(18, (size_t)SD_TOP_BUCKETS * 4);
    if (!subidx) return 1;
    memset(subidx, 0xFF, (size_t)SD_TOP_BUCKETS * 4);
    int32_t nplanned = 0;
    SdPlan *plans = (SdPlan *)sd_get(19, (size_t)kept * sizeof(SdPlan));
    if (!plans) return 1;
    int64_t gather_total = 0;
    {
        int64_t rank0 = 0;
        for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
            int64_t c = (int64_t)top[b].cnt;
            if (c == 0) continue;
            int64_t jlo = (offset < rank0)
                              ? (rank0 - offset + stride - 1) / stride
                              : 0;
            if (jlo < kept && offset + jlo * stride < rank0 + c) {
                if (top[b].mn == top[b].mx) {
                    double v = key_f64(top[b].mn);
                    for (int64_t j = jlo;
                         j < kept && offset + j * stride < rank0 + c; j++)
                        samples[j] = v;
                } else {
                    int64_t jhi = jlo;
                    while (jhi < kept && offset + jhi * stride < rank0 + c)
                        jhi++;
                    SdPlan *p = &plans[nplanned];
                    p->rank0 = rank0;
                    p->jlo = jlo;
                    p->jhi = jhi;
                    p->gofs = gather_total;
                    p->fill = gather_total;
                    p->kmin = top[b].mn;
                    p->kmax = top[b].mx;
                    gather_total += c;
                    subidx[b] = nplanned++;
                }
            }
            rank0 += c;
        }
    }

    long double m2acc = 0.0L;
    double bm2 = 0.0;
    int bm2n = 0;
    double avg = mom && m > 0 ? mom[1] / (double)m : 0.0;
    if (nplanned == 0) {
        /* every wanted bucket was constant; m2 still needs a pass */
        if (mom && m > 0) {
            for (int64_t i = 0; i < n; i++) {
                if (sd_masked_out(valid, where, i)) continue;
                double d = x[i] - avg;
                bm2 += d * d;
                if (++bm2n == 2048) {
                    m2acc += bm2;
                    bm2 = 0.0;
                    bm2n = 0;
                }
            }
            m2acc += bm2;
            mom[4] = (double)m2acc;
        }
        return 0;
    }

    /* ---- P2: gather planned buckets' keys whole (sizes known from
     * P1), m2 riding the same pass; each plan's contiguous segment is
     * then resolved by the recursive radix select, whose histograms run
     * over the (cache-friendly) gathered data instead of a third full
     * scan of x ------------------------------------------------------ */
    uint64_t *scratch = (uint64_t *)sd_get(0, (size_t)gather_total * 8);
    if (!scratch) return 1;
    for (int64_t i = 0; i < n; i++) {
        if (sd_masked_out(valid, where, i)) continue;
        uint64_t k = f64_key(x[i]);
        int32_t si = subidx[k >> SD_TOP_SHIFT];
        if (si >= 0) scratch[plans[si].fill++] = k;
        if (mom) {
            double d = x[i] - avg;
            bm2 += d * d;
            if (++bm2n == 2048) {
                m2acc += bm2;
                bm2 = 0.0;
                bm2n = 0;
            }
        }
    }
    if (mom) {
        m2acc += bm2;
        mom[4] = (double)m2acc;
    }

    /* ---- resolve each plan's gathered segment ----------------------- */
    for (int32_t s = 0; s < nplanned; s++) {
        SdPlan *sg = &plans[s];
        int rc = resolve_segment(scratch + sg->gofs, sg->fill - sg->gofs,
                                 sg->kmin, sg->kmax, offset - sg->rank0,
                                 stride, sg->jlo, sg->jhi, samples, 1);
        if (rc) return rc;
    }
    return 0;
}

int masked_select_decimate(const double *x, const uint8_t *valid,
                           const uint8_t *where, int64_t n, int64_t cap,
                           double *samples, int64_t *meta) {
    return sd_core(x, valid, where, n, cap, samples, meta, NULL, NULL, 0,
                   NULL);
}

/* Combined family kernel: moments + decimated quantile sample in the
 * same traversals. mom = [count, sum, min, max, m2, n_where] (the
 * masked_moments contract); samples/meta as masked_select_decimate. */
int masked_moments_select(const double *x, const uint8_t *valid,
                          const uint8_t *where, int64_t n, int64_t cap,
                          double *samples, int64_t *meta, double *mom,
                          const int64_t *hashvals, int hll_mode,
                          int32_t *regs) {
    return sd_core(x, valid, where, n, cap, samples, meta, mom, hashvals,
                   hll_mode, regs);
}

/* =====================================================================
 * Multi-column batched family kernel.
 *
 * One row-blocked traversal computes the full fused-moment family
 * (count/sum/min/max/m2/n_where), the decimated quantile sample, and
 * optional HLL registers for K columns at once: a block of rows is
 * processed across all K columns before advancing, so the shared where
 * mask and loop machinery are paid once per block instead of once per
 * column-pass, and per-column call overhead disappears.
 *
 * Bit-exactness contract: every accumulation below replicates sd_core's
 * order exactly — the 2048-valid-row f64 partial folded into a long
 * double (block boundaries counted in *valid rows per column*, which is
 * invariant to how rows are blocked), per-row masking order (where
 * before n_where before valid), the compact-prefix path's unblocked
 * long-double m2 over compacted keys, and resolve_segment on gathered
 * segments. The parity tests assert the outputs are bit-identical to K
 * independent masked_moments_select calls.
 * ================================================================== */

#define SD_MC_BLOCK 4096 /* rows per tile; multiple of the 2048 fold */
#define SD_MC_TABLE_BUDGET (1 << 19) /* per-chunk sub-table cap, bytes */
/* Planned buckets at or under this count skip the count-then-gather
 * machinery entirely: their keys are gathered wholesale DURING the P2
 * m2 pass (sd_core's per-bucket strategy) and resolved straight from
 * the gathered segment, so a column whose every planned bucket is
 * small — the common case for spread-out keys, where a bucket holds
 * n/16384-ish rows — never pays the third full-row scan (P3). Only a
 * pathologically skewed bucket above the threshold keeps the
 * sub-histogram + selective-gather route, where counting first prunes
 * the gathered volume by roughly the stride factor. */
#define SD_MC_DIRECT_MAX 4096

typedef struct {
    const double *x;
    const uint8_t *valid;    /* NULL = all rows valid */
    const int64_t *hashvals; /* hll_mode 2 canonical values */
    int32_t *regs;
    SdTop *top;
    int hll_mode;
    int done; /* column fully resolved; no P2 work left */
    int64_t m, n_where;
    uint64_t kmin, kmax;
    long double sum; /* outer fold */
    double bsum;     /* 2048-row inner partial (sd_core order) */
    int bn;
    double avg;
    long double m2acc;
    double bm2;
    int bm2n;
    int32_t *subidx;
    SdPlan *plans;
    int32_t nplanned;
    int32_t *subhist;  /* per-plan adaptive-width sub counters */
    int64_t *subfill;  /* parallel gather cursors, -1 = skip */
    uint64_t *scratch; /* chunk-shared gather area (subfill indexes it) */
    uint64_t *direct;  /* chunk-shared direct-gather area (P2-filled) */
    int64_t gather_total;
    int64_t direct_total; /* keys across this column's direct plans */
    int64_t ndirect;      /* direct (subw == 0) plan count */
    int64_t subentries; /* sum of 1 << subw over this column's plans
                         * (a direct plan contributes its 1 cursor) */
    int64_t offset, stride, kept;
} SdMCol;

/* sub-bucket of key k within plan p: the next subw bits below the
 * top-bucket prefix */
static inline int64_t sd_mc_sub(uint64_t k, const SdPlan *p) {
    return (int64_t)((k >> (SD_TOP_SHIFT - p->subw)) &
                     ((1ULL << p->subw) - 1));
}

/* P1 over rows [i0, i1): exact clone of sd_core's P1 body, minus the
 * per-row global kmin/kmax update — the global extrema are recovered
 * exactly from the per-bucket mn/mx at finalize (the bucket minima ARE
 * the keys, so min-over-buckets == min-over-rows bit for bit). */
static void mc_p1_block(SdMCol *s, const uint8_t *where, int64_t i0,
                        int64_t i1) {
    const double *x = s->x;
    const uint8_t *valid = s->valid;
    SdTop *top = s->top;
    for (int64_t i = i0; i < i1; i++) {
        if (where && !where[i]) continue;
        s->n_where++;
        if (valid && !valid[i]) continue;
        uint64_t k = f64_key(x[i]);
        SdTop *t = &top[k >> SD_TOP_SHIFT];
        s->m++;
        t->cnt++;
        if (k < t->mn) t->mn = k;
        if (k > t->mx) t->mx = k;
        s->bsum += x[i];
        if (++s->bn == 2048) {
            s->sum += s->bsum;
            s->bsum = 0.0;
            s->bn = 0;
        }
        if (s->hll_mode) {
            uint64_t canon;
            if (s->hll_mode == 1) {
                memcpy(&canon, &x[i], 8);
            } else {
                canon = (uint64_t)s->hashvals[i];
            }
            uint64_t h = xxhash64_u64(canon);
            int32_t idx = (int32_t)(h >> (64 - P));
            uint64_t rest = (h << P) | (1ULL << (P - 1));
            int rank = 1 + __builtin_clzll(rest);
            if (rank > 64 - P + 1) rank = 64 - P + 1;
            if (rank > s->regs[idx]) s->regs[idx] = rank;
        }
    }
}

/* P1 fast path: no masks, no HLL, branchless key transform. */
static void mc_p1_block_fast(SdMCol *s, int64_t i0, int64_t i1) {
    const double *x = s->x;
    SdTop *top = s->top;
    double bsum = s->bsum;
    int bn = s->bn;
    for (int64_t i = i0; i < i1; i++) {
        double v = x[i];
        uint64_t u;
        memcpy(&u, &v, 8);
        uint64_t k = u ^ ((uint64_t)((int64_t)u >> 63) | 0x8000000000000000ULL);
        SdTop *t = &top[k >> SD_TOP_SHIFT];
        t->cnt++;
        if (k < t->mn) t->mn = k;
        if (k > t->mx) t->mx = k;
        bsum += v;
        if (++bn == 2048) {
            s->sum += bsum;
            bsum = 0.0;
            bn = 0;
        }
    }
    s->m += i1 - i0;
    s->bsum = bsum;
    s->bn = bn;
}

/* P1 fast path, four columns per row iteration. Each column keeps its
 * own sequential bsum chain (bit-identical per-column order), but the
 * four independent FP-add chains overlap in the pipeline — on this
 * latency-bound loop that is where the multi-column win comes from.
 * All four columns are all-valid, so their 2048-row fold counters are
 * always equal and one shared bn drives all four folds. */
static void mc_p1_block_fast4(SdMCol *s0, SdMCol *s1, SdMCol *s2, SdMCol *s3,
                              int64_t i0, int64_t i1) {
    const double *x0 = s0->x, *x1 = s1->x, *x2 = s2->x, *x3 = s3->x;
    SdTop *t0 = s0->top, *t1 = s1->top, *t2 = s2->top, *t3 = s3->top;
    double b0 = s0->bsum, b1 = s1->bsum, b2 = s2->bsum, b3 = s3->bsum;
    int bn = s0->bn;
    for (int64_t i = i0; i < i1; i++) {
#define MC_P1_ONE(xv, tt, bs)                                                \
    do {                                                                     \
        double v = (xv)[i];                                                  \
        uint64_t u;                                                          \
        memcpy(&u, &v, 8);                                                   \
        uint64_t k =                                                         \
            u ^ ((uint64_t)((int64_t)u >> 63) | 0x8000000000000000ULL);      \
        SdTop *t = &(tt)[k >> SD_TOP_SHIFT];                                 \
        t->cnt++;                                                            \
        if (k < t->mn) t->mn = k;                                            \
        if (k > t->mx) t->mx = k;                                            \
        (bs) += v;                                                           \
    } while (0)
        MC_P1_ONE(x0, t0, b0);
        MC_P1_ONE(x1, t1, b1);
        MC_P1_ONE(x2, t2, b2);
        MC_P1_ONE(x3, t3, b3);
#undef MC_P1_ONE
        if (++bn == 2048) {
            s0->sum += b0;
            s1->sum += b1;
            s2->sum += b2;
            s3->sum += b3;
            b0 = b1 = b2 = b3 = 0.0;
            bn = 0;
        }
    }
    int64_t cnt = i1 - i0;
    s0->m += cnt;
    s1->m += cnt;
    s2->m += cnt;
    s3->m += cnt;
    s0->bsum = b0;
    s1->bsum = b1;
    s2->bsum = b2;
    s3->bsum = b3;
    s0->bn = s1->bn = s2->bn = s3->bn = bn;
}

/* After P1: fold the tail partial, publish moments/meta, and either
 * finish the column outright (empty / constant / compact-prefix — the
 * latter pays its own compaction pass, as sd_core does) or plan the
 * P2 gather. Mirrors sd_core line for line. */
static int mc_finalize_p1(SdMCol *s, const uint8_t *where, int64_t n,
                          int64_t cap, double *samples, int64_t *meta,
                          double *mom) {
    s->sum += s->bsum;
    s->bsum = 0.0;
    s->bn = 0;
    /* global extrema from the bucket extrema: exact (bucket mn/mx are
     * actual keys), and cheaper than a per-row compare pair in P1 */
    for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
        if (!s->top[b].cnt) continue;
        if (s->top[b].mn < s->kmin) s->kmin = s->top[b].mn;
        if (s->top[b].mx > s->kmax) s->kmax = s->top[b].mx;
    }
    int64_t m = s->m;
    mom[0] = (double)m;
    mom[1] = (double)s->sum;
    mom[2] = m > 0 ? key_f64(s->kmin) : (double)INFINITY;
    mom[3] = m > 0 ? key_f64(s->kmax) : -(double)INFINITY;
    mom[4] = 0.0;
    mom[5] = where ? (double)s->n_where : (double)n;
    meta[0] = m;
    meta[1] = 0;
    meta[2] = 0;
    s->done = 1;
    if (m == 0) return 0;

    int level = 0;
    while (((int64_t)cap << level) < m) level++;
    int64_t stride = 1LL << level;
    int64_t offset = stride / 2;
    int64_t kept = (m - offset + stride - 1) / stride;
    if (kept < 0) kept = 0;
    meta[1] = level;
    meta[2] = kept;
    if (kept == 0) return 0;
    s->stride = stride;
    s->offset = offset;
    s->kept = kept;

    if (s->kmin == s->kmax) {
        double v = key_f64(s->kmin);
        for (int64_t j = 0; j < kept; j++) samples[j] = v;
        return 0;
    }
    s->avg = mom[1] / (double)m;
    if ((s->kmin >> SD_TOP_SHIFT) == (s->kmax >> SD_TOP_SHIFT)) {
        /* all keys share the top 16 bits: compact and go adaptive */
        uint64_t *keys = (uint64_t *)sd_get(0, (size_t)m * 8);
        if (!keys) return 1;
        int64_t w = 0;
        for (int64_t i = 0; i < n; i++) {
            if (sd_masked_out(s->valid, where, i)) continue;
            keys[w++] = f64_key(s->x[i]);
        }
        {
            long double m2 = 0.0L;
            double avg = s->avg;
            for (int64_t i = 0; i < m; i++) {
                double d = key_f64(keys[i]) - avg;
                m2 += d * d;
            }
            mom[4] = (double)m2;
        }
        return resolve_segment(keys, m, s->kmin, s->kmax, offset, stride, 0,
                               kept, samples, 0);
    }

    /* walk top buckets: resolve constant ones, plan the rest. Each
     * plan's sub level gets an adaptive width: enough bits that its
     * sub-buckets hold ~128 keys, so the per-column sub tables are
     * bounded by ~m/128 entries no matter how the keys distribute, and
     * sub-buckets are fine enough (vs the rank stride) for the P3
     * gather to actually prune. */
    memset(s->subidx, 0xFF, (size_t)SD_TOP_BUCKETS * 4);
    s->nplanned = 0;
    s->gather_total = 0;
    s->direct_total = 0;
    s->ndirect = 0;
    s->subentries = 0;
    {
        int64_t rank0 = 0;
        for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
            int64_t c = (int64_t)s->top[b].cnt;
            if (c == 0) continue;
            int64_t jlo = (offset < rank0)
                              ? (rank0 - offset + stride - 1) / stride
                              : 0;
            if (jlo < kept && offset + jlo * stride < rank0 + c) {
                if (s->top[b].mn == s->top[b].mx) {
                    double v = key_f64(s->top[b].mn);
                    for (int64_t j = jlo;
                         j < kept && offset + j * stride < rank0 + c; j++)
                        samples[j] = v;
                } else {
                    int64_t jhi = jlo;
                    while (jhi < kept && offset + jhi * stride < rank0 + c)
                        jhi++;
                    SdPlan *p = &s->plans[s->nplanned];
                    p->rank0 = rank0;
                    p->jlo = jlo;
                    p->jhi = jhi;
                    p->kmin = s->top[b].mn;
                    p->kmax = s->top[b].mx;
                    if (c <= SD_MC_DIRECT_MAX) {
                        /* direct: gathered whole during P2; gofs/fill
                         * carry the column-local region offset/size */
                        p->subw = 0;
                        p->gofs = s->direct_total;
                        p->fill = c;
                        s->direct_total += c;
                        s->ndirect++;
                        p->subofs = s->subentries;
                        s->subentries += 1; /* its gather cursor slot */
                    } else {
                        int32_t w = 4;
                        while (w < 16 && (c >> w) > 64) w++;
                        p->subw = w;
                        p->subofs = s->subentries;
                        s->subentries += (int64_t)1 << w;
                    }
                    s->subidx[b] = s->nplanned++;
                }
            }
            rank0 += c;
        }
    }
    /* nplanned == 0 still needs the P2 m2 pass (sd_core's "every wanted
     * bucket was constant" branch) — the P2 block handles both shapes */
    s->done = 0;
    return 0;
}

/* P2 over rows [i0, i1): blocked m2 (sd_core's exact fold order) plus,
 * per planned bucket, EITHER a wholesale gather (direct plans, count
 * <= SD_MC_DIRECT_MAX — sd_core's strategy, resolved straight from the
 * segment with no third scan) OR adaptive-width sub-histogram counting
 * (big plans), where counting first lets P3 gather only the
 * sub-buckets that own wanted ranks, shrinking the gathered volume
 * (and the resolve work on it) by roughly the stride factor. The
 * selected sample values are exact order statistics either way. */
static void mc_p2_block(SdMCol *s, const uint8_t *where, int64_t i0,
                        int64_t i1) {
    const double *x = s->x;
    const uint8_t *valid = s->valid;
    double avg = s->avg;
    double bm2 = s->bm2;
    int bm2n = s->bm2n;
    if (s->nplanned > 0) {
        int32_t *subidx = s->subidx;
        int32_t *subhist = s->subhist;
        int64_t *subfill = s->subfill;
        uint64_t *direct = s->direct;
        const SdPlan *plans = s->plans;
        for (int64_t i = i0; i < i1; i++) {
            if (sd_masked_out(valid, where, i)) continue;
            uint64_t k = f64_key(x[i]);
            int32_t si = subidx[k >> SD_TOP_SHIFT];
            if (si >= 0) {
                const SdPlan *p = &plans[si];
                if (p->subw)
                    subhist[p->subofs + sd_mc_sub(k, p)]++;
                else
                    direct[subfill[p->subofs]++] = k;
            }
            double d = x[i] - avg;
            bm2 += d * d;
            if (++bm2n == 2048) {
                s->m2acc += bm2;
                bm2 = 0.0;
                bm2n = 0;
            }
        }
    } else {
        for (int64_t i = i0; i < i1; i++) {
            if (sd_masked_out(valid, where, i)) continue;
            double d = x[i] - avg;
            bm2 += d * d;
            if (++bm2n == 2048) {
                s->m2acc += bm2;
                bm2 = 0.0;
                bm2n = 0;
            }
        }
    }
    s->bm2 = bm2;
    s->bm2n = bm2n;
}

/* P2 fast path: no masks, branchless key transform. */
static void mc_p2_block_fast(SdMCol *s, int64_t i0, int64_t i1) {
    const double *x = s->x;
    double avg = s->avg;
    double bm2 = s->bm2;
    int bm2n = s->bm2n;
    int32_t *subidx = s->subidx;
    int32_t *subhist = s->subhist;
    int64_t *subfill = s->subfill;
    uint64_t *direct = s->direct;
    const SdPlan *plans = s->plans;
    int counting = s->nplanned > 0;
    for (int64_t i = i0; i < i1; i++) {
        double v = x[i];
        if (counting) {
            uint64_t u;
            memcpy(&u, &v, 8);
            uint64_t k =
                u ^ ((uint64_t)((int64_t)u >> 63) | 0x8000000000000000ULL);
            int32_t si = subidx[k >> SD_TOP_SHIFT];
            if (si >= 0) {
                const SdPlan *p = &plans[si];
                if (p->subw)
                    subhist[p->subofs + sd_mc_sub(k, p)]++;
                else
                    direct[subfill[p->subofs]++] = k;
            }
        }
        double d = v - avg;
        bm2 += d * d;
        if (++bm2n == 2048) {
            s->m2acc += bm2;
            bm2 = 0.0;
            bm2n = 0;
        }
    }
    s->bm2 = bm2;
    s->bm2n = bm2n;
}

/* P2 fast path, four columns per row iteration (see mc_p1_block_fast4:
 * independent bm2 chains overlap; shared fold counter is valid because
 * every column sees every row). */
static void mc_p2_block_fast4(SdMCol *s0, SdMCol *s1, SdMCol *s2, SdMCol *s3,
                              int64_t i0, int64_t i1) {
    const double *x0 = s0->x, *x1 = s1->x, *x2 = s2->x, *x3 = s3->x;
    double a0 = s0->avg, a1 = s1->avg, a2 = s2->avg, a3 = s3->avg;
    double m0 = s0->bm2, m1 = s1->bm2, m2 = s2->bm2, m3 = s3->bm2;
    int g0 = s0->nplanned > 0, g1 = s1->nplanned > 0, g2 = s2->nplanned > 0,
        g3 = s3->nplanned > 0;
    int bm2n = s0->bm2n;
    for (int64_t i = i0; i < i1; i++) {
#define MC_P2_ONE(ss, xv, av, bm, gg)                                        \
    do {                                                                     \
        double v = (xv)[i];                                                  \
        if (gg) {                                                            \
            uint64_t u;                                                      \
            memcpy(&u, &v, 8);                                               \
            uint64_t k =                                                     \
                u ^ ((uint64_t)((int64_t)u >> 63) | 0x8000000000000000ULL);  \
            int32_t si = (ss)->subidx[k >> SD_TOP_SHIFT];                    \
            if (si >= 0) {                                                   \
                const SdPlan *p = &(ss)->plans[si];                          \
                if (p->subw)                                                 \
                    (ss)->subhist[p->subofs + sd_mc_sub(k, p)]++;            \
                else                                                         \
                    (ss)->direct[(ss)->subfill[p->subofs]++] = k;            \
            }                                                                \
        }                                                                    \
        double d = v - (av);                                                 \
        (bm) += d * d;                                                       \
    } while (0)
        MC_P2_ONE(s0, x0, a0, m0, g0);
        MC_P2_ONE(s1, x1, a1, m1, g1);
        MC_P2_ONE(s2, x2, a2, m2, g2);
        MC_P2_ONE(s3, x3, a3, m3, g3);
#undef MC_P2_ONE
        if (++bm2n == 2048) {
            s0->m2acc += m0;
            s1->m2acc += m1;
            s2->m2acc += m2;
            s3->m2acc += m3;
            m0 = m1 = m2 = m3 = 0.0;
            bm2n = 0;
        }
    }
    s0->bm2 = m0;
    s1->bm2 = m1;
    s2->bm2 = m2;
    s3->bm2 = m3;
    s0->bm2n = s1->bm2n = s2->bm2n = s3->bm2n = bm2n;
}

/* Between P2 and P3: walk each plan's sub-counters in key order,
 * decide which sub-buckets own wanted ranks, and assign their gather
 * cursors in the chunk-shared scratch (subfill; -1 = not gathered).
 * Same rank arithmetic as the entry-level planning loop, one radix
 * level down. Returns the updated chunk gather cursor. */
static int64_t mc_plan_subs(SdMCol *s, int64_t chunk_gofs) {
    int64_t offset = s->offset, stride = s->stride, kept = s->kept;
    s->gather_total = 0;
    for (int32_t p = 0; p < s->nplanned; p++) {
        const SdPlan *pl = &s->plans[p];
        if (pl->subw == 0) {
            /* direct plan: P2 already gathered it; park the cursor at
             * -1 so the P3 gather skips it (resolve recomputes the
             * segment from gofs/fill) */
            s->subfill[pl->subofs] = -1;
            continue;
        }
        int64_t rank0 = pl->rank0;
        int64_t nsub = (int64_t)1 << pl->subw;
        int32_t *hist = s->subhist + pl->subofs;
        int64_t *fill = s->subfill + pl->subofs;
        for (int64_t sub = 0; sub < nsub; sub++) {
            int64_t c = (int64_t)hist[sub];
            fill[sub] = -1;
            if (c == 0) continue;
            int64_t jlo = (offset < rank0)
                              ? (rank0 - offset + stride - 1) / stride
                              : 0;
            if (jlo < kept && offset + jlo * stride < rank0 + c) {
                fill[sub] = chunk_gofs;
                chunk_gofs += c;
                s->gather_total += c;
            }
            rank0 += c;
        }
    }
    return chunk_gofs;
}

/* P3 over rows [i0, i1): gather keys of wanted sub-buckets only. */
static void mc_p3_block(SdMCol *s, const uint8_t *where, int64_t i0,
                        int64_t i1) {
    const double *x = s->x;
    const uint8_t *valid = s->valid;
    int32_t *subidx = s->subidx;
    int64_t *subfill = s->subfill;
    const SdPlan *plans = s->plans;
    uint64_t *scratch = s->scratch;
    for (int64_t i = i0; i < i1; i++) {
        if (sd_masked_out(valid, where, i)) continue;
        uint64_t k = f64_key(x[i]);
        int32_t si = subidx[k >> SD_TOP_SHIFT];
        if (si < 0) continue;
        const SdPlan *p = &plans[si];
        int64_t *g = &subfill[p->subofs + sd_mc_sub(k, p)];
        if (*g >= 0) scratch[(*g)++] = k;
    }
}

/* P3 fast path: no masks. */
static void mc_p3_block_fast(SdMCol *s, int64_t i0, int64_t i1) {
    const double *x = s->x;
    int32_t *subidx = s->subidx;
    int64_t *subfill = s->subfill;
    const SdPlan *plans = s->plans;
    uint64_t *scratch = s->scratch;
    for (int64_t i = i0; i < i1; i++) {
        double v = x[i];
        uint64_t u;
        memcpy(&u, &v, 8);
        uint64_t k = u ^ ((uint64_t)((int64_t)u >> 63) | 0x8000000000000000ULL);
        int32_t si = subidx[k >> SD_TOP_SHIFT];
        if (si < 0) continue;
        const SdPlan *p = &plans[si];
        int64_t *g = &subfill[p->subofs + sd_mc_sub(k, p)];
        if (*g >= 0) scratch[(*g)++] = k;
    }
}

/* After P3: resolve each gathered sub-segment. Walks subs in the same
 * key order as mc_plan_subs, so each wanted sub's segment is
 * [subfill - count, subfill) in the chunk scratch. Segment min/max are
 * scanned from the gathered keys (exact: they ARE the keys). */
static int mc_resolve_subs(SdMCol *s, double *samples) {
    int64_t offset = s->offset, stride = s->stride, kept = s->kept;
    for (int32_t p = 0; p < s->nplanned; p++) {
        const SdPlan *pl = &s->plans[p];
        if (pl->subw == 0) {
            /* direct plan: the whole bucket sits at gofs in the
             * column's direct region; its extrema are the P1 bucket
             * extrema (actual keys), and depth 1 matches sd_core's
             * top-segment resolve */
            int rc = resolve_segment(s->direct + pl->gofs, pl->fill,
                                     pl->kmin, pl->kmax,
                                     offset - pl->rank0, stride, pl->jlo,
                                     pl->jhi, samples, 1);
            if (rc) return rc;
            continue;
        }
        int64_t rank0 = pl->rank0;
        int64_t nsub = (int64_t)1 << pl->subw;
        int32_t *hist = s->subhist + pl->subofs;
        int64_t *fill = s->subfill + pl->subofs;
        for (int64_t sub = 0; sub < nsub; sub++) {
            int64_t c = (int64_t)hist[sub];
            if (c == 0) continue;
            if (fill[sub] >= 0) {
                uint64_t *seg = s->scratch + (fill[sub] - c);
                uint64_t smin = ~0ULL, smax = 0ULL;
                for (int64_t i = 0; i < c; i++) {
                    if (seg[i] < smin) smin = seg[i];
                    if (seg[i] > smax) smax = seg[i];
                }
                int64_t jlo = (offset < rank0)
                                  ? (rank0 - offset + stride - 1) / stride
                                  : 0;
                int64_t jhi = jlo;
                while (jhi < kept && offset + jhi * stride < rank0 + c) jhi++;
                int rc = resolve_segment(seg, c, smin, smax, offset - rank0,
                                         stride, jlo, jhi, samples, 2);
                if (rc) return rc;
            }
            rank0 += c;
        }
    }
    return 0;
}

/* Entry point. xs[c] are K same-length f64 columns; valids[c] may be
 * NULL (all valid); where is shared across columns (NULL = all rows).
 * samples is ncols*cap, meta ncols*3, mom ncols*6; hashvals[c] feeds
 * hll_modes[c] == 2; regs is ncols*(1<<P) caller-zeroed int32 (may be
 * NULL when every hll_modes[c] == 0). Output layout per column c is
 * identical to masked_moments_select. Returns nonzero on allocation
 * failure (outputs then unspecified — caller falls back per-column). */
int masked_moments_select_multi(const double **xs, const uint8_t **valids,
                                const uint8_t *where, int64_t n,
                                int64_t ncols, int64_t cap, double *samples,
                                int64_t *meta, double *mom,
                                const int64_t **hashvals,
                                const int32_t *hll_modes, int32_t *regs) {
    if (cap <= 0 || ncols <= 0 || n < 0) return 1;
    SdMCol *cols =
        (SdMCol *)sd_get(SD_SLOT_MC_COLS, (size_t)ncols * sizeof(SdMCol));
    SdTop *tops = (SdTop *)sd_get(
        SD_SLOT_MC_TOPS, (size_t)ncols * SD_TOP_BUCKETS * sizeof(SdTop));
    int32_t *subidx = (int32_t *)sd_get(SD_SLOT_MC_SUBIDX,
                                        (size_t)ncols * SD_TOP_BUCKETS * 4);
    /* kept <= cap always (cap << level >= m), so cap plans per column */
    SdPlan *plans = (SdPlan *)sd_get(
        SD_SLOT_MC_PLANS, (size_t)ncols * (size_t)cap * sizeof(SdPlan));
    if (!cols || !tops || !subidx || !plans) return 1;

    for (int64_t c = 0; c < ncols; c++) {
        SdMCol *s = &cols[c];
        memset(s, 0, sizeof(SdMCol));
        s->x = xs[c];
        s->valid = valids ? valids[c] : NULL;
        s->hll_mode = hll_modes ? (int)hll_modes[c] : 0;
        s->hashvals = hashvals ? hashvals[c] : NULL;
        s->regs = regs ? regs + (size_t)c * (1 << P) : NULL;
        if (!s->regs || (s->hll_mode == 2 && !s->hashvals)) s->hll_mode = 0;
        s->top = tops + (size_t)c * SD_TOP_BUCKETS;
        s->subidx = subidx + (size_t)c * SD_TOP_BUCKETS;
        s->plans = plans + (size_t)c * cap;
        s->kmin = ~0ULL;
        s->kmax = 0ULL;
        for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
            s->top[b].mn = ~0ULL;
            s->top[b].mx = 0ULL;
            s->top[b].cnt = 0;
        }
    }

    /* index scratch: fast / generic partitions + pending list */
    int64_t *idxbuf = (int64_t *)malloc((size_t)ncols * 8 * 3);
    if (!idxbuf) return 1;
    int64_t *fastc = idxbuf;
    int64_t *genc = idxbuf + ncols;
    int64_t *pend = idxbuf + 2 * ncols;

    /* ---- P1, row-blocked across columns; unmasked no-HLL columns run
     * the quad fast path (four interleaved accumulation chains) ---- */
    int64_t nfast = 0, ngen = 0;
    for (int64_t c = 0; c < ncols; c++) {
        SdMCol *s = &cols[c];
        if (!s->valid && !where && !s->hll_mode)
            fastc[nfast++] = c;
        else
            genc[ngen++] = c;
    }
    for (int64_t i0 = 0; i0 < n; i0 += SD_MC_BLOCK) {
        int64_t i1 = i0 + SD_MC_BLOCK;
        if (i1 > n) i1 = n;
        int64_t f = 0;
        for (; f + 4 <= nfast; f += 4)
            mc_p1_block_fast4(&cols[fastc[f]], &cols[fastc[f + 1]],
                              &cols[fastc[f + 2]], &cols[fastc[f + 3]], i0,
                              i1);
        for (; f < nfast; f++) mc_p1_block_fast(&cols[fastc[f]], i0, i1);
        for (int64_t g = 0; g < ngen; g++)
            mc_p1_block(&cols[genc[g]], where, i0, i1);
    }

    /* ---- per-column finalize: moments out, P2 plans in ---- */
    for (int64_t c = 0; c < ncols; c++) {
        int rc = mc_finalize_p1(&cols[c], where, n, cap,
                                samples + (size_t)c * cap, meta + c * 3,
                                mom + c * 6);
        if (rc) {
            free(idxbuf);
            return rc;
        }
    }

    /* ---- P2 (sub-hist count + m2) / P3 (sparse gather) / resolve,
     * row-blocked, chunked so the per-plan sub tables stay under
     * budget (at least one column per chunk) ---- */
    int64_t npend = 0;
    for (int64_t c = 0; c < ncols; c++)
        if (!cols[c].done) pend[npend++] = c;

    int64_t pi = 0;
    while (pi < npend) {
        int64_t pj = pi;
        int64_t tentries = 0;
        int64_t tdirect = 0;
        int64_t tcost = 0;
        while (pj < npend) {
            SdMCol *sc = &cols[pend[pj]];
            /* a direct plan's hot write set is its cursor plus the one
             * cache line being appended to — count it as a line, not
             * its whole (sequentially written) region */
            int64_t cost = sc->subentries * 12 + sc->ndirect * 64;
            if (pj > pi && tcost + cost > SD_MC_TABLE_BUDGET) break;
            tcost += cost;
            tentries += sc->subentries;
            tdirect += sc->direct_total;
            pj++;
        }
        int32_t *subhist = NULL;
        int64_t *subfill = NULL;
        if (tentries > 0) {
            subhist =
                (int32_t *)sd_get(SD_SLOT_MC_SUBHIST, (size_t)tentries * 4);
            subfill =
                (int64_t *)sd_get(SD_SLOT_MC_SUBFILL, (size_t)tentries * 8);
            if (!subhist || !subfill) {
                free(idxbuf);
                return 1;
            }
            memset(subhist, 0, (size_t)tentries * 4);
        }
        uint64_t *direct_buf = NULL;
        if (tdirect > 0) {
            direct_buf =
                (uint64_t *)sd_get(SD_SLOT_MC_DIRECT, (size_t)tdirect * 8);
            if (!direct_buf) {
                free(idxbuf);
                return 1;
            }
        }
        int64_t eofs = 0;
        int64_t dofs = 0;
        nfast = 0;
        ngen = 0;
        for (int64_t p = pi; p < pj; p++) {
            SdMCol *s = &cols[pend[p]];
            s->subhist = subhist + eofs;
            s->subfill = subfill + eofs;
            eofs += s->subentries;
            /* column-shifted base: cursors stay column-local (gofs) */
            s->direct = direct_buf ? direct_buf + dofs : NULL;
            dofs += s->direct_total;
            for (int32_t q = 0; q < s->nplanned; q++) {
                const SdPlan *pl = &s->plans[q];
                if (pl->subw == 0) s->subfill[pl->subofs] = pl->gofs;
            }
            if (!s->valid && !where)
                fastc[nfast++] = pend[p];
            else
                genc[ngen++] = pend[p];
        }
        for (int64_t i0 = 0; i0 < n; i0 += SD_MC_BLOCK) {
            int64_t i1 = i0 + SD_MC_BLOCK;
            if (i1 > n) i1 = n;
            int64_t f = 0;
            for (; f + 4 <= nfast; f += 4)
                mc_p2_block_fast4(&cols[fastc[f]], &cols[fastc[f + 1]],
                                  &cols[fastc[f + 2]], &cols[fastc[f + 3]],
                                  i0, i1);
            for (; f < nfast; f++) mc_p2_block_fast(&cols[fastc[f]], i0, i1);
            for (int64_t g = 0; g < ngen; g++)
                mc_p2_block(&cols[genc[g]], where, i0, i1);
        }
        int64_t chunk_g = 0;
        for (int64_t p = pi; p < pj; p++) {
            SdMCol *s = &cols[pend[p]];
            int64_t c = pend[p];
            s->m2acc += s->bm2;
            s->bm2 = 0.0;
            mom[c * 6 + 4] = (double)s->m2acc;
            if (s->nplanned > 0) chunk_g = mc_plan_subs(s, chunk_g);
        }
        if (chunk_g > 0) {
            /* only columns with an above-threshold plan gather here;
             * direct plans were gathered during P2 */
            uint64_t *scratch = (uint64_t *)sd_get(0, (size_t)chunk_g * 8);
            if (!scratch) {
                free(idxbuf);
                return 1;
            }
            nfast = 0;
            ngen = 0;
            for (int64_t p = pi; p < pj; p++) {
                SdMCol *s = &cols[pend[p]];
                s->scratch = scratch;
                if (s->gather_total <= 0) continue;
                if (!s->valid && !where)
                    fastc[nfast++] = pend[p];
                else
                    genc[ngen++] = pend[p];
            }
            for (int64_t i0 = 0; i0 < n; i0 += SD_MC_BLOCK) {
                int64_t i1 = i0 + SD_MC_BLOCK;
                if (i1 > n) i1 = n;
                for (int64_t f = 0; f < nfast; f++)
                    mc_p3_block_fast(&cols[fastc[f]], i0, i1);
                for (int64_t g = 0; g < ngen; g++)
                    mc_p3_block(&cols[genc[g]], where, i0, i1);
            }
        }
        for (int64_t p = pi; p < pj; p++) {
            SdMCol *s = &cols[pend[p]];
            if (s->gather_total <= 0 && s->ndirect <= 0) continue;
            int rc = mc_resolve_subs(s, samples + (size_t)pend[p] * cap);
            if (rc) {
                free(idxbuf);
                return rc;
            }
        }
        for (int64_t p = pi; p < pj; p++) cols[pend[p]].done = 1;
        pi = pj;
    }
    free(idxbuf);
    return 0;
}

/* Native host kernels for the scan hot path.
 *
 * The placement engine folds discrete analyzers on the host when the
 * device link is slow (ops/runtime.py:placement_mode); the one host stage
 * that is not a single vectorized numpy reduction is HLL hashing: xxhash64
 * per row plus register index/rank extraction. numpy needs ~15 passes over
 * the buffer for that; this C loop does it in one pass at memory speed.
 *
 * Same semantics as the vectorized numpy path (ops/sketches/hll.py):
 * xxhash64 of the 8-byte value with seed 42, idx = top P bits, rank =
 * 1 + leading zeros of the remainder (capped for a 6-bit register) —
 * the same parameters as the reference kernel
 * (reference: catalyst/StatefulHyperloglogPlus.scala:86-155, p=9 from
 * RELATIVE_SD=0.05, 512 registers).
 */

#include <math.h>
#include <stdint.h>
#include <stddef.h>

#define P 9
#define SEED 42ULL

static const uint64_t PRIME1 = 0x9E3779B185EBCA87ULL;
static const uint64_t PRIME2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t PRIME3 = 0x165667B19E3779F9ULL;
static const uint64_t PRIME4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t PRIME5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t xxhash64_u64(uint64_t v) {
    uint64_t acc = v * PRIME2;
    acc = rotl64(acc, 31);
    acc *= PRIME1;
    acc ^= SEED + PRIME5 + 8ULL;
    acc = rotl64(acc, 27);
    acc *= PRIME1;
    acc += PRIME4;
    acc ^= acc >> 33;
    acc *= PRIME2;
    acc ^= acc >> 29;
    acc *= PRIME3;
    acc ^= acc >> 32;
    return acc;
}

/* packed[i] = (register_idx << 6) | rank for valid rows, 0 otherwise.
 * values: canonical 8-byte representation per row (int64 buffer). */
void xxhash64_pack(const int64_t *values, const uint8_t *valid, int64_t n,
                   int32_t *packed) {
    const int max_rank = 64 - P + 1;
    for (int64_t i = 0; i < n; i++) {
        if (!valid[i]) {
            packed[i] = 0;
            continue;
        }
        uint64_t h = xxhash64_u64((uint64_t)values[i]);
        int32_t idx = (int32_t)(h >> (64 - P));
        uint64_t rest = (h << P) | (1ULL << (P - 1));
        int rank = 1 + __builtin_clzll(rest);
        if (rank > max_rank) rank = max_rank;
        packed[i] = (idx << 6) | rank;
    }
}

/* register scatter-max over packed codes (the host fold of the HLL
 * reduce): regs must hold 1 << P int32 slots. where==NULL means all rows. */
void hll_update_registers(const int32_t *packed, const uint8_t *where,
                          int64_t n, int32_t *regs) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int32_t code = packed[i];
        int32_t idx = code >> 6;
        int32_t rank = code & 0x3F;
        if (rank > regs[idx]) regs[idx] = rank;
    }
}

/* Dense-code bincount: out[codes[i] + base]++ for in-range codes, one
 * pass with no shifted-copy temporary (numpy's bincount(codes + 1)
 * allocates an n-row temp and re-casts). The host fold of the group-by
 * count the reference runs as groupBy().agg(count)
 * (reference: GroupingAnalyzers.scala:67-72). where==NULL means all
 * rows; out must hold nbins slots (caller-zeroed). */
void bincount_i64(const int64_t *codes, const uint8_t *where, int64_t n,
                  int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Same for int32 codes (arrow dictionary indices stay int32 end-to-end:
 * upcasting 4M codes to int64 per batch costs a copy plus 2x bincount
 * read traffic). */
void bincount_i32(const int32_t *codes, const uint8_t *where, int64_t n,
                  int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = (int64_t)codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Same for narrow codes (type-class codes, int8 wire formats). */
void bincount_i8(const int8_t *codes, const uint8_t *where, int64_t n,
                 int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = (int64_t)codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Windowed dense value counting for integer columns: counts[v - lo]++
 * for rows passing the masks whose value lies in [lo, lo + nbins).
 * Returns via meta: [0] = count of valid&where rows in-window,
 * [1] = count of where rows (n when where == NULL), [2] = 1 when any
 * valid&where value fell OUTSIDE the window (the pass aborts
 * immediately: the caller falls back to the select kernel, so a
 * speculative window on a wide-range column costs only the prefix it
 * scanned). One such pass replaces a whole family-kernel radix select
 * for low-range integer columns (the counts table answers moments,
 * decimated quantile sample, HLL registers and value histogram in
 * O(nbins) — see ops/fused.py counts fast path). */
void bincount_window_i64(const int64_t *v, const uint8_t *valid,
                         const uint8_t *where, int64_t n, int64_t lo,
                         int64_t nbins, int64_t *counts, int64_t *meta) {
    int64_t count = 0, n_where = 0;
    meta[0] = 0;
    meta[1] = where ? 0 : n;
    meta[2] = 0;
    for (int64_t i = 0; i < n; i++) {
        if (where) {
            if (!where[i]) continue;
            n_where++;
        }
        if (valid && !valid[i]) continue;
        /* unsigned subtraction: defined wraparound even at int64 extremes */
        uint64_t idx = (uint64_t)v[i] - (uint64_t)lo;
        if (idx >= (uint64_t)nbins) {
            meta[2] = 1;
            return;
        }
        counts[idx]++;
        count++;
    }
    meta[0] = count;
    if (where) meta[1] = n_where;
}

/* Open-addressing distinct-value counter over raw 8-byte keys (float64
 * bit patterns or int64 values — the same canonical identity HLL
 * hashes). counts[slot]==0 marks an empty slot, so keys[] needs no
 * sentinel and ANY bit pattern (including +0.0 == all-zero bits) is a
 * valid key. Returns the number of distinct keys, or -1 the moment the
 * table would exceed max_distinct — a high-cardinality column aborts
 * after seeing ~max_distinct distinct values (typically a small prefix
 * of the data), so speculatively probing every column is cheap. The
 * caller allocates keys[1<<cap2_log] / counts[1<<cap2_log] zeroed;
 * choose 1<<cap2_log >= 2*max_distinct so the load factor stays <= 0.5.
 * A skew guard bounds the worst case (a column whose distinct count
 * sits just above the cap with the tail appearing late, e.g. Zipf):
 * once probe_rows rows are scanned, a table already 3/4 full aborts —
 * heavy-tailed near-cap columns bail after a bounded prefix instead of
 * scanning almost everything before the inevitable overflow. Columns
 * rejected by the guard merely fall back to the select kernel.
 * On success the counts table answers the whole numeric family in
 * O(#distinct) (ops/counts_family.py) — this extends the windowed
 * integer fast path to LOW-CARDINALITY FLOAT columns (discount/tax/
 * rate-style data) and sparse wide-range integers. */
int64_t hashcount_u64(const uint64_t *x, const uint8_t *valid,
                      const uint8_t *where, int64_t n, int64_t cap2_log,
                      int64_t max_distinct, int64_t probe_rows,
                      uint64_t *keys, int64_t *counts, int64_t *meta) {
    uint64_t mask = ((uint64_t)1 << cap2_log) - 1;
    int64_t distinct = 0, count = 0, n_where = 0;
    int64_t guard_distinct = max_distinct - (max_distinct >> 2);
    meta[0] = 0;
    meta[1] = where ? 0 : n;
    for (int64_t i = 0; i < n; i++) {
        if (probe_rows > 0 && i == probe_rows && distinct >= guard_distinct)
            return -1;
        if (where) {
            if (!where[i]) continue;
            n_where++;
        }
        if (valid && !valid[i]) continue;
        uint64_t k = x[i];
        uint64_t h = xxhash64_u64(k) & mask;
        for (;;) {
            if (counts[h] == 0) {
                if (distinct >= max_distinct) return -1;
                distinct++;
                keys[h] = k;
                counts[h] = 1;
                break;
            }
            if (keys[h] == k) {
                counts[h]++;
                break;
            }
            h = (h + 1) & mask;
        }
        count++;
    }
    meta[0] = count;
    if (where) meta[1] = n_where;
    return distinct;
}

/* Fused masked numeric moments: one data traversal feeds Mean, Sum,
 * Minimum, Maximum, StandardDeviation and the count of a whole
 * (column, where) family — the reductions the reference pushes into one
 * Catalyst pass (reference: runners/AnalysisRunner.scala:279-326) need
 * ~15 separate numpy passes host-side; this does two cache-friendly
 * passes (sum/min/max, then centered m2 at the batch mean — the same
 * centering the device kernel uses, StatefulStdDevPop semantics).
 *
 * valid/where may each be NULL (= all rows). Long-double accumulators
 * keep sequential summation within 1e-15 of numpy's pairwise sums.
 * out[6]: count, sum, min (+inf when empty), max (-inf), m2, n_where. */
void masked_moments(const double *x, const uint8_t *valid,
                    const uint8_t *where, int64_t n, double *out) {
    long double sum = 0.0L;
    int64_t count = 0, n_where = 0;
    double mn = (double)INFINITY, mx = -(double)INFINITY;
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        n_where++;
        if (valid && !valid[i]) continue;
        double v = x[i];
        sum += v;
        count++;
        if (v < mn) mn = v;
        if (v > mx) mx = v;
    }
    double avg = count > 0 ? (double)(sum / count) : 0.0;
    long double m2 = 0.0L;
    if (count > 0) {
        for (int64_t i = 0; i < n; i++) {
            if (valid && !valid[i]) continue;
            if (where && !where[i]) continue;
            double d = x[i] - avg;
            m2 += d * d;
        }
    }
    out[0] = (double)count;
    out[1] = (double)sum;
    out[2] = mn;
    out[3] = mx;
    out[4] = (double)m2;
    out[5] = where ? (double)n_where : (double)n;
}

/* ---------------------------------------------------------------------
 * Masked select-decimate: the per-batch heavy step of the quantile
 * sketch (analyzers/sketch.py device_batch). Computes EXACTLY
 *
 *     xm = sorted(x[valid & where]); xm[stride/2 :: stride][:cap]
 *     with stride = 2^level, level = ceil(log2(n_valid / cap))
 *
 * i.e. `cap` evenly spaced order statistics — WITHOUT sorting the whole
 * batch. The role this plays is the reference's per-partition quantile
 * digest update (reference: catalyst/StatefulApproxQuantile.scala:28).
 *
 * Method: map doubles to order-preserving uint64 keys and run an MSD
 * radix SELECT: histogram the keys on the most significant varying bits
 * (16 at the top level, 8 below), locate each wanted rank's bucket via
 * prefix sums, then gather and recurse ONLY into buckets that own a
 * wanted rank. Buckets whose min==max key are constant and resolve
 * without gathering (low-cardinality columns stay O(n)); segments
 * below 48 keys use insertion sort. IEEE exponent clustering (the case
 * that defeats single-level top-bit bucketing) just recurses one level
 * deeper into the mantissa bits.
 *
 * All large buffers come from a THREAD-LOCAL grow-only arena: repeated
 * calls (one per column per batch) reuse warm pages instead of paying
 * ~8k page faults per fresh 32MB malloc (measured: that was half the
 * kernel's wall time). Bounded by the largest batch ever processed per
 * thread.
 *
 * Determinism: key order equals IEEE total order on doubles (with -0.0
 * before +0.0 and NaN last; equal doubles are interchangeable in the
 * decimated sample, so the result matches the numpy sort path).
 *
 * Returns 0 on success (meta = [n_valid, level, kept], samples[kept]
 * filled), 1 on allocation failure (caller falls back to numpy). */

#include <stdlib.h>
#include <string.h>

#define SD_MAX_DEPTH 16
#define SD_TOP_BUCKETS 16384

/* arena slots: 0 = keys, 1 = top-level tables, 2+d = scratch at depth d */
/* slots: 0 = keys/gather scratch, 1 = top tables, 2+d = recursion
 * scratch at depth d, 18..23 = entry-point planning tables */
#define SD_ARENA_SLOTS (2 + SD_MAX_DEPTH + 6)
static __thread struct { void *p; size_t cap; } sd_arena[SD_ARENA_SLOTS];

static void *sd_get(int slot, size_t bytes) {
    if (sd_arena[slot].cap < bytes) {
        free(sd_arena[slot].p);
        size_t ncap = bytes + bytes / 2 + 64;
        sd_arena[slot].p = malloc(ncap);
        sd_arena[slot].cap = sd_arena[slot].p ? ncap : 0;
    }
    return sd_arena[slot].p;
}

static inline uint64_t f64_key(double v) {
    uint64_t u;
    memcpy(&u, &v, 8);
    return (u >> 63) ? ~u : (u | 0x8000000000000000ULL);
}

static inline double key_f64(uint64_t k) {
    uint64_t u = (k >> 63) ? (k & 0x7FFFFFFFFFFFFFFFULL) : ~k;
    double v;
    memcpy(&v, &u, 8);
    return v;
}

static void ins_sort_u64(uint64_t *a, int64_t n) {
    for (int64_t i = 1; i < n; i++) {
        uint64_t v = a[i];
        int64_t j = i - 1;
        while (j >= 0 && a[j] > v) {
            a[j + 1] = a[j];
            j--;
        }
        a[j + 1] = v;
    }
}

/* Resolve wanted ranks r_j = roff + j*step (j in [j0, j1), all with
 * 0 <= r_j < m) against the UNSORTED keys[0..m) whose min/max are
 * kmin/kmax. Writes samples[j]. May permute keys. */
static int resolve_segment(uint64_t *keys, int64_t m, uint64_t kmin,
                           uint64_t kmax, int64_t roff, int64_t step,
                           int64_t j0, int64_t j1, double *samples,
                           int depth) {
    if (j0 >= j1) return 0;
    if (kmin == kmax) {
        double v = key_f64(kmin);
        for (int64_t j = j0; j < j1; j++) samples[j] = v;
        return 0;
    }
    if (m <= 48 || depth + 1 >= SD_MAX_DEPTH) {
        ins_sort_u64(keys, m);
        for (int64_t j = j0; j < j1; j++)
            samples[j] = key_f64(keys[roff + j * step]);
        return 0;
    }

    int width = depth == 0 ? 16 : 8;
    int hb = 63 - __builtin_clzll(kmin ^ kmax);
    int shift = hb + 1 - width;
    if (shift < 0) shift = 0;
    uint64_t base = kmin >> shift;
    int64_t nbuckets = (int64_t)((kmax >> shift) - base) + 1;

    /* tables: stack at depth >= 1 (<= 256 buckets), arena at the top */
    uint32_t hist_stack[256];
    uint64_t bmin_stack[256], bmax_stack[256];
    int64_t cstart_stack[256], cfill_stack[256];
    uint32_t *hist;
    uint64_t *bmin, *bmax;
    int64_t *cstart, *cfill;
    if (nbuckets <= 256) {
        hist = hist_stack;
        bmin = bmin_stack;
        bmax = bmax_stack;
        cstart = cstart_stack;
        cfill = cfill_stack;
    } else {
        char *tables = (char *)sd_get(
            1, (size_t)nbuckets * (4 + 8 + 8 + 8 + 8));
        if (!tables) return 1;
        hist = (uint32_t *)tables;
        bmin = (uint64_t *)(tables + (size_t)nbuckets * 4);
        bmax = bmin + nbuckets;
        cstart = (int64_t *)(bmax + nbuckets);
        cfill = cstart + nbuckets;
    }
    memset(hist, 0, (size_t)nbuckets * 4);
    memset(bmin, 0xFF, (size_t)nbuckets * 8);
    memset(bmax, 0x00, (size_t)nbuckets * 8);

    for (int64_t i = 0; i < m; i++) {
        uint64_t k = keys[i];
        int64_t b = (int64_t)((k >> shift) - base);
        hist[b]++;
        if (k < bmin[b]) bmin[b] = k;
        if (k > bmax[b]) bmax[b] = k;
    }

    /* walk buckets in key order; resolve constant ones, mark the rest */
    int64_t collect_total = 0;
    {
        int64_t rank0 = 0;
        for (int64_t b = 0; b < nbuckets; b++) {
            int64_t c = (int64_t)hist[b];
            cstart[b] = -1;
            if (c > 0) {
                int64_t jlo =
                    (roff + j0 * step < rank0)
                        ? j0 + (rank0 - roff - j0 * step + step - 1) / step
                        : j0;
                if (jlo < j1 && roff + jlo * step < rank0 + c) {
                    if (bmin[b] == bmax[b]) {
                        double v = key_f64(bmin[b]);
                        for (int64_t j = jlo;
                             j < j1 && roff + j * step < rank0 + c; j++)
                            samples[j] = v;
                    } else {
                        cstart[b] = collect_total;
                        collect_total += c;
                    }
                }
                rank0 += c;
            }
        }
    }

    int rc = 0;
    if (collect_total > 0) {
        uint64_t *scratch =
            (uint64_t *)sd_get(2 + depth, (size_t)collect_total * 8);
        if (!scratch) return 1;
        memcpy(cfill, cstart, (size_t)nbuckets * 8);
        for (int64_t i = 0; i < m; i++) {
            uint64_t k = keys[i];
            int64_t b = (int64_t)((k >> shift) - base);
            if (cstart[b] >= 0) scratch[cfill[b]++] = k;
        }
        int64_t rank0 = 0;
        for (int64_t b = 0; b < nbuckets && rc == 0; b++) {
            int64_t c = (int64_t)hist[b];
            if (c > 0) {
                if (cstart[b] >= 0) {
                    int64_t jlo =
                        (roff + j0 * step < rank0)
                            ? j0 + (rank0 - roff - j0 * step + step - 1) / step
                            : j0;
                    int64_t jhi = jlo;
                    while (jhi < j1 && roff + jhi * step < rank0 + c) jhi++;
                    /* shift == 0 with bmin != bmax is impossible (the
                     * bucket id is then the full key), so recursion
                     * always has bits left to split on */
                    rc = resolve_segment(scratch + cstart[b], c, bmin[b],
                                         bmax[b], roff - rank0, step, jlo,
                                         jhi, samples, depth + 1);
                }
                rank0 += c;
            }
        }
    }
    return rc;
}

/* Entry point. Three direct masked passes over x (no key-buffer
 * materialization for the common case):
 *   P1: fixed 16-bit-prefix histogram + per-bucket min/max key
 *   P2: 8-bit count-only sub-histograms for buckets owning wanted ranks
 *   P3: gather only the sub-buckets owning wanted ranks
 * then resolve each gathered sub-bucket with resolve_segment (insertion
 * sort when tiny, recursion when an adversarial distribution concentrates
 * a sub-bucket). Constant buckets short-circuit at both levels. The rare
 * all-keys-share-top-16-bits case compacts keys and uses the adaptive
 * recursive path directly. */

#define SD_TOP_SHIFT 50
#define SD_SUB_BITS 8
#define SD_SUB_W (1 << SD_SUB_BITS)

static inline int sd_masked_out(const uint8_t *valid, const uint8_t *where,
                                int64_t i) {
    return (valid && !valid[i]) || (where && !where[i]);
}

/* core: select-decimate, optionally accumulating the masked-moments
 * family outputs [count, sum, min, max, m2, n_where] into mom (NULL =
 * skip) — the moments ride P1/P2's traversals instead of paying their
 * own two passes (ops/native masked_moments). hll_mode additionally
 * folds the HLL++ register update into P1 (the reference's
 * StatefulHyperloglogPlus per-row loop): 0 = off, 1 = hash the f64 bit
 * pattern of x[i] (float columns' canonical identity), 2 = hash
 * hashvals[i] (caller-supplied canonical int64 per row — int/bool
 * columns, whose identity is the integer value, not the float bits).
 * regs must hold 1 << P int32 slots (caller-zeroed). */
static int sd_core(const double *x, const uint8_t *valid,
                   const uint8_t *where, int64_t n, int64_t cap,
                   double *samples, int64_t *meta, double *mom,
                   const int64_t *hashvals, int hll_mode, int32_t *regs) {
    if (cap <= 0) return 1;

    /* ---- P1: top histogram + per-bucket min/max + global min/max.
     * One 24-byte struct per bucket (single cache line per update);
     * 14-bit top level keeps the whole table L2-resident. ---- */
    typedef struct {
        uint64_t mn, mx;
        uint32_t cnt, pad;
    } SdTop;
    SdTop *top = (SdTop *)sd_get(1, (size_t)SD_TOP_BUCKETS * sizeof(SdTop));
    if (!top) return 1;
    for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
        top[b].mn = ~0ULL;
        top[b].mx = 0ULL;
        top[b].cnt = 0;
    }

    int64_t m = 0, n_where = 0;
    uint64_t kmin = ~0ULL, kmax = 0ULL;
    /* block accumulation: the inner 2048-element partial runs in SSE
     * doubles (an x87 long-double add per row serializes the loop); the
     * outer fold stays long double, so total error ~ pairwise-summation
     * class, comfortably inside the 1e-12 parity tests */
    long double sum = 0.0L;
    double bsum = 0.0;
    int bn = 0;
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        n_where++;
        if (valid && !valid[i]) continue;
        uint64_t k = f64_key(x[i]);
        SdTop *t = &top[k >> SD_TOP_SHIFT];
        m++;
        t->cnt++;
        if (k < t->mn) t->mn = k;
        if (k > t->mx) t->mx = k;
        if (k < kmin) kmin = k;
        if (k > kmax) kmax = k;
        if (mom) {
            bsum += x[i];
            if (++bn == 2048) {
                sum += bsum;
                bsum = 0.0;
                bn = 0;
            }
        }
        if (hll_mode) {
            uint64_t canon;
            if (hll_mode == 1) {
                memcpy(&canon, &x[i], 8);
            } else {
                canon = (uint64_t)hashvals[i];
            }
            uint64_t h = xxhash64_u64(canon);
            int32_t idx = (int32_t)(h >> (64 - P));
            uint64_t rest = (h << P) | (1ULL << (P - 1));
            int rank = 1 + __builtin_clzll(rest);
            if (rank > 64 - P + 1) rank = 64 - P + 1;
            if (rank > regs[idx]) regs[idx] = rank;
        }
    }
    if (mom) {
        sum += bsum;
        mom[0] = (double)m;
        mom[1] = (double)sum;
        mom[2] = m > 0 ? key_f64(kmin) : (double)INFINITY;
        mom[3] = m > 0 ? key_f64(kmax) : -(double)INFINITY;
        mom[4] = 0.0; /* m2 filled below */
        mom[5] = where ? (double)n_where : (double)n;
    }
    meta[0] = m;
    meta[1] = 0;
    meta[2] = 0;
    if (m == 0) return 0;

    int level = 0;
    while (((int64_t)cap << level) < m) level++;
    int64_t stride = 1LL << level;
    int64_t offset = stride / 2;
    int64_t kept = (m - offset + stride - 1) / stride;
    if (kept < 0) kept = 0;
    meta[1] = level;
    meta[2] = kept;
    if (kept == 0) return 0;

    if (kmin == kmax) {
        double v = key_f64(kmin);
        for (int64_t j = 0; j < kept; j++) samples[j] = v;
        return 0;
    }
    if ((kmin >> SD_TOP_SHIFT) == (kmax >> SD_TOP_SHIFT)) {
        /* all keys share the top 16 bits: compact and go adaptive */
        uint64_t *keys = (uint64_t *)sd_get(0, (size_t)m * 8);
        if (!keys) return 1;
        int64_t w = 0;
        for (int64_t i = 0; i < n; i++) {
            if (sd_masked_out(valid, where, i)) continue;
            keys[w++] = f64_key(x[i]);
        }
        if (mom) {
            long double m2 = 0.0L;
            double avg = mom[1] / (double)m;
            for (int64_t i = 0; i < m; i++) {
                double d = key_f64(keys[i]) - avg;
                m2 += d * d;
            }
            mom[4] = (double)m2;
        }
        return resolve_segment(keys, m, kmin, kmax, offset, stride, 0, kept,
                               samples, 0);
    }

    /* ---- walk top buckets: resolve constant ones, plan the rest ----- */
    /* per planned bucket: its gather area offset (sizes known from P1) */
    int32_t *subidx = (int32_t *)sd_get(18, (size_t)SD_TOP_BUCKETS * 4);
    if (!subidx) return 1;
    memset(subidx, 0xFF, (size_t)SD_TOP_BUCKETS * 4);
    int32_t nplanned = 0;
    typedef struct {
        int64_t rank0, jlo, jhi, gofs, fill;
        uint64_t kmin, kmax;
    } SdPlan;
    SdPlan *plans = (SdPlan *)sd_get(19, (size_t)kept * sizeof(SdPlan));
    if (!plans) return 1;
    int64_t gather_total = 0;
    {
        int64_t rank0 = 0;
        for (int64_t b = 0; b < SD_TOP_BUCKETS; b++) {
            int64_t c = (int64_t)top[b].cnt;
            if (c == 0) continue;
            int64_t jlo = (offset < rank0)
                              ? (rank0 - offset + stride - 1) / stride
                              : 0;
            if (jlo < kept && offset + jlo * stride < rank0 + c) {
                if (top[b].mn == top[b].mx) {
                    double v = key_f64(top[b].mn);
                    for (int64_t j = jlo;
                         j < kept && offset + j * stride < rank0 + c; j++)
                        samples[j] = v;
                } else {
                    int64_t jhi = jlo;
                    while (jhi < kept && offset + jhi * stride < rank0 + c)
                        jhi++;
                    SdPlan *p = &plans[nplanned];
                    p->rank0 = rank0;
                    p->jlo = jlo;
                    p->jhi = jhi;
                    p->gofs = gather_total;
                    p->fill = gather_total;
                    p->kmin = top[b].mn;
                    p->kmax = top[b].mx;
                    gather_total += c;
                    subidx[b] = nplanned++;
                }
            }
            rank0 += c;
        }
    }

    long double m2acc = 0.0L;
    double bm2 = 0.0;
    int bm2n = 0;
    double avg = mom && m > 0 ? mom[1] / (double)m : 0.0;
    if (nplanned == 0) {
        /* every wanted bucket was constant; m2 still needs a pass */
        if (mom && m > 0) {
            for (int64_t i = 0; i < n; i++) {
                if (sd_masked_out(valid, where, i)) continue;
                double d = x[i] - avg;
                bm2 += d * d;
                if (++bm2n == 2048) {
                    m2acc += bm2;
                    bm2 = 0.0;
                    bm2n = 0;
                }
            }
            m2acc += bm2;
            mom[4] = (double)m2acc;
        }
        return 0;
    }

    /* ---- P2: gather planned buckets' keys whole (sizes known from
     * P1), m2 riding the same pass; each plan's contiguous segment is
     * then resolved by the recursive radix select, whose histograms run
     * over the (cache-friendly) gathered data instead of a third full
     * scan of x ------------------------------------------------------ */
    uint64_t *scratch = (uint64_t *)sd_get(0, (size_t)gather_total * 8);
    if (!scratch) return 1;
    for (int64_t i = 0; i < n; i++) {
        if (sd_masked_out(valid, where, i)) continue;
        uint64_t k = f64_key(x[i]);
        int32_t si = subidx[k >> SD_TOP_SHIFT];
        if (si >= 0) scratch[plans[si].fill++] = k;
        if (mom) {
            double d = x[i] - avg;
            bm2 += d * d;
            if (++bm2n == 2048) {
                m2acc += bm2;
                bm2 = 0.0;
                bm2n = 0;
            }
        }
    }
    if (mom) {
        m2acc += bm2;
        mom[4] = (double)m2acc;
    }

    /* ---- resolve each plan's gathered segment ----------------------- */
    for (int32_t s = 0; s < nplanned; s++) {
        SdPlan *sg = &plans[s];
        int rc = resolve_segment(scratch + sg->gofs, sg->fill - sg->gofs,
                                 sg->kmin, sg->kmax, offset - sg->rank0,
                                 stride, sg->jlo, sg->jhi, samples, 1);
        if (rc) return rc;
    }
    return 0;
}

int masked_select_decimate(const double *x, const uint8_t *valid,
                           const uint8_t *where, int64_t n, int64_t cap,
                           double *samples, int64_t *meta) {
    return sd_core(x, valid, where, n, cap, samples, meta, NULL, NULL, 0,
                   NULL);
}

/* Combined family kernel: moments + decimated quantile sample in the
 * same traversals. mom = [count, sum, min, max, m2, n_where] (the
 * masked_moments contract); samples/meta as masked_select_decimate. */
int masked_moments_select(const double *x, const uint8_t *valid,
                          const uint8_t *where, int64_t n, int64_t cap,
                          double *samples, int64_t *meta, double *mom,
                          const int64_t *hashvals, int hll_mode,
                          int32_t *regs) {
    return sd_core(x, valid, where, n, cap, samples, meta, mom, hashvals,
                   hll_mode, regs);
}

/* Native host kernels for the scan hot path.
 *
 * The placement engine folds discrete analyzers on the host when the
 * device link is slow (ops/runtime.py:placement_mode); the one host stage
 * that is not a single vectorized numpy reduction is HLL hashing: xxhash64
 * per row plus register index/rank extraction. numpy needs ~15 passes over
 * the buffer for that; this C loop does it in one pass at memory speed.
 *
 * Same semantics as the vectorized numpy path (ops/sketches/hll.py):
 * xxhash64 of the 8-byte value with seed 42, idx = top P bits, rank =
 * 1 + leading zeros of the remainder (capped for a 6-bit register) —
 * the same parameters as the reference kernel
 * (reference: catalyst/StatefulHyperloglogPlus.scala:86-155, p=9 from
 * RELATIVE_SD=0.05, 512 registers).
 */

#include <math.h>
#include <stdint.h>
#include <stddef.h>

#define P 9
#define SEED 42ULL

static const uint64_t PRIME1 = 0x9E3779B185EBCA87ULL;
static const uint64_t PRIME2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t PRIME3 = 0x165667B19E3779F9ULL;
static const uint64_t PRIME4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t PRIME5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t xxhash64_u64(uint64_t v) {
    uint64_t acc = v * PRIME2;
    acc = rotl64(acc, 31);
    acc *= PRIME1;
    acc ^= SEED + PRIME5 + 8ULL;
    acc = rotl64(acc, 27);
    acc *= PRIME1;
    acc += PRIME4;
    acc ^= acc >> 33;
    acc *= PRIME2;
    acc ^= acc >> 29;
    acc *= PRIME3;
    acc ^= acc >> 32;
    return acc;
}

/* packed[i] = (register_idx << 6) | rank for valid rows, 0 otherwise.
 * values: canonical 8-byte representation per row (int64 buffer). */
void xxhash64_pack(const int64_t *values, const uint8_t *valid, int64_t n,
                   int32_t *packed) {
    const int max_rank = 64 - P + 1;
    for (int64_t i = 0; i < n; i++) {
        if (!valid[i]) {
            packed[i] = 0;
            continue;
        }
        uint64_t h = xxhash64_u64((uint64_t)values[i]);
        int32_t idx = (int32_t)(h >> (64 - P));
        uint64_t rest = (h << P) | (1ULL << (P - 1));
        int rank = 1 + __builtin_clzll(rest);
        if (rank > max_rank) rank = max_rank;
        packed[i] = (idx << 6) | rank;
    }
}

/* register scatter-max over packed codes (the host fold of the HLL
 * reduce): regs must hold 1 << P int32 slots. where==NULL means all rows. */
void hll_update_registers(const int32_t *packed, const uint8_t *where,
                          int64_t n, int32_t *regs) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int32_t code = packed[i];
        int32_t idx = code >> 6;
        int32_t rank = code & 0x3F;
        if (rank > regs[idx]) regs[idx] = rank;
    }
}

/* Dense-code bincount: out[codes[i] + base]++ for in-range codes, one
 * pass with no shifted-copy temporary (numpy's bincount(codes + 1)
 * allocates an n-row temp and re-casts). The host fold of the group-by
 * count the reference runs as groupBy().agg(count)
 * (reference: GroupingAnalyzers.scala:67-72). where==NULL means all
 * rows; out must hold nbins slots (caller-zeroed). */
void bincount_i64(const int64_t *codes, const uint8_t *where, int64_t n,
                  int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Same for narrow codes (type-class codes, int8 wire formats). */
void bincount_i8(const int8_t *codes, const uint8_t *where, int64_t n,
                 int64_t base, int64_t nbins, int64_t *out) {
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        int64_t c = (int64_t)codes[i] + base;
        if (c >= 0 && c < nbins) out[c]++;
    }
}

/* Fused masked numeric moments: one data traversal feeds Mean, Sum,
 * Minimum, Maximum, StandardDeviation and the count of a whole
 * (column, where) family — the reductions the reference pushes into one
 * Catalyst pass (reference: runners/AnalysisRunner.scala:279-326) need
 * ~15 separate numpy passes host-side; this does two cache-friendly
 * passes (sum/min/max, then centered m2 at the batch mean — the same
 * centering the device kernel uses, StatefulStdDevPop semantics).
 *
 * valid/where may each be NULL (= all rows). Long-double accumulators
 * keep sequential summation within 1e-15 of numpy's pairwise sums.
 * out[6]: count, sum, min (+inf when empty), max (-inf), m2, n_where. */
void masked_moments(const double *x, const uint8_t *valid,
                    const uint8_t *where, int64_t n, double *out) {
    long double sum = 0.0L;
    int64_t count = 0, n_where = 0;
    double mn = (double)INFINITY, mx = -(double)INFINITY;
    for (int64_t i = 0; i < n; i++) {
        if (where && !where[i]) continue;
        n_where++;
        if (valid && !valid[i]) continue;
        double v = x[i];
        sum += v;
        count++;
        if (v < mn) mn = v;
        if (v > mx) mx = v;
    }
    double avg = count > 0 ? (double)(sum / count) : 0.0;
    long double m2 = 0.0L;
    if (count > 0) {
        for (int64_t i = 0; i < n; i++) {
            if (valid && !valid[i]) continue;
            if (where && !where[i]) continue;
            double d = x[i] - avg;
            m2 += d * d;
        }
    }
    out[0] = (double)count;
    out[1] = (double)sum;
    out[2] = mn;
    out[3] = mx;
    out[4] = (double)m2;
    out[5] = where ? (double)n_where : (double)n;
}

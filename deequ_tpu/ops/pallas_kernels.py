"""Pallas TPU kernels for device ops XLA lowers poorly.

The fused scan leaves almost everything to XLA (reductions fuse well on
the MXU/VPU), with ONE exception: the HLL register update is a
scatter-max into 512 registers, which XLA serializes on TPU. This
kernel reformulates it as a blockwise one-hot compare + max reduction —
pure VPU work, sequential-grid accumulation into the 512-register
output (reference hot loop: catalyst/StatefulHyperloglogPlus.scala:86-115;
kernel playbook: the repo's pallas guide).

Used automatically on the TPU platform when shapes allow (row count a
multiple of the 1024-row block); every caller falls back to the
`.at[idx].max(rank)` XLA path otherwise, and interpret mode backs the
CPU tests — results are identical by construction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.ops.sketches.hll import M as N_REGISTERS

# the (8, N_REGISTERS) output tile assumes the register count is a lane
# multiple; a precision change that breaks this must fail loudly, not
# drop registers
assert N_REGISTERS % 128 == 0, N_REGISTERS
_BLOCK_ROWS = 8  # (8, 128) int32 tile -> 1024 codes per grid step
_BLOCK = _BLOCK_ROWS * 128

_USABLE: Optional[bool] = None


def _kernel(codes_ref, out_ref):
    from jax.experimental import pallas as pl

    codes = codes_ref[:]  # (BLOCK_ROWS, 128) int32, masked rows carry 0
    idx = codes >> 6
    rank = codes & 0x3F
    # one-hot compare against all 512 registers: (BR, 128, 512) VPU work.
    # The per-sublane partial max keeps the output a clean (8, 512) tile
    # (an in-kernel (512,) -> (4,128) reshape fails to lower on some
    # mosaic builds); the final 8-way max is one tiny XLA op outside.
    regs = jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_ROWS, 128, N_REGISTERS), 2)
    contrib = jnp.where(idx[:, :, None] == regs, rank[:, :, None], 0)
    block_max = jnp.max(contrib, axis=1)  # (BLOCK_ROWS, 512)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros((_BLOCK_ROWS, N_REGISTERS), dtype=jnp.int32)

    out_ref[:] = jnp.maximum(out_ref[:], block_max)


def hll_register_max(codes, interpret: bool = False):
    """Register-wise max over packed (idx << 6 | rank) codes.

    `codes` length must be a multiple of 1024 (callers check
    `shape_supported`); masked/invalid rows must carry code 0 (idx 0,
    rank 0 — a no-op for the max)."""
    from jax.experimental import pallas as pl

    n = codes.shape[0]
    grid = n // _BLOCK
    codes2d = codes.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.int32)
    out = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, N_REGISTERS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_BLOCK_ROWS, N_REGISTERS), jnp.int32),
        interpret=interpret,
    )(codes2d)
    return jnp.max(out, axis=0)


def shape_supported(n: int) -> bool:
    return n >= _BLOCK and n % _BLOCK == 0


def usable() -> bool:
    """True when the attached platform compiles and runs the kernel
    (checked once with a tiny smoke input; any failure disables the
    pallas path for the process — the XLA scatter path is always a
    correct fallback)."""
    global _USABLE
    if _USABLE is None:
        try:
            if jax.devices()[0].platform != "tpu":
                _USABLE = False
                return _USABLE
        except Exception:  # noqa: BLE001 - backend init failure => no pallas
            _USABLE = False
            return _USABLE
        # two attempts: a single transient tunnel hiccup (observed under
        # heavy concurrent transfers) must not pin the pallas path off —
        # and must not pin a spurious 'skipped' into bench artifacts
        for _attempt in range(2):
            try:
                smoke = jnp.zeros(_BLOCK, dtype=jnp.int32)
                np.asarray(jax.jit(hll_register_max)(smoke))
                _USABLE = True
                break
            except Exception:  # noqa: BLE001 - compile/runtime failure
                _USABLE = False
    return _USABLE


# ---------------------------------------------------------------------------
# hist16: full 16-bit histogram via MXU one-hot matmuls
# ---------------------------------------------------------------------------
#
# The quantile sketch's device-side heavy step used to be a full XLA sort
# (bitonic, ~25-100ns/elem on the VPU). The radix-select view only needs
# COUNTS at 16-bit key granularity: hist[h, l] = #rows whose sortable-key
# top byte is h and next byte is l. Per block that is
#
#     onehot_high^T @ onehot_low        -- a (256, B) x (B, 256) matmul
#
# i.e. pure MXU work (~65k MACs/row ≈ 1ns/row), accumulated across the
# grid into one (256, 256) float32 tile. The host walks the 65536 counts
# (256KB) to locate the wanted decimation ranks, then gathers and sorts
# ONLY the few bins that own a rank — the same histogram-assisted
# selection the host C kernel runs, with the counting on the TPU.
# (Reference role: catalyst/StatefulApproxQuantile.scala:28 — the
# per-partition digest update this feeds.)

_HIST_BINS = 256  # per axis; 256 x 256 = full 16-bit space


def _hist16_kernel(bins_ref, out_ref):
    from jax.experimental import pallas as pl

    bins = bins_ref[:]  # (BLOCK_ROWS, 128) int32 in [0, 65536)
    high = (bins >> 8) & 0xFF
    low = bins & 0xFF
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (_BLOCK_ROWS, 128, _HIST_BINS), 2
    )
    oh_high = (high[:, :, None] == iota).astype(jnp.float32)
    oh_low = (low[:, :, None] == iota).astype(jnp.float32)
    # per-sublane (256,128)x(128,256) matmuls batched over the sublane
    # dim, summed on the VPU: mosaic's tpu.matmul wants standard 2-D
    # contractions (a fused multi-dim contraction fails verification)
    per_sublane = jax.lax.dot_general(
        oh_high,
        oh_low,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # (BLOCK_ROWS, 256, 256)
    block_hist = jnp.sum(per_sublane, axis=0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros((_HIST_BINS, _HIST_BINS), dtype=jnp.float32)

    out_ref[:] = out_ref[:] + block_hist


def hist16(bins, interpret: bool = False):
    """(256, 256) float32 histogram over 16-bit bin ids.

    `bins` length must be a multiple of 1024 (`shape_supported`); rows
    to exclude must carry the sentinel 65535 (the NaN region of the
    float32 sortable-key space — real masked-in values never reach it),
    which the host walk drops. Counts are exact in f32 up to 2^24 rows.
    """
    from jax.experimental import pallas as pl

    n = bins.shape[0]
    grid = n // _BLOCK
    bins2d = bins.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.int32)
    return pl.pallas_call(
        _hist16_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_HIST_BINS, _HIST_BINS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_HIST_BINS, _HIST_BINS), jnp.float32),
        interpret=interpret,
    )(bins2d)


# ---------------------------------------------------------------------------
# masked moment folds: count/sum/min/max (+ centered sum-of-squares)
# ---------------------------------------------------------------------------
#
# The numeric analyzers' per-batch folds (Mean/Sum/Minimum/Maximum/
# StandardDeviation) are masked reductions XLA handles as separate
# reduce ops, each re-reading the (x, m) operands from HBM. The pallas
# form reads every (8, 128) block ONCE and accumulates all four partials
# in VMEM over the sequential grid — one HBM pass for the whole moment
# set — with a tiny XLA lane-reduce epilog outside the kernel.
#
# BIT-IDENTITY CAVEAT: blocked accumulation is a different float
# summation ORDER than XLA's flat reduce, so sums/means need not match
# an XLA fold bitwise (min/max/count are exact in any order). That is
# why `runtime.fold_variant()` hashes "pallas-folds" into the plan
# signature: committed states from the two arithmetics never mix in the
# state cache. tests/test_pallas_kernels.py pins the kernels bitwise
# against an identically-blocked XLA reference (and exactly against the
# naive fold for the order-insensitive stats).


def _masked_moments_kernel(x_ref, m_ref, cnt_ref, sum_ref, min_ref, max_ref):
    from jax.experimental import pallas as pl

    x = x_ref[:]  # (BLOCK_ROWS, 128) f32
    m = m_ref[:]  # (BLOCK_ROWS, 128) f32 in {0, 1}
    live = m > 0

    @pl.when(pl.program_id(0) == 0)
    def _init():
        cnt_ref[:] = jnp.zeros((_BLOCK_ROWS, 128), dtype=jnp.float32)
        sum_ref[:] = jnp.zeros((_BLOCK_ROWS, 128), dtype=jnp.float32)
        min_ref[:] = jnp.full((_BLOCK_ROWS, 128), jnp.inf, dtype=jnp.float32)
        max_ref[:] = jnp.full((_BLOCK_ROWS, 128), -jnp.inf, dtype=jnp.float32)

    cnt_ref[:] = cnt_ref[:] + m
    sum_ref[:] = sum_ref[:] + x * m
    min_ref[:] = jnp.minimum(min_ref[:], jnp.where(live, x, jnp.inf))
    max_ref[:] = jnp.maximum(max_ref[:], jnp.where(live, x, -jnp.inf))


def masked_moments(x, m, interpret: bool = False):
    """(count, sum, min, max) scalars of `x` under mask `m` in one pass.

    `x` length must be a multiple of 1024 (`shape_supported`); masked
    rows (m == 0) contribute nothing: 0 to count/sum, ±inf identities to
    min/max — exactly the analyzers' XLA fold semantics."""
    from jax.experimental import pallas as pl

    n = x.shape[0]
    grid = n // _BLOCK
    x2d = x.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.float32)
    m2d = m.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.float32)
    tile = pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0))
    acc = pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (0, 0))
    out = jax.ShapeDtypeStruct((_BLOCK_ROWS, 128), jnp.float32)
    cnt, total, mn, mx = pl.pallas_call(
        _masked_moments_kernel,
        grid=(grid,),
        in_specs=[tile, tile],
        out_specs=[acc, acc, acc, acc],
        out_shape=[out, out, out, out],
        interpret=interpret,
    )(x2d, m2d)
    return jnp.sum(cnt), jnp.sum(total), jnp.min(mn), jnp.max(mx)


def _sumsq_kernel(d_ref, out_ref):
    from jax.experimental import pallas as pl

    d = d_ref[:]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros((_BLOCK_ROWS, 128), dtype=jnp.float32)

    out_ref[:] = out_ref[:] + d * d


def masked_centered_sumsq(x, m, avg, interpret: bool = False):
    """sum(((x - avg) * m)^2) — StandardDeviation's m2 fold. The
    centering is a cheap XLA prolog; the square-accumulate runs blocked
    in VMEM like `masked_moments`. Same shape contract."""
    from jax.experimental import pallas as pl

    n = x.shape[0]
    grid = n // _BLOCK
    d = ((x.astype(jnp.float32) - avg) * m.astype(jnp.float32)).reshape(
        grid * _BLOCK_ROWS, 128
    )
    out = pl.pallas_call(
        _sumsq_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_BLOCK_ROWS, 128), jnp.float32),
        interpret=interpret,
    )(d)
    return jnp.sum(out)


def fold_moments_or_none(x, m):
    """The analyzers' gate: (count, sum, min, max) via the pallas fold
    when the knob, platform, and shape all allow — else None and the
    caller runs its XLA fold. Mirrors `runtime.fold_variant()`: whenever
    this returns non-None, the plan signature carries "pallas-folds"."""
    from deequ_tpu.ops import runtime

    if not runtime.pallas_folds_enabled():
        return None
    if getattr(x, "ndim", 0) != 1 or not shape_supported(int(x.shape[0])):
        return None
    if not usable():
        return None
    return masked_moments(x, m)


def f32_sortable_bin16(values_f32, live_mask):
    """Top-16 sortable-key bins for float32 values (order-preserving:
    bin ascending == value ascending); excluded rows get sentinel 65535.
    Pure XLA VPU ops — runs inside the fused program before hist16."""
    u = jax.lax.bitcast_convert_type(values_f32, jnp.int32)
    key = jnp.where(u < 0, ~u, u | jnp.int32(-2147483648))
    bins = jax.lax.shift_right_logical(key, jnp.int32(16))
    return jnp.where(live_mask, bins, jnp.int32(65535))

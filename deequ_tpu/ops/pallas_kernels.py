"""Pallas TPU kernels for device ops XLA lowers poorly.

The fused scan leaves almost everything to XLA (reductions fuse well on
the MXU/VPU), with ONE exception: the HLL register update is a
scatter-max into 512 registers, which XLA serializes on TPU. This
kernel reformulates it as a blockwise one-hot compare + max reduction —
pure VPU work, sequential-grid accumulation into the 512-register
output (reference hot loop: catalyst/StatefulHyperloglogPlus.scala:86-115;
kernel playbook: the repo's pallas guide).

Used automatically on the TPU platform when shapes allow (row count a
multiple of the 1024-row block); every caller falls back to the
`.at[idx].max(rank)` XLA path otherwise, and interpret mode backs the
CPU tests — results are identical by construction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.ops.sketches.hll import M as N_REGISTERS

# the (8, N_REGISTERS) output tile assumes the register count is a lane
# multiple; a precision change that breaks this must fail loudly, not
# drop registers
assert N_REGISTERS % 128 == 0, N_REGISTERS
_BLOCK_ROWS = 8  # (8, 128) int32 tile -> 1024 codes per grid step
_BLOCK = _BLOCK_ROWS * 128

_USABLE: Optional[bool] = None


def _kernel(codes_ref, out_ref):
    from jax.experimental import pallas as pl

    codes = codes_ref[:]  # (BLOCK_ROWS, 128) int32, masked rows carry 0
    idx = codes >> 6
    rank = codes & 0x3F
    # one-hot compare against all 512 registers: (BR, 128, 512) VPU work.
    # The per-sublane partial max keeps the output a clean (8, 512) tile
    # (an in-kernel (512,) -> (4,128) reshape fails to lower on some
    # mosaic builds); the final 8-way max is one tiny XLA op outside.
    regs = jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_ROWS, 128, N_REGISTERS), 2)
    contrib = jnp.where(idx[:, :, None] == regs, rank[:, :, None], 0)
    block_max = jnp.max(contrib, axis=1)  # (BLOCK_ROWS, 512)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros((_BLOCK_ROWS, N_REGISTERS), dtype=jnp.int32)

    out_ref[:] = jnp.maximum(out_ref[:], block_max)


def hll_register_max(codes, interpret: bool = False):
    """Register-wise max over packed (idx << 6 | rank) codes.

    `codes` length must be a multiple of 1024 (callers check
    `shape_supported`); masked/invalid rows must carry code 0 (idx 0,
    rank 0 — a no-op for the max)."""
    from jax.experimental import pallas as pl

    n = codes.shape[0]
    grid = n // _BLOCK
    codes2d = codes.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.int32)
    out = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, N_REGISTERS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_BLOCK_ROWS, N_REGISTERS), jnp.int32),
        interpret=interpret,
    )(codes2d)
    return jnp.max(out, axis=0)


def shape_supported(n: int) -> bool:
    return n >= _BLOCK and n % _BLOCK == 0


def usable() -> bool:
    """True when the attached platform compiles and runs the kernel
    (checked once with a tiny smoke input; any failure disables the
    pallas path for the process — the XLA scatter path is always a
    correct fallback)."""
    global _USABLE
    if _USABLE is None:
        try:
            if jax.devices()[0].platform != "tpu":
                _USABLE = False
                return _USABLE
        except Exception:  # noqa: BLE001 - backend init failure => no pallas
            _USABLE = False
            return _USABLE
        # two attempts: a single transient tunnel hiccup (observed under
        # heavy concurrent transfers) must not pin the pallas path off —
        # and must not pin a spurious 'skipped' into bench artifacts
        for _attempt in range(2):
            try:
                smoke = jnp.zeros(_BLOCK, dtype=jnp.int32)
                np.asarray(jax.jit(hll_register_max)(smoke))
                _USABLE = True
                break
            except Exception:  # noqa: BLE001 - compile/runtime failure
                _USABLE = False
    return _USABLE

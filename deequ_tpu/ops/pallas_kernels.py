"""Pallas TPU kernels for device ops XLA lowers poorly.

The fused scan leaves almost everything to XLA (reductions fuse well on
the MXU/VPU), with ONE exception: the HLL register update is a
scatter-max into 512 registers, which XLA serializes on TPU. This
kernel reformulates it as a blockwise one-hot compare + max reduction —
pure VPU work, sequential-grid accumulation into the 512-register
output (reference hot loop: catalyst/StatefulHyperloglogPlus.scala:86-115;
kernel playbook: the repo's pallas guide).

Used automatically on the TPU platform when shapes allow (row count a
multiple of the 1024-row block); every caller falls back to the
`.at[idx].max(rank)` XLA path otherwise, and interpret mode backs the
CPU tests — results are identical by construction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.ops.sketches.hll import M as N_REGISTERS

# the (8, N_REGISTERS) output tile assumes the register count is a lane
# multiple; a precision change that breaks this must fail loudly, not
# drop registers
assert N_REGISTERS % 128 == 0, N_REGISTERS
_BLOCK_ROWS = 8  # (8, 128) int32 tile -> 1024 codes per grid step
_BLOCK = _BLOCK_ROWS * 128

_USABLE: Optional[bool] = None


def _kernel(codes_ref, out_ref):
    from jax.experimental import pallas as pl

    codes = codes_ref[:]  # (BLOCK_ROWS, 128) int32, masked rows carry 0
    idx = codes >> 6
    rank = codes & 0x3F
    # one-hot compare against all 512 registers: (BR, 128, 512) VPU work.
    # The per-sublane partial max keeps the output a clean (8, 512) tile
    # (an in-kernel (512,) -> (4,128) reshape fails to lower on some
    # mosaic builds); the final 8-way max is one tiny XLA op outside.
    regs = jax.lax.broadcasted_iota(jnp.int32, (_BLOCK_ROWS, 128, N_REGISTERS), 2)
    contrib = jnp.where(idx[:, :, None] == regs, rank[:, :, None], 0)
    block_max = jnp.max(contrib, axis=1)  # (BLOCK_ROWS, 512)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros((_BLOCK_ROWS, N_REGISTERS), dtype=jnp.int32)

    out_ref[:] = jnp.maximum(out_ref[:], block_max)


def hll_register_max(codes, interpret: bool = False):
    """Register-wise max over packed (idx << 6 | rank) codes.

    `codes` length must be a multiple of 1024 (callers check
    `shape_supported`); masked/invalid rows must carry code 0 (idx 0,
    rank 0 — a no-op for the max)."""
    from jax.experimental import pallas as pl

    n = codes.shape[0]
    grid = n // _BLOCK
    codes2d = codes.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.int32)
    out = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, N_REGISTERS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_BLOCK_ROWS, N_REGISTERS), jnp.int32),
        interpret=interpret,
    )(codes2d)
    return jnp.max(out, axis=0)


def shape_supported(n: int) -> bool:
    return n >= _BLOCK and n % _BLOCK == 0


def usable() -> bool:
    """True when the attached platform compiles and runs the kernel
    (checked once with a tiny smoke input; any failure disables the
    pallas path for the process — the XLA scatter path is always a
    correct fallback)."""
    global _USABLE
    if _USABLE is None:
        try:
            if jax.devices()[0].platform != "tpu":
                _USABLE = False
                return _USABLE
        except Exception:  # noqa: BLE001 - backend init failure => no pallas
            _USABLE = False
            return _USABLE
        # two attempts: a single transient tunnel hiccup (observed under
        # heavy concurrent transfers) must not pin the pallas path off —
        # and must not pin a spurious 'skipped' into bench artifacts
        for _attempt in range(2):
            try:
                smoke = jnp.zeros(_BLOCK, dtype=jnp.int32)
                np.asarray(jax.jit(hll_register_max)(smoke))
                _USABLE = True
                break
            except Exception:  # noqa: BLE001 - compile/runtime failure
                _USABLE = False
    return _USABLE


# ---------------------------------------------------------------------------
# hist16: full 16-bit histogram via MXU one-hot matmuls
# ---------------------------------------------------------------------------
#
# The quantile sketch's device-side heavy step used to be a full XLA sort
# (bitonic, ~25-100ns/elem on the VPU). The radix-select view only needs
# COUNTS at 16-bit key granularity: hist[h, l] = #rows whose sortable-key
# top byte is h and next byte is l. Per block that is
#
#     onehot_high^T @ onehot_low        -- a (256, B) x (B, 256) matmul
#
# i.e. pure MXU work (~65k MACs/row ≈ 1ns/row), accumulated across the
# grid into one (256, 256) float32 tile. The host walks the 65536 counts
# (256KB) to locate the wanted decimation ranks, then gathers and sorts
# ONLY the few bins that own a rank — the same histogram-assisted
# selection the host C kernel runs, with the counting on the TPU.
# (Reference role: catalyst/StatefulApproxQuantile.scala:28 — the
# per-partition digest update this feeds.)

_HIST_BINS = 256  # per axis; 256 x 256 = full 16-bit space


def _hist16_kernel(bins_ref, out_ref):
    from jax.experimental import pallas as pl

    bins = bins_ref[:]  # (BLOCK_ROWS, 128) int32 in [0, 65536)
    high = (bins >> 8) & 0xFF
    low = bins & 0xFF
    iota = jax.lax.broadcasted_iota(
        jnp.int32, (_BLOCK_ROWS, 128, _HIST_BINS), 2
    )
    oh_high = (high[:, :, None] == iota).astype(jnp.float32)
    oh_low = (low[:, :, None] == iota).astype(jnp.float32)
    # per-sublane (256,128)x(128,256) matmuls batched over the sublane
    # dim, summed on the VPU: mosaic's tpu.matmul wants standard 2-D
    # contractions (a fused multi-dim contraction fails verification)
    per_sublane = jax.lax.dot_general(
        oh_high,
        oh_low,
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # (BLOCK_ROWS, 256, 256)
    block_hist = jnp.sum(per_sublane, axis=0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros((_HIST_BINS, _HIST_BINS), dtype=jnp.float32)

    out_ref[:] = out_ref[:] + block_hist


def hist16(bins, interpret: bool = False):
    """(256, 256) float32 histogram over 16-bit bin ids.

    `bins` length must be a multiple of 1024 (`shape_supported`); rows
    to exclude must carry the sentinel 65535 (the NaN region of the
    float32 sortable-key space — real masked-in values never reach it),
    which the host walk drops. Counts are exact in f32 up to 2^24 rows.
    """
    from jax.experimental import pallas as pl

    n = bins.shape[0]
    grid = n // _BLOCK
    bins2d = bins.reshape(grid * _BLOCK_ROWS, 128).astype(jnp.int32)
    return pl.pallas_call(
        _hist16_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_HIST_BINS, _HIST_BINS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_HIST_BINS, _HIST_BINS), jnp.float32),
        interpret=interpret,
    )(bins2d)


def f32_sortable_bin16(values_f32, live_mask):
    """Top-16 sortable-key bins for float32 values (order-preserving:
    bin ascending == value ascending); excluded rows get sentinel 65535.
    Pure XLA VPU ops — runs inside the fused program before hist16."""
    u = jax.lax.bitcast_convert_type(values_f32, jnp.int32)
    key = jnp.where(u < 0, ~u, u | jnp.int32(-2147483648))
    bins = jax.lax.shift_right_logical(key, jnp.int32(16))
    return jnp.where(live_mask, bins, jnp.int32(65535))

"""Backpressured stream-pipeline stages: the staged streaming executor.

A streaming scan moves every batch through four kinds of work: decode
(Parquet -> Arrow -> Table, already overlapped by the prefetch thread in
data/source.py), host prep (input builds, wire packing + the H2D put,
family kernels), device compute (async XLA dispatch), and the ordered
fold (async D2H fetch + merge_agg + host member folds, see
`PipelinedAggFold`). Serially, everything between the prefetch thread
and the D2H fold shares one consumer thread; this module runs the prep
work on its own stage thread with a bounded queue to the fold stage:

    decode thread ──q──> prep thread ──q──> consumer (dispatch + fold)

  * batch N+1's H2D put (`jnp.asarray` inside `pack_batch_inputs` /
    `jax.device_put` in the mesh pass) overlaps batch N's device
    compute — the H2D twin of `PipelinedAggFold`'s async D2H, giving
    double-buffered device inputs at queue depth 1;
  * batch N+1's family kernels and input builds overlap batch N's host
    fold on multicore hosts.

Bit-identity with the serial path (`DEEQU_TPU_PIPELINE=0`): every fold
(`PipelinedAggFold` merges and `fold_host_batch` member folds) still
runs on the consumer thread in batch order over the same inputs, and
the sticky wire dict is only ever mutated by the single prep thread in
batch order — the pipeline changes WHERE per-batch work runs, never
what is computed. The one permitted divergence: liveness feedback lags
by at most the queue depth, so a member that errors mid-stream can have
its family kernel still run for the batches already in flight — wasted
work on an already-failing plan, not a results change on healthy
streams (the pipeline-on/off differential in
tests/test_suite_differential_fuzz.py pins bit-identical metrics).

Stage threads must never host-sync: `jax.device_get` /
`block_until_ready` belong to the fold stage only (the PIPELINE rule in
tools/lint.py bans them in this file and in data/source.py). Stage
threads adopt the dispatching thread's trace context
(`observe.attached`) and report a `pipe_stage` span with one
`pipe_item` child per batch — what the run report's pipeline-occupancy
section aggregates; with tracing off, spans hit the no-op fast path.

Occupancy attribution under decode-to-wire fusion
(`DEEQU_TPU_WIRE_FUSED`): a fused column's bit-packing and value
narrowing/shifting run inside the decode workers' native kernels, so
that work leaves the prep stage's `pack_batch_inputs` bucket and lands
in the DECODE stage's busy time (where the arrow_decode spans live).
The occupancy report therefore re-baselines when fusion toggles —
decode busy_s rises by roughly the pack time that prep loses, and the
total stays accounted: time moves between stage buckets, it is never
dropped (BENCH.md's round-10 table shows the A/B).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, List

from deequ_tpu import observe
from deequ_tpu.ops import runtime
from deequ_tpu.testing import faults

_SENTINEL = object()

#: how long shutdown waits for a stage thread before giving up on it —
#: matches the decode thread's join timeout in data/source.py
JOIN_TIMEOUT_S = 10.0


def staged(
    iterable: Iterable[Any],
    fn: Callable[[Any], Any],
    *,
    name: str = "prep",
    depth: int | None = None,
    progress: Any = None,
) -> Iterator[Any]:
    """Run `fn` over `iterable`'s items on a dedicated stage thread,
    yielding `fn(item)` results in input order through a bounded queue.

    Backpressure: the stage blocks once `depth` (default
    `runtime.pipeline_depth()`) results wait unconsumed, so at most
    `depth` + 1 prepped batches are resident regardless of how far the
    consumer falls behind.

    Shutdown contract (pinned by tests/test_pipeline_shutdown.py):
      * early consumer exit (the generator is closed or abandoned
        mid-stream) signals the stage thread, drains the queue so a
        blocked put() wakes, and joins within `JOIN_TIMEOUT_S`;
      * the stage thread closes the upstream iterator ON the stage
        thread before exiting — a generator upstream (e.g.
        `DataSource.batches`) runs its own finally there, so decode
        threads and file handles unwind transitively;
      * an exception from `fn` or the upstream iterator terminates the
        stage and re-raises in the consumer, after the same cleanup.

    Trace context is captured when the consumer starts iterating and
    adopted by the stage thread, so `fn`'s spans stay under the
    dispatching scan's subtree.

    `progress` is an optional live-heartbeat handle
    (`observe.heartbeat.ScanProgress`): the stage accounts the upstream
    `next()` wait to the `decode` stage bucket and `fn`'s work to this
    stage's bucket, which is what the heartbeat's bottleneck/occupancy
    snapshot reads. Defaults to the no-op handle.
    """
    if depth is None:
        depth = runtime.pipeline_depth()
    if progress is None:
        progress = observe.heartbeat.NOOP_PROGRESS
    q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    error: List[BaseException] = []
    tracer = observe.current_tracer()
    parent = observe.current_span()

    def _put(item: Any) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _apply(item: Any) -> Any:
        faults.fault_point("pipeline.stage")
        return fn(item)

    def worker() -> None:
        it = iter(iterable)
        try:
            with observe.attached(tracer, parent):
                with observe.span(
                    "pipe_stage", cat="pipeline", stage=name
                ) as stage_sp:
                    items = 0
                    while not stop.is_set():
                        # the next() wait is upstream stall, not this
                        # stage's work — kept outside the item span so
                        # occupancy attributes it to the right stage
                        try:
                            with progress.timed("decode"):
                                item = next(it)
                        except StopIteration:
                            break
                        sp = observe.span(
                            "pipe_item", cat="pipeline", stage=name
                        )
                        with sp, progress.timed(name):
                            rows = getattr(item, "num_rows", None)
                            if sp and rows is not None:
                                sp.set(rows=int(rows))
                            faults.fault_point("pipeline.stall")
                            try:
                                out = _apply(item)
                            except Exception:  # noqa: BLE001 - one redo
                                # contained stage fault: fn is a pure
                                # per-batch prep, so one in-place redo
                                # is bit-identical; a second failure is
                                # a real error and propagates
                                runtime.record_fault(injected=1)
                                out = _apply(item)
                                runtime.record_retry(1, 1, 0)
                        if not _put(out):
                            return
                        items += 1
                    if stage_sp:
                        stage_sp.set(items=items)
        except BaseException as e:  # noqa: BLE001 - re-raised consumer-side
            error.append(e)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except BaseException as e:  # noqa: BLE001
                    if not error:
                        error.append(e)
            _put(_SENTINEL)

    # stack dumps / py-spy on a mesh worker must say WHICH shard's
    # pipeline a stage thread belongs to
    tag = runtime.shard_tag()
    thread = threading.Thread(
        target=worker,
        daemon=True,
        name=f"deequ-pipe-{name}" + (f"-shard{tag}" if tag else ""),
    )
    thread.start()
    try:
        while True:
            out = q.get()
            if out is _SENTINEL:
                break
            yield out
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:  # fault-ok: drain-until-empty teardown
            pass
        thread.join(timeout=JOIN_TIMEOUT_S)
    if error:
        raise error[0]

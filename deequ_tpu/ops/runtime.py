"""Execution-engine runtime: dtypes, the pass monitor, jit cache keys.

The monitor is the production analogue of the reference's test-only
SparkMonitor job/stage listener (reference:
src/test/scala/com/amazon/deequ/SparkMonitor.scala:25-75): it counts fused
device passes and program launches so scan-sharing is an *asserted*
property (SURVEY.md §6 efficiency invariants).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.observe import counters as _counters
from deequ_tpu.observe.spans import timed_call as _timed


def compute_dtype() -> jnp.dtype:
    """float64 when x64 is live (CPU tests / parity), float32 on TPU.

    Per-batch reductions are XLA tree-reductions (error ~ eps·log n); the
    cross-batch fold happens host-side in float64 either way, so f32 device
    partials stay accurate as long as batches are < 2^24 rows.
    """
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


MAX_F32_EXACT_COUNT_BATCH = 1 << 24  # f32 integers exact below 2^24


def check_int_wire_width(dtype, key: str) -> None:
    """With jax_enable_x64 off, jnp.asarray/device_put silently
    canonicalizes 64-bit integers to 32 bits (verified: values > 2^31
    arrive corrupted). Every engine that ships an int column to the
    device must make that limitation a loud error instead."""
    if np.dtype(dtype).itemsize >= 8 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"input '{key}' needs a 64-bit integer wire format "
            "(values exceed 32-bit range) but jax_enable_x64 is "
            "off; enable x64 or pre-cast the column to float."
        )


def narrow_int_wire(arr: np.ndarray, key: str, sticky: dict) -> np.ndarray:
    """Range-downcast an integer array to the narrowest exact wire dtype.

    Shared by both engines (fused packing and distributed device_put).
    `sticky` pins each key's dtype monotonically wider across batches so
    compiled layouts stay stable instead of flapping per batch range.
    Raises when the range genuinely needs 64-bit ints the engine can't
    ship exactly (x64 off)."""
    unsigned = np.issubdtype(arr.dtype, np.unsignedinteger)
    candidates = (
        (np.uint8, np.uint16, np.uint32, np.uint64)
        if unsigned
        else (np.int8, np.int16, np.int32, np.int64)
    )
    chosen = np.dtype(sticky.get(key, candidates[0]))
    if arr.size:
        mn, mx = int(arr.min()), int(arr.max())
        # the widest candidate of arr's own signedness family always
        # covers [mn, mx], so this loop always picks one
        for cand in candidates:
            info = np.iinfo(cand)
            if (
                np.dtype(cand).itemsize >= chosen.itemsize
                and info.min <= mn
                and mx <= info.max
            ):
                chosen = np.dtype(cand)
                break
    chosen = np.dtype(min(chosen, arr.dtype, key=lambda d: np.dtype(d).itemsize))
    check_int_wire_width(chosen, key)
    sticky[key] = chosen
    return arr.astype(chosen, copy=False)


# ---------------------------------------------------------------------------
# Placement: where a reduction earns its bytes
# ---------------------------------------------------------------------------

_PLACEMENT_CACHE: Optional[str] = None
# Cost model, bytes vs FLOPs: a value reduction ships ~4 B/row and costs
# ~2 ns/row on the host, so the device only wins above ~2 GB/s links
# (PCIe/ICI-attached accelerators). Discrete (mask/code-only) reductions
# ship ~0.1-2 B/row against ~1 ns/row of host popcount, breaking even
# around 100 MB/s.
PLACEMENT_DEVICE_ALL_BANDWIDTH = 2e9  # bytes/s: everything on device
PLACEMENT_BANDWIDTH_FLOOR = 100e6  # bytes/s: below, nothing earns the wire


def measure_device_bandwidth(nbytes: int = 4 << 20, iters: int = 3) -> float:
    """Effective H2D+D2H bandwidth probe (synchronized via a value fetch —
    async dispatch makes un-fetched timings meaningless on tunneled
    devices). Best-of-`iters` with a measured empty-dispatch baseline
    subtracted, so per-dispatch latency doesn't misclassify a fast
    (PCIe-class) link as slow on a one-shot noisy sample."""
    data = np.zeros(nbytes // 4, dtype=np.float32)
    tiny = np.zeros(1, dtype=np.float32)
    total = jax.jit(jnp.sum)
    float(total(data))  # compile + warm
    float(total(tiny))
    best = _timed(lambda: float(total(data)))
    if nbytes / best < PLACEMENT_BANDWIDTH_FLOOR / 10:
        # hopelessly slow link: extra samples can only raise the estimate
        # by the dispatch baseline, never flip the 'host-all' call, and
        # each costs ~nbytes/bandwidth seconds of startup
        return nbytes / best
    dispatch = min(
        _timed(lambda: float(total(tiny))) for _ in range(iters)
    )
    for _ in range(iters - 1):
        best = min(best, _timed(lambda: float(total(data))))
    return nbytes / max(best - dispatch, 1e-9)


def placement_mode() -> str:
    """Where reductions run, by measured link economics:

      'device'        — everything in the fused XLA pass (fast links:
                        PCIe/ICI-attached chips, or CPU-backend jax where
                        "transfer" is a memcpy)
      'host-discrete' — mask/code-only reductions fold on the host;
                        value-dense work (moments, sorts) still earns its
                        4 B/row on a mid-speed link
      'host-all'      — the link is slower than the host can simply
                        REDUCE (e.g. a ~10 MB/s tunnel): every analyzer
                        folds on the host through the same xp-generic
                        reduction code; the device program is skipped

    The scheduler analogue of Spark's map-side combine decision, decided
    by a synchronized bandwidth probe whose measurement is cached on disk
    per (host, platform, device kind) with a TTL (PLACEMENT_CACHE_TTL_S) — on
    slow tunnels the probe costs seconds of startup per process, so only
    the first process in a week pays it. Override with
    DEEQU_TPU_PLACEMENT=device|host-discrete|host|auto ('host' =
    host-all); delete <cache dir>/placement.json to force a re-probe.
    """
    global _PLACEMENT_CACHE
    import os

    env = os.environ.get("DEEQU_TPU_PLACEMENT", "auto")
    if env == "device":
        return "device"
    if env in ("host", "host-all"):
        return "host-all"
    if env == "host-discrete":
        return "host-discrete"
    if _PLACEMENT_CACHE is None:
        bandwidth = _load_bandwidth_from_disk()
        if bandwidth is None:
            try:
                bandwidth = measure_device_bandwidth()
            except Exception:  # noqa: BLE001 - no device at all -> host
                _PLACEMENT_CACHE = "host-all"
                return _PLACEMENT_CACHE
            _save_bandwidth_to_disk(bandwidth)
        # classify at use time, so cached probes survive threshold tuning
        if bandwidth >= PLACEMENT_DEVICE_ALL_BANDWIDTH:
            _PLACEMENT_CACHE = "device"
        elif bandwidth >= PLACEMENT_BANDWIDTH_FLOOR:
            _PLACEMENT_CACHE = "host-discrete"
        else:
            _PLACEMENT_CACHE = "host-all"
    return _PLACEMENT_CACHE


# a cached probe is trusted this long; after that, re-measure (links can
# change between sessions even for the same device kind)
PLACEMENT_CACHE_TTL_S = 7 * 24 * 3600


# ---------------------------------------------------------------------------
# Stream pipeline knobs (ops/pipeline.py — the staged streaming executor)
# ---------------------------------------------------------------------------

DEFAULT_PIPELINE_DEPTH = 2


def pipeline_enabled() -> bool:
    """Whether streaming scans run the backpressured stage pipeline
    (ops/pipeline.py): per-batch prep work — input builds, wire packing
    with its H2D put, family kernels — moves onto a dedicated stage
    thread that runs ahead of the consumer's ordered fold, so batch
    N+1's transfer/host work overlaps batch N's compute.

    `DEEQU_TPU_PIPELINE=0` (or `off`) forces the serial path, which is
    bit-identical: the pipeline changes WHERE per-batch work runs, never
    what is computed or the fold order."""
    import os

    return os.environ.get("DEEQU_TPU_PIPELINE", "") not in ("0", "off")


def pushdown_enabled() -> bool:
    """Whether parquet scans may skip row groups the static pruning
    interpreter (lint/pushdown.py) proves carry no qualifying row for
    ANY fused member's where filter, and may swap proven-all-true
    filters for constant masks.

    `DEEQU_TPU_PUSHDOWN=0` (or `off`) disables both: every group decodes
    and every filter evaluates, exactly as before the analyzer existed —
    the baseline the pushdown differential suite compares against.
    Pruning is a pure decode-skip: folds are where-masked, so results
    are bit-identical either way."""
    import os

    return os.environ.get("DEEQU_TPU_PUSHDOWN", "") not in ("0", "off")


def decode_fastpath_enabled() -> bool:
    """Whether parquet decode may route planner-approved columns through
    the buffer-level native kernels (ops/native/decode.c) instead of the
    host from_arrow chain.

    `DEEQU_TPU_DECODE_FASTPATH=0` (or `off`) forces every column through
    the host chain — the baseline the decode differential suite compares
    against. Both paths emit bit-identical Columns, so this knob only
    moves decode time, never results."""
    import os

    return os.environ.get("DEEQU_TPU_DECODE_FASTPATH", "") not in ("0", "off")


def wire_fused_enabled() -> bool:
    """Whether planner-approved packed-only columns may decode STRAIGHT
    to the device wire format (ops/native/decode.c wire kernels):
    bitpacked mask rows, narrowed int rows, shifted float rows emitted
    by the decode workers, skipping both the Column intermediate and
    pack_batch_inputs' serial numpy pack for those columns.

    `DEEQU_TPU_WIRE_FUSED=0` (or `off`) is the kill switch: every column
    materializes a Column and packs in prep, exactly as before — the
    baseline the wire differential suite compares against. The device
    sees identical input values either way, so metrics are
    bit-identical; only where the wire bytes are produced changes."""
    import os

    return os.environ.get("DEEQU_TPU_WIRE_FUSED", "") not in ("0", "off")


def state_cache_enabled() -> bool:
    """Whether partitioned scans may consult an attached StateRepository
    (repository/states.py): partitions whose fingerprint + plan
    signature already have a stored state envelope load as states
    instead of decoding and folding their rows.

    `DEEQU_TPU_STATE_CACHE=0` (or `off`) is the kill switch: every
    partition scans, exactly as with no repository attached — the
    baseline the state-cache differential suite compares against.
    Partitioned sources fold per partition and merge in deterministic
    partition order either way, so results are bit-identical; only
    whether a partition's states come from a scan or from disk
    changes."""
    import os

    return os.environ.get("DEEQU_TPU_STATE_CACHE", "") not in ("0", "off")


def scan_sharing_enabled() -> bool:
    """Whether the DQService may merge co-tenant submissions over the
    same dataset fingerprint into ONE superset fused scan (fleet-level
    scan sharing, service/sharing.py) when the plan-subsumption prover
    (lint/subsume.py) proves every participant contained.

    `DEEQU_TPU_SCAN_SHARING=0` (or `off`) is the kill switch: every
    submission scans solo, exactly as before sharing existed — the
    baseline the sharing differential suite compares against. Metrics
    are bit-identical either way (the fan-out rides the state
    semigroup); only how many times the table is read changes."""
    import os

    return os.environ.get("DEEQU_TPU_SCAN_SHARING", "") not in ("0", "off")


def share_group_max() -> int:
    """Cap on participants in one shared scan
    (`DEEQU_TPU_SHARE_GROUP_MAX`, default 8): bounds the fan-out a
    single worker performs and the blast radius of one preemption."""
    import os

    raw = os.environ.get("DEEQU_TPU_SHARE_GROUP_MAX", "")
    try:
        n = int(raw) if raw else 8
    except ValueError:
        return 8
    return max(1, n)


def pallas_folds_enabled() -> bool:
    """Whether the numeric moments/min-max state folds may run as
    Pallas kernels (ops/pallas_kernels.py) on platforms that compile
    them. `DEEQU_TPU_PALLAS_FOLDS=0` (or `off`) is the kill switch.
    Call sites additionally require `pallas_kernels.usable()` (a TPU
    probe — always False on CPU, where the XLA fold runs unchanged) and
    a block-aligned batch shape. UNLIKE the pipeline/pushdown/decode
    knobs, the blocked Pallas sum is NOT bit-identical to the XLA
    reduction, so this knob enters the plan signature as a fold
    variant (`fold_variant`) — cached states never cross the two
    arithmetics."""
    import os

    return os.environ.get("DEEQU_TPU_PALLAS_FOLDS", "") not in ("0", "off")


def fold_variant() -> str:
    """The fold-arithmetic variant tag the plan signature hashes:
    "pallas-folds" when the Pallas moments folds are enabled AND the
    platform actually compiles them, else "" (the default arithmetic —
    signatures unchanged). On CPU this is always "" — interpret-mode
    kernel runs live only in tests, never in the product fold."""
    if not pallas_folds_enabled():
        return ""
    from deequ_tpu.ops import pallas_kernels

    return "pallas-folds" if pallas_kernels.usable() else ""


def fold_signature_variant() -> str:
    """The variant tag plan signatures actually hash: `fold_variant`
    plus an "encfold" mode tag whenever the encoded-fold path could
    engage (kill switch on, the native reader stack it rides on
    enabled, and the native library loadable). Encoded-fold results are
    bit-identical to the row fold by construction, but cached states
    must still never mix across the two fold modes — same conservatism
    as the pallas tag, applied to a mode that changes where states come
    from rather than their arithmetic."""
    base = fold_variant()
    if (
        encoded_fold_enabled()
        and native_reader_enabled()
        and decode_fastpath_enabled()
    ):
        from deequ_tpu.ops import native

        if native.available():
            return base + "+encfold" if base else "encfold"
    return base


def shard_tag() -> str:
    """This process's shard tag in a sharded scan (`DEEQU_TPU_SHARD`,
    set by the mesh launcher for each worker): a short string like "2"
    that worker-thread names and heartbeat lines carry, so watchdog
    dumps and merged cross-process traces attribute work to the right
    shard. Empty outside sharded runs — names are unchanged."""
    import os

    return os.environ.get("DEEQU_TPU_SHARD", "")


def native_reader_enabled() -> bool:
    """Whether planner-approved column chunks may be read by the native
    parquet reader (ops/native/parquet_read.c): page headers parsed,
    page bodies decompressed (snappy/zstd via dlopen) and PLAIN /
    RLE-dictionary / RLE-boolean values decoded straight into the same
    Arrow-layout buffers the decode fast path consumes — pyarrow never
    touches those chunks, and the read thread preads ahead of decode.

    `DEEQU_TPU_NATIVE_READER=0` (or `off`) is the kill switch: every
    chunk arrives through pyarrow exactly as before — the baseline the
    reader differential suite compares against. The decode and wire
    kernels see bit-identical buffers either way, so metrics are
    bit-identical; only who produced the bytes changes."""
    import os

    return os.environ.get("DEEQU_TPU_NATIVE_READER", "") not in ("0", "off")


def encoded_fold_enabled() -> bool:
    """Whether planner-approved dictionary-coded columns may fold
    analyzer family state over (run_length, dict_code) streams straight
    off the page decoder (ops/native/parquet_read.c runs mode +
    ops/native/encfold.c) instead of expanding to row width first.

    `DEEQU_TPU_ENCODED_FOLD=0` (or `off`) is the kill switch: every
    chunk expands to rows exactly as before — the baseline the
    encoded-fold differential suite compares against. The run-fold
    derivations share the row path's counts-family code and decline
    whenever bit-identity is not proven for a batch, so metrics are
    bit-identical either way; only how many bytes get materialized
    changes. The mode still enters the plan signature
    (`fold_signature_variant`) so cached states never mix across the
    two fold paths."""
    import os

    return os.environ.get("DEEQU_TPU_ENCODED_FOLD", "") not in ("0", "off")


def forensics_enabled() -> bool:
    """Whether verification runs capture failure forensics by default
    (observe/forensics.py): a bounded deterministic sample of violating
    rows per row-level-capable constraint, plus the run's provenance
    record, persisted as an audit trail.

    Unlike every other knob this one defaults OFF — capture does real
    per-batch work, so it must be asked for: `DEEQU_TPU_FORENSICS=1`
    (or `on`/`true`), or `with_forensics()` on the run builder. When
    off the fused pass carries a None capture and the per-batch hook is
    one falsy check — the forensics differential suite proves the off
    path bit-identical and the overhead suite bounds it under the same
    budget as tracing."""
    import os

    return os.environ.get("DEEQU_TPU_FORENSICS", "") in ("1", "on", "true")


def wire_pad_size(n: int, batch_size: int) -> int:
    """The fused pass's padded row length for an n-row batch (mirror of
    ops/fused.py:_pad_size, which delegates here): power of two, min 8,
    capped at batch_size rounded up to a multiple of 8. Lives in runtime
    so data/source.py's decode-to-wire path can size wire rows without
    importing the fused engine."""
    size = 8
    while size < n:
        size *= 2
    return min(size, max(-(-batch_size // 8) * 8, 8))


@dataclass(frozen=True)
class ColumnWireSpec:
    """Statically pinned wire layout for one decode-to-wire column:
    which wire rows its packed consumers need and the exact dtypes, so
    every batch of the pass ships the same layout (the sticky contract)
    and decode can emit final wire bytes without seeing any data."""

    column: str
    token: str  # arrow type token the chunk must match at decode time
    want_value: bool  # a num:{column} spec is live
    want_valid: bool  # a valid:{column} spec is live
    value_kind: str = ""  # "val" (compute dtype) | "ival" (narrow int)
    value_dtype: str = ""  # numpy dtype name of the wire value row
    needs_shift: bool = False  # f32 wire: wait for the sticky shift
    desc: str = ""  # short render token for EXPLAIN ("f64", "i8", ...)


@dataclass
class WireRow:
    """One pre-packed wire row decode attaches to a batch Table
    (`table.wire_rows[key]`): the padded buffer pack_batch_inputs splices
    into the batch's group buffer verbatim."""

    kind: str  # "bits" | "val" | "ival"
    arr: "np.ndarray"
    shift: float = 0.0
    all_valid: bool = False  # bits row with zero invalid rows (may elide)


class WireFusionPlan:
    """The decode↔pack handshake for one fused pass.

    Carries the per-column ColumnWireSpecs plus the pass batch size (for
    padded-row sizing), and coordinates the f32 wire's scan-constant
    pre-centering shifts: decode cannot know them statically, so
    shift-needing columns stay on the Column path until the FIRST
    batch's pack resolves the shifts (resolve_shift, single prep thread)
    and publishes them here; later batches then fuse with the exact
    sticky shift. On the f64 wire no key shifts and the gate is open
    from the start."""

    def __init__(self, columns, batch_size: int):
        import threading

        self.columns = dict(columns)  # column -> ColumnWireSpec
        self.batch_size = int(batch_size)
        self.shifts: dict = {}
        self._abandoned = False
        self._pack_started = False
        self._shift_ready = threading.Event()
        if not any(s.needs_shift for s in self.columns.values()):
            self._shift_ready.set()

    @property
    def shift_keys(self) -> List[str]:
        return [
            f"num:{c}" for c, s in self.columns.items() if s.needs_shift
        ]

    def mark_pack_started(self) -> None:
        """The prep thread is about to pack a batch. Until this point a
        shift_for wait is pure stall — nothing can possibly publish —
        so decode workers return None immediately instead (the
        first-batch fallback is by design). GIL-atomic bool write."""
        self._pack_started = True

    def publish_shifts(self, shifts: dict) -> None:
        self.shifts.update(shifts)
        self._shift_ready.set()

    def abandon_shifts(self) -> None:
        """The pack path died before resolving shifts (device failure):
        shift-needing columns decode through the Column path forever."""
        self._abandoned = True
        self._shift_ready.set()

    def shift_for(self, key: str, timeout: float = 0.25):
        """The published sticky shift for a num: key, or None when not
        (yet) available — the caller falls back to the Column path for
        this batch and retries on the next. Non-blocking until the
        first pack is underway (mark_pack_started): before that the
        publish cannot happen, and waiting would serialize a full
        timeout per shift-needing column into the first batch's decode.
        Once a pack is in flight the short wait lets the overlapped
        next batch catch the publish instead of falling back."""
        if not self._shift_ready.is_set():
            if not self._pack_started:
                return None
            if not self._shift_ready.wait(timeout):
                return None
        if self._abandoned:
            return None
        return float(self.shifts.get(key, 0.0))


def decode_workers() -> int:
    """Number of parallel row-group decode workers
    (`DEEQU_TPU_DECODE_WORKERS`, default `min(cores, 4)`; 1 = the
    single decode thread the pipeline always had). pyarrow and the
    native decode kernels release the GIL, so workers scale decode on
    multi-core boxes; the merge back into the pipeline is in submission
    order, so results are bit-identical at any worker count."""
    import os

    raw = os.environ.get("DEEQU_TPU_DECODE_WORKERS", "")
    try:
        workers = int(raw)
    except ValueError:
        workers = 0
    if workers < 1:
        workers = min(os.cpu_count() or 1, 4)
    return workers


def pipeline_depth() -> int:
    """Bounded inter-stage queue depth (`DEEQU_TPU_PIPELINE_DEPTH`,
    default 2): at most this many prepped batches — packed wire buffers
    already put to the device — wait between the prep and fold stages.
    Depth 1 is classic double-buffering; deeper queues smooth decode
    jitter at the cost of one resident batch each. Host memory stays
    O(depth + constant) batches."""
    import os

    raw = os.environ.get("DEEQU_TPU_PIPELINE_DEPTH", "")
    try:
        depth = int(raw)
    except ValueError:
        return DEFAULT_PIPELINE_DEPTH
    return max(1, min(depth, 64))


def source_stall_s() -> float:
    """Per-row-group source stall in seconds (`DEEQU_TPU_SOURCE_STALL_MS`,
    default 0 = off): a latency-injection knob for benchmarking the
    pipeline against sources with real per-read wait — object-store GETs,
    network filesystems — on boxes whose local disk is too fast (and
    whose kernel readahead too good) for decode/IO overlap to matter.
    The stall is paid by whichever thread runs the decode: the caller
    under `DEEQU_TPU_PIPELINE=0`, the decode stage thread when pipelined
    — so an A/B with the knob set measures exactly how much source wait
    the pipeline hides. Never set it for real-throughput numbers."""
    import os

    raw = os.environ.get("DEEQU_TPU_SOURCE_STALL_MS", "")
    if not raw:
        return 0.0
    try:
        return max(0.0, float(raw)) / 1000.0
    except ValueError:
        return 0.0


def retry_budget() -> int:
    """Bounded retries per transient-IO operation in the readahead
    fetch path (`DEEQU_TPU_RETRIES`, default 3, 0 = no retry): a failed
    or short pread/ranged GET re-issues with exponential backoff up to
    this many times before the unit degrades to the pyarrow fallback —
    a retried transient fault costs milliseconds, an exhausted budget
    costs one unit's fallback decode, and neither ever changes a metric
    (the chaos differential in tests/test_suite_differential_fuzz.py
    pins bit-identity under injected faults). Outcomes are counted as
    `engine.retry.*` telemetry watched by the sentinel."""
    import os

    raw = os.environ.get("DEEQU_TPU_RETRIES", "")
    if not raw:
        return 3
    try:
        return max(0, int(raw))
    except ValueError:
        return 3


def retry_base_s() -> float:
    """First-retry backoff in seconds (`DEEQU_TPU_RETRY_BASE_MS`,
    default 10ms): attempt k sleeps `base * 2^k` with deterministic
    jitter (core/controller.backoff_s). Tests shrink it to keep chaos
    runs fast; production leaves the default so a flapping object store
    is not hammered."""
    import os

    raw = os.environ.get("DEEQU_TPU_RETRY_BASE_MS", "")
    if not raw:
        return 0.010
    try:
        return max(0.0, float(raw)) / 1000.0
    except ValueError:
        return 0.010


def stall_watchdog_s() -> float:
    """Stall-watchdog window in seconds (`DEEQU_TPU_STALL_WATCHDOG_S`,
    default 0 = off): when positive AND a RunController is attached to
    the run, a watchdog thread checks the controller's per-batch beat
    counter every window; one silent window dumps per-stage state to
    stderr (heartbeat snapshot when live, else engine thread stacks),
    two consecutive silent windows cancel the run with DQ404 — a wedged
    scan fails with forensics instead of hanging forever."""
    import os

    raw = os.environ.get("DEEQU_TPU_STALL_WATCHDOG_S", "")
    if not raw:
        return 0.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 0.0


def service_workers() -> int:
    """Worker-pool size for the long-lived DQService
    (`DEEQU_TPU_SERVICE_WORKERS`, default 2): how many suites execute
    concurrently over the shared pool. Admission control bounds what
    reaches the pool; this bounds what runs at once."""
    import os

    raw = os.environ.get("DEEQU_TPU_SERVICE_WORKERS", "")
    if not raw:
        return 2
    try:
        return max(1, int(raw))
    except ValueError:
        return 2


def service_drain_s() -> float:
    """Graceful-drain window in seconds for the DQService
    (`DEEQU_TPU_SERVICE_DRAIN_S`, default 30): on SIGTERM / close(),
    running suites get this long to commit their in-flight partition
    and unwind through the soft-cancel (DQ407) before the drain
    escalates to a hard cancel. Queued work is returned immediately
    with DQ414 either way."""
    import os

    raw = os.environ.get("DEEQU_TPU_SERVICE_DRAIN_S", "")
    if not raw:
        return 30.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 30.0


def heartbeat_s() -> float:
    """Live scan heartbeat interval in seconds (`DEEQU_TPU_HEARTBEAT_S`,
    default 0 = off): when positive, streaming scans emit periodic
    progress snapshots — completed/predicted batches, instantaneous
    rows/s, pipeline-stage bottleneck, ETA — through
    `observe.heartbeat` (registered callbacks, or JSONL lines at
    `DEEQU_TPU_HEARTBEAT_OUT`, falling back to stderr). Disabled, the
    scan loop touches only a falsy no-op handle and no timer thread is
    ever spawned."""
    from deequ_tpu.observe import heartbeat

    return heartbeat.env_interval_s()


def _platform_key() -> Optional[str]:
    """Identity of the attached LINK — the cache key. Bandwidth is a
    property of how THIS HOST reaches the device, not of the device kind
    alone: the same device kind reached locally vs over a tunnel has
    wildly different bandwidth, so the host name is part of the key."""
    import socket

    try:
        device = jax.devices()[0]
        host = socket.gethostname() or "?"
        return f"{host}:{device.platform}:{getattr(device, 'device_kind', '?')}"
    except Exception:  # noqa: BLE001
        return None


def _placement_cache_path() -> Optional[str]:
    import os

    from deequ_tpu.ops.native import per_user_cache_dir

    directory = per_user_cache_dir()
    if directory is None:
        return None
    return os.path.join(directory, "placement.json")


def _load_bandwidth_from_disk() -> Optional[float]:
    """The probe costs seconds of real time on slow tunnels (two device
    compiles + synchronized fetches), so the MEASURED BANDWIDTH is
    cached per (host, platform, device kind) with a TTL. Delete the file
    (or set DEEQU_TPU_PLACEMENT) to force a re-probe."""
    import json
    import os

    path = _placement_cache_path()
    key = _platform_key()
    if path is None or key is None or not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            return None
        entry = data.get(key)
        if not isinstance(entry, dict):
            return None
        bandwidth = entry.get("bandwidth")
        ts = entry.get("ts", 0)
        if not isinstance(bandwidth, (int, float)) or bandwidth <= 0:
            return None
        if time.time() - float(ts) > PLACEMENT_CACHE_TTL_S:
            return None
        return float(bandwidth)
    except (OSError, ValueError, TypeError):
        return None


def _save_bandwidth_to_disk(bandwidth: float) -> None:
    import json
    import os

    from deequ_tpu.core.fileio import write_text_output

    path = _placement_cache_path()
    key = _platform_key()
    if path is None or key is None:
        return
    data = {}
    try:
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                loaded = json.load(f)
            if isinstance(loaded, dict):
                data = loaded
    except (OSError, ValueError):
        data = {}
    data[key] = {"bandwidth": float(bandwidth), "ts": time.time()}
    # drop expired/garbage entries on save (old key formats and renamed
    # hosts would otherwise sit in placement.json forever)
    now = time.time()
    data = {
        k: v
        for k, v in data.items()
        if isinstance(v, dict)
        and isinstance(v.get("ts"), (int, float))
        and now - float(v["ts"]) <= PLACEMENT_CACHE_TTL_S
    }
    try:
        write_text_output(path, json.dumps(data), overwrite=True)
    except OSError:
        pass


@dataclass
class ExecutionStats:
    """Counts of engine work during a monitored block."""

    device_passes: int = 0  # one per fused scan over a dataset (≈ Spark job)
    device_launches: int = 0  # one per compiled-program invocation (per batch)
    group_passes: int = 0  # one per group-by frequency computation
    pass_labels: List[str] = field(default_factory=list)

    @property
    def jobs(self) -> int:
        return self.device_passes + self.group_passes


@contextlib.contextmanager
def monitored() -> Iterator[ExecutionStats]:
    """Collect engine-execution counts for everything run inside the block.

    Counting itself lives in `deequ_tpu.observe.counters` (thread-local
    sink stack, shared with the tracing subsystem so span pass-count
    attributes stay bit-identical to these stats); this wrapper keeps
    the historical `runtime.monitored()` surface."""
    stats = ExecutionStats()
    with _counters.collect(stats):
        yield stats


def record_pass(label: str) -> None:
    _counters.record_pass(label)


def record_launch() -> None:
    _counters.record_launch()


def record_group_pass(label: str) -> None:
    _counters.record_group_pass(label)


def record_pruned_groups(skipped: int, total: int) -> None:
    _counters.record_pruned_groups(skipped, total)


def record_decode_fastpath(fast: int, total: int, workers: int) -> None:
    _counters.record_decode_fastpath(fast, total, workers)


def record_wire_fused(fused: int, total: int) -> None:
    _counters.record_wire_fused(fused, total)


def record_plan_cache(hit: bool) -> None:
    _counters.record_plan_cache(hit)


def record_state_cache(cached: int, scanned: int, total: int) -> None:
    _counters.record_state_cache(cached, scanned, total)


def record_window(
    segments: int, hits: int, built: int, rescanned: int, partitions: int
) -> None:
    _counters.record_window(segments, hits, built, rescanned, partitions)


def record_reader_chunks(native: int, fallback: int, total: int) -> None:
    _counters.record_reader_chunks(native, fallback, total)


def record_encfold_plan(cols: int, total: int) -> None:
    _counters.record_encfold_plan(cols, total)


def record_encfold(
    chunks: int,
    fallback: int,
    runs: int,
    values: int,
    codes: int,
    bytes_saved: int,
) -> None:
    _counters.record_encfold(
        chunks, fallback, runs, values, codes, bytes_saved
    )


def record_retry(attempts: int, recovered: int, exhausted: int) -> None:
    _counters.record_retry(attempts, recovered, exhausted)


def record_fault(injected: int = 0, fallback_units: int = 0) -> None:
    _counters.record_fault(injected, fallback_units)


def record_shard_scan(
    shard: int,
    num_shards: int,
    partitions_local: int,
    partitions_max: int,
    partitions_total: int,
    merge_bytes: int,
    rows_local: int,
) -> None:
    _counters.record_shard_scan(
        shard,
        num_shards,
        partitions_local,
        partitions_max,
        partitions_total,
        merge_bytes,
        rows_local,
    )


def pad_to(arr: np.ndarray, size: int) -> np.ndarray:
    """Pad a 1-D host array to `size` rows (content irrelevant: padded rows
    carry where/valid = False so they never contribute to reductions).
    Keeps one compiled shape per batch size instead of one per tail."""
    n = len(arr)
    if n == size:
        return arr
    pad = np.zeros(size - n, dtype=arr.dtype)
    return np.concatenate([arr, pad])

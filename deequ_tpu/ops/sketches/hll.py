"""HyperLogLog++ distinct-count sketch, TPU-shaped.

Replaces the reference's imperative JVM kernel
(reference: catalyst/StatefulHyperloglogPlus.scala:31-298) with a split
design: the host vectorizes hashing (numpy xxhash64 for 8-byte values,
a vectorized xxhash-style mix over unique strings — ops/strings.py), the
device owns the register
scatter-max (`zeros.at[idx].max(rank)`), and merging is elementwise max —
which on a mesh is literally `lax.pmax` over the register array.

Same parameters as the reference: relativeSD=0.05 -> p=9, m=512 registers
(reference: StatefulHyperloglogPlus.scala:154-155). Estimation is the
full HLL++ pipeline — linear counting under the precision threshold,
empirical bias interpolation (K=6 nearest points of the published p=9
tables, hll_bias.py) below 5m, raw estimate above — with the same branch
structure as the reference (StatefulHyperloglogPlus.scala:210-297), so
small cardinalities are exact integers and mid-range estimates carry the
same correction.
"""

from __future__ import annotations

import numpy as np

P = 9  # precision: derived from RELATIVE_SD = 0.05 like the reference
M = 1 << P  # 512 registers
ALPHA_M2 = (0.7213 / (1.0 + 1.079 / M)) * M * M
SEED = np.uint64(42)

# xxhash64 constants (public algorithm constants, Cyan4973/xxHash)
_PRIME1 = np.uint64(0x9E3779B185EBCA87)
_PRIME2 = np.uint64(0xC2B2AE3D27D4EB4F)
_PRIME3 = np.uint64(0x165667B19E3779F9)
_PRIME4 = np.uint64(0x85EBCA77C2B2AE63)
_PRIME5 = np.uint64(0x27D4EB2F165667C5)


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    r = np.uint64(r)
    return (x << r) | (x >> (np.uint64(64) - r))


def _rotl_inplace(x: np.ndarray, r: int, scratch: np.ndarray) -> np.ndarray:
    """x <- rotl(x, r) using a preallocated scratch buffer."""
    np.right_shift(x, np.uint64(64 - r), out=scratch)
    np.left_shift(x, np.uint64(r), out=x)
    np.bitwise_or(x, scratch, out=x)
    return x


def xxhash64_u64(values: np.ndarray, seed: np.uint64 = SEED) -> np.ndarray:
    """Vectorized xxhash64 of 8-byte values (the hot path for numeric
    columns). In-place numpy ops: two buffers total, no per-op
    temporaries — this runs at memory speed over billions of rows."""
    with np.errstate(over="ignore"):
        v = values.view(np.uint64) if values.dtype == np.int64 else values.astype(np.uint64)
        acc = v * _PRIME2  # fresh buffer; v itself is never written
        scratch = np.empty_like(acc)
        _rotl_inplace(acc, 31, scratch)
        acc *= _PRIME1
        acc ^= seed + _PRIME5 + np.uint64(8)
        _rotl_inplace(acc, 27, scratch)
        acc *= _PRIME1
        acc += _PRIME4
        np.right_shift(acc, np.uint64(33), out=scratch)
        acc ^= scratch
        acc *= _PRIME2
        np.right_shift(acc, np.uint64(29), out=scratch)
        acc ^= scratch
        acc *= _PRIME3
        np.right_shift(acc, np.uint64(32), out=scratch)
        acc ^= scratch
        return acc


def canonical_int64(values: np.ndarray) -> np.ndarray:
    """Canonical 8-byte form whose xxhash64 defines a value's identity:
    floats by their float64 bit pattern, timestamps as epoch-us, ints and
    bools as int64 (reference: the Catalyst kernel hashes the raw 8-byte
    value the same way, StatefulHyperloglogPlus.scala:86-115).

    Strings have no 8-byte canonical form — they go through the
    dictionary + hash_strings path (pack_codes handles the dispatch)."""
    if values.dtype == object or values.dtype.kind == "U":
        raise TypeError(
            "string values have no canonical int64 form; use the "
            "dictionary hash path"
        )
    if values.dtype == np.bool_:
        return values.astype(np.int64)
    if np.issubdtype(values.dtype, np.floating):
        return values.astype(np.float64).view(np.int64)
    if np.issubdtype(values.dtype, np.datetime64):
        return values.astype("datetime64[us]").astype(np.int64)
    return values.astype(np.int64, copy=False)


def pack_codes(values: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """(register idx << 6 | rank) int32 per row; 0 for invalid rows.

    The one-pass C kernel (ops/native) does hash+clz+pack at memory
    speed; the numpy fallback computes the identical codes in ~15
    vectorized passes. String dtypes (object or numpy-unicode) hash via
    the unique-dictionary path instead."""
    from deequ_tpu.ops import native

    if values.dtype == object or values.dtype.kind == "U":
        from deequ_tpu.ops.strings import hash_strings

        uniques, inv = np.unique(values[valid].astype(str), return_inverse=True)
        idx, rank = registers_from_hashes(hash_strings(uniques))
        packed = np.zeros(len(values), dtype=np.int32)
        packed[valid] = ((idx << 6) | rank)[inv]
        return packed

    canon = canonical_int64(values)
    packed = native.xxhash64_pack(canon, valid)
    if packed is not None:
        return packed
    idx, rank = registers_from_hashes(xxhash64_u64(canon[valid]))
    packed = np.zeros(len(values), dtype=np.int32)
    packed[valid] = (idx << 6) | rank
    return packed


def registers_from_hashes(hashes: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(register index, rank) per hash: idx = top P bits, rank = 1 +
    leading zeros of the remaining bits (capped for the 6-bit register).

    CLZ is vectorized EXACTLY via the f64 exponent of the top 32 bits
    (uint32 -> f64 is lossless, so floor(log2(top)) is the true
    exponent); this matches the C kernel's __builtin_clzll bit for bit.
    top==0 (probability 2^-32 per value) falls back to a scalar loop."""
    idx = (hashes >> np.uint64(64 - P)).astype(np.int32)
    rest = (hashes << np.uint64(P)) | (np.uint64(1) << np.uint64(P - 1))
    top = (rest >> np.uint64(32)).astype(np.uint32)
    f_bits = top.astype(np.float64).view(np.uint64)
    exponent = (f_bits >> np.uint64(52)).astype(np.int32) - 1023
    rank = 32 - exponent
    zero_top = top == 0
    if zero_top.any():
        for i in np.nonzero(zero_top)[0]:
            rank[i] = 65 - int(rest[i]).bit_length()
    np.clip(rank, 1, 64 - P + 1, out=rank)
    return idx, rank


def update_registers(registers: np.ndarray, idx: np.ndarray, rank: np.ndarray) -> np.ndarray:
    """Host-side register max-merge; device path uses .at[idx].max."""
    np.maximum.at(registers, idx, rank)
    return registers


def merge_registers(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.maximum(a, b)


def estimate_bias(e: float) -> float:
    """Empirical bias for a raw estimate: mean bias of the K=6 nearest
    interpolation points, by squared distance, exactly like the reference
    (reference: StatefulHyperloglogPlus.scala:258-297)."""
    from deequ_tpu.ops.sketches.hll_bias import BIAS_P9, K_NEAREST, RAW_ESTIMATE_P9

    estimates = RAW_ESTIMATE_P9
    n = len(estimates)
    nearest = int(np.searchsorted(estimates, e, side="left"))

    low = max(nearest - K_NEAREST + 1, 0)
    high = min(low + K_NEAREST, n)
    while high < n and (e - estimates[high]) ** 2 < (e - estimates[low]) ** 2:
        low += 1
        high += 1
    return float(np.mean(BIAS_P9[low:high]))


def estimate(registers: np.ndarray) -> float:
    """Full HLL++ estimator: raw estimate with empirical bias correction
    below 5m, linear counting below the precision threshold, rounded
    (reference: StatefulHyperloglogPlus.scala:210-256 — same branch
    structure and constants)."""
    from deequ_tpu.ops.sketches.hll_bias import THRESHOLD_P9

    z_inverse = np.sum(np.float64(1.0) / (np.uint64(1) << registers.astype(np.uint64)))
    v = float(np.sum(registers == 0))

    e = ALPHA_M2 / z_inverse
    e_bias_corrected = e - estimate_bias(e) if e < 5.0 * M else e

    if v > 0:
        # linear counting for small cardinalities
        h = M * np.log(M / v)
        if h <= THRESHOLD_P9:
            return float(round(h))
    return float(round(e_bias_corrected))


def pack_words(registers: np.ndarray) -> np.ndarray:
    """512 6-bit registers -> 52 packed int64 words (10 registers/word),
    the reference's persisted layout
    (reference: StatefulHyperloglogPlus.scala:154, HLLConstants)."""
    regs_per_word = 10
    num_words = (M + regs_per_word - 1) // regs_per_word  # 52
    words = np.zeros(num_words, dtype=np.uint64)
    for i in range(M):
        w, slot = divmod(i, regs_per_word)
        words[w] |= np.uint64(int(registers[i]) & 0x3F) << np.uint64(6 * slot)
    return words.view(np.int64)


def unpack_words(words: np.ndarray) -> np.ndarray:
    regs_per_word = 10
    uw = words.view(np.uint64) if words.dtype == np.int64 else words.astype(np.uint64)
    registers = np.zeros(M, dtype=np.int32)
    for i in range(M):
        w, slot = divmod(i, regs_per_word)
        registers[i] = int((uw[w] >> np.uint64(6 * slot)) & np.uint64(0x3F))
    return registers

"""KLL quantile sketch: mergeable, bounded-memory rank queries.

Replaces the reference's Greenwald-Khanna digest fork
(reference: catalyst/StatefulApproxQuantile.scala:28 — forked so `eval`
returns the serialized, mergeable digest). KLL fits the TPU engine better:
updates are batched sorts/decimations over dense arrays (vectorized, no
per-item pointer chasing) and merge is concatenate+compact, so per-batch
partial sketches stream from device-filtered values and fold on the host.

Rank error: eps ~ 2.3/k with the default k chosen for the reference's
relativeError=0.01 contract (reference: analyzers/ApproxQuantile.scala:49).
Quantile answers pick the smallest item whose cumulative weight reaches
q*n, matching percentile-of-dataset-element semantics (exact below k items,
like the reference's digest on small data).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

DEFAULT_K = 512  # eps ≈ 2.3/k ≈ 0.0045 < 0.01 default contract


def k_for_error(relative_error: float) -> int:
    if relative_error <= 0:
        return 1 << 16
    return max(8, int(np.ceil(2.3 / relative_error)))


class KLLSketch:
    """Levels of sorted buffers; level i items carry weight 2^i."""

    __slots__ = ("k", "levels", "n", "_rng", "_buffer")

    def __init__(self, k: int = DEFAULT_K, seed: int = 0):
        self.k = int(k)
        self.levels: List[np.ndarray] = [np.empty(0, dtype=np.float64)]
        self.n = 0
        self._rng = np.random.default_rng(seed)
        self._buffer: List[np.ndarray] = []

    # -- updates -------------------------------------------------------------

    def update_batch(self, values: np.ndarray) -> "KLLSketch":
        values = np.asarray(values, dtype=np.float64)
        m = len(values)
        if m == 0:
            return self
        if m >= 8 * self.k:
            return self._bulk_insert(values)
        self.n += m
        self._buffer.append(values)
        buffered = sum(len(b) for b in self._buffer)
        if buffered >= self._capacity(0):
            self._flush()
        return self

    def _bulk_insert(self, values: np.ndarray) -> "KLLSketch":
        """Large batch: ONE sort, then stride-2^L decimation straight into
        level L — equivalent to L cascaded pairwise compactions collapsed
        into a single step (one random offset instead of L independent
        ones; the introduced rank error stays O(2^L), the same order as
        the cascade's). Turns per-batch cost from ~2 sorts of m into one."""
        m = len(values)
        target_level = max(0, int(np.ceil(np.log2(m / (2.0 * self.k)))))
        stride = 1 << target_level
        sorted_vals = np.sort(values)
        offset = int(self._rng.integers(0, stride))
        promoted = sorted_vals[offset::stride]
        return self.insert_level(promoted, target_level, true_count=m)

    def insert_level(
        self,
        sorted_values: np.ndarray,
        level: int,
        true_count: Optional[int] = None,
    ) -> "KLLSketch":
        """Insert an already-decimated SORTED sample whose items carry
        weight 2^level (the device-sort path hands these over: the device
        sorts and stride-decimates, the host only merges). `true_count`
        is the exact number of underlying rows the sample summarizes."""
        self.n += int(true_count) if true_count is not None else (
            len(sorted_values) << level
        )
        if len(sorted_values) == 0:
            return self
        while len(self.levels) <= level:
            self.levels.append(np.empty(0, dtype=np.float64))
        # both sides sorted: timsort exploits the runs (linear merge)
        self.levels[level] = np.sort(
            np.concatenate(
                [self.levels[level], np.asarray(sorted_values, dtype=np.float64)]
            ),
            kind="stable",
        )
        self._compress()
        return self

    def _flush(self) -> None:
        if self._buffer:
            merged = np.concatenate([self.levels[0]] + self._buffer)
            self.levels[0] = np.sort(merged)
            self._buffer = []
        self._compress()

    def _capacity(self, level: int) -> int:
        # geometrically shrinking capacities toward lower levels (c = 2/3)
        depth = len(self.levels)
        c = 2.0 / 3.0
        return max(8, int(np.ceil(self.k * (c ** (depth - 1 - level)))))

    def _compress(self) -> None:
        level = 0
        while level < len(self.levels):
            if len(self.levels[level]) > self._capacity(level):
                buf = self.levels[level]
                if len(buf) % 2 == 1:
                    # hold one item back to keep pairs aligned
                    keep, buf = buf[:1], buf[1:]
                else:
                    keep = np.empty(0, dtype=np.float64)
                offset = int(self._rng.integers(0, 2))
                promoted = buf[offset::2]
                if level + 1 >= len(self.levels):
                    self.levels.append(np.empty(0, dtype=np.float64))
                self.levels[level + 1] = np.sort(
                    np.concatenate([self.levels[level + 1], promoted]),
                    kind="stable",  # two sorted runs: linear merge
                )
                self.levels[level] = keep
            level += 1

    # -- merge ---------------------------------------------------------------

    def merge(self, other: "KLLSketch") -> "KLLSketch":
        result = KLLSketch(k=min(self.k, other.k), seed=int(self._rng.integers(1 << 31)))
        result.n = self.n + other.n
        self._flush()
        other._flush()
        depth = max(len(self.levels), len(other.levels))
        result.levels = []
        for i in range(depth):
            a = self.levels[i] if i < len(self.levels) else np.empty(0)
            b = other.levels[i] if i < len(other.levels) else np.empty(0)
            result.levels.append(np.sort(np.concatenate([a, b])))
        result._compress()
        return result

    # -- queries -------------------------------------------------------------

    def _weighted_items(self) -> tuple[np.ndarray, np.ndarray]:
        self._flush()
        items = []
        weights = []
        for level, buf in enumerate(self.levels):
            if len(buf):
                items.append(buf)
                weights.append(np.full(len(buf), 1 << level, dtype=np.int64))
        if not items:
            return np.empty(0), np.empty(0, dtype=np.int64)
        all_items = np.concatenate(items)
        all_weights = np.concatenate(weights)
        order = np.argsort(all_items, kind="stable")
        return all_items[order], all_weights[order]

    def quantile(self, q: float) -> float:
        if self.n == 0:
            raise ValueError("empty sketch")
        items, weights = self._weighted_items()
        total = weights.sum()
        target = q * total
        cum = np.cumsum(weights)
        idx = int(np.searchsorted(cum, target, side="left"))
        idx = min(idx, len(items) - 1)
        return float(items[idx])

    def quantiles(self, qs) -> List[float]:
        if self.n == 0:
            raise ValueError("empty sketch")
        items, weights = self._weighted_items()
        total = weights.sum()
        cum = np.cumsum(weights)
        out = []
        for q in qs:
            idx = int(np.searchsorted(cum, q * total, side="left"))
            out.append(float(items[min(idx, len(items) - 1)]))
        return out

    def rank(self, value: float) -> float:
        """Approximate fraction of items <= value."""
        if self.n == 0:
            return 0.0
        items, weights = self._weighted_items()
        idx = int(np.searchsorted(items, value, side="right"))
        return float(weights[:idx].sum()) / float(weights.sum())

    # -- serde ---------------------------------------------------------------

    def to_arrays(self) -> tuple[int, int, List[np.ndarray]]:
        self._flush()
        return self.k, self.n, self.levels

    @staticmethod
    def from_arrays(k: int, n: int, levels: List[np.ndarray]) -> "KLLSketch":
        sketch = KLLSketch(k=k)
        sketch.n = n
        sketch.levels = [np.asarray(lv, dtype=np.float64) for lv in levels]
        return sketch

    # `merge` seeds its result from self._rng, so a sketch's future merge
    # behaviour depends on the generator's position, not just (k, n,
    # levels). Round-tripping that position is what lets a deserialized
    # partial (state cache, DCN envelope) merge bit-identically to the
    # live sketch it was saved from.

    RNG_STATE_LEN = 37

    def rng_state_bytes(self) -> bytes:
        """PCG64 generator position as a fixed 37-byte blob."""
        st = self._rng.bit_generator.state
        inner = st["state"]
        return (
            int(inner["state"]).to_bytes(16, "big")
            + int(inner["inc"]).to_bytes(16, "big")
            + int(st["has_uint32"]).to_bytes(1, "big")
            + int(st["uinteger"]).to_bytes(4, "big")
        )

    def set_rng_state_bytes(self, raw: bytes) -> None:
        """Inverse of rng_state_bytes; raises ValueError on a bad blob."""
        if len(raw) != self.RNG_STATE_LEN:
            raise ValueError(f"expected 37-byte rng state, got {len(raw)}")
        self._rng.bit_generator.state = {
            "bit_generator": "PCG64",
            "state": {
                "state": int.from_bytes(raw[:16], "big"),
                "inc": int.from_bytes(raw[16:32], "big"),
            },
            "has_uint32": raw[32],
            "uinteger": int.from_bytes(raw[33:37], "big"),
        }

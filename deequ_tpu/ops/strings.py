"""Vectorized host-side string kernels: the TPU can't regex, so strings
are dict-encoded once per batch and every string operation (type
classification, hashing, numeric parse, pattern match) runs over the
*unique* values only, vectorized — never a Python loop over rows.

This replaces the reference's JVM-side string handling
(reference: catalyst/StatefulDataType.scala:36-38 classification regexes,
catalyst/StatefulHyperloglogPlus.scala:92 value hashing) with numpy
kernels over the UCS4 code-point matrix of the unique strings: a numpy
'U'-dtype array views as a (n_unique, max_len) uint32 matrix, on which
the classifier's character tests and the hash's mixing rounds vectorize.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# class codes — order matches DataTypeHistogram fields
CODE_NULL, CODE_FRACTIONAL, CODE_INTEGRAL, CODE_BOOLEAN, CODE_STRING = range(5)

_ZERO, _NINE = ord("0"), ord("9")
_DOT, _PLUS, _MINUS, _SPACE = ord("."), ord("+"), ord("-"), ord(" ")


def to_codepoint_matrix(uniques: np.ndarray) -> np.ndarray:
    """'U'-dtype array -> (n, max_len) uint32 code points, 0-padded."""
    if uniques.dtype.kind != "U":
        uniques = uniques.astype("U")
    n = len(uniques)
    width = uniques.dtype.itemsize // 4
    if n == 0 or width == 0:
        return np.zeros((n, max(width, 1)), dtype=np.uint32)
    return np.ascontiguousarray(uniques).view(np.uint32).reshape(n, width)


# One long outlier value must not widen the matrix for every unique (an
# (n x max_len) buffer is O(n * longest string)): values are bucketed by
# length and each bucket gets a matrix of its own width; values longer
# than _BUCKET_CAP take a per-value scalar fallback (rare by construction).
_LENGTH_BUCKETS = (8, 16, 32, 64, 128)
_BUCKET_CAP = _LENGTH_BUCKETS[-1]


def _by_length_buckets(uniques: np.ndarray, vectorized, scalar_fallback, out_dtype):
    """Apply `vectorized(sub_uniques_U)` per length bucket and
    `scalar_fallback(python_str)` to over-cap outliers; scatter results
    back into one array aligned with `uniques`."""
    as_obj = uniques if uniques.dtype == object else uniques.astype(object)
    lengths = np.array([len(s) for s in as_obj], dtype=np.int64)
    out = np.zeros(len(uniques), dtype=out_dtype)
    lo = 0
    for cap in _LENGTH_BUCKETS:
        sel = (lengths > lo) | ((lengths == 0) if lo == 0 else False)
        sel &= lengths <= cap
        if sel.any():
            out[sel] = vectorized(as_obj[sel].astype(f"U{cap}"))
        lo = cap
    big = lengths > _BUCKET_CAP
    if big.any():
        for i in np.nonzero(big)[0]:
            out[i] = scalar_fallback(str(as_obj[i]))
    return out


def classify(uniques: np.ndarray) -> np.ndarray:
    """Vectorized value-type classification, same decision as the
    reference's regexes (reference: catalyst/StatefulDataType.scala:36-38):

        FRACTIONAL  ^(-|\\+)? ?\\d*\\.\\d*$
        INTEGRAL    ^(-|\\+)? ?\\d*$
        BOOLEAN     ^(true|false)$

    checked in that order ('\\d' ASCII-only, like Java's default).
    Returns int32 class codes per unique value.
    """
    if len(uniques) == 0:
        return np.zeros(0, dtype=np.int32)
    return _by_length_buckets(
        uniques, _classify_bucket, _classify_scalar, np.int32
    )


def _classify_scalar(value: str) -> int:
    import re

    body = value
    for term in ("\r\n", "\n", "\r", "", " ", " "):
        if body.endswith(term):
            body = body[: -len(term)]
            break
    if re.fullmatch(r"(-|\+)? ?[0-9]*\.[0-9]*", body):
        return CODE_FRACTIONAL
    if re.fullmatch(r"(-|\+)? ?[0-9]*", body):
        return CODE_INTEGRAL
    if body in ("true", "false"):
        return CODE_BOOLEAN
    return CODE_STRING


def _classify_bucket(uniques: np.ndarray) -> np.ndarray:
    cm = to_codepoint_matrix(uniques)
    n, width = cm.shape
    if n == 0:
        return np.zeros(0, dtype=np.int32)

    length = _effective_lengths(cm)

    first = cm[:, 0]
    has_sign = (first == _PLUS) | (first == _MINUS)
    start = has_sign.astype(np.int64)
    # optional single space right after the (optional) sign
    after_sign = cm[np.arange(n), np.minimum(start, width - 1)]
    start = start + ((after_sign == _SPACE) & (start < width))

    pos = np.arange(width)[None, :]
    in_body = (pos >= start[:, None]) & (pos < length[:, None])
    is_digit = (cm >= _ZERO) & (cm <= _NINE)
    is_dot = cm == _DOT

    body_digits_or_dots = np.all(~in_body | is_digit | is_dot, axis=1)
    n_dots = (is_dot & in_body).sum(axis=1)
    fractional = body_digits_or_dots & (n_dots == 1)
    integral = np.all(~in_body | is_digit, axis=1)
    boolean = _equals_literal(cm, length, "true") | _equals_literal(cm, length, "false")

    out = np.full(n, CODE_STRING, dtype=np.int32)
    out[boolean] = CODE_BOOLEAN
    out[integral] = CODE_INTEGRAL
    out[fractional] = CODE_FRACTIONAL
    return out


# Java's `$` (non-MULTILINE) matches before one FINAL line terminator:
# \n, \r\n, \r, ,  ,   — the reference's regexes run
# under java.util.regex, so a single trailing terminator is outside the
# matched body.
_LONE_TERMS = (0x0D, 0x85, 0x2028, 0x2029)
_NL = 0x0A


def _effective_lengths(cm: np.ndarray) -> np.ndarray:
    n, width = cm.shape
    trailing_zeros = np.cumprod((cm == 0)[:, ::-1], axis=1).sum(axis=1)
    length = width - trailing_zeros
    idx = np.arange(n)
    last = cm[idx, np.maximum(length - 1, 0)] * (length > 0)
    is_nl = last == _NL
    length = length - is_nl
    last2 = cm[idx, np.maximum(length - 1, 0)] * (length > 0)
    strip2 = (is_nl & (last2 == 0x0D)) | (
        ~is_nl & np.isin(last2, _LONE_TERMS)
    )
    return length - strip2


def _equals_literal(cm: np.ndarray, length: np.ndarray, literal: str) -> np.ndarray:
    n, width = cm.shape
    if width < len(literal):
        return np.zeros(n, dtype=bool)
    hit = length == len(literal)
    for j, c in enumerate(literal):
        hit &= cm[:, j] == ord(c)
    return hit


# -- hashing ----------------------------------------------------------------

# xxhash64 mixing constants + rotl shared with the numeric-value hash
from deequ_tpu.ops.sketches.hll import (  # noqa: E402
    _PRIME1 as _P1,
    _PRIME2 as _P2,
    _PRIME3 as _P3,
    _PRIME4 as _P4,
    _PRIME5 as _P5,
    _rotl,
)


def hash_strings(uniques: np.ndarray, seed: int = 42) -> np.ndarray:
    """Vectorized 64-bit hash of each unique string: xxhash-style mixing
    rounds over the code-point matrix viewed as uint64 words, one
    vectorized pass per word column. Values are bucketed by length so the
    matrix width — and therefore the hash of a given string — depends
    only on the string itself, never on what else is in the batch.
    Not byte-identical to any reference hash — HLL accuracy needs only
    uniform 64-bit hashes, and the sketch's register layout (not its hash)
    is the compatibility surface."""
    if len(uniques) == 0:
        return np.zeros(0, dtype=np.uint64)
    return _by_length_buckets(
        uniques,
        lambda sub: _hash_bucket(sub, seed),
        lambda s: _hash_scalar(s, seed),
        np.uint64,
    )


def _hash_scalar(value: str, seed: int) -> np.uint64:
    """Over-cap outliers: hash 128-codepoint chunks through the bucket
    hash, chaining the seed — deterministic and length-independent."""
    acc = np.uint64(seed)
    for i in range(0, len(value), _BUCKET_CAP):
        chunk = np.array([value[i : i + _BUCKET_CAP]], dtype=f"U{_BUCKET_CAP}")
        acc = _hash_bucket(chunk, int(acc))[0]
    return acc


def _hash_bucket(uniques: np.ndarray, seed: int) -> np.ndarray:
    cm = to_codepoint_matrix(uniques)
    n, width = cm.shape
    if width % 2:
        cm = np.concatenate([cm, np.zeros((n, 1), dtype=np.uint32)], axis=1)
        width += 1
    words = np.ascontiguousarray(cm).view(np.uint64)  # (n, width//2)
    lengths = (cm != 0).sum(axis=1).astype(np.uint64)

    with np.errstate(over="ignore"):
        acc = np.uint64(seed) + _P5 + lengths * _P2
        for j in range(words.shape[1]):
            k = _rotl(words[:, j] * _P2, 31) * _P1
            acc = _rotl(acc ^ k, 27) * _P1 + _P4
        acc ^= acc >> np.uint64(33)
        acc *= _P2
        acc ^= acc >> np.uint64(29)
        acc *= _P3
        acc ^= acc >> np.uint64(32)
    return acc


# -- numeric parse ----------------------------------------------------------


def parse_floats(uniques: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(float64 values, ok mask) per unique string — C-speed via pandas
    to_numeric, matching float()'s accepted forms (sci notation, inf)."""
    if len(uniques) == 0:
        return np.zeros(0, dtype=np.float64), np.zeros(0, dtype=bool)
    try:
        import pandas as pd

        parsed = pd.to_numeric(
            pd.Series(uniques, dtype=object), errors="coerce"
        ).to_numpy(dtype=np.float64)
    except Exception:  # pandas missing/odd input: slow fallback
        parsed = np.full(len(uniques), np.nan, dtype=np.float64)
        for i, v in enumerate(uniques):
            try:
                parsed[i] = float(v)
            except (TypeError, ValueError):
                pass
    ok = ~np.isnan(parsed)
    # pandas coerces "nan" to NaN (ok=False) — float("nan") parses, but a
    # NaN value is null under this engine's convention anyway, so ok=False
    # is the correct verdict for both.
    return np.where(ok, parsed, 0.0), ok


def match_pattern(uniques: np.ndarray, pattern: str) -> np.ndarray:
    """Regex search over unique values (Python re for full lookahead /
    backreference support — vector win comes from uniques << rows).
    Spark semantics: regexp_extract(col, regex, 0) != '' — a present but
    empty match is a miss (reference: analyzers/PatternMatch.scala:42-50).
    """
    import re

    rx = re.compile(pattern)
    out = np.zeros(len(uniques), dtype=bool)
    for i, v in enumerate(uniques):
        m = rx.search(str(v))
        out[i] = m is not None and m.group(0) != ""
    return out

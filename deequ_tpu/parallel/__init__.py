from deequ_tpu.parallel import multihost
from deequ_tpu.parallel.distributed import (
    DistributedScanPass,
    data_mesh,
    run_distributed_analysis,
)
from deequ_tpu.parallel.multihost import run_sharded_analysis
from deequ_tpu.parallel.shard import ShardAssignment, ShardPlan, plan_shards

__all__ = [
    "DistributedScanPass",
    "ShardAssignment",
    "ShardPlan",
    "data_mesh",
    "multihost",
    "plan_shards",
    "run_distributed_analysis",
    "run_sharded_analysis",
]

from deequ_tpu.parallel import multihost
from deequ_tpu.parallel.distributed import (
    DistributedScanPass,
    data_mesh,
    run_distributed_analysis,
)

__all__ = [
    "DistributedScanPass",
    "data_mesh",
    "multihost",
    "run_distributed_analysis",
]

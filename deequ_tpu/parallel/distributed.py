"""Distributed fused scan: shard rows over a device mesh, merge states
with collectives.

This is the TPU-native form of the reference's partition-parallel
aggregation (reference: SURVEY.md §2.10 — Spark map-side partial
aggregation + driver merge): each device reduces its row shard with the
SAME fused computation the single-chip path uses, then the semigroup merge
(`State.sum`, analyzers/Analyzer.scala:34-48) runs IN-GRAPH as an
all_gather over the tiny state pytrees followed by a static fold of each
analyzer's `merge_agg` — sums lower to psum-like collectives, min/max to
pmin/pmax, HLL registers to an elementwise-max reduction, all riding ICI.

Multi-host (DCN) is the second tier: parallel/multihost.py runs this pass
per host on each host's partition and allgathers the serialized states —
only state pytrees (bytes to KB) ever cross host boundaries, never rows.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deequ_tpu import observe
from deequ_tpu.analyzers.base import ScanShareableAnalyzer
from deequ_tpu.data.table import Table
from deequ_tpu.ops import pipeline, runtime
from deequ_tpu.ops.fused import (
    AnalyzerRunResult,
    HostInputs,
    PipelinedAggFold,
    _pad_size,
    _precompute_family_kernels,
    apply_decode_plan,
    fold_host_batch,
    materialize_host_results,
    plan_decode_fastpath,
    plan_scan_members,
    prune_table_columns,
    resolve_shift,
)

DATA_AXIS = "data"

_DIST_CACHE: Dict[Any, Any] = {}
_DIST_CACHE_LOCK = threading.Lock()


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` moved to top level around jax 0.6; on earlier
    versions (e.g. 0.4.x) it lives in jax.experimental.shard_map and the
    `check_vma` kwarg is spelled `check_rep`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
    )


def data_mesh(devices: Optional[Sequence] = None, axis_name: str = DATA_AXIS) -> Mesh:
    """1-D data-parallel mesh over all (or given) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def _get_distributed_fn(analyzers, mesh: Mesh, axis_name: str, assisted=()):
    # Mesh hashes/compares by content (devices + axis names), giving a
    # stable cache identity — unlike id(mesh), which can be recycled
    # after GC and return a function compiled for a dead mesh.
    key = (
        tuple(repr(a) for a in analyzers),
        tuple(repr(a) for a in assisted),
        mesh,
        axis_name,
        bool(jax.config.jax_enable_x64),
    )
    with _DIST_CACHE_LOCK:
        fn = _DIST_CACHE.get(key)
    if fn is not None:
        return fn

    n_devices = mesh.shape[axis_name]

    def per_device(inputs):
        # wire-narrowed ints (1-2 B/row on the put) widen back to int32
        # before reduction, matching the fused engine's unpack stage
        inputs = {
            k: (
                v.astype(jnp.int32)
                if jnp.issubdtype(v.dtype, jnp.integer) and v.dtype.itemsize < 4
                else v
            )
            for k, v in inputs.items()
        }
        # local shard reduce: identical computation to the single-chip pass
        partials = tuple(a.device_reduce(inputs, jnp) for a in analyzers)

        # in-graph semigroup merge: all_gather the state pytrees (tiny),
        # then a static fold with each analyzer's merge law
        gathered = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis_name), partials
        )

        merged = []
        for analyzer, tree in zip(analyzers, gathered):
            acc = jax.tree_util.tree_map(lambda x: x[0], tree)
            for d in range(1, n_devices):
                shard = jax.tree_util.tree_map(lambda x, d=d: x[d], tree)
                acc = analyzer.merge_agg(acc, shard, jnp)
            merged.append(acc)

        # device-assisted outputs (e.g. the quantile sort+decimate) stay
        # per-device: each shard's fixed-size artifact is gathered along
        # axis 0 and consumed host-side shard by shard
        assisted_out = tuple(a.device_batch(inputs, jnp) for a in assisted)
        return tuple(merged), assisted_out

    sharded = _shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axis_name),),
        # merged states replicated; assisted artifacts concatenated per device
        out_specs=(P(), P(axis_name)),
        check_vma=False,
    )
    fn = jax.jit(sharded)
    with _DIST_CACHE_LOCK:
        fn = _DIST_CACHE.setdefault(key, fn)
    return fn


class DistributedScanPass:
    """Mesh-sharded variant of FusedScanPass: device-reduced analyzers
    merge in-graph via collectives; device-assisted analyzers (quantile
    sketches) produce fixed-size per-shard artifacts gathered along the
    mesh axis and folded on the host shard by shard."""

    def __init__(
        self,
        analyzers: Sequence[ScanShareableAnalyzer],
        mesh: Optional[Mesh] = None,
        batch_size_per_device: int = 1 << 21,
        axis_name: str = DATA_AXIS,
    ):
        self.analyzers = list(analyzers)
        self.mesh = mesh if mesh is not None else data_mesh()
        self.axis_name = axis_name
        self.batch_size_per_device = batch_size_per_device

    def run(self, table: Table) -> List[AnalyzerRunResult]:
        with observe.span(
            "dist_scan",
            cat="scan",
            devices=int(self.mesh.shape[self.axis_name]),
            analyzers=len(self.analyzers),
        ):
            return self._run(table)

    def _run(self, table: Table) -> List[AnalyzerRunResult]:
        # same placement policy as FusedScanPass — the shared pure
        # planner partitions members: on a slow device link, discrete
        # (mask/code-only) analyzers — or under 'host-all', every
        # analyzer — fold on the host while the mesh reduces the rest
        plan = plan_scan_members(self.analyzers)
        results: Dict[int, AnalyzerRunResult] = {}
        for i, err in plan.spec_errors.items():
            results[i] = AnalyzerRunResult(self.analyzers[i], error=err)
        merge_idx = plan.merge_idx
        assisted_idx = plan.assisted_idx
        merge_analyzers = [self.analyzers[i] for i in merge_idx]
        assisted = [self.analyzers[i] for i in assisted_idx]
        host_members = [(i, self.analyzers[i]) for i in plan.host_idx]
        host_assisted = [(i, self.analyzers[i]) for i in plan.host_assisted_idx]
        host_member_keys = plan.host_keys
        specs = plan.specs
        device_keys = plan.device_keys

        table = prune_table_columns(table, specs)
        # decode routing after pruning, exactly as in FusedScanPass: the
        # mesh shards the packed wire arrays, so whether a column decoded
        # through the native kernels or the host chain is invisible to it
        decode_plan = plan_decode_fastpath(table, specs)
        if decode_plan is not None:
            table = apply_decode_plan(table, decode_plan)
        n_devices = self.mesh.shape[self.axis_name]
        global_batch = self.batch_size_per_device * n_devices
        dtype = runtime.compute_dtype()
        fn = (
            _get_distributed_fn(
                merge_analyzers, self.mesh, self.axis_name, assisted
            )
            if merge_analyzers or assisted
            else None
        )
        runtime.record_pass(
            f"dist-scan[{n_devices}x]:"
            + ",".join(a.name for a in self.analyzers)
        )
        in_sharding = jax.tree_util.tree_map(
            lambda _: NamedSharding(self.mesh, P(self.axis_name)), specs
        )

        host_aggs: Dict[int, Any] = {}
        host_assisted_states: Dict[int, Any] = {}
        host_errors: Dict[int, BaseException] = {}
        sticky: Dict[str, Any] = {}
        family_memo: Dict[Any, Any] = {}  # cross-batch, one scan's scope
        streaming = bool(getattr(table, "is_streaming", False))
        try:
            fold = PipelinedAggFold(
                merge_analyzers, assisted, n_dev=n_devices, sticky=sticky
            )

            all_host = list(host_members) + list(host_assisted)

            def _shard_inputs(batch, built) -> Dict[str, Any]:
                """Pad/narrow/shift each device key exactly like the
                single-chip wire, then place it with the mesh sharding —
                the H2D put the pipeline overlaps with compute."""
                for key in device_keys:
                    if key in built.build_errors:
                        raise built.build_errors[key]
                # pad to a multiple of n_devices (pow2 per shard)
                per_dev = _pad_size(
                    -(-batch.num_rows // n_devices),
                    self.batch_size_per_device,
                )
                padded = per_dev * n_devices
                inputs: Dict[str, Any] = {}
                for key in device_keys:
                    arr = runtime.pad_to(built[key], padded)
                    if np.issubdtype(arr.dtype, np.integer):
                        arr = runtime.narrow_int_wire(arr, key, sticky)
                    elif arr.dtype != np.bool_:
                        if (
                            np.dtype(dtype) == np.float32
                            and key.startswith("num:")
                        ):
                            # same f32 pre-centering as
                            # pack_batch_inputs (see fused.py)
                            shift = resolve_shift(key, arr, sticky, built.get)
                            if shift != 0.0:
                                arr = np.asarray(arr, dtype=np.float64) - shift
                        arr = arr.astype(dtype)
                    inputs[key] = jax.device_put(arr, in_sharding[key])
                return inputs

            device_error: Any = None
            if streaming and runtime.pipeline_enabled():
                device_error = self._scan_pipelined(
                    table, global_batch, fn, specs, device_keys, n_devices,
                    _shard_inputs, fold, all_host, host_members,
                    host_assisted, host_member_keys, host_aggs,
                    host_assisted_states, host_errors, family_memo,
                )
            else:
                for batch in table.batches(global_batch):
                    # per-key builds with error capture — same isolation
                    # contract as FusedScanPass._run_pass; host-only keys
                    # build lazily (fused.HostInputs)
                    device_live = fn is not None and device_error is None
                    host_live = any(
                        i not in host_errors for i, _m in all_host
                    )
                    if not device_live and not host_live:
                        break  # everything already failed; stop scanning
                    built = HostInputs(specs, batch)
                    build_errors = built.build_errors
                    if device_live:
                        for key in sorted(device_keys):
                            built.materialize(key)
                    if fn is not None and device_error is None:
                        try:
                            with observe.span(
                                "dispatch",
                                cat="dispatch",
                                rows=batch.num_rows,
                                devices=int(n_devices),
                            ) as dispatch_sp:
                                inputs = _shard_inputs(batch, built)
                                if dispatch_sp:
                                    dispatch_sp.set(
                                        wire_bytes=sum(
                                            int(getattr(v, "nbytes", 0))
                                            for v in inputs.values()
                                        )
                                    )
                                runtime.record_launch()
                                fold.submit(fn(inputs))
                        except Exception as e:  # noqa: BLE001
                            device_error = e
                    with observe.span(
                        "host_fold", cat="host", rows=batch.num_rows
                    ):
                        fold_host_batch(
                            built, build_errors, host_members, host_assisted,
                            host_member_keys, host_aggs, host_assisted_states,
                            host_errors,
                            batch=batch, streaming=streaming,
                            family_memo=family_memo,
                        )
            aggs, assisted_states = [], []
            if device_error is None:
                try:
                    aggs, assisted_states = fold.finish()
                    from deequ_tpu.ops.fused import wire_shifts

                    shifts = wire_shifts(sticky)
                    if shifts:
                        aggs = [
                            a.unshift_agg(agg, shifts)
                            for a, agg in zip(merge_analyzers, aggs)
                        ]
                except Exception as e:  # noqa: BLE001
                    device_error = e
            if device_error is not None:
                for i in merge_idx + assisted_idx:
                    results[i] = AnalyzerRunResult(
                        self.analyzers[i], error=device_error
                    )
            else:
                for i, analyzer, agg in zip(merge_idx, merge_analyzers, aggs):
                    try:
                        results[i] = AnalyzerRunResult(
                            analyzer, state=analyzer.state_from_aggregates(agg)
                        )
                    except Exception as e:  # noqa: BLE001
                        results[i] = AnalyzerRunResult(analyzer, error=e)
                for i, state in zip(assisted_idx, assisted_states):
                    results[i] = AnalyzerRunResult(self.analyzers[i], state=state)
            results.update(
                materialize_host_results(
                    host_members, host_assisted, host_aggs,
                    host_assisted_states, host_errors,
                )
            )
        except Exception as e:  # noqa: BLE001
            for i in range(len(self.analyzers)):
                results.setdefault(i, AnalyzerRunResult(self.analyzers[i], error=e))

        return [results[i] for i in range(len(self.analyzers))]

    def _scan_pipelined(
        self,
        table,
        global_batch,
        fn,
        specs,
        device_keys,
        n_devices,
        shard_inputs,
        fold,
        all_host,
        host_members,
        host_assisted,
        host_member_keys,
        host_aggs,
        host_assisted_states,
        host_errors,
        family_memo,
    ):
        """Sharded-stream twin of `FusedScanPass._scan_pipelined`: the
        per-batch prep — eager builds, pad/narrow/shift, the sharded
        `jax.device_put` — runs on a stage thread so batch N+1's H2D
        lands on the mesh while batch N's collectives run; every fold
        stays on this thread in batch order (bit-identical to serial)."""
        device_down = threading.Event()

        def _prep(batch):
            built = HostInputs(specs, batch)
            inputs = device_exc = None
            if fn is not None and not device_down.is_set():
                for key in sorted(device_keys):
                    built.materialize(key)
                try:
                    with observe.span(
                        "dispatch",
                        cat="dispatch",
                        rows=batch.num_rows,
                        devices=int(n_devices),
                    ) as dispatch_sp:
                        inputs = shard_inputs(batch, built)
                        if dispatch_sp:
                            dispatch_sp.set(
                                wire_bytes=sum(
                                    int(getattr(v, "nbytes", 0))
                                    for v in inputs.values()
                                )
                            )
                except Exception as e:  # noqa: BLE001
                    device_exc = e
                    inputs = None
                    device_down.set()
            if any(i not in host_errors for i, _m in all_host):
                with observe.span(
                    "host_prep", cat="host", rows=batch.num_rows
                ):
                    _precompute_family_kernels(
                        built, host_assisted, batch,
                        host_members=host_members, host_errors=host_errors,
                        streaming=True, family_memo=family_memo,
                    )
            return batch, built, inputs, device_exc

        device_error: Any = None
        items = pipeline.staged(table.batches(global_batch), _prep, name="prep")
        with contextlib.closing(items):
            with observe.span(
                "pipe_stage", cat="pipeline", stage="fold"
            ) as stage_sp:
                n_items = 0
                for batch, built, inputs, device_exc in items:
                    device_live = fn is not None and device_error is None
                    host_live = any(i not in host_errors for i, _m in all_host)
                    if not device_live and not host_live:
                        break  # everything already failed; stop scanning
                    with observe.span(
                        "pipe_item", cat="pipeline", stage="fold",
                        rows=batch.num_rows,
                    ):
                        if device_live:
                            if device_exc is not None:
                                device_error = device_exc
                            elif inputs is not None:
                                try:
                                    runtime.record_launch()
                                    fold.submit(fn(inputs))
                                except Exception as e:  # noqa: BLE001
                                    device_error = e
                            if device_error is not None:
                                device_down.set()
                        with observe.span(
                            "host_fold", cat="host", rows=batch.num_rows
                        ):
                            fold_host_batch(
                                built, built.build_errors, host_members,
                                host_assisted, host_member_keys, host_aggs,
                                host_assisted_states, host_errors,
                                batch=batch, streaming=True,
                                family_memo=family_memo, precomputed=True,
                            )
                    n_items += 1
                if stage_sp:
                    stage_sp.set(items=n_items)
        return device_error


_BINCOUNT_CACHE: Dict[Any, Any] = {}
_BINCOUNT_CACHE_LOCK = threading.Lock()


def sharded_bincount(
    codes: np.ndarray, nbins: int, mesh: Mesh, axis_name: str = DATA_AXIS
) -> np.ndarray:
    """Row-sharded group counting: each device scatter-adds its shard of
    dense group codes into a fixed-size count table, merged in-graph with
    psum over the mesh — the device form of the reference's
    groupBy().agg(count) shuffle (reference: GroupingAnalyzers.scala:67-72).

    `codes` may contain -1 (null group) — counted into a trash bin and
    dropped. Returns int64 counts[nbins].
    """
    n_devices = mesh.shape[axis_name]
    nbins_p = _pad_size(nbins + 1, 1 << 30)
    per_dev = _pad_size(-(-len(codes) // n_devices), 1 << 30)
    padded_rows = per_dev * n_devices

    full = np.full(padded_rows, nbins, dtype=np.int64)  # pad/null -> trash
    np.copyto(full[: len(codes)], np.where(codes >= 0, codes, nbins))

    key = (padded_rows, nbins_p, mesh, axis_name)
    with _BINCOUNT_CACHE_LOCK:
        fn = _BINCOUNT_CACHE.get(key)
    if fn is None:

        def per_device(c):
            counts = jnp.zeros(nbins_p, dtype=jnp.int32).at[c].add(1)
            return jax.lax.psum(counts, axis_name)

        fn = jax.jit(
            _shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(axis_name),),
                out_specs=P(),
                check_vma=False,
            )
        )
        with _BINCOUNT_CACHE_LOCK:
            fn = _BINCOUNT_CACHE.setdefault(key, fn)
    with observe.span(
        "group_bincount",
        cat="dispatch",
        rows=len(codes),
        bins=nbins,
        devices=int(n_devices),
    ):
        runtime.record_launch()
        sharding = NamedSharding(mesh, P(axis_name))
        counts = np.asarray(fn(jax.device_put(full, sharding)))
    return counts[:nbins].astype(np.int64)


def run_distributed_analysis(
    table: Table,
    analyzers: Sequence[ScanShareableAnalyzer],
    mesh: Optional[Mesh] = None,
    batch_size_per_device: int = 1 << 21,
):
    """Convenience: sharded pass -> AnalyzerContext."""
    from deequ_tpu.runners.context import AnalyzerContext

    results = DistributedScanPass(
        analyzers, mesh=mesh, batch_size_per_device=batch_size_per_device
    ).run(table)
    metrics = {}
    for result in results:
        if result.error is not None:
            metrics[result.analyzer] = result.analyzer.to_failure_metric(result.error)
        else:
            metrics[result.analyzer] = result.analyzer.compute_metric_from(result.state)
    return AnalyzerContext(metrics)

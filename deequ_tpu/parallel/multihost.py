"""Multi-host (DCN) execution: per-host analysis, state merge across hosts.

The reference scales across machines by letting Spark shuffle partial
aggregates between executors (reference: SURVEY.md §2.10, §5.8). The
TPU-native shape of that is two-tier:

  * WITHIN a host/slice: rows shard over the local mesh and states merge
    in-graph with collectives over ICI (parallel/distributed.py).
  * ACROSS hosts: each process analyzes ITS OWN partition of the data
    (the partition it can read locally), produces per-analyzer States —
    bytes to KB of sufficient statistics — and the states cross DCN via
    `process_allgather`, serialized in the SAME binary layouts the
    checkpoint layer uses (analyzers/state_provider.py,
    reference: StateProvider.scala:85-174). Every host then folds the
    semigroup (`State.sum`, reference: analyzers/Analyzer.scala:34-48)
    and ends with identical table-level metrics.

Only states ever cross host boundaries — never rows — so DCN bandwidth
is irrelevant to scan throughput; this is the same property that makes
`runOnAggregatedStates` (reference: AnalysisRunner.scala:375-446) scan-free.

Usage on an N-host pod / CPU fleet:

    from deequ_tpu.parallel import multihost
    multihost.initialize(coordinator_address="host0:1234",
                         num_processes=N, process_id=rank)
    context = multihost.run_multihost_analysis(my_local_partition, analyzers)

Single-process (jax.process_count() == 1) this degrades to a plain local
run, so the same program runs unchanged from a laptop to a pod.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

from deequ_tpu import observe
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.state_provider import (
    InMemoryStateProvider,
    deserialize_state,
    serialize_state,
)
from deequ_tpu.data.table import Table
from deequ_tpu.runners.context import AnalyzerContext


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Join the multi-process JAX runtime (jax.distributed.initialize).

    On TPU pods the arguments are auto-detected from the environment; on
    CPU/GPU fleets pass coordinator_address/num_processes/process_id."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def global_data_mesh(axis_name: str = "data"):
    """1-D mesh over ALL devices of ALL processes (ICI within a slice,
    DCN between slices — XLA routes the collectives)."""
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def allgather_bytes(payload: bytes) -> List[bytes]:
    """Gather one variable-length byte string from every process.

    Two collectives over DCN: fixed-size length exchange, then a
    max-length padded uint8 gather. With one process this is the
    identity — no device work at all."""
    if jax.process_count() == 1:
        return [payload]
    from jax.experimental import multihost_utils

    lengths = multihost_utils.process_allgather(
        np.array([len(payload)], dtype=np.int32)
    ).reshape(-1)
    max_len = int(lengths.max())
    buf = np.zeros(max(max_len, 1), dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    return [
        gathered[i, : int(lengths[i])].tobytes() for i in range(jax.process_count())
    ]


# wire envelope tags: a host's contribution per analyzer
_EMPTY = b"\x00"  # no state (all rows NULL in this partition)
_STATE = b"\x01"  # serialized state follows
_FAILED = b"\x02"  # analyzer failed on that host; utf-8 message follows


def analyzer_list_digest(analyzers: Sequence[Analyzer]) -> bytes:
    """8-byte digest of the (deduped, ordered) analyzer list that leads
    every state envelope; all hosts must produce the same digest."""
    import hashlib

    return hashlib.sha1(
        "\x1f".join(repr(a) for a in analyzers).encode("utf-8")
    ).digest()[:8]


def _dedup(analyzers: Sequence[Analyzer]) -> List[Analyzer]:
    seen = set()
    unique: List[Analyzer] = []
    for analyzer in analyzers:
        if analyzer not in seen:
            seen.add(analyzer)
            unique.append(analyzer)
    return unique


def merge_states_across_hosts(
    analyzers: Sequence[Analyzer],
    local_states,
    gather=allgather_bytes,
    local_errors=None,
) -> tuple:
    """Allgather + semigroup-fold every analyzer's state across processes.

    ALL analyzers' tagged payloads ride ONE gather (a single
    length-prefixed envelope per host): total state volume is bytes to
    KB, so one DCN round-trip replaces 2·N sequential collective
    barriers. Duplicate analyzers are merged once.

    Returns (merged_states, errors): `errors` maps an analyzer to the
    first failure message any host reported — a host-local failure must
    fail the GLOBAL metric, not silently shrink it to the healthy hosts'
    data. An analyzer whose local state is empty (all rows NULL in this
    partition) contributes nothing, exactly like the reference's
    optional-state merge (reference: Analyzer.scala:343-362).

    `gather` is injectable so the merge law is testable without a real
    multi-process runtime (it receives/returns one envelope per host).
    """
    import struct

    analyzers = _dedup(analyzers)
    merged = InMemoryStateProvider()
    errors = {}
    local_errors = local_errors or {}

    # The envelope decodes positionally against the local analyzer list;
    # if hosts ran differently ordered/composed lists, two same-size
    # payloads could silently swap. The leading digest must match on
    # every host.
    digest = analyzer_list_digest(analyzers)
    parts: List[bytes] = [digest]
    for analyzer in analyzers:
        if analyzer in local_errors:
            payload = _FAILED + str(local_errors[analyzer]).encode("utf-8")
        else:
            state = local_states.load(analyzer)
            payload = (
                _EMPTY if state is None else _STATE + serialize_state(analyzer, state)
            )
        parts.append(struct.pack(">i", len(payload)))
        parts.append(payload)
    envelope = b"".join(parts)

    with observe.span(
        "state_allgather",
        cat="transfer",
        analyzers=len(analyzers),
        envelope_bytes=len(envelope),
    ):
        host_envelopes = gather(envelope)

    with observe.span(
        "state_merge",
        cat="merge",
        analyzers=len(analyzers),
        hosts=len(host_envelopes),
    ):
        _merge_host_envelopes(
            analyzers, host_envelopes, digest, merged, errors
        )
    return merged, errors


def _merge_host_envelopes(analyzers, host_envelopes, digest, merged, errors):
    """Decode each host's tagged envelope positionally and semigroup-fold
    states into `merged` (first failure per analyzer wins in `errors`)."""
    import struct

    for host_envelope in host_envelopes:
        if host_envelope[:8] != digest:
            raise ValueError(
                "multihost analyzer-list mismatch: a host sent a state "
                "envelope for a different analyzer set/order; all hosts "
                "must pass identical analyzer lists to "
                "merge_states_across_hosts."
            )
        offset = 8
        for analyzer in analyzers:
            (length,) = struct.unpack(">i", host_envelope[offset : offset + 4])
            offset += 4
            blob = host_envelope[offset : offset + length]
            offset += length
            tag, body = blob[:1], blob[1:]
            if tag == _FAILED and analyzer not in errors:
                errors[analyzer] = body.decode("utf-8")
            if tag != _STATE:
                continue
            other = deserialize_state(analyzer, body)
            prev = merged.load(analyzer)
            merged.persist(analyzer, other if prev is None else prev.merge(other))


def run_multihost_analysis(
    local_table: Table,
    analyzers: Sequence[Analyzer],
    mesh=None,
    engine: str = "auto",
    gather=allgather_bytes,
    save_states_with=None,
) -> AnalyzerContext:
    """Analyze this process's partition locally, then merge states across
    all processes; returns identical table-level metrics on every host
    (the distributed form of runOnAggregatedStates,
    reference: examples/UpdateMetricsOnPartitionedDataExample.scala:30-95).

    `save_states_with` optionally receives this host's LOCAL
    (pre-merge) states — callers that want to inspect or persist the
    partition contribution (e.g. the dryrun asserting a spilled
    frequency state) get them from the single analysis pass instead of
    recomputing. The persisted values are the SAME state objects the
    cross-host merge then serializes, so the receiving persister must
    treat them as read-only. The merge itself always reads a FRESH
    internal provider, so a reused/pre-populated caller provider can
    never leak a previous run's state into this host's contribution (an
    empty local state is never persisted, so it would not overwrite a
    stale entry).

    A failure on ANY host fails that analyzer's global metric on EVERY
    host — a partition that errored must not silently drop out of a
    "successful" table-level number."""
    from deequ_tpu.core.exceptions import MetricCalculationException
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    analyzers = _dedup(analyzers)
    local_states = InMemoryStateProvider()
    local_context = AnalysisRunner.do_analysis_run(
        local_table,
        analyzers,
        save_states_with=local_states,
        engine=engine,
        mesh=mesh,
    )
    if save_states_with is not None:
        for analyzer in analyzers:
            state = local_states.load(analyzer)
            if state is not None:
                save_states_with.persist(analyzer, state)
    from deequ_tpu.core.exceptions import EmptyStateException

    # an all-NULL local partition is a legitimately empty contribution
    # (EmptyStateException), not a failure — other hosts may have data
    local_errors = {
        analyzer: metric.value.exception
        for analyzer, metric in local_context.metric_map.items()
        if metric.value.is_failure
        and not isinstance(metric.value.exception, EmptyStateException)
    }
    merged, errors = merge_states_across_hosts(
        analyzers, local_states, gather=gather, local_errors=local_errors
    )
    metrics = {}
    for analyzer in analyzers:
        if analyzer in errors:
            metrics[analyzer] = analyzer.to_failure_metric(
                MetricCalculationException(errors[analyzer])
            )
        else:
            metrics[analyzer] = analyzer.compute_metric_from(merged.load(analyzer))
    return AnalyzerContext(metrics)

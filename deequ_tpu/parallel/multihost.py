"""Multi-host (DCN) execution: per-host analysis, state merge across hosts.

The reference scales across machines by letting Spark shuffle partial
aggregates between executors (reference: SURVEY.md §2.10, §5.8). The
TPU-native shape of that is two-tier:

  * WITHIN a host/slice: rows shard over the local mesh and states merge
    in-graph with collectives over ICI (parallel/distributed.py).
  * ACROSS hosts: each process analyzes ITS OWN partition of the data
    (the partition it can read locally), produces per-analyzer States —
    bytes to KB of sufficient statistics — and the states cross DCN via
    `process_allgather`, serialized in the SAME binary layouts the
    checkpoint layer uses (analyzers/state_provider.py,
    reference: StateProvider.scala:85-174). Every host then folds the
    semigroup (`State.sum`, reference: analyzers/Analyzer.scala:34-48)
    and ends with identical table-level metrics.

Only states ever cross host boundaries — never rows — so DCN bandwidth
is irrelevant to scan throughput; this is the same property that makes
`runOnAggregatedStates` (reference: AnalysisRunner.scala:375-446) scan-free.

Usage on an N-host pod / CPU fleet:

    from deequ_tpu.data.source import PartitionedParquetSource
    from deequ_tpu.parallel import multihost
    multihost.initialize(coordinator_address="host0:1234",
                         num_processes=N, process_id=rank)
    source = PartitionedParquetSource(partition_paths)
    context = multihost.run_sharded_analysis(source, analyzers)

`run_sharded_analysis` (ISSUE 15) shards the dataset's PARTITIONS over
processes with a rendezvous hash, streams each shard through the full
solo scan path (state cache included), and all-merges per-partition
state envelopes in one gather — bit-identical to a solo run at any
shard count. The older `run_multihost_analysis` (deprecated) instead
takes this process's partition as an in-memory Table.

Single-process (jax.process_count() == 1) this degrades to a plain local
run, so the same program runs unchanged from a laptop to a pod.
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from deequ_tpu import observe
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.state_provider import (
    InMemoryStateProvider,
    deserialize_state,
    serialize_state,
)
from deequ_tpu.data.table import Table
from deequ_tpu.runners.context import AnalyzerContext


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Join the multi-process JAX runtime (jax.distributed.initialize).

    On TPU pods the arguments are auto-detected from the environment; on
    CPU/GPU fleets pass coordinator_address/num_processes/process_id."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def global_data_mesh(axis_name: str = "data"):
    """1-D mesh over ALL devices of ALL processes (ICI within a slice,
    DCN between slices — XLA routes the collectives)."""
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def allgather_bytes(payload: bytes) -> List[bytes]:
    """Gather one variable-length byte string from every process.

    Two collectives over DCN: fixed-size length exchange, then a
    max-length padded uint8 gather. With one process this is the
    identity — no device work at all."""
    if jax.process_count() == 1:
        return [payload]
    from jax.experimental import multihost_utils

    lengths = multihost_utils.process_allgather(
        np.array([len(payload)], dtype=np.int32)
    ).reshape(-1)
    max_len = int(lengths.max())
    buf = np.zeros(max(max_len, 1), dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    return [
        gathered[i, : int(lengths[i])].tobytes() for i in range(jax.process_count())
    ]


# wire envelope tags: a host's contribution per analyzer
_EMPTY = b"\x00"  # no state (all rows NULL in this partition)
_STATE = b"\x01"  # serialized state follows
_FAILED = b"\x02"  # analyzer failed on that host; utf-8 message follows


def analyzer_list_digest(analyzers: Sequence[Analyzer]) -> bytes:
    """8-byte digest of the (deduped, ordered) analyzer list that leads
    every state envelope; all hosts must produce the same digest."""
    import hashlib

    return hashlib.sha1(
        "\x1f".join(repr(a) for a in analyzers).encode("utf-8")
    ).digest()[:8]


def _dedup(analyzers: Sequence[Analyzer]) -> List[Analyzer]:
    seen = set()
    unique: List[Analyzer] = []
    for analyzer in analyzers:
        if analyzer not in seen:
            seen.add(analyzer)
            unique.append(analyzer)
    return unique


def merge_states_across_hosts(
    analyzers: Sequence[Analyzer],
    local_states,
    gather=allgather_bytes,
    local_errors=None,
) -> tuple:
    """Allgather + semigroup-fold every analyzer's state across processes.

    ALL analyzers' tagged payloads ride ONE gather (a single
    length-prefixed envelope per host): total state volume is bytes to
    KB, so one DCN round-trip replaces 2·N sequential collective
    barriers. Duplicate analyzers are merged once.

    Returns (merged_states, errors): `errors` maps an analyzer to the
    first failure message any host reported — a host-local failure must
    fail the GLOBAL metric, not silently shrink it to the healthy hosts'
    data. An analyzer whose local state is empty (all rows NULL in this
    partition) contributes nothing, exactly like the reference's
    optional-state merge (reference: Analyzer.scala:343-362).

    `gather` is injectable so the merge law is testable without a real
    multi-process runtime (it receives/returns one envelope per host).
    """
    import struct

    analyzers = _dedup(analyzers)
    merged = InMemoryStateProvider()
    errors = {}
    local_errors = local_errors or {}

    # The envelope decodes positionally against the local analyzer list;
    # if hosts ran differently ordered/composed lists, two same-size
    # payloads could silently swap. The leading digest must match on
    # every host.
    digest = analyzer_list_digest(analyzers)
    parts: List[bytes] = [digest]
    for analyzer in analyzers:
        if analyzer in local_errors:
            payload = _FAILED + str(local_errors[analyzer]).encode("utf-8")
        else:
            state = local_states.load(analyzer)
            payload = (
                _EMPTY if state is None else _STATE + serialize_state(analyzer, state)
            )
        parts.append(struct.pack(">i", len(payload)))
        parts.append(payload)
    envelope = b"".join(parts)

    with observe.span(
        "state_allgather",
        cat="transfer",
        analyzers=len(analyzers),
        envelope_bytes=len(envelope),
    ):
        host_envelopes = gather(envelope)

    with observe.span(
        "state_merge",
        cat="merge",
        analyzers=len(analyzers),
        hosts=len(host_envelopes),
    ):
        _merge_host_envelopes(
            analyzers, host_envelopes, digest, merged, errors
        )
    return merged, errors


def _merge_host_envelopes(analyzers, host_envelopes, digest, merged, errors):
    """Decode each host's tagged envelope positionally and semigroup-fold
    states into `merged` (first failure per analyzer wins in `errors`)."""
    import struct

    for host_envelope in host_envelopes:
        if host_envelope[:8] != digest:
            raise ValueError(
                "multihost analyzer-list mismatch: a host sent a state "
                "envelope for a different analyzer set/order; all hosts "
                "must pass identical analyzer lists to "
                "merge_states_across_hosts."
            )
        offset = 8
        for analyzer in analyzers:
            (length,) = struct.unpack(">i", host_envelope[offset : offset + 4])
            offset += 4
            blob = host_envelope[offset : offset + length]
            offset += length
            tag, body = blob[:1], blob[1:]
            if tag == _FAILED and analyzer not in errors:
                errors[analyzer] = body.decode("utf-8")
            if tag != _STATE:
                continue
            other = deserialize_state(analyzer, body)
            prev = merged.load(analyzer)
            merged.persist(analyzer, other if prev is None else prev.merge(other))


def run_sharded_analysis(
    source,
    analyzers: Sequence[Analyzer],
    *,
    shard: Optional[int] = None,
    num_shards: Optional[int] = None,
    exclude: Sequence[int] = (),
    state_repository=None,
    dataset_name: str = "default",
    engine: str = "auto",
    mesh=None,
    gather=allgather_bytes,
    controller=None,
    cancel_token=None,
    batch_size: Optional[int] = None,
) -> AnalyzerContext:
    """The sharded streaming scan (ISSUE 15 tentpole): every process
    folds ITS OWN deterministic slice of a `PartitionedParquetSource`
    through the full streamed path (native reader read-ahead,
    decode->wire fusion, backpressured pipeline, per-partition state
    commits), then all processes exchange per-partition `DQST` state
    envelopes in ONE allgather and fold the semigroup in GLOBAL
    partition order — only states ever cross DCN, never rows.

    Bit-identity contract: every partition's states are produced by the
    same `scan_partition` sub-scan a solo `_run_partitioned` pass runs,
    committed under the same `(dataset, plan signature, fingerprint)`
    keys, and merged in the same global partition order — so a sharded
    run at ANY shard count is bit-identical to a solo run, the caches
    interoperate, and either can resume the other (pinned by
    tests/test_sharded_scan.py across fuzzed shard counts/placements).

    Crash/straggler handling falls out of the state cache: a shard
    whose envelope is missing or defective (host loss — chaos point
    `shard.host_loss`) loses nothing globally; every surviving shard
    recovers its partitions from the committed states in
    `state_repository`, rescanning only what the lost host had not yet
    committed (the `shard.merge` chaos point corrupts a single
    partition entry the same way).

    Cancellation (`controller` + optional cross-process
    `cancel_token`): a cancel never unwinds PAST the collective — the
    cancelled shard stops scanning at a partition boundary, still
    gathers an envelope flagged cancelled (with whatever it committed),
    and every shard raises `RunCancelled` uniformly after the exchange,
    so no process is left waiting in a dead collective. A later rerun
    resumes from the committed partitions.

    `shard`/`num_shards` default to `jax.process_index()` /
    `jax.process_count()`; `exclude` re-plans around lost shards;
    `gather` is injectable so an N-shard run is testable in-process.
    Non-scan-shareable analyzers (grouping, non-shareable scanning) run
    over this shard's partition subset and merge through
    `merge_states_across_hosts` — a second gather, approximation
    contracts unchanged."""
    from deequ_tpu.analyzers.base import Preconditions, ScanShareableAnalyzer
    from deequ_tpu.analyzers.grouping import GroupingAnalyzer
    from deequ_tpu.core.controller import RunCancelled
    from deequ_tpu.core.exceptions import (
        EmptyStateException,
        MetricCalculationException,
    )
    from deequ_tpu.core.metrics import Metric
    from deequ_tpu.ops import runtime
    from deequ_tpu.ops.fused import scan_partition
    from deequ_tpu.repository.states import (
        StateDecodeError,
        decode_shard_states,
        decode_states,
        encode_shard_states,
        encode_states,
        merge_states,
        plan_signature_for,
    )
    from deequ_tpu.runners.analysis_runner import AnalysisRunner
    from deequ_tpu.testing import faults

    analyzers = _dedup(analyzers)
    if shard is None:
        shard = jax.process_index()
    if num_shards is None:
        num_shards = jax.process_count()
    shard = int(shard)
    num_shards = int(num_shards)
    if not (0 <= shard < num_shards):
        raise ValueError(f"shard {shard} out of range for {num_shards} shards")

    # preconditions against the FULL dataset schema — identical on every
    # shard, so all shards agree on which analyzers run (the gathered
    # envelopes decode positionally against that shared list)
    passed: List[Analyzer] = []
    failure_map: Dict[Analyzer, Metric] = {}
    for a in analyzers:
        err = Preconditions.find_first_failing(source, a.preconditions())
        if err is None:
            passed.append(a)
        else:
            failure_map[a] = a.to_failure_metric(err)

    shareable = [
        a
        for a in passed
        if isinstance(a, ScanShareableAnalyzer)
        and not isinstance(a, GroupingAnalyzer)
    ]
    rest = [a for a in passed if a not in shareable]

    from deequ_tpu.parallel.shard import plan_shards

    all_parts = list(source.partitions())
    parts_by_name = {p.name: p for p in all_parts}
    plan = plan_shards(all_parts, num_shards, exclude=exclude)
    mine = plan.assignment(shard)

    ctl = controller
    if ctl is not None and cancel_token is not None:
        ctl.bind_shared_cancel(cancel_token)

    repo = state_repository if runtime.state_cache_enabled() else None
    metrics: Dict[Analyzer, Metric] = {}
    merge_bytes = 0

    if shareable:
        signature = plan_signature_for(shareable, source, batch_size)
        entries: List[tuple] = []
        #: states of partitions this shard scanned but could not ship
        #: (an analyzer errored): recovery consults this before a
        #: second local rescan
        local_states_by_fp: Dict[str, List] = {}
        scan_errors: Dict[Analyzer, BaseException] = {}
        cancelled = False
        cancel_reason = ""
        cached_n = 0
        scanned_n = 0

        def _scan_one(part):
            """One partition through the solo sub-scan path; commits to
            the repository when clean. Returns (states, pairs, clean)."""
            results = scan_partition(
                shareable, part, batch_size=batch_size, controller=ctl
            )
            for a, r in zip(shareable, results):
                if r.error is not None and a not in scan_errors:
                    scan_errors[a] = r.error
            clean = all(r.error is None for r in results)
            pairs = [
                (r.analyzer, r.state if r.error is None else None)
                for r in results
            ]
            if repo is not None and clean:
                with observe.span(
                    "state_cache", cat="cache", op="save", partition=part.name
                ):
                    repo.save_states(
                        dataset_name, part.fingerprint, signature, pairs
                    )
            return [r.state if r.error is None else None for r in results], pairs, clean

        for part in (parts_by_name[n] for n in mine.names):
            try:
                if ctl is not None:
                    ctl.check(
                        where=f"shard {shard} partition {part.name}",
                        progress={
                            "shard": shard,
                            "partitions_done": cached_n + scanned_n,
                            "partitions_total": mine.num_partitions,
                            "partitions_cached": cached_n,
                        },
                        boundary=True,
                    )
                states = None
                if repo is not None:
                    sp = observe.span(
                        "state_cache", cat="cache", op="load",
                        partition=part.name,
                    )
                    with sp:
                        states = repo.load_states(
                            dataset_name, part.fingerprint, signature,
                            shareable,
                        )
                        if sp:
                            sp.set(hit=states is not None)
                if states is not None:
                    # resume from committed progress: re-encoding decoded
                    # states reproduces the committed envelope bytes
                    # (state serde round-trips bit-exactly)
                    entries.append(
                        (part.fingerprint,
                         encode_states(list(zip(shareable, states))))
                    )
                    cached_n += 1
                else:
                    states, pairs, clean = _scan_one(part)
                    scanned_n += 1
                    if clean:
                        entries.append((part.fingerprint, encode_states(pairs)))
                    else:
                        # an errored partition never ships: every shard
                        # rescans it locally and observes the failure
                        # itself, so the error can't silently drop out
                        local_states_by_fp[part.fingerprint] = states
            except RunCancelled as rc:
                # do NOT unwind past the collective: flag the envelope,
                # gather, and raise uniformly after the exchange
                cancelled = True
                cancel_reason = rc.reason
                if cancel_token is not None:
                    cancel_token.trip(rc.reason)
                break

        envelope = encode_shard_states(
            shard, signature, entries,
            cancelled=cancelled, reason=cancel_reason,
        )
        lost_directive = faults.fault_point("shard.host_loss")
        with observe.span(
            "shard_allgather", cat="transfer", shard=shard,
            shards=num_shards, envelope_bytes=len(envelope),
        ):
            shard_envelopes = list(gather(envelope))
        if lost_directive == "lost":
            # chaos: one host's contribution vanishes after the exchange
            shard_envelopes[(shard + 1) % len(shard_envelopes)] = b""
        merge_bytes = sum(len(e) for e in shard_envelopes)

        decoded = []
        for i, env in enumerate(shard_envelopes):
            try:
                decoded.append(decode_shard_states(env))
            except StateDecodeError as e:
                warnings.warn(
                    f"DQ320: shard envelope {i} is unusable ({e}); its "
                    "partitions fall back to committed states or a rescan",
                    RuntimeWarning,
                    stacklevel=2,
                )
        for env in decoded:
            if env.signature != signature:
                raise ValueError(
                    "sharded-scan plan-signature mismatch: shard "
                    f"{env.shard} folded under {env.signature!r}, this "
                    f"shard under {signature!r}; all shards must run "
                    "identical plans over the same runtime knobs."
                )
        remote_cancel = next(
            ((e.reason or "cancelled") for e in decoded if e.cancelled), None
        )
        if cancelled or remote_cancel is not None:
            if cancel_token is not None:
                cancel_token.trip(cancel_reason or remote_cancel)
            raise RunCancelled(
                cancel_reason or remote_cancel,
                where=f"shard {shard}",
                progress={
                    "shard": shard,
                    "partitions_done": cached_n + scanned_n,
                    "partitions_total": mine.num_partitions,
                },
            )

        blob_by_fp: Dict[str, bytes] = {}
        for env in decoded:
            for fp, blob in env.entries:
                blob_by_fp.setdefault(fp, blob)

        merged: List = [None] * len(shareable)
        recovered_n = 0
        with observe.span(
            "shard_merge", cat="merge", shard=shard,
            shards=len(shard_envelopes), partitions=len(plan.order),
        ):
            # GLOBAL partition order — the same order a solo
            # `_run_partitioned` merges in, which is the whole
            # bit-identity argument (float merge order is the contract)
            for name, _path, fp in plan.order:
                states = None
                blob = blob_by_fp.get(fp)
                if blob is not None:
                    directive = faults.fault_point("shard.merge")
                    if directive == "corrupt":
                        blob = blob[:-1]
                    try:
                        states = decode_states(blob, shareable)
                    except StateDecodeError as e:
                        warnings.warn(
                            f"DQ320: gathered states for partition "
                            f"{name!r} are unusable ({e}); falling back "
                            "to committed states or a rescan",
                            RuntimeWarning,
                            stacklevel=2,
                        )
                        states = None
                if states is None:
                    # lost-host / corrupt-entry recovery: committed
                    # progress first, then a local rescan — the same
                    # fold either way, so the result is bit-identical
                    recovered_n += 1
                    states = local_states_by_fp.get(fp)
                    if states is None and repo is not None:
                        states = repo.load_states(
                            dataset_name, fp, signature, shareable
                        )
                    if states is None:
                        states, _pairs, _clean = _scan_one(parts_by_name[name])
                        scanned_n += 1
                merged = [merge_states(m, s) for m, s in zip(merged, states)]

        for a, state in zip(shareable, merged):
            if a in scan_errors:
                metrics[a] = a.to_failure_metric(scan_errors[a])
            else:
                metrics[a] = a.compute_metric_from(state)
        runtime.record_state_cache(cached_n, scanned_n, mine.num_partitions)

    if rest:
        local_provider = InMemoryStateProvider()
        local_errors: Dict[Analyzer, object] = {}
        rest_cancel = None
        if mine.num_partitions:
            try:
                local_context = AnalysisRunner.do_analysis_run(
                    source.subset(list(mine.paths)),
                    rest,
                    save_states_with=local_provider,
                    engine=engine,
                    mesh=mesh,
                    controller=ctl,
                )
                local_errors = {
                    a: metric.value.exception
                    for a, metric in local_context.metric_map.items()
                    if metric.value.is_failure
                    and not isinstance(metric.value.exception, EmptyStateException)
                }
            except RunCancelled as rc:
                # same no-unwind-past-the-collective rule: contribute a
                # per-analyzer failure so other shards fail these
                # metrics loudly instead of shrinking them silently
                rest_cancel = rc
                if cancel_token is not None:
                    cancel_token.trip(rc.reason)
                local_errors = {
                    a: f"shard {shard} cancelled: {rc.reason}" for a in rest
                }
        merged_rest, rest_errors = merge_states_across_hosts(
            rest, local_provider, gather=gather, local_errors=local_errors
        )
        if rest_cancel is not None:
            raise rest_cancel
        for a in rest:
            if a in rest_errors:
                metrics[a] = a.to_failure_metric(
                    MetricCalculationException(rest_errors[a])
                )
            else:
                metrics[a] = a.compute_metric_from(merged_rest.load(a))

    rows_local = 0
    if mine.num_partitions:
        import pyarrow.parquet as pq

        for path in mine.paths:
            pf = pq.ParquetFile(path)
            try:
                rows_local += int(pf.metadata.num_rows)
            finally:
                pf.close()
    runtime.record_shard_scan(
        shard,
        num_shards,
        mine.num_partitions,
        plan.max_partitions,
        len(plan.order),
        merge_bytes,
        rows_local,
    )

    metrics.update(failure_map)
    return AnalyzerContext(metrics)


def run_multihost_analysis(
    local_table: Table,
    analyzers: Sequence[Analyzer],
    mesh=None,
    engine: str = "auto",
    gather=allgather_bytes,
    save_states_with=None,
) -> AnalyzerContext:
    """DEPRECATED (ISSUE 15): the Table-only entry point — this
    process's partition must already sit in memory, so the full
    streamed path (native reader, decode->wire fusion, pipeline, state
    cache) never runs. Use `run_sharded_analysis` with a
    `PartitionedParquetSource`; this shim stays so existing callers
    keep working and now warns.

    Analyze this process's partition locally, then merge states across
    all processes; returns identical table-level metrics on every host
    (the distributed form of runOnAggregatedStates,
    reference: examples/UpdateMetricsOnPartitionedDataExample.scala:30-95).

    `save_states_with` optionally receives this host's LOCAL
    (pre-merge) states — callers that want to inspect or persist the
    partition contribution (e.g. the dryrun asserting a spilled
    frequency state) get them from the single analysis pass instead of
    recomputing. The persisted values are the SAME state objects the
    cross-host merge then serializes, so the receiving persister must
    treat them as read-only. The merge itself always reads a FRESH
    internal provider, so a reused/pre-populated caller provider can
    never leak a previous run's state into this host's contribution (an
    empty local state is never persisted, so it would not overwrite a
    stale entry).

    A failure on ANY host fails that analyzer's global metric on EVERY
    host — a partition that errored must not silently drop out of a
    "successful" table-level number."""
    warnings.warn(
        "run_multihost_analysis is deprecated: it takes an in-memory Table "
        "and bypasses the streamed scan path. Use run_sharded_analysis with "
        "a PartitionedParquetSource instead.",
        DeprecationWarning,
        stacklevel=2,
    )
    from deequ_tpu.core.exceptions import MetricCalculationException
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    analyzers = _dedup(analyzers)
    local_states = InMemoryStateProvider()
    local_context = AnalysisRunner.do_analysis_run(
        local_table,
        analyzers,
        save_states_with=local_states,
        engine=engine,
        mesh=mesh,
    )
    if save_states_with is not None:
        for analyzer in analyzers:
            state = local_states.load(analyzer)
            if state is not None:
                save_states_with.persist(analyzer, state)
    from deequ_tpu.core.exceptions import EmptyStateException

    # an all-NULL local partition is a legitimately empty contribution
    # (EmptyStateException), not a failure — other hosts may have data
    local_errors = {
        analyzer: metric.value.exception
        for analyzer, metric in local_context.metric_map.items()
        if metric.value.is_failure
        and not isinstance(metric.value.exception, EmptyStateException)
    }
    merged, errors = merge_states_across_hosts(
        analyzers, local_states, gather=gather, local_errors=local_errors
    )
    metrics = {}
    for analyzer in analyzers:
        if analyzer in errors:
            metrics[analyzer] = analyzer.to_failure_metric(
                MetricCalculationException(errors[analyzer])
            )
        else:
            metrics[analyzer] = analyzer.compute_metric_from(merged.load(analyzer))
    return AnalyzerContext(metrics)

"""Spawn-and-collect harness for REAL multi-process JAX runs.

Shared by the two-process multihost test and the driver-facing
`dryrun_multihost` (the composed ICI×DCN dry run) so the loopback
coordinator scaffolding — free port, worker script on disk, Popen
fan-out, RESULT-line protocol, diagnostic-preserving timeout — exists
once. The workers are real OS processes running real
`jax.distributed.initialize`, which is the only way to exercise the
non-identity branch of `allgather_bytes` without a multi-host fleet.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
from typing import List, Sequence


class WorkerFailure(RuntimeError):
    """A worker exited non-zero or the cluster timed out; `details`
    carries every worker's captured stderr tail for diagnosis.
    `runtime_unavailable` distinguishes "the multi-process runtime
    could not run here" (timeout / non-zero exit — callers that treat
    it as optional may skip) from a PROTOCOL failure (a worker ran to
    completion but broke the RESULT contract — always a real bug, never
    an environment problem)."""

    def __init__(
        self,
        message: str,
        details: str = "",
        runtime_unavailable: bool = True,
    ):
        super().__init__(message + ("\n" + details if details else ""))
        self.details = details
        self.runtime_unavailable = runtime_unavailable


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_worker_processes(
    worker_source: str,
    n_processes: int,
    extra_args: Sequence[str] = (),
    timeout: float = 240.0,
) -> List[dict]:
    """Run `worker_source` in n_processes real interpreters with argv
    ``[rank, port, tmpdir, *extra_args]``; each worker must print one
    ``RESULT:<json>`` line. Returns the parsed RESULT payloads in rank
    order. Raises WorkerFailure (with every worker's stderr tail) on
    non-zero exits, missing RESULT lines, or timeout — the timeout path
    drains and reaps every process so no pipes or zombies leak."""
    port = free_port()
    env = dict(os.environ)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    # workers pick their own platform/device count in code; an inherited
    # forced host-device-count flag must not override them
    env.pop("XLA_FLAGS", None)

    with tempfile.TemporaryDirectory() as tmpdir:
        worker_path = os.path.join(tmpdir, "worker.py")
        with open(worker_path, "w") as f:
            f.write(worker_source)
        procs = [
            subprocess.Popen(
                [sys.executable, worker_path, str(rank), str(port), tmpdir]
                + [str(a) for a in extra_args],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
            )
            for rank in range(n_processes)
        ]
        outs = []
        timed_out = False
        for p in procs:
            try:
                stdout, stderr = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                timed_out = True
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                # drain + reap everything so diagnostics survive and no
                # zombies/pipes leak
                stdout, stderr = p.communicate()
            outs.append((p.returncode, stdout, stderr))
        details = "\n---\n".join(
            f"rank {i} rc={rc}:\n{err[-2000:]}"
            for i, (rc, _out, err) in enumerate(outs)
        )
        if timed_out:
            raise WorkerFailure(
                f"{n_processes}-process JAX runtime timed out after "
                f"{timeout:.0f}s",
                details,
            )
        if any(rc != 0 for rc, _o, _e in outs):
            raise WorkerFailure(
                f"{n_processes}-process JAX worker failed", details
            )
        results = []
        for rank, (_rc, stdout, _err) in enumerate(outs):
            lines = [
                l for l in stdout.splitlines() if l.startswith("RESULT:")
            ]
            if not lines:
                raise WorkerFailure(
                    f"rank {rank} exited 0 but produced no RESULT line "
                    "(broken worker protocol, not an environment issue)",
                    details,
                    runtime_unavailable=False,
                )
            try:
                results.append(json.loads(lines[-1][len("RESULT:"):]))
            except ValueError as e:
                raise WorkerFailure(
                    f"rank {rank} produced a malformed RESULT line: {e}",
                    details,
                    runtime_unavailable=False,
                )
        return results

"""Deterministic partition->shard planning for the sharded streaming scan.

The sharded scan (parallel/multihost.py:run_sharded_analysis) gives each
process a range of a `PartitionedParquetSource`'s partitions to fold
locally; only the folded states ever cross process boundaries. The
assignment here is the contract that makes that safe:

  * DETERMINISTIC — every process computes the same plan from the same
    partition list with no coordination round: the owner of a partition
    is a pure function of its content fingerprint
    (`data/source.py:partition_fingerprint`, the same key the state
    cache stores envelopes under) and the shard count.
  * MINIMAL MOVEMENT — ownership is a rendezvous (highest-random-weight)
    hash: each (fingerprint, shard) pair hashes to an independent
    weight and the live shard with the highest weight owns the
    partition. Removing a shard therefore moves ONLY the partitions it
    owned (each to its runner-up shard), and adding one steals only the
    partitions it now wins — no global reshuffle, so a membership
    change invalidates the minimum amount of committed per-partition
    progress.
  * ORDER-PRESERVING — within a shard, partitions keep their global
    (dataset name) order, and the plan records the full global order:
    the merge side folds states in THAT order, which is what keeps a
    sharded run bit-identical to a solo `_run_partitioned` pass (float
    merge order is the contract, ops/fused.py).

Host loss is re-planning with the lost shard in `exclude`: its
partitions land on the surviving shards, which rescan anything the lost
host had not committed to the StateRepository — from committed
progress, bit-identically (pinned by tests/test_sharded_scan.py).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from deequ_tpu.testing import faults


def rendezvous_weight(fingerprint: str, shard: int) -> int:
    """The (partition, shard) rendezvous weight: the first 8 bytes of
    sha256("<fingerprint>:<shard>") as a big-endian integer. Pure in its
    two arguments — no shard ever influences another's weights, which is
    what bounds re-assignment under membership change."""
    digest = hashlib.sha256(f"{fingerprint}:{shard}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


@dataclass(frozen=True)
class ShardAssignment:
    """One shard's slice of the dataset, in global partition order."""

    shard: int
    names: Tuple[str, ...]
    paths: Tuple[str, ...]
    fingerprints: Tuple[str, ...]

    @property
    def num_partitions(self) -> int:
        return len(self.names)


@dataclass(frozen=True)
class ShardPlan:
    """The full deterministic assignment: one `ShardAssignment` per
    shard id (excluded/empty shards get empty assignments, so indexing
    is always total), plus the global partition order the merge side
    folds in."""

    num_shards: int
    assignments: Tuple[ShardAssignment, ...]
    #: (name, path, fingerprint) for EVERY partition, in dataset order —
    #: the one merge order all shards share
    order: Tuple[Tuple[str, str, str], ...]

    def assignment(self, shard: int) -> ShardAssignment:
        return self.assignments[shard]

    def owner_of(self, name: str) -> int:
        for a in self.assignments:
            if name in a.names:
                return a.shard
        raise KeyError(name)

    @property
    def max_partitions(self) -> int:
        return max(a.num_partitions for a in self.assignments)

    @property
    def min_partitions(self) -> int:
        live = [a.num_partitions for a in self.assignments if a.num_partitions]
        return min(live) if live else 0

    @property
    def skew(self) -> float:
        """max shard size over the ideal (total/num_shards) — 1.0 is a
        perfectly even split; the `engine.shard.skew_ratio` telemetry
        series and the EXPLAIN `shards:` line both report this."""
        total = len(self.order)
        if total == 0 or self.num_shards == 0:
            return 1.0
        ideal = total / float(self.num_shards)
        return self.max_partitions / ideal if ideal > 0 else 1.0


def plan_shards(
    partitions: Sequence,
    num_shards: int,
    exclude: Sequence[int] = (),
) -> ShardPlan:
    """Assign `partitions` (objects with `.name` / `.path` /
    `.fingerprint`, already in dataset order) to `num_shards` shards by
    rendezvous hash over the fingerprints. Shards in `exclude` (lost
    hosts) receive nothing; their partitions fall to the highest-weight
    survivor — and ONLY theirs move."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    excluded = set(int(s) for s in exclude)
    alive = [s for s in range(num_shards) if s not in excluded]
    if not alive:
        raise ValueError(
            f"all {num_shards} shards excluded — nothing can own the data"
        )
    faults.fault_point("shard.assign")
    owned: Dict[int, List] = {s: [] for s in range(num_shards)}
    order: List[Tuple[str, str, str]] = []
    for part in partitions:
        fingerprint = part.fingerprint
        order.append((part.name, part.path, fingerprint))
        # ties broken by shard id so the plan is total even under a
        # (vanishingly unlikely) weight collision
        owner = max(alive, key=lambda s: (rendezvous_weight(fingerprint, s), s))
        owned[owner].append(part)
    assignments = tuple(
        ShardAssignment(
            shard=s,
            names=tuple(p.name for p in owned[s]),
            paths=tuple(p.path for p in owned[s]),
            fingerprints=tuple(p.fingerprint for p in owned[s]),
        )
        for s in range(num_shards)
    )
    return ShardPlan(
        num_shards=num_shards, assignments=assignments, order=tuple(order)
    )


__all__ = [
    "ShardAssignment",
    "ShardPlan",
    "plan_shards",
    "rendezvous_weight",
]

from deequ_tpu.profiles.column_profile import (
    ColumnProfile,
    ColumnProfiles,
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.profiles.column_profiler import ColumnProfiler
from deequ_tpu.profiles.runner import ColumnProfilerRunner

__all__ = [
    "ColumnProfile",
    "ColumnProfiles",
    "NumericColumnProfile",
    "StandardColumnProfile",
    "ColumnProfiler",
    "ColumnProfilerRunner",
]

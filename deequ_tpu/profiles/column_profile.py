"""Column profile model + JSON export.

reference: profiles/ColumnProfile.scala:24-147.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from deequ_tpu.core.metrics import Distribution


@dataclass
class ColumnProfile:
    column: str
    completeness: float
    approximate_num_distinct_values: int
    data_type: str
    is_data_type_inferred: bool
    type_counts: Dict[str, int] = field(default_factory=dict)
    histogram: Optional[Distribution] = None


@dataclass
class StandardColumnProfile(ColumnProfile):
    pass


@dataclass
class NumericColumnProfile(ColumnProfile):
    mean: Optional[float] = None
    maximum: Optional[float] = None
    minimum: Optional[float] = None
    sum: Optional[float] = None
    std_dev: Optional[float] = None
    approx_percentiles: Optional[List[float]] = None


@dataclass
class ColumnProfiles:
    profiles: Dict[str, ColumnProfile]
    num_records: int

    def to_json(self) -> str:
        """reference: ColumnProfiles.toJson (ColumnProfile.scala:66+)."""
        columns = []
        for profile in self.profiles.values():
            entry: Dict[str, object] = {
                "column": profile.column,
                "dataType": profile.data_type,
                "isDataTypeInferred": str(profile.is_data_type_inferred).lower(),
                "completeness": profile.completeness,
                "approximateNumDistinctValues": profile.approximate_num_distinct_values,
            }
            if profile.type_counts:
                entry["typeCounts"] = dict(profile.type_counts)
            if profile.histogram is not None:
                entry["histogram"] = [
                    {
                        "value": value,
                        "count": dv.absolute,
                        "ratio": dv.ratio,
                    }
                    for value, dv in profile.histogram.values.items()
                ]
            if isinstance(profile, NumericColumnProfile):
                for key, value in [
                    ("mean", profile.mean),
                    ("maximum", profile.maximum),
                    ("minimum", profile.minimum),
                    ("sum", profile.sum),
                    ("stdDev", profile.std_dev),
                ]:
                    if value is not None:
                        entry[key] = value
                if profile.approx_percentiles:
                    entry["approxPercentiles"] = list(profile.approx_percentiles)
            columns.append(entry)
        return json.dumps({"columns": columns}, indent=2)

"""ColumnProfiler: full single-column profiles in AT MOST three scans.

reference: profiles/ColumnProfiler.scala:54-669. The reference's pass
structure is:
  1. Size + per-column Completeness + ApproxCountDistinct (+ DataType for
     strings) — ONE fused device pass;
  2. numeric columns (schema-numeric or inferred-numeric strings, cast
     host-side) get Minimum/Maximum/Mean/StandardDeviation/Sum/
     ApproxQuantiles(0.01..1.00) — ONE fused pass (device + host-reduced
     quantile sketches share it);
  3. exact histograms for low-cardinality string/bool columns — one
     group-by pass.

Pass-budget improvement over the reference: a SCHEMA-numeric column's
pass-2 analyzer set does not depend on pass-1 results (only
inferred-numeric STRING columns need the post-inference cast), so its
numeric statistics fuse into pass 1. Pass 2 then runs only for the
string-cast columns — and, under column pruning, decodes ONLY those
columns from a streaming source. A table with no numeric-looking string
columns profiles in 2 scans; the reference's 3 is the ceiling either
way (the reference itself always pays 3:
ColumnProfiler.scala:103-153)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantiles,
    Completeness,
    DataType,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.scan import DataTypeInstances, determine_type
from deequ_tpu.core.metrics import Distribution, DistributionValue
from deequ_tpu.data.table import Column, ColumnType, Table
from deequ_tpu.profiles.column_profile import (
    ColumnProfiles,
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.runners.analysis_runner import AnalysisRunner

DEFAULT_CARDINALITY_THRESHOLD = 120

_PERCENTILES = tuple(i / 100 for i in range(1, 101))


def _numeric_stat_analyzers(name: str) -> List:
    """The numeric-statistics bundle of the reference's pass 2
    (ColumnProfiler.scala:219-235)."""
    return [
        Minimum(name),
        Maximum(name),
        Mean(name),
        StandardDeviation(name),
        Sum(name),
        ApproxQuantiles(name, _PERCENTILES),
    ]


@dataclass
class GenericColumnStatistics:
    num_records: int
    inferred_types: Dict[str, str]
    known_types: Dict[str, str]
    type_detection_histograms: Dict[str, Dict[str, int]]
    approximate_num_distincts: Dict[str, int]
    completenesses: Dict[str, float]

    def type_of(self, column: str) -> str:
        if column in self.inferred_types:
            return self.inferred_types[column]
        return self.known_types[column]


class ColumnProfiler:
    @staticmethod
    def profile(
        data: Table,
        restrict_to_columns: Optional[Sequence[str]] = None,
        print_status_updates: bool = False,
        low_cardinality_histogram_threshold: int = DEFAULT_CARDINALITY_THRESHOLD,
        metrics_repository=None,
        reuse_existing_results_for_key=None,
        fail_if_results_missing: bool = False,
        save_in_metrics_repository_using_key=None,
        engine: str = "auto",
        mesh=None,
    ) -> ColumnProfiles:
        """reference: ColumnProfiler.scala:81-188."""
        relevant = (
            list(restrict_to_columns)
            if restrict_to_columns is not None
            else data.column_names
        )
        for name in relevant:
            data.column(name)  # raises NoSuchColumnException early

        # ---- Pass 1 (reference: :103-126) --------------------------------
        # Schema-numeric columns also get their full numeric statistics
        # HERE: their pass-2 analyzer choice never depends on pass-1
        # inference, so fusing them saves a whole scan (see module
        # docstring). Only inferred-numeric strings still need pass 2.
        # NOTE for repository reuse: the analyzer-per-pass assignment
        # changed when this fusion landed, so a key saved by an older
        # version misses the numeric metrics — reuse still works
        # analyzer-by-analyzer unless fail_if_results_missing demands
        # completeness.
        may_need_pass2 = any(
            data.column(name).ctype == ColumnType.STRING for name in relevant
        )

        def _with_repository(builder):
            if metrics_repository is not None:
                builder = builder.use_repository(metrics_repository)
                if reuse_existing_results_for_key is not None:
                    builder = builder.reuse_existing_results_for_key(
                        reuse_existing_results_for_key, fail_if_results_missing
                    )
                if save_in_metrics_repository_using_key is not None:
                    builder = builder.save_or_append_result(
                        save_in_metrics_repository_using_key
                    )
            return builder

        total_passes = 3 if may_need_pass2 else 2
        if print_status_updates:
            print(
                "### PROFILING: Computing generic column statistics in "
                f"pass (1/{total_passes})..."
            )
        from deequ_tpu.profiles.internal_analyzers import (
            LowCardCountsState,
            OptimisticNumericState,
            _LowCardCounts,
            _OptimisticNumericStats,
            synthesize_numeric_metrics,
        )

        # optimistic members fold passes 2 and 3 into pass 1 (see
        # internal_analyzers module docstring); the count cap leaves HLL
        # estimation error (rsd 0.05) generous headroom over the
        # histogram threshold
        lcc_cap = max(4 * low_cardinality_histogram_threshold, 256)
        analyzers_pass1 = [Size()]
        for name in relevant:
            analyzers_pass1.append(Completeness(name))
            analyzers_pass1.append(ApproxCountDistinct(name))
            ctype = data.column(name).ctype
            if ctype == ColumnType.STRING:
                analyzers_pass1.append(DataType(name))
                analyzers_pass1.append(_LowCardCounts(name, lcc_cap))
                analyzers_pass1.append(_OptimisticNumericStats(name))
            elif ctype == ColumnType.BOOLEAN:
                analyzers_pass1.append(_LowCardCounts(name, lcc_cap))
            elif ctype.is_numeric:
                analyzers_pass1.extend(_numeric_stat_analyzers(name))

        results_pass1 = _with_repository(
            AnalysisRunner.on_data(data)
            .add_analyzers(analyzers_pass1)
            .with_engine(engine, mesh)
        ).run()

        generic_stats = _extract_generic_statistics(relevant, data, results_pass1)
        low_card_counts: Dict[str, LowCardCountsState] = {}
        optimistic_numeric: Dict[str, OptimisticNumericState] = {}
        for analyzer, metric in results_pass1.metric_map.items():
            if not metric.value.is_success:
                continue
            state = metric.value.get()
            if isinstance(analyzer, _LowCardCounts) and isinstance(
                state, LowCardCountsState
            ):
                if not state.aborted:
                    low_card_counts[analyzer.column] = state
            elif isinstance(analyzer, _OptimisticNumericStats) and isinstance(
                state, OptimisticNumericState
            ):
                if state.usable:
                    optimistic_numeric[analyzer.column] = state

        # ---- Pass 2 (reference: :128-153, cast at :399-417) --------------
        # runs ONLY for inferred-numeric STRING columns, which need the
        # post-inference cast; schema-numeric stats came from pass 1
        numeric_columns = [
            name
            for name in relevant
            if generic_stats.type_of(name)
            in (DataTypeInstances.INTEGRAL, DataTypeInstances.FRACTIONAL)
        ]
        cast_columns = [
            name for name in numeric_columns if name in generic_stats.inferred_types
        ]
        combined = results_pass1
        # optimistic pass-1 stats replace pass 2 for columns where they
        # survived (every value cast cleanly — guaranteed whenever
        # inference landed numeric, see internal_analyzers). With a reuse
        # key the classic pass keeps its repository short-circuit
        # semantics instead.
        synthesized: Dict = {}
        if reuse_existing_results_for_key is None:
            for name in list(cast_columns):
                state = optimistic_numeric.get(name)
                if state is not None:
                    synthesized.update(
                        synthesize_numeric_metrics(name, state, _PERCENTILES)
                    )
                    cast_columns.remove(name)
        if synthesized:
            from deequ_tpu.runners.context import AnalyzerContext

            synthesized_ctx = AnalyzerContext(synthesized)
            combined = combined + synthesized_ctx
            if (
                metrics_repository is not None
                and save_in_metrics_repository_using_key is not None
            ):
                AnalysisRunner._save_or_append(
                    metrics_repository,
                    save_in_metrics_repository_using_key,
                    synthesized_ctx,
                )
        analyzers_pass2 = []
        for name in cast_columns:
            analyzers_pass2.extend(_numeric_stat_analyzers(name))
        if analyzers_pass2:
            if print_status_updates:
                print(
                    "### PROFILING: Computing numeric column statistics "
                    f"in pass (2/{total_passes})..."
                )
            casted_data = _cast_numeric_string_columns(
                cast_columns, data, generic_stats
            )
            # same repository options as every other pass
            # (reference: ColumnProfiler.scala:128-153 threads them through)
            combined = combined + _with_repository(
                AnalysisRunner.on_data(casted_data)
                .add_analyzers(analyzers_pass2)
                .with_engine(engine, mesh)
            ).run()
        numeric_stats = _extract_numeric_statistics(combined)

        # ---- Pass 3 (reference: :487-565) --------------------------------
        # Normally already answered by the pass-1 _LowCardCounts fold; a
        # separate counting pass runs only for stragglers (column whose
        # exact distinct blew the optimistic cap while its HLL estimate
        # still cleared the threshold — possible but rare at rsd 0.05).
        target_columns = _find_target_columns_for_histograms(
            data, generic_stats, low_cardinality_histogram_threshold
        )
        histograms: Dict[str, Distribution] = {}
        stragglers = []
        for name in target_columns:
            state = low_card_counts.get(name)
            if state is None:
                stragglers.append(name)
                continue
            histograms[name] = _distribution_from_counts(
                data.column(name).ctype,
                state.as_dict(),
                state.null_count,
                generic_stats.num_records,
            )
        if stragglers:
            if print_status_updates:
                print(
                    "### PROFILING: Computing histograms of low-cardinality "
                    f"columns in pass ({total_passes}/{total_passes})..."
                )
            histograms.update(
                _compute_histograms(data, stragglers, generic_stats.num_records)
            )

        return _create_profiles(relevant, generic_stats, numeric_stats, histograms)


def _extract_generic_statistics(
    columns: Sequence[str], data: Table, results
) -> GenericColumnStatistics:
    """reference: ColumnProfiler.scala:341-396."""
    num_records = 0
    inferred_types: Dict[str, str] = {}
    type_detection: Dict[str, Dict[str, int]] = {}
    approx_distincts: Dict[str, int] = {}
    completenesses: Dict[str, float] = {}

    for analyzer, metric in results.metric_map.items():
        if isinstance(analyzer, Size) and metric.value.is_success:
            num_records = int(metric.value.get())
        elif isinstance(analyzer, DataType) and metric.value.is_success:
            dist = metric.value.get()
            inferred_types[analyzer.column] = determine_type(dist)
            type_detection[analyzer.column] = {
                key: dv.absolute for key, dv in dist.values.items()
            }
        elif isinstance(analyzer, ApproxCountDistinct) and metric.value.is_success:
            approx_distincts[analyzer.column] = int(metric.value.get())
        elif isinstance(analyzer, Completeness) and metric.value.is_success:
            completenesses[analyzer.column] = metric.value.get()

    known_types: Dict[str, str] = {}
    for name, ctype in data.schema:
        if name not in columns or ctype == ColumnType.STRING:
            continue
        known_types[name] = {
            ColumnType.LONG: DataTypeInstances.INTEGRAL,
            ColumnType.DOUBLE: DataTypeInstances.FRACTIONAL,
            ColumnType.DECIMAL: DataTypeInstances.FRACTIONAL,
            ColumnType.BOOLEAN: DataTypeInstances.BOOLEAN,
            ColumnType.TIMESTAMP: DataTypeInstances.STRING,
        }[ctype]

    return GenericColumnStatistics(
        num_records,
        inferred_types,
        known_types,
        type_detection,
        approx_distincts,
        completenesses,
    )


def _cast_numeric_string_columns(
    columns: Sequence[str], data: Table, stats: GenericColumnStatistics
) -> Table:
    """Cast the given inferred-numeric string columns for pass 2
    (reference: ColumnProfiler.scala:329-339, 399-417); the caller passes
    exactly the columns whose inferred type is Integral/Fractional. On a
    streaming source the cast is a lazy per-batch transform."""
    to_cast = list(columns)
    if not to_cast:
        return data

    def cast_batch(batch: Table) -> Table:
        out = batch
        for name in to_cast:
            if not batch.has_column(name):
                continue  # column-pruned batch: nothing to cast
            col = batch.column(name)
            values, valid = col.numeric_values()
            out = out.with_column(Column(name, ColumnType.DOUBLE, values, valid))
        return out

    if getattr(data, "is_streaming", False):
        from deequ_tpu.data.source import MappedSource

        return MappedSource(
            data,
            cast_batch,
            schema_overrides=[(name, ColumnType.DOUBLE) for name in to_cast],
            # cast_batch is an IN-PLACE transform (reads only the columns
            # it rewrites, and skips pruned-away ones), so it needs no
            # extra base columns beyond whatever the consumer requests
            fn_columns=(),
        )
    return cast_batch(data)


@dataclass
class NumericColumnStatistics:
    means: Dict[str, float] = field(default_factory=dict)
    maxima: Dict[str, float] = field(default_factory=dict)
    minima: Dict[str, float] = field(default_factory=dict)
    sums: Dict[str, float] = field(default_factory=dict)
    std_devs: Dict[str, float] = field(default_factory=dict)
    approx_percentiles: Dict[str, List[float]] = field(default_factory=dict)


def _extract_numeric_statistics(results) -> NumericColumnStatistics:
    stats = NumericColumnStatistics()
    for analyzer, metric in results.metric_map.items():
        if not metric.value.is_success:
            continue
        if isinstance(analyzer, Mean):
            stats.means[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, Maximum):
            stats.maxima[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, Minimum):
            stats.minima[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, Sum):
            stats.sums[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, StandardDeviation):
            stats.std_devs[analyzer.column] = metric.value.get()
        elif isinstance(analyzer, ApproxQuantiles):
            keyed = metric.value.get()
            ordered = [keyed[k] for k in sorted(keyed, key=float)]
            stats.approx_percentiles[analyzer.column] = ordered
    return stats


def _find_target_columns_for_histograms(
    data: Table, stats: GenericColumnStatistics, threshold: int
) -> List[str]:
    """string/bool columns with approx distinct <= threshold
    (reference: ColumnProfiler.scala:487-516)."""
    out = []
    for name, count in stats.approximate_num_distincts.items():
        ctype = data.column(name).ctype
        if ctype not in (ColumnType.STRING, ColumnType.BOOLEAN):
            continue
        if stats.type_of(name) not in (
            DataTypeInstances.STRING,
            DataTypeInstances.BOOLEAN,
        ):
            continue
        if count <= threshold:
            out.append(name)
    return out


def _distribution_from_counts(
    ctype: ColumnType,
    counts: Dict,
    null_count: int,
    num_records: int,
) -> Distribution:
    """Shared rendering of exact value counts into the reference's
    Distribution shape (null bucket name 'NullValue', booleans as
    'true'/'false' — reference: Histogram.scala:108, ColumnProfiler.scala
    :523-565)."""
    values: Dict[str, DistributionValue] = {}
    if null_count > 0:
        values["NullValue"] = DistributionValue(
            null_count, null_count / num_records
        )
    for unique, count in counts.items():
        if ctype == ColumnType.BOOLEAN:
            key = "true" if unique else "false"
        else:
            key = str(unique)
        prev = values.get(key)
        if prev is not None:
            count = count + prev.absolute
        values[key] = DistributionValue(count, count / num_records)
    return Distribution(values, number_of_bins=len(values))


def _compute_histograms(
    data: Table, target_columns: Sequence[str], num_records: int
) -> Dict[str, Distribution]:
    """One exact counting pass over all target columns
    (reference: ColumnProfiler.scala:523-565). Streaming sources fold
    per-batch count maps — host memory is O(#distinct), and only
    low-cardinality columns are targeted here."""
    if not target_columns:
        return {}
    from deequ_tpu.ops import runtime

    runtime.record_group_pass("profiler-histograms:" + ",".join(target_columns))
    if hasattr(data, "with_columns"):
        data = data.with_columns(list(target_columns))

    totals: Dict[str, Dict[str, int]] = {name: {} for name in target_columns}
    null_counts: Dict[str, int] = {name: 0 for name in target_columns}

    def accumulate(batch: Table) -> None:
        from deequ_tpu.ops import native

        for name in target_columns:
            col = batch.column(name)
            codes, uniques = col.dict_encode()
            counts = native.bincount(codes, len(uniques) + 1, base=1)
            if counts is None:
                counts = np.bincount(codes + 1, minlength=len(uniques) + 1)
            null_counts[name] += int(counts[0])
            bucket = totals[name]
            for i, unique in enumerate(uniques):
                count = int(counts[i + 1])
                if count == 0:
                    continue
                if col.ctype == ColumnType.BOOLEAN:
                    key = "true" if unique else "false"
                else:
                    key = str(unique)
                bucket[key] = bucket.get(key, 0) + count

    if getattr(data, "is_streaming", False):
        for batch in data.batches(getattr(data, "batch_rows", 1 << 22)):
            accumulate(batch)
    else:
        accumulate(data)

    histograms: Dict[str, Distribution] = {}
    for name in target_columns:
        values: Dict[str, DistributionValue] = {}
        if null_counts[name] > 0:
            values["NullValue"] = DistributionValue(
                null_counts[name], null_counts[name] / num_records
            )
        for key, count in totals[name].items():
            values[key] = DistributionValue(count, count / num_records)
        histograms[name] = Distribution(values, number_of_bins=len(values))
    return histograms


def _create_profiles(
    columns: Sequence[str],
    generic_stats: GenericColumnStatistics,
    numeric_stats: NumericColumnStatistics,
    histograms: Dict[str, Distribution],
) -> ColumnProfiles:
    """reference: ColumnProfiler.scala:617-669."""
    profiles = {}
    for name in columns:
        completeness = generic_stats.completenesses.get(name, 0.0)
        approx_distinct = generic_stats.approximate_num_distincts.get(name, 0)
        data_type = generic_stats.type_of(name)
        is_inferred = name in generic_stats.inferred_types
        type_counts = generic_stats.type_detection_histograms.get(name, {})
        histogram = histograms.get(name)

        if data_type in (DataTypeInstances.INTEGRAL, DataTypeInstances.FRACTIONAL):
            profile = NumericColumnProfile(
                name,
                completeness,
                approx_distinct,
                data_type,
                is_inferred,
                type_counts,
                histogram,
                mean=numeric_stats.means.get(name),
                maximum=numeric_stats.maxima.get(name),
                minimum=numeric_stats.minima.get(name),
                sum=numeric_stats.sums.get(name),
                std_dev=numeric_stats.std_devs.get(name),
                approx_percentiles=numeric_stats.approx_percentiles.get(name),
            )
        else:
            profile = StandardColumnProfile(
                name,
                completeness,
                approx_distinct,
                data_type,
                is_inferred,
                type_counts,
                histogram,
            )
        profiles[name] = profile
    return ColumnProfiles(profiles, generic_stats.num_records)

"""Profiler-internal scan members: fold pass 3 (and usually pass 2) into
pass 1.

The reference's ColumnProfiler pays 3 scans: generic stats, numeric stats
for cast columns, low-cardinality histograms
(reference: profiles/ColumnProfiler.scala:54-65, 103-187). These two
host-only scan-shareable members ride pass 1's fused scan instead:

- `_LowCardCounts` counts exact values for a string/bool column while its
  dict codes are hot (the pass-3 work), aborting once the running distinct
  count exceeds a cap — the profiler only keeps histograms for columns
  whose approx distinct is under the threshold anyway.
- `_OptimisticNumericStats` computes the full pass-2 numeric bundle
  (min/max/mean/stddev/sum + quantile sketch) for a STRING column under
  the optimistic assumption that type inference will land
  Integral/Fractional. This is sound: `determine_type` (reference:
  analyzers/DataType.scala:116-146) returns a numeric type only when NO
  value classified as String, i.e. every value matched a numeric regex —
  so a numeric verdict implies every batch was fully castable and the
  optimistic stats equal what pass 2 would have computed. Any parse
  failure kills the optimistic state (`dead`) and the final type cannot
  be numeric; if inference and castability ever disagree (pathological
  forms like "+ 5" that match the regex but not float()), the profiler
  simply falls back to a real pass 2 for that column.

Both are `internal`: their metrics never reach a MetricsRepository
(AnalysisRunner._save_or_append filters them), and they are host_only —
strings and dict codes never ship to the device.

A streamed profile with these members on board decodes the input ONCE
for the whole profile (the round-3 verdict's single-decode demand).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deequ_tpu.analyzers.base import (
    InputSpec,
    Preconditions,
    ScanShareableAnalyzer,
)
from deequ_tpu.analyzers.sketch import ApproxQuantileState, _batch_seed
from deequ_tpu.analyzers.states import State
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import Entity, Metric
from deequ_tpu.data.table import Table
from deequ_tpu.ops.sketches.kll import KLLSketch, k_for_error


@dataclass(frozen=True)
class _InternalStateMetric(Metric):
    """Carries a raw state through the runner's metric map; internal-only
    (filtered from repositories, never serialized)."""

    def flatten(self):
        return []


def _internal_metric(name: str, instance: str, value) -> "_InternalStateMetric":
    return _InternalStateMetric(Entity.COLUMN, name, instance, value)


# ---------------------------------------------------------------------------
# _LowCardCounts: exact value counts while the dict codes are hot
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LowCardCountsState(State):
    """counts[value] over non-null rows + null count; aborted=True once
    the RUNNING distinct count exceeded the cap (histogram not wanted
    for such columns anyway). The cap travels with the state so merges
    enforce it too: a stream whose batches each stay under the cap but
    whose cumulative dictionary does not still aborts instead of
    growing without bound."""

    counts: Tuple[Tuple[Any, int], ...]
    null_count: int
    aborted: bool
    cap: int = 1 << 30

    def merge(self, other: "LowCardCountsState") -> "LowCardCountsState":
        cap = min(self.cap, other.cap)
        if self.aborted or other.aborted:
            return LowCardCountsState(
                (), self.null_count + other.null_count, True, cap
            )
        merged: Dict[Any, int] = dict(self.counts)
        for key, count in other.counts:
            merged[key] = merged.get(key, 0) + count
        if len(merged) > cap:
            return LowCardCountsState(
                (), self.null_count + other.null_count, True, cap
            )
        return LowCardCountsState(
            tuple(merged.items()), self.null_count + other.null_count, False, cap
        )

    def as_dict(self) -> Dict[Any, int]:
        return dict(self.counts)


@dataclass(frozen=True)
class _LowCardCounts(ScanShareableAnalyzer):
    """Pass-3 exact histogram counting fused into pass 1
    (reference: profiles/ColumnProfiler.scala:487-565 — the rdd
    countByKey pass this replaces)."""

    column: str
    cap: int
    internal = True
    device_assisted = True
    host_only = True

    @property
    def name(self) -> str:
        return "_LowCardCounts"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.has_column(self.column)]

    def input_specs(self) -> List[InputSpec]:
        from deequ_tpu.data.table import ColumnType

        column = self.column

        def build_codes(t: Table) -> np.ndarray:
            col = t.column(column)
            if col.ctype == ColumnType.BOOLEAN:
                # bool fast path: raw values; counting is three popcounts,
                # no dictionary encode (device_batch dispatches on dtype)
                return col.values
            codes, _ = col.dict_encode()
            return codes

        def build_uniques(t: Table) -> np.ndarray:
            col = t.column(column)
            if col.ctype == ColumnType.BOOLEAN:
                return col.valid  # the bool path carries valid here
            _, uniques = col.dict_encode()
            return np.asarray(uniques)

        return [
            InputSpec(
                key=f"lcc_codes:{column}", build=build_codes, columns=(column,)
            ),
            InputSpec(
                key=f"lcc_uniq:{column}", build=build_uniques, columns=(column,)
            ),
        ]

    def device_batch(self, inputs: Dict[str, Any], xp) -> Any:
        from deequ_tpu.ops import native

        codes = np.asarray(inputs[f"lcc_codes:{self.column}"])
        uniques = inputs[f"lcc_uniq:{self.column}"]
        if codes.dtype == np.bool_:
            # bool fast path: codes = raw values, uniques slot = valid
            valid = np.asarray(uniques)
            n_true = int(np.count_nonzero(codes & valid))
            n_valid = int(np.count_nonzero(valid))
            counts = np.asarray(
                [len(codes) - n_valid, n_valid - n_true, n_true],
                dtype=np.int64,
            )
            # side-products: ApproxCountDistinct builds registers from
            # the ≤2 present identities; Completeness reads the counts
            inputs[f"__lccbool:{self.column}"] = (
                n_valid - n_true > 0,
                n_true > 0,
            )
            inputs[f"__lccnulls:{self.column}"] = (
                int(counts[0]),
                len(codes),
            )
            return {
                "counts": counts,
                "uniques": np.asarray([False, True], dtype=object),
            }
        aborted = len(uniques) > self.cap
        if aborted and len(uniques) > (1 << 16):
            # dictionary too large even for the presence side-product
            return {"aborted": True}
        counts = native.bincount(codes, len(uniques) + 1, base=1)
        if counts is None:
            counts = np.bincount(
                codes + 1, minlength=len(uniques) + 1
            ).astype(np.int64)
        # side-products for this string column: which dictionary entries
        # actually occur (ApproxCountDistinct builds registers over the
        # PRESENT uniques instead of a full-row scatter), the null
        # count (Completeness answers without a popcount), and the full
        # per-entry counts (DataType classifies the dictionary and
        # weighs the classes by these counts; _OptimisticNumericStats
        # derives the whole numeric family from them — both in
        # O(#uniques) instead of an O(rows) pass)
        inputs[f"__lccpresence:{self.column}"] = (counts[1:] > 0, uniques)
        inputs[f"__lccnulls:{self.column}"] = (int(counts[0]), len(codes))
        inputs[f"__lcccounts:{self.column}"] = (counts, uniques, len(codes))
        if aborted:
            # cap blown: no histogram for this column, skip dict building
            return {"aborted": True}
        return {"counts": counts, "uniques": uniques}

    def host_consume(self, state: Optional[State], out: Any) -> Optional[State]:
        if out.get("aborted"):
            partial = LowCardCountsState((), 0, True, self.cap)
            return partial if state is None else state.merge(partial)
        counts = np.asarray(out["counts"])
        uniques = out["uniques"]
        partial_counts = []
        for i, unique in enumerate(uniques):
            c = int(counts[i + 1])
            if c > 0:
                partial_counts.append((unique, c))
        partial = LowCardCountsState(
            tuple(partial_counts),
            int(counts[0]),
            len(partial_counts) > self.cap,
            self.cap,
        )
        return partial if state is None else state.merge(partial)

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        return _internal_metric(self.name, self.instance, Success(state))

    def __repr__(self) -> str:
        return f"_LowCardCounts({self.column},{self.cap})"


# ---------------------------------------------------------------------------
# _OptimisticNumericStats: the pass-2 numeric bundle, speculatively
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class OptimisticNumericState(State):
    """The whole numeric-stat family for one cast column: moments
    (merged with the same Chan law the scan analyzers use) + KLL digest.
    dead=True once any non-null value failed to cast."""

    n: float
    total: float
    minimum: float
    maximum: float
    m2: float
    digest: Optional[KLLSketch]
    dead: bool

    def merge(self, other: "OptimisticNumericState") -> "OptimisticNumericState":
        if self.dead or other.dead:
            return OptimisticNumericState(
                0.0, 0.0, float("inf"), float("-inf"), 0.0, None, True
            )
        n = self.n + other.n
        safe_n = max(n, 1.0)
        avg_a = self.total / max(self.n, 1.0)
        avg_b = other.total / max(other.n, 1.0)
        delta = avg_b - avg_a
        m2 = self.m2 + other.m2 + delta * delta * self.n * other.n / safe_n
        if self.digest is None:
            digest = other.digest
        elif other.digest is None:
            digest = self.digest
        else:
            digest = self.digest.merge(other.digest)
        return OptimisticNumericState(
            n,
            self.total + other.total,
            min(self.minimum, other.minimum),
            max(self.maximum, other.maximum),
            m2,
            digest,
            False,
        )

    @property
    def usable(self) -> bool:
        return not self.dead and self.n > 0 and self.digest is not None


_DEAD_SENTINEL = "__dead__"


@dataclass(frozen=True)
class _OptimisticNumericStats(ScanShareableAnalyzer):
    """Pass-2 numeric statistics computed during pass 1 for a string
    column that MAY infer numeric (reference:
    profiles/ColumnProfiler.scala:128-153, 329-339 — the cast + numeric
    pass this makes redundant when inference lands numeric)."""

    column: str
    relative_error: float = 0.01
    internal = True
    device_assisted = True
    host_only = True

    @property
    def name(self) -> str:
        return "_OptimisticNumericStats"

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Callable[[Table], None]]:
        return [Preconditions.has_column(self.column)]

    def _cap(self) -> int:
        return 2 * k_for_error(self.relative_error)

    def input_specs(self) -> List[InputSpec]:
        column = self.column

        def cast_or_dead(col):
            """(values, cast_valid) or the dead sentinel — shared by both
            specs through numeric_values' per-column memoization."""
            _, uniques = col.dict_encode()
            if len(uniques):
                # cheap castability probe on the head of the dictionary:
                # a clearly non-numeric column (names, UUIDs, ...) dies
                # here without paying a full parse of its dictionary
                from deequ_tpu.ops.strings import parse_floats

                _, ok = parse_floats(np.asarray(uniques[:64], dtype=object))
                if not ok.all():
                    return None
            values, cast_valid = col.numeric_values()
            # rows that were present but failed to parse kill the state
            if np.count_nonzero(np.asarray(col.valid) & ~np.asarray(cast_valid)):
                return None
            return values, cast_valid

        def build_values(t: Table):
            res = cast_or_dead(t.column(column))
            if res is None:
                return np.asarray(_DEAD_SENTINEL)
            return np.asarray(res[0])

        def build_valid(t: Table):
            res = cast_or_dead(t.column(column))
            if res is None:
                return np.asarray(_DEAD_SENTINEL)
            return np.asarray(res[1])

        return [
            InputSpec(
                key=f"optnum:{column}", build=build_values, columns=(column,)
            ),
            InputSpec(
                key=f"optnumv:{column}", build=build_valid, columns=(column,)
            ),
        ]

    def _from_counts(self, inputs: Dict[str, Any], lcc) -> Optional[Any]:
        """Derive the whole numeric-stat bundle from a _LowCardCounts
        dictionary-counts side-product: parse the DICTIONARY once and
        take weighted moments + rank-gathered decimation sample over
        (parsed value, count) pairs — O(#uniques) instead of the per-row
        cast + select-kernel pass. The sample is the exact
        sorted-decimation contract (ties are interchangeable), the level
        law mirrors the C kernel, and a parse failure on any PRESENT
        entry reproduces the dead-state semantics of cast_or_dead."""
        counts, uniques, _n_batch = lcc
        counts = np.asarray(counts)
        cs_all = counts[1:]
        uniques = np.asarray(uniques, dtype=object)
        if len(cs_all) != len(uniques):
            return None

        batch = getattr(inputs, "batch", None)
        try:
            if batch is not None:
                from deequ_tpu.data.table import parsed_dictionary

                u_vals, u_ok = parsed_dictionary(batch.column(self.column))
            else:
                from deequ_tpu.ops.strings import parse_floats

                u_vals, u_ok = parse_floats(uniques)
        except Exception:  # noqa: BLE001 - fall back to the per-row path
            return None
        if len(u_vals) != len(cs_all):
            return None
        present = cs_all > 0
        if np.any(present & ~np.asarray(u_ok, dtype=bool)):
            return {"dead": True}
        from deequ_tpu.ops.counts_family import weighted_moments_and_sample

        cs = cs_all[present]
        vals = np.asarray(u_vals, dtype=np.float64)[present]
        order = np.argsort(vals)
        core, sample, m, level = weighted_moments_and_sample(
            vals[order], cs[order], self._cap()
        )
        count, total, vmin, vmax, m2 = core
        return {
            "dead": False,
            "count": count,
            "sum": total,
            "min": vmin,
            "max": vmax,
            "m2": m2,
            "sample": sample,
            "n": m,
            "level": level,
        }

    def device_batch(self, inputs: Dict[str, Any], xp) -> Any:
        from deequ_tpu.ops import counts_family

        lcc = inputs.get(f"__lcccounts:{self.column}")
        if lcc is not None and counts_family.enabled():
            out = self._from_counts(inputs, lcc)
            if out is not None:
                return out
        values = inputs[f"optnum:{self.column}"]
        cast_valid = inputs[f"optnumv:{self.column}"]
        if np.asarray(values).ndim == 0:
            return {"dead": True}
        from deequ_tpu.ops import native

        cap = self._cap()
        res = native.masked_moments_select(values, cast_valid, None, cap)
        if res is not None:
            mom, sample, n_valid, level, _regs = res
            return {
                "dead": False,
                "count": float(mom[0]),
                "sum": float(mom[1]),
                "min": float(mom[2]),
                "max": float(mom[3]),
                "m2": float(mom[4]),
                "sample": sample,
                "n": n_valid,
                "level": level,
            }
        # numpy fallback: same math, same decimation law
        mask = np.asarray(cast_valid, dtype=bool)
        xm = np.asarray(values, dtype=np.float64)[mask]
        n = xm.size
        if n == 0:
            return {
                "dead": False, "count": 0.0, "sum": 0.0,
                "min": float("inf"), "max": float("-inf"), "m2": 0.0,
                "sample": np.zeros(0), "n": 0, "level": 0,
            }
        avg = float(xm.sum()) / n
        level = max(0, int(np.ceil(np.log2(max(n, 1) / cap))))
        stride = 1 << level
        xs = np.sort(xm)
        kept = max(0, -(-(n - stride // 2) // stride))
        return {
            "dead": False,
            "count": float(n),
            "sum": float(xm.sum()),
            "min": float(xs[0]),
            "max": float(xs[-1]),
            "m2": float(((xm - avg) ** 2).sum()),
            "sample": xs[stride // 2 :: stride][:kept],
            "n": n,
            "level": level,
        }

    def host_consume(self, state: Optional[State], out: Any) -> Optional[State]:
        if out.get("dead"):
            partial = OptimisticNumericState(
                0.0, 0.0, float("inf"), float("-inf"), 0.0, None, True
            )
        else:
            n = int(out["n"])
            level = int(out["level"]) if n > 0 else 0
            if n > 0:
                stride = 1 << level
                kept = max(0, -(-(n - stride // 2) // stride))
                sample = np.asarray(out["sample"], dtype=np.float64)[:kept]
            else:
                sample = np.empty(0, dtype=np.float64)
            digest = KLLSketch(
                k=k_for_error(self.relative_error),
                seed=_batch_seed(sample, n, level),
            )
            if n > 0:
                digest.insert_level(sample, level, true_count=n)
            partial = OptimisticNumericState(
                float(out["count"]),
                float(out["sum"]),
                float(out["min"]),
                float(out["max"]),
                float(out["m2"]),
                digest,
                False,
            )
        return partial if state is None else state.merge(partial)

    def compute_metric_from(self, state: Optional[State]) -> Metric:
        return _internal_metric(self.name, self.instance, Success(state))

    def __repr__(self) -> str:
        return f"_OptimisticNumericStats({self.column},{self.relative_error})"


def synthesize_numeric_metrics(
    column: str,
    state: OptimisticNumericState,
    percentiles,
    relative_error: float = 0.01,
) -> Dict[Any, Metric]:
    """Build the EXACT metric map pass 2 would have produced for this
    column, through the real analyzers' compute_metric_from — so shapes,
    names and failure semantics are identical
    (reference: ColumnProfiler.scala:219-235's analyzer bundle)."""
    from deequ_tpu.analyzers import (
        ApproxQuantiles,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
        Sum,
    )
    from deequ_tpu.analyzers.states import (
        MaxState,
        MeanState,
        MinState,
        StandardDeviationState,
        SumState,
    )

    n = state.n
    avg = state.total / max(n, 1.0)
    out: Dict[Any, Metric] = {}
    out[Minimum(column)] = Minimum(column).compute_metric_from(
        MinState(state.minimum)
    )
    out[Maximum(column)] = Maximum(column).compute_metric_from(
        MaxState(state.maximum)
    )
    out[Mean(column)] = Mean(column).compute_metric_from(
        MeanState(state.total, int(n))
    )
    out[Sum(column)] = Sum(column).compute_metric_from(SumState(state.total))
    out[StandardDeviation(column)] = StandardDeviation(column).compute_metric_from(
        StandardDeviationState(n, avg, state.m2)
    )
    aq = ApproxQuantiles(column, tuple(percentiles), relative_error)
    out[aq] = aq.compute_metric_from(ApproxQuantileState(state.digest))
    return out

"""Fluent entry for column profiling.

reference: profiles/ColumnProfilerRunner.scala:36-108 +
ColumnProfilerRunBuilder.scala:70-217.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from deequ_tpu.data.table import Table
from deequ_tpu.profiles.column_profile import ColumnProfiles
from deequ_tpu.profiles.column_profiler import (
    DEFAULT_CARDINALITY_THRESHOLD,
    ColumnProfiler,
)


class ColumnProfilerRunner:
    @staticmethod
    def on_data(data: Table) -> "ColumnProfilerRunBuilder":
        return ColumnProfilerRunBuilder(data)


class ColumnProfilerRunBuilder:
    def __init__(self, data: Table):
        self._data = data
        self._print_status_updates = False
        self._low_cardinality_histogram_threshold = DEFAULT_CARDINALITY_THRESHOLD
        self._restrict_to_columns: Optional[Sequence[str]] = None
        self._metrics_repository = None
        self._reuse_key = None
        self._fail_if_results_missing = False
        self._save_key = None
        self._save_profiles_json_path: Optional[str] = None
        self._overwrite_output_files = False
        self._engine: str = "auto"
        self._mesh = None

    def with_engine(self, engine: str, mesh=None) -> "ColumnProfilerRunBuilder":
        """"auto" (mesh when >1 device), "single", or "distributed"."""
        self._engine = engine
        self._mesh = mesh
        return self

    def print_status_updates(self, value: bool) -> "ColumnProfilerRunBuilder":
        self._print_status_updates = value
        return self

    def with_low_cardinality_histogram_threshold(
        self, threshold: int
    ) -> "ColumnProfilerRunBuilder":
        self._low_cardinality_histogram_threshold = threshold
        return self

    def restrict_to_columns(self, columns: Sequence[str]) -> "ColumnProfilerRunBuilder":
        self._restrict_to_columns = columns
        return self

    def use_repository(self, repository) -> "ColumnProfilerRunBuilder":
        self._metrics_repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key, fail_if_results_missing: bool = False
    ) -> "ColumnProfilerRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key) -> "ColumnProfilerRunBuilder":
        self._save_key = key
        return self

    def save_column_profiles_json_to_path(self, path: str) -> "ColumnProfilerRunBuilder":
        self._save_profiles_json_path = path
        return self

    def overwrite_output_files(self, value: bool) -> "ColumnProfilerRunBuilder":
        self._overwrite_output_files = value
        return self

    def run(self) -> ColumnProfiles:
        profiles = ColumnProfiler.profile(
            self._data,
            restrict_to_columns=self._restrict_to_columns,
            print_status_updates=self._print_status_updates,
            low_cardinality_histogram_threshold=self._low_cardinality_histogram_threshold,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_in_metrics_repository_using_key=self._save_key,
            engine=self._engine,
            mesh=self._mesh,
        )
        if self._save_profiles_json_path is not None:
            if os.path.exists(self._save_profiles_json_path) and not self._overwrite_output_files:
                raise FileExistsError(
                    f"File {self._save_profiles_json_path} already exists and "
                    "overwrite disabled"
                )
            with open(self._save_profiles_json_path, "w", encoding="utf-8") as f:
                f.write(profiles.to_json())
        return profiles

from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.memory import InMemoryMetricsRepository
from deequ_tpu.repository.fs import FileSystemMetricsRepository

__all__ = [
    "AnalysisResult",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "ResultKey",
    "InMemoryMetricsRepository",
    "FileSystemMetricsRepository",
]

from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.memory import InMemoryMetricsRepository
from deequ_tpu.repository.fs import FileSystemMetricsRepository
from deequ_tpu.repository.states import (
    FileSystemStateRepository,
    InMemoryStateRepository,
    StateCacheContext,
    StateRepository,
)

__all__ = [
    "AnalysisResult",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "ResultKey",
    "InMemoryMetricsRepository",
    "FileSystemMetricsRepository",
    "FileSystemStateRepository",
    "InMemoryStateRepository",
    "StateCacheContext",
    "StateRepository",
]

"""Forensics audit trail as first-class repository citizens.

The forensics report (observe/forensics.py: sampled violating rows +
metric provenance) persists through the ordinary `MetricsRepository`
path the same way engine telemetry does (repository/engine.py): an
`AuditRecord` pseudo-analyzer keys one report in the saved metric map,
so the audit trail rides the exact save/load/filter/serde machinery as
the data-quality metrics it explains — one store, one history.

The payload is a versioned binary envelope (NO pickle — this file is
covered by the tools/lint.py SERDE rule):

    DQFA | version u32 | payload_len u32 | payload json utf-8
      | sha256(previous bytes)

base64-wrapped when it crosses the JSON serde. Decode failures follow
the state-cache safety contract (repository/states.py): a corrupt,
truncated or version-bumped entry NEVER produces a wrong answer — it
degrades to "no forensics available", surfaced as a DQ317 lenient
warning.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import warnings
from typing import Any, Dict, Optional, Tuple

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, Entity
from deequ_tpu.repository.base import MetricsRepository, ResultKey

__all__ = [
    "AUDIT_FORMAT_VERSION",
    "AUDIT_MAGIC",
    "AuditDecodeError",
    "AuditRecord",
    "audit_entry_for",
    "decode_audit",
    "encode_audit",
    "load_audit_trail",
]

#: envelope magic — "DeeQu Forensics Audit"; bump AUDIT_FORMAT_VERSION
#: whenever the ForensicsReport dict shape changes incompatibly
AUDIT_MAGIC = b"DQFA"
AUDIT_FORMAT_VERSION = 1

_DIGEST = hashlib.sha256
_DIGEST_LEN = 32


class AuditDecodeError(ValueError):
    """An audit-trail entry that cannot be decoded (corrupt, truncated,
    or version-mismatched). Callers degrade to no-forensics — never a
    wrong answer."""


def _warn_fallback(reason: str) -> None:
    """The DQ317 lenient warning: one line, machine-greppable code."""
    warnings.warn(
        f"DQ317: forensics audit-trail entry is unusable ({reason}); "
        "the run's forensics are unavailable from this repository",
        RuntimeWarning,
        stacklevel=3,
    )


# -- versioned envelope -------------------------------------------------------


def encode_audit(payload: Dict[str, Any]) -> bytes:
    """Serialize one forensics-report dict into the versioned envelope.
    The JSON is canonicalized (sorted keys) so identical reports encode
    to identical bytes."""
    raw = json.dumps(payload, sort_keys=True, allow_nan=False).encode("utf-8")
    body = bytearray()
    body += AUDIT_MAGIC
    body += struct.pack(">I", AUDIT_FORMAT_VERSION)
    body += struct.pack(">I", len(raw))
    body += raw
    return bytes(body) + _DIGEST(bytes(body)).digest()


def decode_audit(blob: bytes) -> Dict[str, Any]:
    """Inverse of `encode_audit`, validated end to end: digest first
    (corruption), then magic/version (format drift), then payload
    bounds (truncation). Any failure raises `AuditDecodeError`."""
    header = len(AUDIT_MAGIC) + 8
    if len(blob) < header + _DIGEST_LEN:
        raise AuditDecodeError("truncated envelope")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if _DIGEST(body).digest() != digest:
        raise AuditDecodeError("integrity digest mismatch")
    if body[: len(AUDIT_MAGIC)] != AUDIT_MAGIC:
        raise AuditDecodeError("bad magic")
    version, length = struct.unpack_from(">II", body, len(AUDIT_MAGIC))
    if version != AUDIT_FORMAT_VERSION:
        raise AuditDecodeError(
            f"format version {version} (this build reads {AUDIT_FORMAT_VERSION})"
        )
    if header + length != len(body):
        raise AuditDecodeError("payload length mismatch")
    try:
        payload = json.loads(body[header:].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise AuditDecodeError(f"undecodable payload: {e}") from e
    if not isinstance(payload, dict):
        raise AuditDecodeError("payload is not an object")
    return payload


# -- the pseudo-analyzer keying one audit entry -------------------------------


class AuditRecord(Analyzer):
    """Pseudo-analyzer keying one forensics audit entry in a repository.

    Never runs against data — it exists so the audit trail rides the
    ordinary `AnalyzerContext`/`MetricsRepository` path. `payload` is
    the base64 of the binary envelope; the repr carries a payload
    digest so two different reports never collide under the base
    Analyzer's repr-keyed identity."""

    def __init__(self, payload: str, instance: str = "forensics"):
        self.payload = str(payload)
        self._instance = str(instance)

    @property
    def name(self) -> str:
        return "ForensicsAudit"

    @property
    def instance(self) -> str:
        return self._instance

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    def compute_state_from(self, table: Any) -> Any:
        raise NotImplementedError(
            "AuditRecord is an audit-trail key, not a data analyzer."
        )

    def to_metric(self) -> DoubleMetric:
        """A success-valued metric (the envelope byte length) so the
        entry survives FileSystemMetricsRepository.save's
        success-metrics filter."""
        try:
            size = len(base64.b64decode(self.payload, validate=True))
        except (ValueError, TypeError):
            size = len(self.payload)
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(float(size))
        )

    def __repr__(self) -> str:
        digest = hashlib.sha256(self.payload.encode("ascii", "replace"))
        return (
            f"AuditRecord(instance={self._instance!r}, "
            f"digest={digest.hexdigest()[:16]!r})"
        )


def audit_entry_for(report: Any) -> Tuple[AuditRecord, DoubleMetric]:
    """(pseudo-analyzer, metric) for one `ForensicsReport` — merge into
    the metric map the suite is about to save and the trail persists
    through whatever repository is attached."""
    blob = encode_audit(report.to_dict())
    record = AuditRecord(base64.b64encode(blob).decode("ascii"))
    return record, record.to_metric()


def load_audit_trail(
    repository: MetricsRepository, result_key: ResultKey
) -> Optional[Any]:
    """The forensics report persisted under `result_key`, or None when
    the key has no audit entry or the entry is unusable (DQ317 warning,
    degrade — never a wrong answer)."""
    from deequ_tpu.observe.forensics import ForensicsReport

    try:
        context = repository.load_by_key(result_key)
    except Exception as e:  # noqa: BLE001 - unreadable history degrades
        _warn_fallback(f"repository load failed: {e}")
        return None
    if context is None:
        return None
    for analyzer in context.metric_map:
        if getattr(analyzer, "name", None) != "ForensicsAudit":
            continue
        payload = getattr(analyzer, "payload", None)
        if not isinstance(payload, str):
            _warn_fallback("entry has no payload")
            return None
        try:
            blob = base64.b64decode(payload, validate=True)
        except (ValueError, TypeError) as e:
            _warn_fallback(f"undecodable base64: {e}")
            return None
        try:
            return ForensicsReport.from_dict(decode_audit(blob))
        except AuditDecodeError as e:
            _warn_fallback(str(e))
            return None
    return None

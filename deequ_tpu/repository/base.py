"""Metrics repository: keyed store of analysis results with history.

reference: repository/MetricsRepository.scala:25-51,
repository/AnalysisResult.scala:25-137,
repository/MetricsRepositoryMultipleResultsLoader.scala:26-139.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from deequ_tpu.runners.context import AnalyzerContext, sanitize_json_values

if TYPE_CHECKING:
    from deequ_tpu.analyzers.base import Analyzer


@dataclass(frozen=True)
class ResultKey:
    """reference: MetricsRepository.scala:51."""

    data_set_date: int
    tags: Dict[str, str] = field(default_factory=dict)

    def __hash__(self):
        return hash((self.data_set_date, tuple(sorted(self.tags.items()))))

    def __eq__(self, other):
        return (
            isinstance(other, ResultKey)
            and self.data_set_date == other.data_set_date
            and self.tags == other.tags
        )


@dataclass
class AnalysisResult:
    """(ResultKey, AnalyzerContext) (reference: AnalysisResult.scala:25)."""

    result_key: ResultKey
    analyzer_context: AnalyzerContext

    def get_success_metrics_as_rows(
        self, for_analyzers=None, with_tags: Optional[Sequence[str]] = None
    ) -> List[Dict[str, object]]:
        """Metric rows + dataset_date + (sanitized) tag columns
        (reference: AnalysisResult.scala:35-137)."""
        rows = self.analyzer_context.success_metrics_as_rows(for_analyzers)
        tags = self.result_key.tags
        if with_tags is not None:
            tags = {k: v for k, v in tags.items() if k in with_tags}
        out = []
        for row in rows:
            row = dict(row)
            row["dataset_date"] = self.result_key.data_set_date
            for key, value in tags.items():
                column = _sanitize_tag_column(key, row)
                row[column] = value
            out.append(row)
        return out

    def get_success_metrics_as_json(self, for_analyzers=None, with_tags=None) -> str:
        return json.dumps(
            sanitize_json_values(
                self.get_success_metrics_as_rows(for_analyzers, with_tags)
            )
        )


def _sanitize_tag_column(tag: str, existing_row: Dict[str, object]) -> str:
    """Sanitize tag names for column use; on collision with a column the
    row already has, suffix `_2`, `_3`, ... until free (a fixed `_2`
    suffix can itself collide — e.g. tags `a b` and `a.b` with a metric
    column `a_b_2` — and would silently overwrite a value).
    (reference: AnalysisResult.scala tag handling)."""
    sanitized = re.sub(r"[^A-Za-z0-9_]", "_", tag)
    if sanitized not in existing_row:
        return sanitized
    n = 2
    while f"{sanitized}_{n}" in existing_row:
        n += 1
    return f"{sanitized}_{n}"


class MetricsRepository:
    """reference: MetricsRepository.scala:25-35."""

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        raise NotImplementedError

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        raise NotImplementedError

    def load(self) -> "MetricsRepositoryMultipleResultsLoader":
        raise NotImplementedError


class MetricsRepositoryMultipleResultsLoader:
    """Query builder over the whole history
    (reference: MetricsRepositoryMultipleResultsLoader.scala:26-139)."""

    def __init__(self):
        self._tag_values: Optional[Dict[str, str]] = None
        self._analyzers: Optional[List["Analyzer"]] = None
        self._after: Optional[int] = None
        self._before: Optional[int] = None

    def with_tag_values(self, tag_values: Dict[str, str]):
        self._tag_values = dict(tag_values)
        return self

    def for_analyzers(self, analyzers: Sequence["Analyzer"]):
        self._analyzers = list(analyzers)
        return self

    def after(self, date_time: int):
        self._after = date_time
        return self

    def before(self, date_time: int):
        self._before = date_time
        return self

    def get(self) -> List[AnalysisResult]:
        raise NotImplementedError

    # -- shared filtering/union helpers --------------------------------------

    def _apply_filters(self, results: List[AnalysisResult]) -> List[AnalysisResult]:
        out = []
        for result in results:
            key = result.result_key
            if self._after is not None and key.data_set_date < self._after:
                continue
            if self._before is not None and key.data_set_date > self._before:
                continue
            if self._tag_values is not None and not all(
                key.tags.get(k) == v for k, v in self._tag_values.items()
            ):
                continue
            context = result.analyzer_context
            if self._analyzers is not None:
                context = AnalyzerContext(
                    {
                        a: m
                        for a, m in context.metric_map.items()
                        if a in self._analyzers
                    }
                )
            out.append(AnalysisResult(key, context))
        return out

    def get_success_metrics_as_rows(self, with_tags=None) -> List[Dict[str, object]]:
        rows: List[Dict[str, object]] = []
        for result in self.get():
            rows.extend(result.get_success_metrics_as_rows(with_tags=with_tags))
        return rows

    def get_success_metrics_as_json(self, with_tags=None) -> str:
        """Union with schema alignment: every row carries every column
        (reference: MetricsRepositoryMultipleResultsLoader.scala:100+)."""
        rows = self.get_success_metrics_as_rows(with_tags)
        all_columns = sorted({k for row in rows for k in row})
        aligned = [
            {col: row.get(col) for col in all_columns} for row in rows
        ]
        return json.dumps(sanitize_json_values(aligned))

    def get_success_metrics_as_table(self, with_tags=None):
        from deequ_tpu.data.table import Table

        rows = self.get_success_metrics_as_rows(with_tags)
        all_columns = sorted({k for row in rows for k in row})
        return Table.from_pydict(
            {col: [row.get(col) for row in rows] for col in all_columns}
        )

"""Engine telemetry as first-class repository citizens.

The paper's product loop persists data-quality metrics through a
`MetricsRepository` and watches the resulting time series with anomaly
detection.  This module applies the identical machinery to the engine's
own health: each flat record from `observe.telemetry.engine_metric_record`
becomes an `AnalyzerContext` keyed by `EngineMetric` pseudo-analyzers
and is saved under a `ResultKey` tagged `telemetry=engine` (plus suite,
dataset, host, placement) — so one store holds both kinds of series,
the same loaders filter both, and `tools/sentinel.py` runs the same
anomaly strategies over both.
"""

from __future__ import annotations

import socket
import time
from typing import TYPE_CHECKING, Any, Dict, List, Optional

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, Entity
from deequ_tpu.repository.base import MetricsRepository, ResultKey
from deequ_tpu.runners.context import AnalyzerContext

if TYPE_CHECKING:  # pragma: no cover
    from deequ_tpu.anomaly import DataPoint

__all__ = [
    "ENGINE_TELEMETRY_TAG",
    "ENGINE_TELEMETRY_VALUE",
    "EngineMetric",
    "engine_metric_names",
    "engine_result_key",
    "engine_series",
    "persist_engine_record",
    "record_run",
    "record_window_run",
]

ENGINE_TELEMETRY_TAG = "telemetry"
ENGINE_TELEMETRY_VALUE = "engine"


class EngineMetric(Analyzer):
    """Pseudo-analyzer keying one engine health metric in a repository.

    Never runs against data — it exists so engine series ride the
    ordinary `AnalyzerContext`/`MetricsRepository` path (save, load,
    filter, serde) with analyzer identity `(metric, instance)`.
    """

    def __init__(self, metric: str, instance: str = "engine"):
        self.metric = str(metric)
        self._instance = str(instance)

    @property
    def name(self) -> str:
        return self.metric

    @property
    def instance(self) -> str:
        return self._instance

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    def compute_state_from(self, table: Any) -> Any:
        raise NotImplementedError(
            "EngineMetric is a telemetry key, not a data analyzer."
        )

    def to_metric(self, value: float) -> DoubleMetric:
        return DoubleMetric(
            self.entity, self.name, self.instance, Success(float(value))
        )

    def __repr__(self) -> str:
        return f"EngineMetric(metric={self.metric!r}, instance={self._instance!r})"


def _placement_tag() -> str:
    try:
        from deequ_tpu.ops import runtime

        return str(runtime.placement_mode())
    except Exception:
        return "unknown"


def engine_result_key(
    data_set_date: Optional[int] = None,
    *,
    suite: str,
    dataset: str,
    tags: Optional[Dict[str, str]] = None,
) -> ResultKey:
    """ResultKey for one engine telemetry point.

    `data_set_date` defaults to now (epoch milliseconds, the repository
    convention); standard tags are telemetry=engine, suite, dataset,
    host, placement — extra `tags` may add to or override them.
    """
    if data_set_date is None:
        data_set_date = int(time.time() * 1000)
    try:
        host = socket.gethostname() or "unknown"
    except OSError:
        host = "unknown"
    all_tags = {
        ENGINE_TELEMETRY_TAG: ENGINE_TELEMETRY_VALUE,
        "suite": str(suite),
        "dataset": str(dataset),
        "host": host,
        "placement": _placement_tag(),
    }
    if tags:
        all_tags.update({str(k): str(v) for k, v in tags.items()})
    return ResultKey(data_set_date, all_tags)


def persist_engine_record(
    repository: MetricsRepository,
    record: Dict[str, float],
    key: ResultKey,
    *,
    instance: str = "engine",
) -> AnalyzerContext:
    """Save one flat engine metric record under `key`; returns the context."""
    metric_map: Dict[Analyzer, DoubleMetric] = {}
    for name, value in record.items():
        try:
            fval = float(value)
        except (TypeError, ValueError):
            continue
        analyzer = EngineMetric(name, instance)
        metric_map[analyzer] = analyzer.to_metric(fval)
    context = AnalyzerContext(metric_map)
    repository.save(key, context)
    return context


def record_run(
    repository: MetricsRepository,
    trace: Any,
    plan_cost: Any = None,
    *,
    suite: str,
    dataset: str,
    data_set_date: Optional[int] = None,
    tags: Optional[Dict[str, str]] = None,
    instance: str = "engine",
    extra: Optional[Dict[str, float]] = None,
) -> ResultKey:
    """Derive the engine record from a RunTrace (+ optional PlanCost)
    and persist it as one time-series point; returns the key used."""
    from deequ_tpu.observe import telemetry

    record = telemetry.engine_metric_record(trace, plan_cost, extra=extra)
    key = engine_result_key(
        data_set_date, suite=suite, dataset=dataset, tags=tags
    )
    persist_engine_record(repository, record, key, instance=instance)
    return key


def record_window_run(
    repository: MetricsRepository,
    trace: Any,
    drift_result: Any = None,
    plan_cost: Any = None,
    *,
    suite: str,
    dataset: str,
    data_set_date: Optional[int] = None,
    tags: Optional[Dict[str, str]] = None,
    instance: str = "engine",
) -> ResultKey:
    """`record_run` for a window query + optional drift evaluation: the
    trace contributes the `engine.window.*` counters (and the derived
    `engine.window.segment_hit_ratio`), and a `DriftCheckResult` adds
    `engine.drift.value_max` (the worst drift measure observed) and
    `engine.drift.failed_constraints` — the two series the sentinel
    watches for a drifting dataset."""
    extra: Dict[str, float] = {}
    if drift_result is not None:
        values = [
            float(r.value)
            for r in drift_result.constraint_results
            if r.value is not None and r.value == r.value
        ]
        finite = [v for v in values if v != float("inf")]
        if finite:
            extra["engine.drift.value_max"] = max(finite)
        extra["engine.drift.failed_constraints"] = float(
            sum(
                1
                for r in drift_result.constraint_results
                if getattr(r.status, "name", "") != "SUCCESS"
            )
        )
    return record_run(
        repository,
        trace,
        plan_cost,
        suite=suite,
        dataset=dataset,
        data_set_date=data_set_date,
        tags=tags,
        instance=instance,
        extra=extra or None,
    )


def _engine_results(
    repository: MetricsRepository, tags: Optional[Dict[str, str]]
) -> List[Any]:
    loader = repository.load().with_tag_values(
        {ENGINE_TELEMETRY_TAG: ENGINE_TELEMETRY_VALUE, **(tags or {})}
    )
    return list(loader.get())


def engine_series(
    repository: MetricsRepository,
    metric: str,
    *,
    instance: str = "engine",
    tags: Optional[Dict[str, str]] = None,
) -> List["DataPoint"]:
    """Load one engine metric's time series (sorted by data_set_date),
    ready for `AnomalyDetector.detect_anomalies_in_history`."""
    from deequ_tpu.anomaly import DataPoint  # lazy: pulls in jax via HoltWinters

    analyzer = EngineMetric(metric, instance)
    points: List[DataPoint] = []
    for result in _engine_results(repository, tags):
        found = result.analyzer_context.metric_map.get(analyzer)
        if found is not None and found.value.is_success:
            points.append(
                DataPoint(result.result_key.data_set_date, float(found.value.get()))
            )
    points.sort(key=lambda p: p.time)
    return points


def engine_metric_names(
    repository: MetricsRepository,
    *,
    tags: Optional[Dict[str, str]] = None,
) -> List[str]:
    """All engine metric names present in the repository (sorted)."""
    names = set()
    for result in _engine_results(repository, tags):
        for analyzer in result.analyzer_context.metric_map:
            if isinstance(analyzer, EngineMetric):
                names.add(analyzer.metric)
    return sorted(names)

"""Filesystem metrics repository: whole history in a single JSON file with
atomic tmp+rename writes.

reference: repository/fs/FileSystemMetricsRepository.scala:32-226.
"""

from __future__ import annotations

from typing import List, Optional

from deequ_tpu.core.fsio import FileSystem, resolve_filesystem

from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.serde import (
    deserialize_analysis_results,
    serialize_analysis_results,
)
from deequ_tpu.runners.context import AnalyzerContext


class FileSystemMetricsRepository(MetricsRepository):
    """`filesystem` selects the storage backend (core/fsio.py): local
    disk by default, MemoryFileSystem for object-store-style semantics,
    FsspecFileSystem for real object stores — the role of the
    reference's Hadoop FileSystem qualification (DfsUtils.scala:24-84)."""

    def __init__(self, path: str, filesystem: FileSystem = None):
        self.path = path
        self.filesystem = resolve_filesystem(filesystem)

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        successful = AnalyzerContext(
            {
                analyzer: metric
                for analyzer, metric in analyzer_context.metric_map.items()
                if metric.value.is_success
            }
        )
        history = self._load_all()
        history = [r for r in history if r.result_key != result_key]
        history.append(AnalysisResult(result_key, successful))
        self._write_atomically(serialize_analysis_results(history))

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        for result in self._load_all():
            if result.result_key == result_key:
                return result.analyzer_context
        return None

    def load(self) -> "FileSystemMetricsRepositoryMultipleResultsLoader":
        return FileSystemMetricsRepositoryMultipleResultsLoader(self)

    # -- internals -----------------------------------------------------------

    def _load_all(self) -> List[AnalysisResult]:
        if not self.filesystem.exists(self.path):
            return []
        payload = self.filesystem.read_bytes(self.path).decode("utf-8")
        if not payload.strip():
            return []
        return deserialize_analysis_results(payload)

    def _write_atomically(self, payload: str) -> None:
        """Atomic publish through the fs seam (local: tmp + rename —
        reference: FileSystemMetricsRepository.scala:167-195)."""
        self.filesystem.write_bytes(self.path, payload.encode("utf-8"))


class FileSystemMetricsRepositoryMultipleResultsLoader(
    MetricsRepositoryMultipleResultsLoader
):
    def __init__(self, repository: FileSystemMetricsRepository):
        super().__init__()
        self._repository = repository

    def get(self) -> List[AnalysisResult]:
        return self._apply_filters(self._repository._load_all())

"""Filesystem metrics repository: whole history in a single JSON file with
atomic tmp+rename writes.

reference: repository/fs/FileSystemMetricsRepository.scala:32-226.
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.serde import (
    deserialize_analysis_results,
    serialize_analysis_results,
)
from deequ_tpu.runners.context import AnalyzerContext


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        self.path = path

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        successful = AnalyzerContext(
            {
                analyzer: metric
                for analyzer, metric in analyzer_context.metric_map.items()
                if metric.value.is_success
            }
        )
        history = self._load_all()
        history = [r for r in history if r.result_key != result_key]
        history.append(AnalysisResult(result_key, successful))
        self._write_atomically(serialize_analysis_results(history))

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        for result in self._load_all():
            if result.result_key == result_key:
                return result.analyzer_context
        return None

    def load(self) -> "FileSystemMetricsRepositoryMultipleResultsLoader":
        return FileSystemMetricsRepositoryMultipleResultsLoader(self)

    # -- internals -----------------------------------------------------------

    def _load_all(self) -> List[AnalysisResult]:
        if not os.path.exists(self.path):
            return []
        with open(self.path, "r", encoding="utf-8") as f:
            payload = f.read()
        if not payload.strip():
            return []
        return deserialize_analysis_results(payload)

    def _write_atomically(self, payload: str) -> None:
        """tmp file + rename (reference: FileSystemMetricsRepository.scala:167-195)."""
        directory = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(directory, exist_ok=True)
        fd, tmp_path = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(payload)
            os.replace(tmp_path, self.path)
        except BaseException:
            if os.path.exists(tmp_path):
                os.unlink(tmp_path)
            raise


class FileSystemMetricsRepositoryMultipleResultsLoader(
    MetricsRepositoryMultipleResultsLoader
):
    def __init__(self, repository: FileSystemMetricsRepository):
        super().__init__()
        self._repository = repository

    def get(self) -> List[AnalysisResult]:
        return self._apply_filters(self._repository._load_all())

"""In-memory metrics repository.

reference: repository/memory/InMemoryMetricsRepository.scala:28-136 —
failed metrics are filtered on save (:34-40).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.runners.context import AnalyzerContext


class InMemoryMetricsRepository(MetricsRepository):
    def __init__(self) -> None:
        self._results: Dict[ResultKey, AnalysisResult] = {}
        self._lock = threading.Lock()

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        successful = AnalyzerContext(
            {
                analyzer: metric
                for analyzer, metric in analyzer_context.metric_map.items()
                if metric.value.is_success
            }
        )
        with self._lock:
            self._results[result_key] = AnalysisResult(result_key, successful)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        with self._lock:
            result = self._results.get(result_key)
        return result.analyzer_context if result is not None else None

    def load(self) -> "InMemoryMetricsRepositoryMultipleResultsLoader":
        return InMemoryMetricsRepositoryMultipleResultsLoader(self)

    def _all_results(self) -> List[AnalysisResult]:
        with self._lock:
            return list(self._results.values())


class InMemoryMetricsRepositoryMultipleResultsLoader(
    MetricsRepositoryMultipleResultsLoader
):
    def __init__(self, repository: InMemoryMetricsRepository):
        super().__init__()
        self._repository = repository

    def get(self) -> List[AnalysisResult]:
        return self._apply_filters(self._repository._all_results())

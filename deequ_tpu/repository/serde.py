"""JSON (de)serialization of analysis results — format-compatible with the
reference's Gson serializers.

reference: repository/AnalysisResultSerde.scala:38-614. Field names, the
per-analyzer dispatch on `analyzerName`, metric serialization by
`metricName`, and the refusal to serialize failed metrics / binning-udf
histograms all mirror the reference so JSON written by either
implementation loads in the other.

Documented deviation: a non-finite DoubleMetric value (NaN/Inf) is stored
as JSON null here so the history file stays RFC-8259 parseable, whereas
the reference's Gson would throw when *writing* such a value and throws on
JsonNull when *reading* — i.e. histories containing non-finite metrics are
writable only by this implementation and loadable only by it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    UniqueValueRatio,
    Uniqueness,
)
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import (
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    HistogramMetric,
    KeyedDoubleMetric,
    Metric,
)
from deequ_tpu.repository.base import AnalysisResult, ResultKey
from deequ_tpu.runners.context import AnalyzerContext

ANALYZER_FIELD = "analyzer"
ANALYZER_NAME_FIELD = "analyzerName"
WHERE_FIELD = "where"
COLUMN_FIELD = "column"
COLUMNS_FIELD = "columns"
METRIC_MAP_FIELD = "metricMap"
METRIC_FIELD = "metric"
DATASET_DATE_FIELD = "dataSetDate"
TAGS_FIELD = "tags"
RESULT_KEY_FIELD = "resultKey"
ANALYZER_CONTEXT_FIELD = "analyzerContext"


# ---------------------------------------------------------------------------
# Analyzer <-> json (reference: AnalysisResultSerde.scala:220-480)
# ---------------------------------------------------------------------------


def serialize_analyzer(analyzer: Analyzer) -> Dict[str, Any]:
    if isinstance(analyzer, Size):
        return {ANALYZER_NAME_FIELD: "Size", WHERE_FIELD: analyzer.where}
    if isinstance(analyzer, Completeness):
        return {
            ANALYZER_NAME_FIELD: "Completeness",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, Compliance):
        return {
            ANALYZER_NAME_FIELD: "Compliance",
            WHERE_FIELD: analyzer.where,
            "instance": analyzer.instance_name,
            "predicate": analyzer.predicate,
        }
    if isinstance(analyzer, PatternMatch):
        return {
            ANALYZER_NAME_FIELD: "PatternMatch",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
            "pattern": analyzer.pattern,
        }
    if isinstance(analyzer, Sum):
        return {
            ANALYZER_NAME_FIELD: "Sum",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, Mean):
        return {
            ANALYZER_NAME_FIELD: "Mean",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, Minimum):
        return {
            ANALYZER_NAME_FIELD: "Minimum",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, Maximum):
        return {
            ANALYZER_NAME_FIELD: "Maximum",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, CountDistinct):
        return {ANALYZER_NAME_FIELD: "CountDistinct", COLUMNS_FIELD: list(analyzer.columns)}
    if isinstance(analyzer, Distinctness):
        return {ANALYZER_NAME_FIELD: "Distinctness", COLUMNS_FIELD: list(analyzer.columns)}
    if isinstance(analyzer, Entropy):
        return {ANALYZER_NAME_FIELD: "Entropy", COLUMN_FIELD: analyzer.columns[0]}
    if isinstance(analyzer, MutualInformation):
        return {
            ANALYZER_NAME_FIELD: "MutualInformation",
            COLUMNS_FIELD: list(analyzer.columns),
        }
    if isinstance(analyzer, UniqueValueRatio):
        return {
            ANALYZER_NAME_FIELD: "UniqueValueRatio",
            COLUMNS_FIELD: list(analyzer.columns),
        }
    if isinstance(analyzer, Uniqueness):
        return {ANALYZER_NAME_FIELD: "Uniqueness", COLUMNS_FIELD: list(analyzer.columns)}
    if isinstance(analyzer, Histogram):
        if analyzer.binning_udf is not None:
            # reference: AnalysisResultSerde.scala:300-306
            raise ValueError(f"Unable to serialize analyzer {analyzer!r}.")
        return {
            ANALYZER_NAME_FIELD: "Histogram",
            COLUMN_FIELD: analyzer.column,
            "maxDetailBins": analyzer.max_detail_bins,
        }
    if isinstance(analyzer, DataType):
        return {
            ANALYZER_NAME_FIELD: "DataType",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, ApproxCountDistinct):
        return {
            ANALYZER_NAME_FIELD: "ApproxCountDistinct",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, Correlation):
        return {
            ANALYZER_NAME_FIELD: "Correlation",
            "firstColumn": analyzer.first_column,
            "secondColumn": analyzer.second_column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, StandardDeviation):
        return {
            ANALYZER_NAME_FIELD: "StandardDeviation",
            COLUMN_FIELD: analyzer.column,
            WHERE_FIELD: analyzer.where,
        }
    if isinstance(analyzer, ApproxQuantile):
        data = {
            ANALYZER_NAME_FIELD: "ApproxQuantile",
            COLUMN_FIELD: analyzer.column,
            "quantile": analyzer.quantile,
            "relativeError": analyzer.relative_error,
        }
        if analyzer.where is not None:  # our extension field
            data[WHERE_FIELD] = analyzer.where
        return data
    if isinstance(analyzer, ApproxQuantiles):
        return {
            ANALYZER_NAME_FIELD: "ApproxQuantiles",
            COLUMN_FIELD: analyzer.column,
            "quantiles": ",".join(str(q) for q in analyzer.quantiles),
            "relativeError": analyzer.relative_error,
        }
    from deequ_tpu.repository.engine import EngineMetric

    if isinstance(analyzer, EngineMetric):
        return {
            ANALYZER_NAME_FIELD: "EngineMetric",
            "metric": analyzer.metric,
            "instance": analyzer.instance,
        }
    from deequ_tpu.repository.audit import AuditRecord

    if isinstance(analyzer, AuditRecord):
        return {
            ANALYZER_NAME_FIELD: "ForensicsAudit",
            "payload": analyzer.payload,
            "instance": analyzer.instance,
        }
    raise ValueError(f"Unable to serialize analyzer {analyzer!r}.")


def deserialize_analyzer(data: Dict[str, Any]) -> Analyzer:
    name = data[ANALYZER_NAME_FIELD]
    where = data.get(WHERE_FIELD)

    if name == "Size":
        return Size(where)
    if name == "Completeness":
        return Completeness(data[COLUMN_FIELD], where)
    if name == "Compliance":
        return Compliance(data["instance"], data["predicate"], where)
    if name == "PatternMatch":
        return PatternMatch(data[COLUMN_FIELD], data["pattern"], where)
    if name == "Sum":
        return Sum(data[COLUMN_FIELD], where)
    if name == "Mean":
        return Mean(data[COLUMN_FIELD], where)
    if name == "Minimum":
        return Minimum(data[COLUMN_FIELD], where)
    if name == "Maximum":
        return Maximum(data[COLUMN_FIELD], where)
    if name == "CountDistinct":
        return CountDistinct(data[COLUMNS_FIELD])
    if name == "Distinctness":
        return Distinctness(data[COLUMNS_FIELD])
    if name == "Entropy":
        return Entropy(data[COLUMN_FIELD])
    if name == "MutualInformation":
        return MutualInformation(data[COLUMNS_FIELD])
    if name == "UniqueValueRatio":
        return UniqueValueRatio(data[COLUMNS_FIELD])
    if name == "Uniqueness":
        return Uniqueness(data[COLUMNS_FIELD])
    if name == "Histogram":
        return Histogram(data[COLUMN_FIELD], None, data["maxDetailBins"])
    if name == "DataType":
        return DataType(data[COLUMN_FIELD], where)
    if name == "ApproxCountDistinct":
        return ApproxCountDistinct(data[COLUMN_FIELD], where)
    if name == "Correlation":
        return Correlation(data["firstColumn"], data["secondColumn"], where)
    if name == "StandardDeviation":
        return StandardDeviation(data[COLUMN_FIELD], where)
    if name == "ApproxQuantile":
        return ApproxQuantile(
            data[COLUMN_FIELD], data["quantile"], data["relativeError"], where
        )
    if name == "ApproxQuantiles":
        quantiles = [float(q) for q in data["quantiles"].split(",")]
        return ApproxQuantiles(data[COLUMN_FIELD], quantiles, data["relativeError"])
    if name == "EngineMetric":
        from deequ_tpu.repository.engine import EngineMetric

        return EngineMetric(data["metric"], data.get("instance", "engine"))
    if name == "ForensicsAudit":
        from deequ_tpu.repository.audit import AuditRecord

        return AuditRecord(
            data.get("payload", ""), data.get("instance", "forensics")
        )
    raise ValueError(f"Unable to deserialize analyzer {name}.")


# ---------------------------------------------------------------------------
# Metric <-> json (reference: AnalysisResultSerde.scala:477-570)
# ---------------------------------------------------------------------------


def serialize_metric(metric: Metric) -> Dict[str, Any]:
    import math

    if metric.value.is_failure:
        raise ValueError("Unable to serialize failed metrics.")
    if isinstance(metric, DoubleMetric):
        value = metric.value.get()
        # NaN/Inf are not RFC-8259 JSON (Gson would refuse them outright);
        # store null so the history file stays parseable everywhere
        if isinstance(value, float) and not math.isfinite(value):
            value = None
        return {
            "metricName": "DoubleMetric",
            "entity": metric.entity.value,
            "instance": metric.instance,
            "name": metric.name,
            "value": value,
        }
    if isinstance(metric, HistogramMetric):
        dist = metric.value.get()
        return {
            "metricName": "HistogramMetric",
            COLUMN_FIELD: metric.instance,
            "numberOfBins": dist.number_of_bins,
            "value": serialize_distribution(dist),
        }
    if isinstance(metric, KeyedDoubleMetric):
        return {
            "metricName": "KeyedDoubleMetric",
            "entity": metric.entity.value,
            "instance": metric.instance,
            "name": metric.name,
            "value": dict(metric.value.get()),
        }
    raise ValueError(f"Unable to serialize metrics {metric!r}.")


def deserialize_metric(data: Dict[str, Any]) -> Metric:
    name = data["metricName"]
    if name == "DoubleMetric":
        value = data["value"]
        return DoubleMetric(
            Entity(data["entity"]),
            data["name"],
            data["instance"],
            Success(float("nan") if value is None else value),
        )
    if name == "HistogramMetric":
        return HistogramMetric(
            Entity.COLUMN,
            "Histogram",
            data[COLUMN_FIELD],
            Success(deserialize_distribution(data["value"])),
        )
    if name == "KeyedDoubleMetric":
        return KeyedDoubleMetric(
            Entity(data["entity"]),
            data["name"],
            data["instance"],
            Success({k: float(v) for k, v in data["value"].items()}),
        )
    raise ValueError(f"Unable to deserialize metric {name}.")


def serialize_distribution(dist: Distribution) -> Dict[str, Any]:
    return {
        "numberOfBins": dist.number_of_bins,
        "values": {
            key: {"absolute": dv.absolute, "ratio": dv.ratio}
            for key, dv in dist.values.items()
        },
    }


def deserialize_distribution(data: Dict[str, Any]) -> Distribution:
    return Distribution(
        {
            key: DistributionValue(entry["absolute"], entry["ratio"])
            for key, entry in data["values"].items()
        },
        data["numberOfBins"],
    )


# ---------------------------------------------------------------------------
# AnalysisResult list <-> json (entry points,
# reference: AnalysisResultSerde.scala:75-106)
# ---------------------------------------------------------------------------


def serialize_result_key(key: ResultKey) -> Dict[str, Any]:
    return {DATASET_DATE_FIELD: key.data_set_date, TAGS_FIELD: dict(key.tags)}


def deserialize_result_key(data: Dict[str, Any]) -> ResultKey:
    return ResultKey(data[DATASET_DATE_FIELD], dict(data.get(TAGS_FIELD) or {}))


def serialize_analysis_results(results: List[AnalysisResult]) -> str:
    out = []
    for result in results:
        metric_map = []
        for analyzer, metric in result.analyzer_context.metric_map.items():
            try:
                entry = {
                    ANALYZER_FIELD: serialize_analyzer(analyzer),
                    METRIC_FIELD: serialize_metric(metric),
                }
            except ValueError:
                continue  # unserializable analyzer/failed metric skipped
            metric_map.append(entry)
        out.append(
            {
                RESULT_KEY_FIELD: serialize_result_key(result.result_key),
                ANALYZER_CONTEXT_FIELD: {METRIC_MAP_FIELD: metric_map},
            }
        )
    return json.dumps(out, indent=2)


def deserialize_analysis_results(payload: str) -> List[AnalysisResult]:
    results = []
    for entry in json.loads(payload):
        key = deserialize_result_key(entry[RESULT_KEY_FIELD])
        metric_map = {}
        for item in entry[ANALYZER_CONTEXT_FIELD][METRIC_MAP_FIELD]:
            analyzer = deserialize_analyzer(item[ANALYZER_FIELD])
            metric = deserialize_metric(item[METRIC_FIELD])
            metric_map[analyzer] = metric
        results.append(AnalysisResult(key, AnalyzerContext(metric_map)))
    return results


# SimpleResultSerde (reference: AnalysisResultSerde.scala:56-73)


def simple_serialize(success_data: List[Dict[str, Any]]) -> str:
    return json.dumps(success_data)


def simple_deserialize(payload: str) -> List[Dict[str, Any]]:
    return json.loads(payload)

"""Persistent partition-state cache: incremental scans as a pure merge.

The reference's core algebra — every analyzer folds its data into a
mergeable sufficient statistic (`State.sum`, a commutative semigroup;
reference: analyzers/Analyzer.scala:48-76) — exists precisely so that
metrics become *incrementally* computable: fold each shard once, merge
forever after. This module is that promise made persistent. After a
partitioned scan, every partition's folded states are serialized to one
compact versioned envelope and stored keyed by

    (dataset, plan signature, partition fingerprint)

where the fingerprint hashes the parquet file's name, size and
row-group metadata (`data/source.py:partition_fingerprint`) so any
modified partition self-invalidates, and the plan signature
(`plan_signature`) hashes everything that changes the fold arithmetic —
analyzer set and order, placement, compute dtype, batch sizing, serde
version — so a cached state is only ever reused by a plan that would
have produced the identical bytes. On the next run the fused pass
(`ops/fused.py:FusedScanPass._run_partitioned`) scans only partitions
without a usable entry and merges everything through the existing
`State.merge` surface in deterministic partition order — bit-identical
to a full rescan, at a cost proportional to NEW data only.

Safety contract:

* writes are atomic (fsio tmp + rename) and serialized per dataset by
  an advisory lock file, so concurrent suite runs never interleave
  partial state files;
* a corrupt, truncated or version-bumped entry NEVER produces a wrong
  answer: the envelope carries a trailing sha256 digest and every
  decode failure degrades to a rescan of that partition, surfaced as a
  DQ314 lenient warning;
* `pickle` is banned from this path (tools/lint.py SERDE rule) — the
  payloads are the exact-width binary formats of
  `analyzers/state_provider.py`, which round-trip bit-exactly.

`merge_range(...)` answers "metrics over these partitions" as a pure
state merge with zero scan (the persistent analogue of
`AnalysisRunner.run_on_aggregated_states`).
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import warnings
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from deequ_tpu.core.fsio import FileSystem, LocalFileSystem, resolve_filesystem
from deequ_tpu.testing import faults

#: envelope magic — "DeeQu STate"; bump STATE_FORMAT_VERSION whenever
#: any per-family payload format in analyzers/state_provider.py changes
STATE_MAGIC = b"DQST"
STATE_FORMAT_VERSION = 1

_DIGEST = hashlib.sha256
_DIGEST_LEN = 32


class StateDecodeError(ValueError):
    """A state-cache entry that cannot be decoded (corrupt, truncated,
    version-mismatched, or missing an analyzer). Callers treat it as a
    cache miss — rescan, never a wrong answer."""


def _warn_fallback(dataset: str, fingerprint: str, reason: str) -> None:
    """The DQ314 lenient warning: one line, machine-greppable code."""
    warnings.warn(
        f"DQ314: state-cache entry for dataset {dataset!r} partition "
        f"{fingerprint[:12]}… is unusable ({reason}); the partition "
        "falls back to a rescan",
        RuntimeWarning,
        stacklevel=3,
    )


# -- plan signature -----------------------------------------------------------


def plan_signature(
    analyzers: Sequence[Any],
    *,
    placement: str,
    compute_dtype: str,
    batch_size: Optional[int],
    batch_rows: Optional[int],
    variant: str = "",
) -> str:
    """Hash of every knob that changes the fold arithmetic of a fused
    pass: the analyzer reprs IN PASS ORDER, the placement mode, the
    compute dtype, the explicit batch size (None = engine default), the
    source's per-batch row cap, and the serde version. Deliberately
    EXCLUDED: pipeline/pushdown/decode/wire knobs — the differential
    suites prove those bit-identical, so toggling them must not evict
    the cache. `variant` names a fold-arithmetic variant that is NOT
    bit-identical to the default (today: "pallas-folds", the on-TPU
    blocked Pallas moments fold) — the empty default leaves signatures
    unchanged."""
    h = _DIGEST()
    h.update(STATE_MAGIC)
    h.update(struct.pack(">I", STATE_FORMAT_VERSION))
    h.update(str(placement).encode("utf-8") + b"\x00")
    h.update(str(compute_dtype).encode("utf-8") + b"\x00")
    h.update(str(batch_size).encode("utf-8") + b"\x00")
    h.update(str(batch_rows).encode("utf-8") + b"\x00")
    if variant:
        h.update(b"variant:" + variant.encode("utf-8") + b"\x00")
    for a in analyzers:
        h.update(repr(a).encode("utf-8") + b"\x00")
    return h.hexdigest()[:32]


def plan_signature_for(
    analyzers: Sequence[Any],
    source: Any = None,
    batch_size: Optional[int] = None,
) -> str:
    """`plan_signature` with placement/dtype read from the live runtime
    knobs — the exact signature `FusedScanPass._run_partitioned` will
    compute for these analyzers over `source`."""
    import numpy as np

    from deequ_tpu.ops import runtime

    batch_rows = getattr(source, "batch_rows", None) if source is not None else None
    return plan_signature(
        analyzers,
        placement=runtime.placement_mode(),
        compute_dtype=np.dtype(runtime.compute_dtype()).name,
        batch_size=batch_size,
        batch_rows=int(batch_rows) if batch_rows else None,
        variant=runtime.fold_signature_variant(),
    )


# -- versioned envelope -------------------------------------------------------


def encode_states(pairs: Sequence[Tuple[Any, Any]]) -> bytes:
    """Serialize `(analyzer, state)` pairs into one versioned envelope:

        DQST | version u32 | count u32 |
          ( repr_len u32 | repr utf8 | flag u8 | payload_len u32 | payload )*
        | sha256(previous bytes)

    Per-analyzer payloads are the exact-width binary formats of
    `analyzers/state_provider.py:serialize_state` (bit-exact round
    trips); flag 0 marks a None state (analyzer produced no state on
    this partition — merges as the identity). Raises ValueError when
    any analyzer has no serde — the partition is then not cacheable."""
    from deequ_tpu.analyzers.state_provider import serialize_state

    body = bytearray()
    body += STATE_MAGIC
    body += struct.pack(">I", STATE_FORMAT_VERSION)
    body += struct.pack(">I", len(pairs))
    for analyzer, state in pairs:
        payload = b"" if state is None else serialize_state(analyzer, state)
        rep = repr(analyzer).encode("utf-8")
        body += struct.pack(">I", len(rep)) + rep
        body += struct.pack(">B", 0 if state is None else 1)
        body += struct.pack(">I", len(payload)) + payload
    return bytes(body) + _DIGEST(bytes(body)).digest()


def decode_states(blob: bytes, analyzers: Sequence[Any]) -> List[Any]:
    """Inverse of `encode_states`, validated end to end: digest first
    (corruption), then magic/version (format drift), then per-entry
    bounds (truncation), then per-analyzer presence. Any failure raises
    `StateDecodeError` — the caller rescans that partition. Returns one
    state (or None) per requested analyzer, in request order."""
    from deequ_tpu.analyzers.state_provider import deserialize_state

    if len(blob) < len(STATE_MAGIC) + 8 + _DIGEST_LEN:
        raise StateDecodeError("truncated envelope")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if _DIGEST(body).digest() != digest:
        raise StateDecodeError("integrity digest mismatch")
    if body[: len(STATE_MAGIC)] != STATE_MAGIC:
        raise StateDecodeError("bad magic")
    off = len(STATE_MAGIC)
    version, count = struct.unpack_from(">II", body, off)
    off += 8
    if version != STATE_FORMAT_VERSION:
        raise StateDecodeError(
            f"state format version {version} != {STATE_FORMAT_VERSION}"
        )
    entries: Dict[str, Tuple[int, bytes]] = {}
    try:
        for _ in range(count):
            (rep_len,) = struct.unpack_from(">I", body, off)
            off += 4
            rep = body[off : off + rep_len].decode("utf-8")
            if len(rep.encode("utf-8")) != rep_len:
                raise StateDecodeError("truncated entry name")
            off += rep_len
            (flag,) = struct.unpack_from(">B", body, off)
            off += 1
            (payload_len,) = struct.unpack_from(">I", body, off)
            off += 4
            payload = body[off : off + payload_len]
            if len(payload) != payload_len:
                raise StateDecodeError("truncated entry payload")
            off += payload_len
            entries[rep] = (flag, payload)
    except struct.error as e:
        raise StateDecodeError(f"truncated envelope: {e}") from e
    if off != len(body):
        raise StateDecodeError("trailing bytes after last entry")
    out: List[Any] = []
    for analyzer in analyzers:
        entry = entries.get(repr(analyzer))
        if entry is None:
            raise StateDecodeError(f"no state for analyzer {analyzer!r}")
        flag, payload = entry
        if flag == 0:
            out.append(None)
            continue
        try:
            out.append(deserialize_state(analyzer, payload))
        except Exception as e:  # noqa: BLE001 — any payload defect = unusable
            raise StateDecodeError(
                f"payload for {analyzer!r} does not decode: {e}"
            ) from e
    return out


# -- shard envelope (sharded streaming scan, parallel/multihost.py) ----------

#: magic for a SHARD's gathered contribution: a bag of per-partition
#: DQST envelopes plus the shard's cancel status. Versioned separately
#: from DQST — the inner blobs carry their own version and digest.
SHARD_MAGIC = b"DQSH"
SHARD_FORMAT_VERSION = 1


@dataclass
class ShardEnvelope:
    """One shard's decoded contribution to the cross-process all-merge:
    which shard, under which plan signature, whether it was cancelled
    (and why), and its `(partition fingerprint, DQST envelope)` entries
    in that shard's partition order."""

    shard: int
    signature: str
    cancelled: bool
    reason: str
    entries: List[Tuple[str, bytes]]


def encode_shard_states(
    shard: int,
    signature: str,
    entries: Sequence[Tuple[str, bytes]],
    *,
    cancelled: bool = False,
    reason: str = "",
) -> bytes:
    """Serialize one shard's per-partition state envelopes for the
    cross-process allgather:

        DQSH | version u32 | shard u32 | flags u8 (bit0 = cancelled) |
          reason_len u32 | reason utf8 | sig_len u32 | signature utf8 |
          count u32 | ( fp_len u32 | fingerprint utf8 |
                        blob_len u32 | DQST blob )*
        | sha256(previous bytes)

    Each entry's blob is a complete self-validated `encode_states`
    envelope — byte-identical to what the shard committed to the
    StateRepository, so the receiving merge decodes partitions exactly
    as a solo resume would load them. The cancelled flag is how a
    cancel crosses the collective WITHOUT deadlocking it: a cancelled
    shard still gathers (an envelope with whatever it committed), and
    every shard raises uniformly after the exchange."""
    body = bytearray()
    body += SHARD_MAGIC
    body += struct.pack(">I", SHARD_FORMAT_VERSION)
    body += struct.pack(">I", int(shard))
    body += struct.pack(">B", 1 if cancelled else 0)
    reason_b = reason.encode("utf-8")
    body += struct.pack(">I", len(reason_b)) + reason_b
    sig_b = signature.encode("utf-8")
    body += struct.pack(">I", len(sig_b)) + sig_b
    body += struct.pack(">I", len(entries))
    for fingerprint, blob in entries:
        fp_b = fingerprint.encode("utf-8")
        body += struct.pack(">I", len(fp_b)) + fp_b
        body += struct.pack(">I", len(blob)) + blob
    return bytes(body) + _DIGEST(bytes(body)).digest()


def decode_shard_states(blob: bytes) -> ShardEnvelope:
    """Inverse of `encode_shard_states`, validated end to end like
    `decode_states`. Any defect raises `StateDecodeError` — the caller
    treats the whole envelope as a lost host and recovers its partitions
    from the StateRepository or by rescanning."""
    header = len(SHARD_MAGIC)
    if len(blob) < header + 8 + _DIGEST_LEN:
        raise StateDecodeError("truncated shard envelope")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if _DIGEST(body).digest() != digest:
        raise StateDecodeError("shard envelope digest mismatch")
    if body[:header] != SHARD_MAGIC:
        raise StateDecodeError("bad shard magic")
    off = header
    try:
        version, shard = struct.unpack_from(">II", body, off)
        off += 8
        if version != SHARD_FORMAT_VERSION:
            raise StateDecodeError(
                f"shard format version {version} != {SHARD_FORMAT_VERSION}"
            )
        (flags,) = struct.unpack_from(">B", body, off)
        off += 1
        (reason_len,) = struct.unpack_from(">I", body, off)
        off += 4
        reason = body[off : off + reason_len].decode("utf-8")
        off += reason_len
        (sig_len,) = struct.unpack_from(">I", body, off)
        off += 4
        signature = body[off : off + sig_len].decode("utf-8")
        off += sig_len
        (count,) = struct.unpack_from(">I", body, off)
        off += 4
        entries: List[Tuple[str, bytes]] = []
        for _ in range(count):
            (fp_len,) = struct.unpack_from(">I", body, off)
            off += 4
            fingerprint = body[off : off + fp_len].decode("utf-8")
            if len(fingerprint.encode("utf-8")) != fp_len:
                raise StateDecodeError("truncated shard entry fingerprint")
            off += fp_len
            (blob_len,) = struct.unpack_from(">I", body, off)
            off += 4
            entry = body[off : off + blob_len]
            if len(entry) != blob_len:
                raise StateDecodeError("truncated shard entry payload")
            off += blob_len
            entries.append((fingerprint, bytes(entry)))
    except struct.error as e:
        raise StateDecodeError(f"truncated shard envelope: {e}") from e
    if off != len(body):
        raise StateDecodeError("trailing bytes after last shard entry")
    return ShardEnvelope(
        shard=int(shard),
        signature=signature,
        cancelled=bool(flags & 1),
        reason=reason,
        entries=entries,
    )


def merge_states(a: Any, b: Any) -> Any:
    """Semigroup merge with None as the identity (an empty partition
    contributes no state)."""
    if a is None:
        return b
    if b is None:
        return a
    return a.merge(b)


# -- repositories -------------------------------------------------------------


class StateRepository:
    """Keyed blob store for partition-state envelopes plus the shared
    load/save/merge logic. Backends implement `_get` / `_put` /
    `_exists` over `(dataset, signature, fingerprint)` keys."""

    def _get(self, dataset: str, signature: str, fingerprint: str) -> Optional[bytes]:
        raise NotImplementedError

    def _put(self, dataset: str, signature: str, fingerprint: str, blob: bytes) -> None:
        raise NotImplementedError

    def _exists(self, dataset: str, signature: str, fingerprint: str) -> bool:
        raise NotImplementedError

    # -- raw envelope surface (windows/segments.py and other layered
    # -- caches store their own self-validated envelopes here) ---------------

    def get_blob(self, dataset: str, signature: str, key: str) -> Optional[bytes]:
        return self._get(dataset, signature, key)

    def put_blob(self, dataset: str, signature: str, key: str, blob: bytes) -> None:
        self._put(dataset, signature, key, blob)

    def has_blob(self, dataset: str, signature: str, key: str) -> bool:
        return self._exists(dataset, signature, key)

    # -- the cache surface the fused pass consumes ---------------------------

    def has_states(self, dataset: str, fingerprint: str, signature: str) -> bool:
        """Cheap pre-scan probe — the planner's cached/scanned split
        prediction (lint/cost.py) rides on this."""
        return self._exists(dataset, signature, fingerprint)

    def load_states(
        self,
        dataset: str,
        fingerprint: str,
        signature: str,
        analyzers: Sequence[Any],
    ) -> Optional[List[Any]]:
        """One state (or None) per analyzer, or None on any miss or
        decode failure (DQ314 lenient warning) — never a wrong answer."""
        try:
            faults.fault_point("state.load")
            blob = self._get(dataset, signature, fingerprint)
        except Exception as e:  # noqa: BLE001 — unreadable entry = miss
            _warn_fallback(dataset, fingerprint, f"unreadable: {e}")
            return None
        if blob is None:
            return None
        try:
            return decode_states(blob, analyzers)
        except StateDecodeError as e:
            _warn_fallback(dataset, fingerprint, str(e))
            return None

    def save_states(
        self,
        dataset: str,
        fingerprint: str,
        signature: str,
        pairs: Sequence[Tuple[Any, Any]],
    ) -> bool:
        """Best-effort atomic publish. False when any analyzer's state
        has no serde (the partition is not cacheable) or the write
        fails — the run itself is never affected."""
        try:
            blob = encode_states(pairs)
        except ValueError:
            return False
        try:
            faults.fault_point("state.save")
            self._put(dataset, signature, fingerprint, blob)
        except Exception:  # noqa: BLE001 — cache write must never break a run
            return False
        return True

    # -- accounting ----------------------------------------------------------

    def disk_usage(self, dataset: str) -> Optional[int]:
        """Bytes of state envelopes stored for `dataset`, or None when
        the backend cannot account (an opaque object store). The
        DQService's per-tenant state-disk budget is enforced against
        this at admission and at partition boundaries."""
        return None

    # -- zero-scan range queries ---------------------------------------------

    def merge_range(
        self,
        dataset: str,
        fingerprints: Sequence[str],
        analyzers: Sequence[Any],
        signature: str,
    ):
        """Metrics over a set of partitions as a PURE state merge — zero
        rows scanned ("metrics over the last N days"). States merge in
        the given fingerprint order through the same semigroup surface
        the fused pass uses, so the result is bit-identical to scanning
        those partitions together. Raises KeyError when any partition
        has no cached entry, and StateDecodeError when an entry is
        unusable — a range query must never silently drop data."""
        from deequ_tpu import observe
        from deequ_tpu.runners.context import AnalyzerContext

        merged: List[Any] = [None] * len(analyzers)
        with observe.span(
            "state_cache", cat="cache", op="merge_range",
            partitions=len(fingerprints),
        ):
            for fingerprint in fingerprints:
                blob = self._get(dataset, signature, fingerprint)
                if blob is None:
                    raise KeyError(
                        f"no cached states for dataset {dataset!r} "
                        f"partition {fingerprint!r} under signature "
                        f"{signature!r}"
                    )
                states = decode_states(blob, analyzers)
                merged = [merge_states(m, s) for m, s in zip(merged, states)]
        metrics = {
            analyzer: analyzer.compute_metric_from(state)
            for analyzer, state in zip(analyzers, merged)
        }
        return AnalyzerContext(metrics)


class InMemoryStateRepository(StateRepository):
    """Process-local backend (tests, notebooks): a locked dict of
    envelopes. Envelopes still round-trip through the binary format so
    the memory and fs backends exercise identical serde."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._blobs: Dict[Tuple[str, str, str], bytes] = {}

    def _get(self, dataset: str, signature: str, fingerprint: str) -> Optional[bytes]:
        with self._lock:
            return self._blobs.get((dataset, signature, fingerprint))

    def _put(self, dataset: str, signature: str, fingerprint: str, blob: bytes) -> None:
        with self._lock:
            self._blobs[(dataset, signature, fingerprint)] = bytes(blob)

    def _exists(self, dataset: str, signature: str, fingerprint: str) -> bool:
        with self._lock:
            return (dataset, signature, fingerprint) in self._blobs

    def disk_usage(self, dataset: str) -> Optional[int]:
        with self._lock:
            return sum(
                len(blob)
                for (ds, _sig, _fp), blob in self._blobs.items()
                if ds == dataset
            )


def _safe_component(name: str) -> str:
    """A dataset name as one path component: pass through simple names,
    hash anything with separators or exotic characters."""
    if name and all(c.isalnum() or c in "-_." for c in name):
        return name
    return "ds-" + hashlib.sha256(name.encode("utf-8")).hexdigest()[:16]


class FileSystemStateRepository(StateRepository):
    """Disk-backed repository:

        <base_path>/<dataset>/<signature>/<fingerprint>.dqstate

    Writes go through the fsio seam — atomic tmp + rename on the local
    filesystem, whole-object puts on stores — and are additionally
    serialized per dataset by an advisory `.lock` file (fcntl.flock on
    POSIX; a process-local lock elsewhere and for non-local backends),
    so concurrent suite runs over the same dataset can't interleave
    partial state files."""

    def __init__(self, base_path: str, filesystem: Optional[FileSystem] = None):
        self.base_path = base_path
        self.fs = resolve_filesystem(filesystem)
        self._local_locks: Dict[str, threading.Lock] = {}
        self._locks_guard = threading.Lock()

    def _path(self, dataset: str, signature: str, fingerprint: str) -> str:
        return os.path.join(
            self.base_path, _safe_component(dataset), signature,
            f"{fingerprint}.dqstate",
        )

    @contextmanager
    def _dataset_lock(self, dataset: str) -> Iterator[None]:
        """Per-dataset writer exclusion. Cross-process via flock on the
        local filesystem; in-process (threads) always, which also covers
        backends with no lockable files (memory/object stores, where the
        atomic whole-object put already prevents interleaving)."""
        key = _safe_component(dataset)
        with self._locks_guard:
            lock = self._local_locks.setdefault(key, threading.Lock())
        with lock:
            if not isinstance(self.fs, LocalFileSystem):
                yield
                return
            lock_path = os.path.join(self.base_path, key, ".lock")
            os.makedirs(os.path.dirname(lock_path), exist_ok=True)
            try:
                import fcntl
            except ImportError:  # non-POSIX: thread lock only
                yield
                return
            with open(lock_path, "a+b") as handle:
                fcntl.flock(handle.fileno(), fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(handle.fileno(), fcntl.LOCK_UN)

    def _get(self, dataset: str, signature: str, fingerprint: str) -> Optional[bytes]:
        path = self._path(dataset, signature, fingerprint)
        if not self.fs.exists(path):
            return None
        return self.fs.read_bytes(path)

    def _put(self, dataset: str, signature: str, fingerprint: str, blob: bytes) -> None:
        with self._dataset_lock(dataset):
            self.fs.write_bytes(self._path(dataset, signature, fingerprint), blob)

    def _exists(self, dataset: str, signature: str, fingerprint: str) -> bool:
        return self.fs.exists(self._path(dataset, signature, fingerprint))

    def disk_usage(self, dataset: str) -> Optional[int]:
        """Sum of `.dqstate` envelope sizes under the dataset's
        directory (every signature). Local filesystems only — other
        backends return None (unknown), and the disk-budget enforcement
        treats unknown as in-budget."""
        if not isinstance(self.fs, LocalFileSystem):
            return None
        root = os.path.join(self.base_path, _safe_component(dataset))
        if not os.path.isdir(root):
            return 0
        total = 0
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in filenames:
                if not name.endswith(".dqstate"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:  # fault-ok: racing delete = size 0
                    pass
        return total


@dataclass
class StateCacheContext:
    """What the fused pass needs to consult the cache: the repository
    and the dataset name the entries are keyed under. Built by
    `AnalysisRunBuilder.with_state_repository(...)` and threaded through
    `AnalysisRunner._run_scanning_analyzers` to `FusedScanPass`."""

    repository: StateRepository
    dataset: str


__all__ = [
    "SHARD_FORMAT_VERSION",
    "SHARD_MAGIC",
    "STATE_FORMAT_VERSION",
    "STATE_MAGIC",
    "FileSystemStateRepository",
    "InMemoryStateRepository",
    "ShardEnvelope",
    "StateCacheContext",
    "StateDecodeError",
    "StateRepository",
    "decode_shard_states",
    "decode_states",
    "encode_shard_states",
    "encode_states",
    "merge_states",
    "plan_signature",
    "plan_signature_for",
]

from deequ_tpu.runners.context import AnalyzerContext
from deequ_tpu.runners.analysis_runner import AnalysisRunner

__all__ = ["AnalyzerContext", "AnalysisRunner"]

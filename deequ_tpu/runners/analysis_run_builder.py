"""Fluent builder for analysis runs.

reference: runners/AnalysisRunBuilder.scala:26-186 (incl. the repository
variant's reuse/save options).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional, Sequence

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.data.table import Table
from deequ_tpu.runners.context import AnalyzerContext

if TYPE_CHECKING:
    from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister
    from deequ_tpu.repository.base import MetricsRepository, ResultKey


class AnalysisRunBuilder:
    def __init__(self, data: Table):
        self._data = data
        self._analyzers: List[Analyzer] = []
        self._metrics_repository: Optional["MetricsRepository"] = None
        self._reuse_key: Optional["ResultKey"] = None
        self._fail_if_results_missing = False
        self._save_key: Optional["ResultKey"] = None
        self._aggregate_with: Optional["StateLoader"] = None
        self._save_states_with: Optional["StatePersister"] = None
        self._engine: str = "auto"
        self._mesh = None
        self._validation: Optional[str] = None
        self._tracing = None
        self._state_repository = None
        self._dataset_name: str = "default"
        self._controller = None
        self._deadline_s: Optional[float] = None

    def with_controller(self, controller) -> "AnalysisRunBuilder":
        """Cooperative run control (deequ_tpu.core.controller): attach a
        `RunController` whose `cancel()` any thread may call; the run
        honors it at batch granularity and raises `RunCancelled`
        (DQ401) carrying progress after every stage thread joined."""
        self._controller = controller
        return self

    def with_deadline(self, seconds: float) -> "AnalysisRunBuilder":
        """Bound the run's wall time: past `seconds` the next batch
        check raises `RunCancelled` (DQ402). With a partitioned source
        and a state repository, partitions committed before the trip
        resume from cache on the rerun."""
        self._deadline_s = float(seconds)
        return self

    def with_tracing(self, trace=True) -> "AnalysisRunBuilder":
        """Run observability (deequ_tpu.observe): True records a
        hierarchical span tree attached as `context.run_trace`; a str
        additionally writes the Chrome-trace JSON to that path (load in
        Perfetto); False forces tracing off regardless of the
        DEEQU_TPU_TRACE env knob."""
        self._tracing = trace
        return self

    def with_plan_validation(self, mode: str) -> "AnalysisRunBuilder":
        """Plan-time static analysis mode: "strict" raises one aggregated
        PlanValidationError before any scan, "lenient" (default) attaches
        diagnostics to the context, "off" skips the pass."""
        self._validation = mode
        return self

    def with_engine(self, engine: str, mesh=None) -> "AnalysisRunBuilder":
        """"auto" (mesh when >1 device), "single", or "distributed" —
        mirrors the reference where partition parallelism is the default
        execution path (reference: AnalysisRunner.scala:279-326)."""
        self._engine = engine
        self._mesh = mesh
        return self

    def explain(self, **kwargs):
        """EXPLAIN the planned run without scanning a row: the static
        cost/effect prediction (passes, batches, wire bytes, family
        groups) plus DQ3xx performance diagnostics, as an
        `ExplainResult` (render with `str(...)`)."""
        from deequ_tpu.lint.explain import explain_plan

        if self._deadline_s is not None:
            kwargs.setdefault("deadline_s", self._deadline_s)
        return explain_plan(self._data, analyzers=self._analyzers, **kwargs)

    def add_analyzer(self, analyzer: Analyzer) -> "AnalysisRunBuilder":
        self._analyzers.append(analyzer)
        return self

    def add_analyzers(self, analyzers: Sequence[Analyzer]) -> "AnalysisRunBuilder":
        self._analyzers.extend(analyzers)
        return self

    def aggregate_with(self, loader: "StateLoader") -> "AnalysisRunBuilder":
        self._aggregate_with = loader
        return self

    def save_states_with(self, persister: "StatePersister") -> "AnalysisRunBuilder":
        self._save_states_with = persister
        return self

    def with_state_repository(
        self, repository, dataset: str = "default"
    ) -> "AnalysisRunBuilder":
        """Attach a partition-state cache (repository/states.py:
        `StateRepository`). Over a partitioned source
        (`Table.scan_parquet_dataset`), partitions whose fingerprint and
        plan signature already have stored states load instead of
        scanning, and newly scanned partitions publish their states —
        making re-runs cost proportional to NEW data while staying
        bit-identical to a full rescan. `dataset` namespaces the
        entries; `DEEQU_TPU_STATE_CACHE=0` is the kill switch."""
        self._state_repository = repository
        self._dataset_name = dataset
        return self

    def use_repository(self, repository: "MetricsRepository") -> "AnalysisRunBuilder":
        self._metrics_repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key: "ResultKey", fail_if_results_missing: bool = False
    ) -> "AnalysisRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key: "ResultKey") -> "AnalysisRunBuilder":
        self._save_key = key
        return self

    def run(self) -> AnalyzerContext:
        from deequ_tpu.runners.analysis_runner import AnalysisRunner

        controller = self._controller
        if controller is None and self._deadline_s is not None:
            from deequ_tpu.core.controller import RunController

            controller = RunController(deadline_s=self._deadline_s)
        return AnalysisRunner.do_analysis_run(
            self._data,
            self._analyzers,
            aggregate_with=self._aggregate_with,
            save_states_with=self._save_states_with,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_or_append_results_with_key=self._save_key,
            engine=self._engine,
            mesh=self._mesh,
            validation=self._validation,
            tracing=self._tracing,
            state_repository=self._state_repository,
            dataset_name=self._dataset_name,
            controller=controller,
        )

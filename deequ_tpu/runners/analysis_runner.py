"""AnalysisRunner: the scheduler/optimizer of the metrics engine.

Pipeline (reference: runners/AnalysisRunner.scala:98-193):
  1. skip analyzers whose metrics already exist in the repository,
  2. partition out analyzers with failing preconditions -> failure metrics,
  3. split grouping vs scanning analyzers,
  4. run ALL scan-shareable analyzers in ONE fused device pass,
  5. one frequency computation per distinct grouping-column-set, shared by
     every grouping analyzer over it,
  6. merge with previous results; save to repository.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from deequ_tpu import observe
from deequ_tpu.analyzers.base import Analyzer, Preconditions, ScanShareableAnalyzer
from deequ_tpu.core.metrics import Metric
from deequ_tpu.data.table import Table
from deequ_tpu.ops.fused import FusedScanPass
from deequ_tpu.runners.context import AnalyzerContext

if TYPE_CHECKING:
    from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister
    from deequ_tpu.repository.base import MetricsRepository, ResultKey


class AnalysisRunner:
    @staticmethod
    def on_data(table: Table) -> "AnalysisRunBuilder":
        from deequ_tpu.runners.analysis_run_builder import AnalysisRunBuilder

        return AnalysisRunBuilder(table)

    # ------------------------------------------------------------------
    @staticmethod
    def do_analysis_run(
        data: Table,
        analyzers: Sequence[Analyzer],
        aggregate_with: Optional["StateLoader"] = None,
        save_states_with: Optional["StatePersister"] = None,
        metrics_repository: Optional["MetricsRepository"] = None,
        reuse_existing_results_for_key: Optional["ResultKey"] = None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key: Optional["ResultKey"] = None,
        engine: str = "auto",
        mesh=None,
        validation: Optional[str] = None,
        tracing=None,
        state_repository=None,
        dataset_name: str = "default",
        forensics=None,
        controller=None,
    ) -> AnalyzerContext:
        if not analyzers:
            return AnalyzerContext.empty()

        # `tracing`: True/False/an output path/None (= the
        # DEEQU_TPU_TRACE env knob). The finished RunTrace attaches to
        # the returned context as `run_trace` (the validation_warnings
        # pattern); nested under a traced verification run this becomes
        # a child subtree of the suite's trace.
        with observe.traced_run(
            "analysis_run", enable=tracing, analyzers=len(analyzers)
        ) as run:
            context = AnalysisRunner._do_analysis_run(
                data,
                analyzers,
                aggregate_with,
                save_states_with,
                metrics_repository,
                reuse_existing_results_for_key,
                fail_if_results_missing,
                save_or_append_results_with_key,
                engine,
                mesh,
                validation,
                state_repository,
                dataset_name,
                forensics,
                controller,
            )
        if run:
            context.run_trace = run.trace
        return context

    # ------------------------------------------------------------------
    @staticmethod
    def _do_analysis_run(
        data: Table,
        analyzers: Sequence[Analyzer],
        aggregate_with: Optional["StateLoader"] = None,
        save_states_with: Optional["StatePersister"] = None,
        metrics_repository: Optional["MetricsRepository"] = None,
        reuse_existing_results_for_key: Optional["ResultKey"] = None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key: Optional["ResultKey"] = None,
        engine: str = "auto",
        mesh=None,
        validation: Optional[str] = None,
        state_repository=None,
        dataset_name: str = "default",
        forensics=None,
        controller=None,
    ) -> AnalyzerContext:
        # partition-state cache (repository/states.py): only partitioned
        # sources have a per-partition fold to cache; the context rides
        # the fused pass (the distributed/mesh path always scans)
        state_cache = None
        if (
            state_repository is not None
            and getattr(data, "partitions", None) is not None
        ):
            from deequ_tpu.repository.states import StateCacheContext

            state_cache = StateCacheContext(state_repository, dataset_name)

        # plan-time static analysis (see deequ_tpu/lint): strict raises
        # before any kernel dispatch, lenient attaches diagnostics to the
        # returned context as `validation_warnings`
        with observe.span("plan_validate", cat="plan"):
            validation_diagnostics, plan_cost = AnalysisRunner._validate_plan(
                data, analyzers, validation, state_cache
            )

        from deequ_tpu.runners.engine import resolve_engine

        mesh = resolve_engine(engine, mesh, num_rows=data.num_rows)

        # deduplicate, preserving order
        seen = set()
        unique: List[Analyzer] = []
        for a in analyzers:
            if a not in seen:
                seen.add(a)
                unique.append(a)
        analyzers = unique

        # 1. repository reuse (reference: AnalysisRunner.scala:116-135)
        reused = AnalyzerContext.empty()
        if metrics_repository is not None and reuse_existing_results_for_key is not None:
            existing = metrics_repository.load_by_key(reuse_existing_results_for_key)
            if existing is not None:
                reused_map = {
                    a: existing.metric_map[a]
                    for a in analyzers
                    if a in existing.metric_map
                }
                reused = AnalyzerContext(reused_map)
            if fail_if_results_missing:
                # internal (profiler pass-fusion) analyzers are never
                # repository-backed; their absence is not "missing"
                missing = [
                    a
                    for a in analyzers
                    if a not in reused.metric_map
                    and not getattr(a, "internal", False)
                ]
                if missing:
                    raise RuntimeError(
                        "Could not find all necessary results in the "
                        "MetricsRepository, the calculation of the metrics "
                        f"for these analyzers would be needed: "
                        f"{', '.join(repr(a) for a in missing)}"
                    )
        analyzers = [a for a in analyzers if a not in reused.metric_map]

        # 2. preconditions (reference: AnalysisRunner.scala:137-147)
        passed: List[Analyzer] = []
        failure_map: Dict[Analyzer, Metric] = {}
        for a in analyzers:
            err = Preconditions.find_first_failing(data, a.preconditions())
            if err is None:
                passed.append(a)
            else:
                failure_map[a] = a.to_failure_metric(err)
        precondition_failures = AnalyzerContext(failure_map)

        # 3. grouping vs scanning (reference: AnalysisRunner.scala:148-150)
        from deequ_tpu.analyzers.grouping import GroupingAnalyzer

        grouping = [a for a in passed if isinstance(a, GroupingAnalyzer)]
        scanning = [a for a in passed if not isinstance(a, GroupingAnalyzer)]

        # 4. fused scan pass (reference: AnalysisRunner.scala:279-326)
        scanning_results = AnalysisRunner._run_scanning_analyzers(
            data, scanning, aggregate_with, save_states_with, mesh,
            state_cache, forensics, controller,
        )

        # 5. one frequency pass per grouping-column-set
        #    (reference: AnalysisRunner.scala:164-180, 249-277)
        grouping_results = AnalyzerContext.empty()
        if grouping:
            from deequ_tpu.runners.grouping_runner import run_grouping_analyzers

            grouping_results = run_grouping_analyzers(
                data, grouping, aggregate_with, save_states_with, mesh=mesh
            )

        context = (
            reused + precondition_failures + scanning_results + grouping_results
        )
        context.validation_warnings = validation_diagnostics
        context.plan_cost = plan_cost

        # 6. save (reference: AnalysisRunner.scala:182-230)
        if metrics_repository is not None and save_or_append_results_with_key is not None:
            AnalysisRunner._save_or_append(
                metrics_repository, save_or_append_results_with_key, context
            )
        return context

    # ------------------------------------------------------------------
    @staticmethod
    def _validate_plan(data, analyzers, validation, state_cache=None):
        """-> (diagnostics, PlanCost | None). The cost prediction rides
        the same static pass and lands on the context as `plan_cost`."""
        from deequ_tpu.lint import PlanValidationError, SchemaInfo, validate_plan
        from deequ_tpu.lint.planlint import resolve_validation_mode

        mode = resolve_validation_mode(validation)
        if mode == "off":
            return [], None
        try:
            schema = SchemaInfo.from_table(data)
            streaming = bool(getattr(data, "is_streaming", False))
            cap = getattr(data, "batch_rows", None) if streaming else None
            # parquet sources expose row-group statistics: the cost pass
            # then predicts the pushdown outcome (skipped groups, batch
            # replay) the runtime will produce, trace-verifiably
            row_groups = None
            stats_fn = getattr(data, "row_group_stats", None)
            if stats_fn is not None:
                try:
                    row_groups = stats_fn()
                except Exception:  # noqa: BLE001 — stats are advisory
                    row_groups = None
            # partitioned sources: predict the state-cache split by
            # probing the repository with the SAME fingerprint + plan
            # signature the fused pass will use — so
            # `drift.partitions_cached` pins to zero on a warm run
            partitions = None
            parts_fn = getattr(data, "partitions", None)
            if parts_fn is not None:
                partitions = AnalysisRunner._predict_partitions(
                    data, analyzers, state_cache
                )
            report = validate_plan(
                schema,
                checks=(),
                required_analyzers=analyzers,
                mode=mode,
                num_rows=int(data.num_rows),
                streaming=streaming,
                stream_batch_rows=int(cap) if cap else None,
                row_groups=row_groups,
                partitions=partitions,
            )
            return list(report.diagnostics), report.plan_cost
        except PlanValidationError:
            raise
        except Exception:  # noqa: BLE001 — lint must never break a run
            return [], None

    # ------------------------------------------------------------------
    @staticmethod
    def _predict_partitions(data, analyzers, state_cache):
        """Per-partition cache prediction records for `analyze_plan`:
        `{"cached": bool, "bytes": int}` per partition, in partition
        order. Mirrors the runner's own filtering (dedupe, grouping
        split, scan-shareable only) so the probe signature matches the
        one `FusedScanPass._run_partitioned` computes."""
        import os

        from deequ_tpu.analyzers.grouping import GroupingAnalyzer
        from deequ_tpu.ops import runtime

        probe = None
        if state_cache is not None and runtime.state_cache_enabled():
            from deequ_tpu.repository.states import plan_signature_for

            seen: set = set()
            shareable = []
            for a in analyzers:
                if a in seen:
                    continue
                seen.add(a)
                if isinstance(a, ScanShareableAnalyzer) and not isinstance(
                    a, GroupingAnalyzer
                ):
                    shareable.append(a)
            probe = plan_signature_for(shareable, data)
        records = []
        for part in data.partitions():
            cached = bool(
                probe is not None
                and state_cache.repository.has_states(
                    state_cache.dataset, part.fingerprint, probe
                )
            )
            try:
                nbytes = int(os.path.getsize(part.path))
            except OSError:
                nbytes = 0
            records.append({"cached": cached, "bytes": nbytes})
        return records

    # ------------------------------------------------------------------
    @staticmethod
    def _run_scanning_analyzers(
        data: Table,
        analyzers: Sequence[Analyzer],
        aggregate_with: Optional["StateLoader"],
        save_states_with: Optional["StatePersister"],
        mesh=None,
        state_cache=None,
        forensics=None,
        controller=None,
    ) -> AnalyzerContext:
        if not analyzers:
            return AnalyzerContext.empty()

        shareable = [a for a in analyzers if isinstance(a, ScanShareableAnalyzer)]
        others = [a for a in analyzers if not isinstance(a, ScanShareableAnalyzer)]

        metrics: Dict[Analyzer, Metric] = {}
        if shareable:
            if mesh is not None:
                # the distributed pass shards batches across devices —
                # there is no per-partition fold to cache, so the mesh
                # path always scans (documented fallback); forensics
                # capture likewise degrades to provenance-only there
                from deequ_tpu.parallel.distributed import DistributedScanPass

                results = DistributedScanPass(shareable, mesh=mesh).run(data)
            else:
                results = FusedScanPass(
                    shareable, state_cache=state_cache, forensics=forensics,
                    controller=controller,
                ).run(data)
            for result in results:
                analyzer = result.analyzer
                if result.error is not None:
                    metrics[analyzer] = analyzer.to_failure_metric(result.error)
                else:
                    metrics[analyzer] = analyzer.calculate_metric(
                        result.state, aggregate_with, save_states_with
                    )
        for analyzer in others:
            metrics[analyzer] = analyzer.calculate(
                data, aggregate_with, save_states_with
            )
        return AnalyzerContext(metrics)

    # ------------------------------------------------------------------
    @staticmethod
    def run_on_aggregated_states(
        schema_table: Table,
        analyzers: Sequence[Analyzer],
        state_loaders: Sequence["StateLoader"],
        save_states_with: Optional["StatePersister"] = None,
        metrics_repository: Optional["MetricsRepository"] = None,
        save_or_append_results_with_key: Optional["ResultKey"] = None,
    ) -> AnalyzerContext:
        """Metrics purely from merged states — NO data scan
        (reference: runners/AnalysisRunner.scala:375-446)."""
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

        if not analyzers or not state_loaders:
            return AnalyzerContext.empty()

        # precondition check against the schema
        passed: List[Analyzer] = []
        failure_map: Dict[Analyzer, Metric] = {}
        for a in analyzers:
            err = Preconditions.find_first_failing(schema_table, a.preconditions())
            if err is None:
                passed.append(a)
            else:
                failure_map[a] = a.to_failure_metric(err)

        aggregated = InMemoryStateProvider()
        with observe.span(
            "state_merge", cat="merge",
            analyzers=len(passed), loaders=len(state_loaders),
        ):
            for analyzer in passed:
                for loader in state_loaders:
                    state = loader.load(analyzer)
                    if state is None:
                        continue
                    existing = aggregated.load(analyzer)
                    merged = (
                        existing.merge(state) if existing is not None else state
                    )
                    aggregated.persist(analyzer, merged)

        metrics: Dict[Analyzer, Metric] = dict(failure_map)
        for analyzer in passed:
            state = aggregated.load(analyzer)
            if save_states_with is not None and state is not None:
                save_states_with.persist(analyzer, state)
            metrics[analyzer] = analyzer.compute_metric_from(state)

        context = AnalyzerContext(metrics)
        if metrics_repository is not None and save_or_append_results_with_key is not None:
            AnalysisRunner._save_or_append(
                metrics_repository, save_or_append_results_with_key, context
            )
        return context

    # ------------------------------------------------------------------
    @staticmethod
    def _save_or_append(
        repository: "MetricsRepository",
        key: "ResultKey",
        context: AnalyzerContext,
    ) -> None:
        """Upsert semantics (reference: AnalysisRunner.scala:195-213).
        Internal analyzers (profiler pass-fusion members) never reach the
        repository: their metrics carry raw states and have no serde."""
        internal = [
            a
            for a in context.metric_map
            if getattr(a, "internal", False)
        ]
        if internal:
            context = AnalyzerContext(
                {
                    a: m
                    for a, m in context.metric_map.items()
                    if not getattr(a, "internal", False)
                }
            )
        existing = repository.load_by_key(key)
        combined = (existing + context) if existing is not None else context
        repository.save(key, combined)

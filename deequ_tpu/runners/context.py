"""AnalyzerContext: Map[Analyzer -> Metric] with merge + exporters.

reference: analyzers/runners/AnalyzerContext.scala:30-105.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from deequ_tpu.core.metrics import Metric

if TYPE_CHECKING:
    from deequ_tpu.analyzers.base import Analyzer


def sanitize_json_values(rows):
    """NaN/Inf are not RFC-8259 JSON — export them as null."""
    import math

    out = []
    for row in rows:
        row = dict(row)
        v = row.get("value")
        if isinstance(v, float) and not math.isfinite(v):
            row["value"] = None
        out.append(row)
    return out


class AnalyzerContext:
    def __init__(self, metric_map: Optional[Dict["Analyzer", Metric]] = None):
        self.metric_map: Dict["Analyzer", Metric] = dict(metric_map or {})
        # plan-validation diagnostics attached by AnalysisRunner in
        # lenient mode (deequ_tpu.lint.Diagnostic items); not part of
        # equality — two contexts with the same metrics are the same
        self.validation_warnings: List = []
        # observability: the run's RunTrace (deequ_tpu.observe) when
        # tracing was enabled, else None; also excluded from equality
        self.run_trace = None
        # static cost prediction (lint/cost.PlanCost) from the same
        # validation pass; None when validation is off. Excluded from
        # equality like the other side-channel attachments.
        self.plan_cost = None

    @staticmethod
    def empty() -> "AnalyzerContext":
        return AnalyzerContext()

    def all_metrics(self) -> List[Metric]:
        return list(self.metric_map.values())

    def __add__(self, other: "AnalyzerContext") -> "AnalyzerContext":
        merged = dict(self.metric_map)
        merged.update(other.metric_map)
        return AnalyzerContext(merged)

    def metric(self, analyzer: "Analyzer") -> Optional[Metric]:
        return self.metric_map.get(analyzer)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, AnalyzerContext) and self.metric_map == other.metric_map
        )

    def __repr__(self) -> str:
        entries = ", ".join(f"{a!r} -> {m!r}" for a, m in self.metric_map.items())
        return f"AnalyzerContext({entries})"

    # -- exporters (reference: AnalyzerContext.scala:48-90) ------------------

    def success_metrics_as_rows(
        self, for_analyzers: Optional[Sequence["Analyzer"]] = None
    ) -> List[Dict[str, object]]:
        include = set(for_analyzers) if for_analyzers else None
        rows: List[Dict[str, object]] = []
        for analyzer, metric in self.metric_map.items():
            if include is not None and analyzer not in include:
                continue
            if not metric.value.is_success:
                continue
            for flattened in metric.flatten():
                rows.append(
                    {
                        "entity": flattened.entity.value,
                        "instance": flattened.instance,
                        "name": flattened.name,
                        "value": flattened.value.get(),
                    }
                )
        return rows

    def success_metrics_as_json(
        self, for_analyzers: Optional[Sequence["Analyzer"]] = None
    ) -> str:
        return json.dumps(
            sanitize_json_values(self.success_metrics_as_rows(for_analyzers))
        )

    def success_metrics_as_table(self, for_analyzers=None):
        """Rows as a Table (the DataFrame exporter analogue)."""
        from deequ_tpu.data.table import Table

        rows = self.success_metrics_as_rows(for_analyzers)
        return Table.from_pydict(
            {
                "entity": [r["entity"] for r in rows],
                "instance": [r["instance"] for r in rows],
                "name": [r["name"] for r in rows],
                "value": [float(r["value"]) for r in rows],
            }
        )


def success_metrics_as_data_frame(context: AnalyzerContext, for_analyzers=None):
    return context.success_metrics_as_table(for_analyzers)

"""Execution-engine selection: single-device fused pass vs mesh-sharded
distributed pass.

The reference's partition parallelism is its DEFAULT execution path —
every aggregation runs map-side partial + merge
(reference: runners/AnalysisRunner.scala:279-326); it is not an opt-in
side door. Mirroring that, every runner here takes `engine`:

    "auto"         -> mesh over all devices when >1 device is attached,
                      single-device otherwise (the default)
    "single"       -> force the single-device fused pass
    "distributed"  -> force the mesh pass (all devices, or `mesh`)

Resolution returns the Mesh to shard over, or None for single-device.
"""

from __future__ import annotations

from typing import Optional

VALID_ENGINES = ("auto", "single", "distributed")

# "auto" shards only when the table can amortize the shard_map compile +
# per-batch collective overhead; below this the single-device fused pass
# wins outright. "distributed" ignores the threshold.
AUTO_MIN_ROWS = 1 << 17


def resolve_engine(engine: str = "auto", mesh=None, num_rows: Optional[int] = None):
    if engine not in VALID_ENGINES:
        raise ValueError(f"engine must be one of {VALID_ENGINES}, got {engine!r}")
    if engine == "single":
        return None
    if engine == "auto" and num_rows is not None and num_rows < AUTO_MIN_ROWS:
        return None
    if mesh is not None:
        return mesh
    import jax

    devices = jax.devices()
    if engine == "distributed" or len(devices) > 1:
        from deequ_tpu.parallel.distributed import data_mesh

        return data_mesh(devices)
    return None

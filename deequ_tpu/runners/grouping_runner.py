"""Grouping-analyzer execution: one frequency computation per distinct
grouping-column-set, shared by every analyzer over it, plus one fused
aggregation pass over the resulting counts.

reference: runners/AnalysisRunner.scala:164-180 (grouping by column set),
:249-277 (runGroupingAnalyzers), :466-534 (shared aggregation over the
frequencies table). Job accounting matches the reference invariant:
N analyzers on the same grouping columns cost 2 jobs (1 group-by + 1
shared aggregation), not 2·N.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Sequence, Tuple

from deequ_tpu import observe
from deequ_tpu.core.metrics import Metric
from deequ_tpu.data.table import Table
from deequ_tpu.runners.context import AnalyzerContext

if TYPE_CHECKING:
    from deequ_tpu.analyzers.grouping import GroupingAnalyzer
    from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister


def run_grouping_analyzers(
    data: Table,
    analyzers: Sequence["GroupingAnalyzer"],
    aggregate_with: Optional["StateLoader"] = None,
    save_states_with: Optional["StatePersister"] = None,
    mesh=None,
) -> AnalyzerContext:
    from deequ_tpu.analyzers.frequency import (
        FrequencyBasedAnalyzer,
        ScanShareableFrequencyBasedAnalyzer,
        compute_frequencies,
    )
    from deequ_tpu.ops.freq_agg import run_shared_freq_agg

    metrics: Dict[object, Metric] = {}

    frequency_based = [a for a in analyzers if isinstance(a, FrequencyBasedAnalyzer)]
    other = [a for a in analyzers if not isinstance(a, FrequencyBasedAnalyzer)]
    for analyzer in other:
        metrics[analyzer] = analyzer.calculate(data, aggregate_with, save_states_with)

    # group by sorted grouping-column set (reference: AnalysisRunner.scala:164-180)
    groups: Dict[Tuple[str, ...], List["FrequencyBasedAnalyzer"]] = {}
    for analyzer in frequency_based:
        groups.setdefault(tuple(sorted(analyzer.grouping_columns())), []).append(analyzer)

    for cols, group in groups.items():
        with observe.span(
            "grouping", cat="group",
            columns=",".join(cols), analyzers=len(group),
        ):
            _run_column_set(
                data, cols, group, metrics,
                aggregate_with, save_states_with, mesh,
                compute_frequencies, ScanShareableFrequencyBasedAnalyzer,
                run_shared_freq_agg,
            )

    return AnalyzerContext(metrics)


def _run_column_set(
    data,
    cols,
    group,
    metrics,
    aggregate_with,
    save_states_with,
    mesh,
    compute_frequencies,
    ScanShareableFrequencyBasedAnalyzer,
    run_shared_freq_agg,
) -> None:
    """One grouping-column set: a shared frequency pass, then either
    per-analyzer state handling or the fused aggregation."""
    try:
        shared_state = compute_frequencies(data, list(cols), mesh=mesh)
    except Exception as e:  # noqa: BLE001
        for analyzer in group:
            metrics[analyzer] = analyzer.to_failure_metric(e)
        return

    if aggregate_with is not None or save_states_with is not None:
        # per-analyzer state merge/persist takes priority over fusion
        for analyzer in group:
            try:
                metrics[analyzer] = analyzer.calculate_metric(
                    shared_state, aggregate_with, save_states_with
                )
            except Exception as e:  # noqa: BLE001
                metrics[analyzer] = analyzer.to_failure_metric(e)
        return

    shareable = [
        a for a in group if isinstance(a, ScanShareableFrequencyBasedAnalyzer)
    ]
    non_shareable = [
        a for a in group if not isinstance(a, ScanShareableFrequencyBasedAnalyzer)
    ]
    if shareable:
        try:
            for analyzer, metric in zip(
                shareable, run_shared_freq_agg(shared_state, shareable)
            ):
                metrics[analyzer] = metric
        except Exception as e:  # noqa: BLE001
            for analyzer in shareable:
                metrics[analyzer] = analyzer.to_failure_metric(e)
    for analyzer in non_shareable:  # e.g. MutualInformation: extra pass
        try:
            metrics[analyzer] = analyzer.compute_metric_from(shared_state)
        except Exception as e:  # noqa: BLE001
            metrics[analyzer] = analyzer.to_failure_metric(e)

"""Grouping-analyzer execution: one frequency computation per distinct
grouping-column-set, shared by every analyzer over it.

reference: runners/AnalysisRunner.scala:164-180 (grouping by column set),
:249-277 (runGroupingAnalyzers), :466-534 (shared aggregation over the
frequencies table). Until the full frequency sharing lands, analyzers run
individually with per-analyzer failure capture.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional, Sequence

from deequ_tpu.core.metrics import Metric
from deequ_tpu.data.table import Table
from deequ_tpu.runners.context import AnalyzerContext

if TYPE_CHECKING:
    from deequ_tpu.analyzers.grouping import GroupingAnalyzer
    from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister


def run_grouping_analyzers(
    data: Table,
    analyzers: Sequence["GroupingAnalyzer"],
    aggregate_with: Optional["StateLoader"] = None,
    save_states_with: Optional["StatePersister"] = None,
) -> AnalyzerContext:
    metrics: Dict[object, Metric] = {}
    for analyzer in analyzers:
        metrics[analyzer] = analyzer.calculate(
            data, aggregate_with, save_states_with
        )
    return AnalyzerContext(metrics)

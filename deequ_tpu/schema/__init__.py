from deequ_tpu.schema.row_level_schema_validator import (
    RowLevelSchema,
    RowLevelSchemaValidationResult,
    RowLevelSchemaValidator,
)

__all__ = [
    "RowLevelSchema",
    "RowLevelSchemaValidationResult",
    "RowLevelSchemaValidator",
]

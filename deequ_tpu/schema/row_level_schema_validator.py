"""Row-level schema validation: split a table into valid (cast) and
invalid rows against typed per-column definitions.

reference: schema/RowLevelSchemaValidator.scala:25-282 — one conjunctive
boolean mask of all per-column predicates, valid rows cast to target
types, both sides counted. Here the CNF is a vectorized numpy mask.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from deequ_tpu.data.table import Column, ColumnType, Table


@dataclass
class ColumnDefinition:
    name: str
    is_nullable: bool = True


@dataclass
class StringColumnDefinition(ColumnDefinition):
    min_length: Optional[int] = None
    max_length: Optional[int] = None
    matches: Optional[str] = None


@dataclass
class IntColumnDefinition(ColumnDefinition):
    min_value: Optional[int] = None
    max_value: Optional[int] = None


@dataclass
class DecimalColumnDefinition(ColumnDefinition):
    precision: int = 10
    scale: int = 0


@dataclass
class TimestampColumnDefinition(ColumnDefinition):
    mask: str = "yyyy-MM-dd HH:mm:ss"


class RowLevelSchema:
    """Fluent schema builder (reference: RowLevelSchemaValidator.scala:73-149)."""

    def __init__(self, column_definitions: Optional[List[ColumnDefinition]] = None):
        self.column_definitions = list(column_definitions or [])

    def with_string_column(
        self,
        name: str,
        is_nullable: bool = True,
        min_length: Optional[int] = None,
        max_length: Optional[int] = None,
        matches: Optional[str] = None,
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + [StringColumnDefinition(name, is_nullable, min_length, max_length, matches)]
        )

    def with_int_column(
        self,
        name: str,
        is_nullable: bool = True,
        min_value: Optional[int] = None,
        max_value: Optional[int] = None,
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + [IntColumnDefinition(name, is_nullable, min_value, max_value)]
        )

    def with_decimal_column(
        self, name: str, precision: int, scale: int, is_nullable: bool = True
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + [DecimalColumnDefinition(name, is_nullable, precision, scale)]
        )

    def with_timestamp_column(
        self, name: str, mask: str, is_nullable: bool = True
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions + [TimestampColumnDefinition(name, is_nullable, mask)]
        )


@dataclass
class RowLevelSchemaValidationResult:
    valid_rows: Table
    num_valid_rows: int
    invalid_rows: Table
    num_invalid_rows: int


def _java_mask_to_strptime(mask: str) -> str:
    """SimpleDateFormat mask -> strptime format (common subset)."""
    out = mask
    for java, py in [
        ("yyyy", "%Y"),
        ("MM", "%m"),
        ("dd", "%d"),
        ("HH", "%H"),
        ("mm", "%M"),
        ("ss", "%S"),
    ]:
        out = out.replace(java, py)
    return out


class RowLevelSchemaValidator:
    @staticmethod
    def validate(data: Table, schema: RowLevelSchema) -> RowLevelSchemaValidationResult:
        """reference: RowLevelSchemaValidator.scala:183-230."""
        n = data.num_rows
        cnf = np.ones(n, dtype=bool)
        casts: List[Column] = []

        for definition in schema.column_definitions:
            col = data.column(definition.name)
            is_null = ~col.valid
            ok = np.ones(n, dtype=bool)

            if isinstance(definition, StringColumnDefinition):
                values = np.array(
                    [str(v) if col.valid[i] else "" for i, v in enumerate(col.values)],
                    dtype=object,
                )
                if definition.min_length is not None:
                    lengths = np.array([len(v) for v in values])
                    ok &= is_null | (lengths >= definition.min_length)
                if definition.max_length is not None:
                    lengths = np.array([len(v) for v in values])
                    ok &= is_null | (lengths <= definition.max_length)
                if definition.matches is not None:
                    rx = re.compile(definition.matches)
                    match = np.array(
                        [bool(rx.search(v)) for v in values], dtype=bool
                    )
                    ok &= is_null | match
                cast_values, cast_valid = values, col.valid.copy()
                cast_col = Column(definition.name, ColumnType.STRING, cast_values, cast_valid)
            elif isinstance(definition, IntColumnDefinition):
                parsed, parse_ok = _parse_ints(col)
                ok &= is_null | parse_ok
                if definition.min_value is not None:
                    ok &= is_null | (parse_ok & (parsed >= definition.min_value))
                if definition.max_value is not None:
                    ok &= is_null | (parse_ok & (parsed <= definition.max_value))
                cast_col = Column(
                    definition.name, ColumnType.LONG, parsed, col.valid & parse_ok
                )
            elif isinstance(definition, DecimalColumnDefinition):
                values, valid = col.numeric_values()
                # Spark's cast to Decimal(precision, scale) rounds HALF_UP
                # to `scale`, then marks rows whose integral part exceeds
                # precision-scale digits as invalid
                # (reference: schema/RowLevelSchemaValidator.scala:209-214)
                rounded = _round_half_up(col, values, valid, definition.scale)
                int_digits = definition.precision - definition.scale
                fits = valid & (np.abs(rounded) < 10.0 ** int_digits)
                ok &= is_null | fits
                cast_col = Column(definition.name, ColumnType.DECIMAL,
                                  np.where(fits, rounded, 0.0), fits)
            elif isinstance(definition, TimestampColumnDefinition):
                parsed, parse_ok = _parse_timestamps(col, definition.mask)
                ok &= is_null | parse_ok
                cast_col = Column(
                    definition.name, ColumnType.TIMESTAMP, parsed, col.valid & parse_ok
                )
            else:
                cast_col = col

            if not definition.is_nullable:
                ok &= ~is_null
            cnf &= ok
            casts.append(cast_col)

        extra_columns = [
            data.column(name)
            for name in data.column_names
            if name not in {d.name for d in schema.column_definitions}
        ]
        cast_table = Table(casts + extra_columns)

        valid_rows = cast_table.filter(cnf)
        invalid_rows = data.filter(~cnf)
        return RowLevelSchemaValidationResult(
            valid_rows, valid_rows.num_rows, invalid_rows, invalid_rows.num_rows
        )


def _round_half_up(col: Column, values: np.ndarray, valid: np.ndarray,
                   scale: int) -> np.ndarray:
    """HALF_UP rounding to `scale`, matching java.math.BigDecimal: the
    vectorized float path decides all rows except those whose scaled
    fraction sits within float error of an exact half — those few are
    re-rounded exactly with decimal.Decimal over the source text (e.g.
    "9.995" is 9.994999…8 as a double, but BigDecimal("9.995") at scale 2
    rounds HALF_UP to 10.00)."""
    from decimal import ROUND_HALF_UP, Decimal, InvalidOperation

    factor = 10.0 ** scale
    scaled = np.abs(values) * factor
    rounded = np.sign(values) * np.floor(scaled + 0.5) / factor
    near_half = valid & (np.abs(np.abs(scaled - np.floor(scaled)) - 0.5) < 1e-6)
    if near_half.any():
        quantum = Decimal(1).scaleb(-scale)
        for i in np.nonzero(near_half)[0]:
            try:
                exact = Decimal(str(col.values[i]).strip())
            except InvalidOperation:
                continue  # unparseable as decimal text: float verdict stands
            rounded[i] = float(exact.quantize(quantum, rounding=ROUND_HALF_UP))
    return rounded


# Spark's integer cast accepts only an optional sign + decimal digits;
# Python's int() is looser (underscore separators, unicode digits), so
# pre-validate with the strict form.
_STRICT_INT_RE = re.compile(r"^[+-]?[0-9]+$")


def _parse_ints(col: Column):
    n = len(col)
    parsed = np.zeros(n, dtype=np.int64)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        if not col.valid[i]:
            continue
        s = str(col.values[i]).strip()
        if not _STRICT_INT_RE.match(s):
            continue
        try:
            parsed[i] = int(s)
            ok[i] = True
        except (TypeError, ValueError, OverflowError):
            pass
    return parsed, ok


def _parse_timestamps(col: Column, mask: str):
    from datetime import datetime

    fmt = _java_mask_to_strptime(mask)
    n = len(col)
    parsed = np.zeros(n, dtype="datetime64[us]")
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        if not col.valid[i]:
            continue
        try:
            parsed[i] = np.datetime64(datetime.strptime(str(col.values[i]), fmt), "us")
            ok[i] = True
        except (TypeError, ValueError):
            pass
    return parsed, ok

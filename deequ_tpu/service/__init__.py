"""Fleet-scale DQ service: run many tenants' suites on one bounded
worker pool without letting any of them hurt the others.

The package is the "data quality as a service" layer from ISSUE 14:

  * `admission`  — EXPLAIN-first admission control (DQ410/411/413);
  * `quotas`     — per-tenant budgets + the sliding scan-bytes ledger;
  * `breaker`    — per-(tenant, dataset) circuit breakers;
  * `service`    — the `DQService` pool: tiered queues, preemptive
                   scheduling (interactive bumps heavy at partition
                   boundaries), shed-on-overload, graceful drain;
  * `telemetry`  — `engine.service.*` counters the sentinel watches;
  * `codes`      — the DQ41x submission-outcome codes.
"""

from .admission import AdmissionController, AdmissionDecision
from .breaker import BreakerBoard
from .codes import (
    CODE_MEANINGS,
    DQ_BREAKER_OPEN,
    DQ_DRAINED,
    DQ_QUOTA_EXCEEDED,
    DQ_REJECTED,
    DQ_SHED,
)
from .quotas import DEFAULT_QUOTA, QuotaLedger, TenantQuota
from .service import DEFAULT_QUEUE_LIMITS, TIERS, DQService, SubmissionHandle
from .telemetry import ServiceTelemetry

__all__ = [
    "CODE_MEANINGS",
    "DEFAULT_QUEUE_LIMITS",
    "DEFAULT_QUOTA",
    "DQ_BREAKER_OPEN",
    "DQ_DRAINED",
    "DQ_QUOTA_EXCEEDED",
    "DQ_REJECTED",
    "DQ_SHED",
    "TIERS",
    "AdmissionController",
    "AdmissionDecision",
    "BreakerBoard",
    "DQService",
    "QuotaLedger",
    "ServiceTelemetry",
    "SubmissionHandle",
    "TenantQuota",
]

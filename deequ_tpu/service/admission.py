"""EXPLAIN-first admission control.

Every submission is cost-analyzed *before* any kernel dispatch: the
same ``explain_plan`` that powers the CLI EXPLAIN runs over the
submission's schema and checks, and its ``PlanCost`` decides the
scheduling tier (interactive / batch / heavy) and whether the
submission can be admitted at all. The gates, in order:

  1. EXPLAIN itself failed, or produced the DQ319 never-admittable
     lint (the plan predicts more scan bytes than the tenant's whole
     quota window) -> DQ410 rejected at admission;
  2. the tenant is at its pending-run budget, or its state-repository
     disk budget is already blown -> DQ411 quota exceeded;
  3. the (tenant, dataset) circuit breaker denies entry -> DQ413.

The breaker check runs LAST so a HALF_OPEN probe slot is never
consumed by a submission that would have been rejected anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

from ..lint.cost import PlanCost
from ..lint.explain import explain_plan
from ..testing import faults
from .breaker import BreakerBoard
from .codes import DQ_BREAKER_OPEN, DQ_QUOTA_EXCEEDED, DQ_REJECTED
from .quotas import QuotaLedger

# the EXPLAIN lint that proves a plan can never fit the quota window
_NEVER_ADMITTABLE_CODE = "DQ319"


@dataclass(frozen=True)
class AdmissionDecision:
    admitted: bool
    #: scheduling tier when admitted (interactive | batch | heavy)
    tier: Optional[str] = None
    #: DQ41x rejection code when not admitted
    code: Optional[str] = None
    reason: str = ""
    cost: Optional[PlanCost] = None


class AdmissionController:
    """Stateless decision logic over the ledger and breaker board."""

    def __init__(self, ledger: QuotaLedger, breakers: BreakerBoard) -> None:
        self._ledger = ledger
        self._breakers = breakers

    def evaluate(
        self,
        tenant: str,
        dataset: str,
        data: Any,
        checks: Sequence[Any],
        analyzers: Sequence[Any],
        *,
        pending_count: int,
        state_disk_usage: Optional[int] = None,
    ) -> AdmissionDecision:
        faults.fault_point("service.admission")
        quota = self._ledger.quota(tenant)

        # gate 1 — EXPLAIN-first: cost the plan before any dispatch
        try:
            report = explain_plan(
                data,
                analyzers=analyzers,
                checks=checks,
                quota_scan_bytes=quota.scan_bytes_per_window,
            )
        except Exception as exc:  # noqa: BLE001 — contain: reject, don't crash the pool
            return AdmissionDecision(
                admitted=False,
                code=DQ_REJECTED,
                reason=f"EXPLAIN failed at admission: {exc}",
            )
        cost = report.cost
        for diag in report.diagnostics:
            if diag.code == _NEVER_ADMITTABLE_CODE:
                return AdmissionDecision(
                    admitted=False,
                    code=DQ_REJECTED,
                    reason=f"never admittable: {diag.message}",
                    cost=cost,
                )

        return self.decide(
            tenant,
            dataset,
            cost,
            pending_count=pending_count,
            state_disk_usage=state_disk_usage,
        )

    def decide(
        self,
        tenant: str,
        dataset: str,
        cost: Optional[PlanCost],
        *,
        pending_count: int,
        state_disk_usage: Optional[int] = None,
    ) -> AdmissionDecision:
        """Gates 2-3 + tier classification over an already-computed
        `PlanCost` — the entry point for submissions that cost
        themselves (window queries cost their own merge tree via
        `WindowQuery.admission_cost`; `evaluate` delegates here after
        its EXPLAIN gate)."""
        quota = self._ledger.quota(tenant)

        # gate 2 — tenant budgets that are knowable before running
        if pending_count >= quota.max_pending:
            return AdmissionDecision(
                admitted=False,
                code=DQ_QUOTA_EXCEEDED,
                reason=(
                    f"tenant {tenant!r} at max_pending="
                    f"{quota.max_pending} runs"
                ),
                cost=cost,
            )
        if (
            quota.state_disk_bytes is not None
            and state_disk_usage is not None
            and state_disk_usage > quota.state_disk_bytes
        ):
            return AdmissionDecision(
                admitted=False,
                code=DQ_QUOTA_EXCEEDED,
                reason=(
                    f"tenant {tenant!r} state repository holds "
                    f"{state_disk_usage} bytes, budget "
                    f"{quota.state_disk_bytes}"
                ),
                cost=cost,
            )

        # gate 3 — breaker last, so probes aren't wasted on rejects
        if not self._breakers.allow(tenant, dataset):
            return AdmissionDecision(
                admitted=False,
                code=DQ_BREAKER_OPEN,
                reason=(
                    f"circuit breaker open for ({tenant!r}, {dataset!r})"
                ),
                cost=cost,
            )

        tier = (cost.admission_tier if cost is not None else None) or "batch"
        return AdmissionDecision(admitted=True, tier=tier, cost=cost)


__all__ = ["AdmissionController", "AdmissionDecision"]

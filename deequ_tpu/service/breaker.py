"""Per-(tenant, dataset) circuit breakers.

A dataset that keeps corrupting its runs (bad parquet, schema drift, a
flaky filesystem) must not be allowed to burn pool capacity forever —
and, just as importantly, its failures must not widen into other
tenants' error budgets. Each (tenant, dataset) pair gets a classic
three-state breaker:

  CLOSED    — healthy; failures are counted, ``threshold`` consecutive
              ones trip the breaker OPEN.
  OPEN      — submissions are rejected (DQ413) until ``cooldown_s``
              elapses, then the breaker moves to HALF_OPEN.
  HALF_OPEN — exactly one probe submission is admitted; success closes
              the breaker, failure re-opens it with a fresh cooldown.

A probe that ends for a *neutral* reason (preempted, drained — the run
said nothing about the dataset's health) releases the probe slot and
stays HALF_OPEN so the next submission probes again.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Tuple

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class _Breaker:
    __slots__ = ("state", "failures", "opened_at", "probing")

    def __init__(self) -> None:
        self.state = CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.probing = False


class BreakerBoard:
    """Thread-safe registry of per-(tenant, dataset) circuit breakers."""

    def __init__(
        self,
        *,
        threshold: int = 3,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._lock = threading.Lock()
        self._threshold = max(1, int(threshold))
        self._cooldown_s = float(cooldown_s)
        self._clock = clock
        self._breakers: Dict[Tuple[str, str], _Breaker] = {}
        self._transitions = 0

    def _get_locked(self, tenant: str, dataset: str) -> _Breaker:
        return self._breakers.setdefault((tenant, dataset), _Breaker())

    def allow(self, tenant: str, dataset: str) -> bool:
        """Whether a submission for this pair may enter the pool now.

        Lazily transitions OPEN -> HALF_OPEN after the cooldown and, in
        HALF_OPEN, grants exactly one in-flight probe.
        """
        with self._lock:
            b = self._get_locked(tenant, dataset)
            if b.state == CLOSED:
                return True
            if b.state == OPEN:
                if self._clock() - b.opened_at < self._cooldown_s:
                    return False
                b.state = HALF_OPEN
                b.probing = False
                self._transitions += 1
            # HALF_OPEN: one probe at a time
            if b.probing:
                return False
            b.probing = True
            return True

    def open_now(self, tenant: str, dataset: str) -> bool:
        """True while the pair is OPEN inside its cooldown — a pure
        read, unlike ``allow()``, so callers can fail fast before doing
        any per-submission work without consuming a half-open probe."""
        with self._lock:
            b = self._get_locked(tenant, dataset)
            return (
                b.state == OPEN
                and self._clock() - b.opened_at < self._cooldown_s
            )

    def record_success(self, tenant: str, dataset: str) -> None:
        with self._lock:
            b = self._get_locked(tenant, dataset)
            if b.state != CLOSED:
                self._transitions += 1
            b.state = CLOSED
            b.failures = 0
            b.probing = False

    def record_failure(self, tenant: str, dataset: str) -> None:
        with self._lock:
            b = self._get_locked(tenant, dataset)
            if b.state == HALF_OPEN:
                b.state = OPEN
                b.opened_at = self._clock()
                b.probing = False
                self._transitions += 1
                return
            b.failures += 1
            if b.state == CLOSED and b.failures >= self._threshold:
                b.state = OPEN
                b.opened_at = self._clock()
                self._transitions += 1

    def record_neutral(self, tenant: str, dataset: str) -> None:
        """The run ended without saying anything about dataset health
        (preempted / drained): release the probe slot, keep the state."""
        with self._lock:
            b = self._get_locked(tenant, dataset)
            b.probing = False

    def state(self, tenant: str, dataset: str) -> str:
        with self._lock:
            return self._get_locked(tenant, dataset).state

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for b in self._breakers.values() if b.state == OPEN)

    def transitions(self) -> int:
        with self._lock:
            return self._transitions

    def pairs(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._breakers)


__all__ = ["CLOSED", "HALF_OPEN", "OPEN", "BreakerBoard"]

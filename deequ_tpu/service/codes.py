"""The service-level DQ4xx codes (ISSUE 14).

The runtime taxonomy splits in two: `core/controller.py` owns the
codes a RUN ends with (DQ401-DQ407 — cancelled, deadline, stalled,
preempted, quota-at-boundary, drain), while this module owns the codes
a SUBMISSION is turned away with before or instead of running:

  * DQ410 — rejected at admission: the EXPLAIN-first gate proved the
    submission should never reach a worker (the plan can never fit the
    tenant's quota window — the DQ319 lint — or admission itself
    failed);
  * DQ411 — quota exceeded at admission: the tenant is at its
    concurrent/pending-run budget or its state-repository disk budget
    (the mid-run variant, tripped at a partition boundary, is the
    controller's DQ406);
  * DQ412 — shed on overload: the tier queue was saturated and this
    submission (or the queued one it displaced) lost the
    priority/deadline comparison, or its deadline expired while
    queued;
  * DQ413 — circuit breaker open: the (tenant, dataset) pair has
    repeatedly failed its runs and is fenced off from the pool until
    the cooldown's half-open probe succeeds;
  * DQ414 — drained: the service was asked to shut down (SIGTERM /
    close()) and returned this queued submission unrun; resubmit after
    restart — any partition states earlier attempts committed still
    resume.
"""

from __future__ import annotations

DQ_REJECTED = "DQ410"
DQ_QUOTA_EXCEEDED = "DQ411"
DQ_SHED = "DQ412"
DQ_BREAKER_OPEN = "DQ413"
DQ_DRAINED = "DQ414"

#: code -> one-line meaning, for operator-facing rendering
CODE_MEANINGS = {
    DQ_REJECTED: "rejected at admission (EXPLAIN-first gate)",
    DQ_QUOTA_EXCEEDED: "tenant quota exceeded at admission",
    DQ_SHED: "shed on overload (priority/deadline)",
    DQ_BREAKER_OPEN: "circuit breaker open for (tenant, dataset)",
    DQ_DRAINED: "returned unrun by a graceful drain",
}

__all__ = [
    "CODE_MEANINGS",
    "DQ_BREAKER_OPEN",
    "DQ_DRAINED",
    "DQ_QUOTA_EXCEEDED",
    "DQ_REJECTED",
    "DQ_SHED",
]

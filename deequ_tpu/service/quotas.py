"""Tenant quotas and the sliding-window scan ledger.

A tenant's quota bounds three resources:

  * concurrency — how many of its runs may occupy workers at once
    (``max_concurrent``) and how many may exist in the service at all,
    running or queued (``max_pending``);
  * scan bytes — how many predicted-scan bytes it may consume inside a
    sliding window (``scan_bytes_per_window`` over ``window_s``
    seconds), charged at admission time and re-charged per partition
    at run boundaries so a long heavy profile cannot outrun its budget;
  * state disk — how many bytes its committed partition states may
    occupy in the state repository (``state_disk_bytes``), checked at
    admission and at every partition boundary.

The ledger is intentionally a plain sliding window rather than a token
bucket: charges are timestamped and expire, so a tenant that bursts is
throttled for exactly one window and then whole again — matching the
"degrade, don't destroy" posture of the rest of the runtime.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class TenantQuota:
    """Resource budget for one tenant. ``None`` fields are unmetered."""

    #: runs that may occupy workers simultaneously
    max_concurrent: int = 2
    #: runs that may exist in the service at all (running + queued)
    max_pending: int = 16
    #: predicted-scan bytes admitted inside one sliding window
    scan_bytes_per_window: Optional[float] = None
    #: width of the scan-bytes window, in seconds
    window_s: float = 60.0
    #: bytes the tenant's committed states may occupy in the state repo
    state_disk_bytes: Optional[int] = None


DEFAULT_QUOTA = TenantQuota()


class QuotaLedger:
    """Thread-safe per-tenant scan-bytes ledger with a sliding window.

    All clock reads go through the injected ``clock`` so tests (and the
    chaos harness) can drive time deterministically.
    """

    def __init__(
        self,
        quotas: Optional[Dict[str, TenantQuota]] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._lock = threading.Lock()
        self._quotas: Dict[str, TenantQuota] = dict(quotas or {})
        self._clock = clock
        # tenant -> deque of (charged_at, nbytes); pruned lazily
        self._charges: Dict[str, Deque[Tuple[float, float]]] = {}
        # lifetime totals survive window pruning, for telemetry
        self._totals: Dict[str, float] = {}

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        with self._lock:
            self._quotas[tenant] = quota

    def quota(self, tenant: str) -> TenantQuota:
        with self._lock:
            return self._quotas.get(tenant, DEFAULT_QUOTA)

    def charge_scan(self, tenant: str, nbytes: float) -> None:
        """Record ``nbytes`` of scan against the tenant's window."""
        if nbytes <= 0:
            return
        now = self._clock()
        with self._lock:
            self._charges.setdefault(tenant, deque()).append((now, float(nbytes)))
            self._totals[tenant] = self._totals.get(tenant, 0.0) + float(nbytes)

    def _prune_locked(self, tenant: str, now: float) -> Deque[Tuple[float, float]]:
        window = self._quotas.get(tenant, DEFAULT_QUOTA).window_s
        charges = self._charges.setdefault(tenant, deque())
        while charges and now - charges[0][0] > window:
            charges.popleft()
        return charges

    def bytes_in_window(self, tenant: str) -> float:
        now = self._clock()
        with self._lock:
            return sum(n for _, n in self._prune_locked(tenant, now))

    def scan_headroom(self, tenant: str) -> Optional[float]:
        """Remaining window budget; negative when overdrawn, None if unmetered."""
        quota = self.quota(tenant)
        if quota.scan_bytes_per_window is None:
            return None
        return quota.scan_bytes_per_window - self.bytes_in_window(tenant)

    def over_scan_budget(self, tenant: str) -> bool:
        headroom = self.scan_headroom(tenant)
        return headroom is not None and headroom < 0

    def bytes_total(self, tenant: str) -> float:
        with self._lock:
            return self._totals.get(tenant, 0.0)

    def tenants(self) -> List[str]:
        with self._lock:
            seen = set(self._quotas) | set(self._totals)
            return sorted(seen)


__all__ = ["DEFAULT_QUOTA", "QuotaLedger", "TenantQuota"]

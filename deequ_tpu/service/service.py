"""The long-lived DQ service: a bounded worker pool with admission
control, tiered queues, preemptive scheduling, and graceful drain.

Life of a submission
--------------------
``submit()`` EXPLAINs the plan first (admission.py): rejected work
never touches a worker. Admitted work lands in its tier's bounded
FIFO (interactive / batch / heavy); a saturated queue sheds by
priority-then-deadline-slack (DQ412). Workers pop interactive before
batch before heavy, skipping tenants at their concurrency cap. An
interactive arrival with no idle worker soft-cancels one running
heavy run (``RunController.cancel_at_boundary("preempted")``, DQ405):
the heavy run's in-flight partition still commits its state, the run
re-queues at the head of its tier, and its retry scans only the
remaining partitions — bit-identical to an uninterrupted run, because
partition states are a mergeable semigroup.

Quota enforcement is two-phase: admission rejects what can never fit
(DQ410/DQ411/DQ413), and a per-partition boundary probe charges
actual progress against the tenant's sliding scan-bytes window and
disk budget, stopping overdrawn runs with DQ406 *after* the partition
commits — the tenant loses headroom, not progress.

Drain (``drain()`` / SIGTERM) stops intake, returns queued work
DQ414, soft-cancels running work (DQ407 — in-flight partitions
commit), then joins every worker and the scheduler. ``close()`` is
idempotent and the service is a context manager.
"""

from __future__ import annotations

import itertools
import signal
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from ..core.controller import RunCancelled, RunController
from ..ops import runtime
from ..testing import faults
from .admission import AdmissionController
from .breaker import BreakerBoard
from .codes import DQ_BREAKER_OPEN, DQ_DRAINED, DQ_REJECTED, DQ_SHED
from .quotas import QuotaLedger, TenantQuota
from ..observe.heartbeat import publish_event
from .telemetry import ServiceTelemetry
from .telemetry import publish as publish_telemetry

TIERS = ("interactive", "batch", "heavy")

DEFAULT_QUEUE_LIMITS = {"interactive": 16, "batch": 16, "heavy": 8}


class SubmissionHandle:
    """The caller's view of a submission: status, code, and result.

    ``status`` moves through submitted -> queued -> running -> one of
    done | failed | rejected | shed | drained | cancelled | quota.
    A preempted run goes back to queued; only terminal states set the
    event ``wait()`` blocks on.
    """

    def __init__(self, tenant: str, dataset: str) -> None:
        self.tenant = tenant
        self.dataset = dataset
        self.status = "submitted"
        self.code: Optional[str] = None
        self.reason = ""
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.tier: Optional[str] = None
        self.cost: Any = None
        self.attempts = 0
        self.preemptions = 0
        # scan-sharing outcome: None when the run never met a share
        # group, else {"shared": bool, ...} with the subsumption proof
        # and its post-execution drift pin (all-zero on a sound share)
        # or the prover's decline reason
        self.sharing: Optional[Dict[str, Any]] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        code = f" {self.code}" if self.code else ""
        return (
            f"<SubmissionHandle {self.tenant}/{self.dataset} "
            f"{self.status}{code}>"
        )


class _Submission:
    """Internal ledger entry for one unit of queued/running work."""

    __slots__ = (
        "tenant", "dataset", "data", "checks", "analyzers", "priority",
        "deadline_s", "submitted_at", "handle", "tier", "cost",
        "controller", "seq", "counted", "engine", "fingerprint",
    )

    def __init__(
        self,
        tenant: str,
        dataset: str,
        data: Any,
        checks: Sequence[Any],
        analyzers: Sequence[Any],
        priority: int,
        deadline_s: Optional[float],
        submitted_at: float,
        handle: SubmissionHandle,
        tier: str,
        cost: Any,
        seq: int,
        engine: str = "single",
    ) -> None:
        self.tenant = tenant
        self.dataset = dataset
        self.data = data
        self.checks = list(checks)
        self.analyzers = list(analyzers)
        self.priority = priority
        self.deadline_s = deadline_s
        self.submitted_at = submitted_at
        self.handle = handle
        self.tier = tier
        self.cost = cost
        self.controller: Optional[RunController] = None
        self.seq = seq
        self.engine = engine
        # content-based dataset identity for scan sharing; None means
        # "cannot prove same data" and the run always scans solo
        self.fingerprint: Optional[str] = None
        # whether this submission currently counts against the
        # tenant's pending budget (decremented exactly once)
        self.counted = True


class DQService:
    """A fleet-scale data-quality service over a bounded worker pool."""

    def __init__(
        self,
        workers: Optional[int] = None,
        *,
        state_repository: Any = None,
        metrics_repository: Any = None,
        quotas: Optional[Dict[str, TenantQuota]] = None,
        queue_limits: Optional[Dict[str, int]] = None,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        tick_s: float = 0.05,
        name: str = "dq",
    ) -> None:
        self._workers_n = workers if workers is not None else runtime.service_workers()
        self._state_repository = state_repository
        self._metrics_repository = metrics_repository
        self._clock = clock
        self._tick_s = float(tick_s)
        self._name = name

        self.ledger = QuotaLedger(quotas, clock=clock)
        self.breakers = BreakerBoard(
            threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            clock=clock,
        )
        self.telemetry = ServiceTelemetry()
        self._admission = AdmissionController(self.ledger, self.breakers)

        self._cv = threading.Condition()
        self._queues: Dict[str, Deque[_Submission]] = {t: deque() for t in TIERS}
        self._queue_limits = dict(DEFAULT_QUEUE_LIMITS)
        if queue_limits:
            self._queue_limits.update(queue_limits)
        self._running: List[_Submission] = []
        self._pending: Dict[str, int] = {}
        self._seq = itertools.count()
        self._accepting = True
        self._stopping = False
        self._stop_event = threading.Event()
        self._prev_sigterm: Any = None

        self._threads: List[threading.Thread] = []
        for i in range(self._workers_n):
            t = threading.Thread(
                target=self._worker_loop,
                name=f"deequ-{name}-service-worker-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._scheduler = threading.Thread(
            target=self._scheduler_loop,
            name=f"deequ-{name}-service-scheduler",
            daemon=True,
        )
        self._scheduler.start()

    # ------------------------------------------------------------------
    # intake

    def submit(
        self,
        tenant: str,
        dataset: str,
        data: Any,
        *,
        checks: Sequence[Any] = (),
        analyzers: Sequence[Any] = (),
        priority: int = 0,
        deadline_s: Optional[float] = None,
        engine: str = "single",
    ) -> SubmissionHandle:
        """Submit a suite; returns immediately with a handle.

        ``data`` is the table to verify, or a zero-arg callable
        returning it (a factory defers any expensive open to the
        worker; admission then costs the plan from the factory's
        result, so prefer cheap lazily-opening tables).
        """
        handle = SubmissionHandle(tenant, dataset)
        handle.attempts = 0
        self.telemetry.count("submitted")

        with self._cv:
            if not self._accepting:
                return self._finalize_locked_handle(
                    handle, "drained", DQ_DRAINED,
                    "service is draining; resubmit after restart",
                )
            pending = self._pending.get(tenant, 0)

        # fail fast on an open breaker before the factory runs: no
        # per-submission work for a fenced-off (tenant, dataset), and
        # no half-open probe consumed (open_now is a pure read)
        if self.breakers.open_now(tenant, dataset):
            self.telemetry.count("rejected")
            return self._finalize_locked_handle(
                handle, "rejected", DQ_BREAKER_OPEN,
                f"circuit breaker open for ({tenant!r}, {dataset!r})",
            )

        try:
            table = data() if callable(data) else data
        except Exception as exc:  # noqa: BLE001 — containment: a bad
            # dataset factory (corrupt file, dead mount) is the
            # dataset's failure, not the service's: feed the breaker
            self.breakers.record_failure(tenant, dataset)
            self.telemetry.count("failed")
            handle.error = exc
            return self._finalize_locked_handle(
                handle, "failed", None,
                f"dataset open failed: {type(exc).__name__}: {exc}",
            )
        try:
            decision = self._admission.evaluate(
                tenant,
                dataset,
                table,
                checks,
                analyzers,
                pending_count=pending,
                state_disk_usage=self._state_disk_usage(tenant, dataset),
            )
        except faults.InjectedFaultError as exc:
            # containment: admission bookkeeping died, the pool did not
            self.telemetry.count("admission_faults")
            return self._finalize_locked_handle(
                handle, "rejected", DQ_REJECTED,
                f"admission unavailable: {exc}",
            )
        if not decision.admitted:
            self.telemetry.count("rejected")
            handle.cost = decision.cost
            status = "rejected"
            publish_event(
                "service.rejected",
                tenant=tenant, dataset=dataset, code=decision.code,
            )
            return self._finalize_locked_handle(
                handle, status, decision.code, decision.reason,
            )

        self.telemetry.count("admitted")
        tier = decision.tier or "batch"
        handle.tier = tier
        handle.cost = decision.cost
        sub = _Submission(
            tenant, dataset, data, checks, analyzers, priority,
            deadline_s, self._clock(), handle, tier, decision.cost,
            next(self._seq), engine,
        )
        if engine == "single" and runtime.scan_sharing_enabled():
            from .sharing import dataset_fingerprint

            try:
                sub.fingerprint = dataset_fingerprint(data, table)
            except Exception:  # fault-ok: no identity = no sharing
                sub.fingerprint = None
        with self._cv:
            if not self._accepting:
                return self._finalize_locked_handle(
                    handle, "drained", DQ_DRAINED,
                    "service began draining during admission",
                )
            if not self._enqueue_locked(sub):
                return handle  # shed; handle already finalized
            self._pending[tenant] = self._pending.get(tenant, 0) + 1
            handle.status = "queued"
            if tier == "interactive":
                self._maybe_preempt_locked()
            self._cv.notify_all()
        return handle

    def submit_window(
        self,
        tenant: str,
        dataset: str,
        source: Any,
        *,
        window: Any,
        analyzers: Sequence[Any],
        priority: int = 0,
        deadline_s: Optional[float] = None,
        extractor: Any = None,
        warm: bool = True,
    ) -> SubmissionHandle:
        """Submit a windowed metrics query (windows/query.py) as an
        ordinary admission-costed submission. The plan costs itself via
        `WindowQuery.admission_cost` — on warm segments the predicted
        scan bytes are near zero, so a per-ingest-tick windowed suite
        admits as 'interactive' and never competes with real scans.
        The handle's result is the window's `AnalyzerContext` (with
        `window_plan` attached)."""
        from ..windows.query import WindowQuery

        handle = SubmissionHandle(tenant, dataset)
        handle.attempts = 0
        self.telemetry.count("submitted")

        with self._cv:
            if not self._accepting:
                return self._finalize_locked_handle(
                    handle, "drained", DQ_DRAINED,
                    "service is draining; resubmit after restart",
                )
            pending = self._pending.get(tenant, 0)

        if self._state_repository is None:
            self.telemetry.count("rejected")
            return self._finalize_locked_handle(
                handle, "rejected", DQ_REJECTED,
                "window submissions need a state repository "
                "(the merge tree resolves against cached states)",
            )
        if self.breakers.open_now(tenant, dataset):
            self.telemetry.count("rejected")
            return self._finalize_locked_handle(
                handle, "rejected", DQ_BREAKER_OPEN,
                f"circuit breaker open for ({tenant!r}, {dataset!r})",
            )

        try:
            src = source() if callable(source) else source
            query = WindowQuery(
                src,
                list(analyzers),
                repository=self._state_repository,
                dataset=self._state_dataset(tenant, dataset),
                extractor=extractor,
            )
            cost = query.admission_cost(window)
        except Exception as exc:  # noqa: BLE001 — containment: a bad
            # source or spec is the submission's failure, not the pool's
            self.breakers.record_failure(tenant, dataset)
            self.telemetry.count("failed")
            handle.error = exc
            return self._finalize_locked_handle(
                handle, "failed", None,
                f"window plan failed: {type(exc).__name__}: {exc}",
            )
        try:
            decision = self._admission.decide(
                tenant,
                dataset,
                cost,
                pending_count=pending,
                state_disk_usage=self._state_disk_usage(tenant, dataset),
            )
        except faults.InjectedFaultError as exc:
            self.telemetry.count("admission_faults")
            return self._finalize_locked_handle(
                handle, "rejected", DQ_REJECTED,
                f"admission unavailable: {exc}",
            )
        if not decision.admitted:
            self.telemetry.count("rejected")
            handle.cost = decision.cost
            publish_event(
                "service.rejected",
                tenant=tenant, dataset=dataset, code=decision.code,
            )
            return self._finalize_locked_handle(
                handle, "rejected", decision.code, decision.reason,
            )

        self.telemetry.count("admitted")
        tier = decision.tier or "batch"
        handle.tier = tier
        handle.cost = decision.cost

        def run_window():
            return query.run(window, warm=warm, tracing=True)

        sub = _Submission(
            tenant, dataset, run_window, (), tuple(analyzers), priority,
            deadline_s, self._clock(), handle, tier, decision.cost,
            next(self._seq), "window",
        )
        with self._cv:
            if not self._accepting:
                return self._finalize_locked_handle(
                    handle, "drained", DQ_DRAINED,
                    "service began draining during admission",
                )
            if not self._enqueue_locked(sub):
                return handle  # shed; handle already finalized
            self._pending[tenant] = self._pending.get(tenant, 0) + 1
            handle.status = "queued"
            if tier == "interactive":
                self._maybe_preempt_locked()
            self._cv.notify_all()
        return handle

    def _enqueue_locked(self, sub: _Submission) -> bool:
        """FIFO enqueue with shed-on-overload. Returns False when the
        new submission itself was shed."""
        q = self._queues[sub.tier]
        if len(q) < self._queue_limits[sub.tier]:
            q.append(sub)
            return True
        # saturated: compare against the worst queued item by
        # (priority, deadline slack) — bigger key = more worth keeping
        now = self._clock()

        def keep_key(s: _Submission) -> Tuple[int, float]:
            if s.deadline_s is None:
                slack = float("inf")
            else:
                slack = (s.submitted_at + s.deadline_s) - now
            # prefer high priority; break ties by LESS slack (closer
            # deadline = more urgent = more worth keeping)
            return (s.priority, -slack)

        worst = min(q, key=keep_key)
        if keep_key(sub) > keep_key(worst):
            q.remove(worst)
            self._shed_locked(worst, "displaced by higher-priority work")
            q.append(sub)
            return True
        self.telemetry.count("shed")
        self._finalize_locked_handle(
            sub.handle, "shed", DQ_SHED,
            f"{sub.tier} queue saturated "
            f"({self._queue_limits[sub.tier]} deep)",
        )
        return False

    def _shed_locked(self, sub: _Submission, why: str) -> None:
        self.telemetry.count("shed")
        self._decrement_pending_locked(sub)
        publish_event(
            "service.shed", tenant=sub.tenant, dataset=sub.dataset, why=why,
        )
        self._finalize_locked_handle(sub.handle, "shed", DQ_SHED, why)

    def _finalize_locked_handle(
        self,
        handle: SubmissionHandle,
        status: str,
        code: Optional[str],
        reason: str,
    ) -> SubmissionHandle:
        handle.status = status
        handle.code = code
        handle.reason = reason
        handle._done.set()
        return handle

    def _decrement_pending_locked(self, sub: _Submission) -> None:
        if not sub.counted:
            return
        sub.counted = False
        n = self._pending.get(sub.tenant, 0)
        if n <= 1:
            self._pending.pop(sub.tenant, None)
        else:
            self._pending[sub.tenant] = n - 1

    def _state_disk_usage(self, tenant: str, dataset: str) -> Optional[int]:
        if self._state_repository is None:
            return None
        try:
            return self._state_repository.disk_usage(self._state_dataset(tenant, dataset))
        except OSError:  # fault-ok: unknowable usage never blocks admission
            return None

    @staticmethod
    def _state_dataset(tenant: str, dataset: str) -> str:
        return f"{tenant}/{dataset}"

    # ------------------------------------------------------------------
    # scheduling

    def _pop_next_locked(self) -> Optional[_Submission]:
        for tier in TIERS:
            q = self._queues[tier]
            if not q:
                continue
            # highest priority first, FIFO within a priority; skip
            # tenants at their concurrency cap
            best: Optional[_Submission] = None
            for s in q:
                if self._tenant_running_locked(s.tenant) >= self.ledger.quota(
                    s.tenant
                ).max_concurrent:
                    continue
                if best is None or (s.priority, -s.seq) > (best.priority, -best.seq):
                    best = s
            if best is None:
                continue
            faults.fault_point("service.queue")
            q.remove(best)
            return best
        return None

    def _tenant_running_locked(self, tenant: str) -> int:
        return sum(1 for s in self._running if s.tenant == tenant)

    def _collect_share_group_locked(self, lead: _Submission) -> List[_Submission]:
        """Gather queued submissions provably over the SAME data as
        ``lead`` (matching dataset fingerprint) into one share group.
        Peers leave their queues and join ``_running`` immediately —
        the group occupies ONE worker and runs one superset scan.
        Tenant concurrency caps count group membership; the group size
        is bounded by DEEQU_TPU_SHARE_GROUP_MAX."""
        if (
            lead.fingerprint is None
            or lead.engine != "single"
            or not runtime.scan_sharing_enabled()
        ):
            return [lead]
        group = [lead]
        limit = runtime.share_group_max()
        for tier in TIERS:
            if len(group) >= limit:
                break
            q = self._queues[tier]
            for s in list(q):
                if len(group) >= limit:
                    break
                if s.fingerprint != lead.fingerprint or s.engine != "single":
                    continue
                # group members already joined _running, so the usual
                # concurrency check naturally counts them
                if self._tenant_running_locked(s.tenant) >= self.ledger.quota(
                    s.tenant
                ).max_concurrent:
                    continue
                q.remove(s)
                self._running.append(s)
                s.handle.status = "running"
                group.append(s)
        return group

    def _maybe_preempt_locked(self) -> None:
        """An interactive arrival with no idle worker bumps one
        running heavy run (soft cancel — its partition commits)."""
        if not self._queues["interactive"]:
            return
        if len(self._running) < self._workers_n:
            return  # an idle worker will pick it up
        for s in self._running:
            if s.tier != "heavy" or s.controller is None:
                continue
            if s.controller.soft_cancelled or s.controller.cancelled:
                continue
            s.controller.cancel_at_boundary("preempted")
            publish_event(
                "service.preempt", tenant=s.tenant, dataset=s.dataset,
            )
            return

    def _scheduler_loop(self) -> None:
        while not self._stop_event.wait(self._tick_s):
            try:
                faults.fault_point("service.scheduler")
            except OSError:  # fault-ok: a raise-kind override loses one tick
                continue
            with self._cv:
                self._expire_queued_locked()
                self._maybe_preempt_locked()
                self._cv.notify_all()

    def _expire_queued_locked(self) -> None:
        now = self._clock()
        for tier in TIERS:
            q = self._queues[tier]
            expired = [
                s for s in q
                if s.deadline_s is not None
                and (s.submitted_at + s.deadline_s) <= now
            ]
            for s in expired:
                q.remove(s)
                self._shed_locked(s, "deadline expired while queued")

    # ------------------------------------------------------------------
    # workers

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                sub = None
                while sub is None:
                    if self._stopping:
                        return
                    try:
                        sub = self._pop_next_locked()
                    except faults.InjectedFaultError:
                        # containment: the pop failed, the queue item
                        # stays put; count it and retry on next wake
                        self.telemetry.count("queue_faults")
                        sub = None
                    if sub is None:
                        self._cv.wait(timeout=0.1)
                self._running.append(sub)
                sub.handle.status = "running"
                group = self._collect_share_group_locked(sub)
            try:
                if len(group) > 1:
                    self._execute_shared(group)
                else:
                    self._execute(sub)
            finally:
                with self._cv:
                    for s in group:
                        if s in self._running:
                            self._running.remove(s)
                    self._cv.notify_all()

    def _execute(self, sub: _Submission) -> None:
        # local import: verification pulls the whole runner stack;
        # keep service import light for tools that only want codes
        from ..verification.suite import VerificationSuite

        handle = sub.handle
        handle.attempts += 1
        remaining: Optional[float] = None
        if sub.deadline_s is not None:
            remaining = (sub.submitted_at + sub.deadline_s) - self._clock()
            if remaining <= 0:
                with self._cv:
                    self._shed_locked(sub, "deadline expired before start")
                return
        ctl = RunController(deadline_s=remaining)
        ctl.set_boundary_probe(self._boundary_probe(sub))
        with self._cv:
            sub.controller = ctl
        try:
            faults.fault_point("service.worker")
            if sub.engine == "window":
                # windowed query: the submission carries its own
                # executor closure (WindowQuery.run) — no suite, no
                # scan; zero data rows on warm segments
                result = sub.data()
                self.breakers.record_success(sub.tenant, sub.dataset)
                self.telemetry.count("completed")
                handle.result = result
                with self._cv:
                    self._decrement_pending_locked(sub)
                    self._finalize_locked_handle(handle, "done", None, "")
                return
            table = sub.data() if callable(sub.data) else sub.data
            builder = VerificationSuite().on_data(table).with_controller(ctl)
            for check in sub.checks:
                builder = builder.add_check(check)
            for analyzer in sub.analyzers:
                builder = builder.add_required_analyzer(analyzer)
            builder = builder.with_engine(sub.engine)
            if self._state_repository is not None:
                builder = builder.with_state_repository(
                    self._state_repository,
                    dataset=self._state_dataset(sub.tenant, sub.dataset),
                )
            result = builder.run()
        except RunCancelled as exc:
            self._on_cancelled(sub, exc)
            return
        except Exception as exc:  # noqa: BLE001 — containment: one bad
            # run (chaos fault, corrupt dataset, kernel error) must not
            # take the worker thread or the pool down with it
            self.breakers.record_failure(sub.tenant, sub.dataset)
            self.telemetry.count("failed")
            if isinstance(exc, faults.InjectedFaultError):
                self.telemetry.count("worker_faults")
            handle.error = exc
            with self._cv:
                self._decrement_pending_locked(sub)
                self._finalize_locked_handle(
                    handle, "failed", None, f"{type(exc).__name__}: {exc}",
                )
            publish_event(
                "service.failed", tenant=sub.tenant, dataset=sub.dataset,
            )
            return
        self.breakers.record_success(sub.tenant, sub.dataset)
        self.telemetry.count("completed")
        handle.result = result
        with self._cv:
            self._decrement_pending_locked(sub)
            self._finalize_locked_handle(handle, "done", None, "")

    # ------------------------------------------------------------------
    # shared scans (service/sharing.py)

    def _execute_shared(self, group: List[_Submission]) -> None:
        """Run one share group: prove every member's plan contained in
        the union plan, run ONE superset scan, and fan the folded
        states back out to each member's constraint evaluation.
        Members the prover declines fall back to solo runs on the same
        worker — sharing is an optimization, never a gate."""
        from . import sharing

        live: List[_Submission] = []
        for sub in group:
            if sub.deadline_s is not None and (
                (sub.submitted_at + sub.deadline_s) - self._clock() <= 0
            ):
                with self._cv:
                    self._shed_locked(sub, "deadline expired before start")
                continue
            live.append(sub)
        if not live:
            return

        participants: List[_Submission] = []
        proofs: List[Any] = []
        solo: List[_Submission] = []
        table = None
        if len(live) > 1:
            lead = live[0]
            try:
                table = lead.data() if callable(lead.data) else lead.data
                plans = [
                    sharing.submission_plan(s.checks, s.analyzers)
                    for s in live
                ]
                _union, group_proofs, declines = sharing.plan_share_group(
                    plans, table
                )
            except Exception:  # noqa: BLE001 — prover/broken open never
                # fails the work: everything just runs solo
                solo = live
            else:
                for sub, proof, decline in zip(live, group_proofs, declines):
                    if decline is None:
                        participants.append(sub)
                        proofs.append(proof)
                    else:
                        self.telemetry.count("sharing_declined")
                        sub.handle.sharing = {
                            "shared": False,
                            "reason": decline,
                        }
                        solo.append(sub)
                if len(participants) < 2:
                    solo = participants + solo
                    participants, proofs = [], []
        else:
            solo = live

        if participants:
            self._run_shared_scan(participants, proofs, table)
        for sub in solo:
            self._execute(sub)

    def _run_shared_scan(
        self,
        participants: List[_Submission],
        proofs: List[Any],
        table: Any,
    ) -> None:
        from ..runners.analysis_runner import AnalysisRunner
        from ..runners.context import AnalyzerContext
        from ..verification.suite import VerificationSuite
        from . import sharing

        self.telemetry.count("shared_scans")
        for _ in participants:
            self.telemetry.count("shared_participants")

        plans = [
            sharing.submission_plan(s.checks, s.analyzers)
            for s in participants
        ]
        union, _memberships = self._union_plan(plans)

        ctl = RunController()
        overdrawn: set = set()
        ctl.set_boundary_probe(
            self._shared_boundary_probe(participants, overdrawn)
        )
        with self._cv:
            for sub in participants:
                sub.controller = ctl
                sub.handle.attempts += 1

        fanout_repo = None
        if self._state_repository is not None:
            tenants = [
                sharing.TenantStatePlan(
                    self._state_dataset(s.tenant, s.dataset), plan, table
                )
                for s, plan in zip(participants, plans)
            ]
            fanout_repo = sharing.FanoutStateRepository(
                self._state_repository, tenants
            )

        captures = None
        forensics = None
        if runtime.forensics_enabled():
            from ..observe.forensics import ForensicsCapture

            captures = [ForensicsCapture(s.checks) for s in participants]
            forensics = sharing.ForensicsFanout(captures)

        try:
            faults.fault_point("service.worker")
            context = AnalysisRunner.do_analysis_run(
                table,
                union,
                engine="single",
                validation="off",
                state_repository=fanout_repo,
                dataset_name=sharing.shared_dataset_name(
                    participants[0].fingerprint or "anon"
                ),
                forensics=forensics,
                controller=ctl,
            )
        except RunCancelled as exc:
            # one scan, one fate: EVERY participant resumes (preempt /
            # drain re-queue) or finalizes with the same DQ4xx — never
            # a partial fan-out
            for sub in participants:
                self._on_cancelled(sub, exc)
            return
        except Exception as exc:  # noqa: BLE001 — containment, as solo
            self.telemetry.count("failed")
            if isinstance(exc, faults.InjectedFaultError):
                self.telemetry.count("worker_faults")
            for sub in participants:
                self.breakers.record_failure(sub.tenant, sub.dataset)
                sub.handle.error = exc
                with self._cv:
                    self._decrement_pending_locked(sub)
                    self._finalize_locked_handle(
                        sub.handle, "failed", None,
                        f"{type(exc).__name__}: {exc}",
                    )
                publish_event(
                    "service.failed", tenant=sub.tenant, dataset=sub.dataset,
                )
            return

        executed = [repr(a) for a in context.metric_map]
        schema = None
        try:
            from ..lint import SchemaInfo

            schema = SchemaInfo.from_table(table)
        except Exception:  # noqa: BLE001 — advisory diagnostics only
            schema = None
        publish_event(
            "service.shared_scan",
            participants=len(participants),
            fingerprint=participants[0].fingerprint,
        )
        for i, sub in enumerate(participants):
            handle = sub.handle
            if sub.tenant in overdrawn:
                self._on_cancelled(
                    sub,
                    RunCancelled(
                        "quota",
                        where="shared scan fan-out",
                        progress={"participants": len(participants)},
                    ),
                )
                continue
            try:
                metrics = {
                    a: context.metric_map[a]
                    for a in plans[i]
                    if a in context.metric_map
                }
                result = VerificationSuite.evaluate(
                    sub.checks, AnalyzerContext(metrics)
                )
                if schema is not None:
                    try:
                        from ..lint.planlint import validate_plan

                        report = validate_plan(
                            schema,
                            sub.checks,
                            sub.analyzers,
                            mode="lenient",
                            num_rows=int(table.num_rows),
                            sharing_with=union,
                        )
                        result.validation_warnings = list(report.diagnostics)
                        result.plan_cost = report.plan_cost
                    except Exception:  # fault-ok: lint diagnostics are
                        # advisory; the verified result stands without them
                        pass
                if captures is not None:
                    result.forensics_report = captures[i].finalize(
                        result.check_results
                    )
                handle.sharing = {
                    "shared": True,
                    "participants": len(participants),
                    "proof": proofs[i].to_dict(),
                    "drift": proofs[i].pin(executed),
                }
                self.breakers.record_success(sub.tenant, sub.dataset)
                self.telemetry.count("completed")
                handle.result = result
                with self._cv:
                    self._decrement_pending_locked(sub)
                    self._finalize_locked_handle(handle, "done", None, "")
            except Exception as exc:  # noqa: BLE001 — one tenant's
                # evaluation failing must not poison its co-tenants
                self.breakers.record_failure(sub.tenant, sub.dataset)
                self.telemetry.count("failed")
                handle.error = exc
                with self._cv:
                    self._decrement_pending_locked(sub)
                    self._finalize_locked_handle(
                        handle, "failed", None,
                        f"{type(exc).__name__}: {exc}",
                    )

    @staticmethod
    def _union_plan(plans: List[List[Any]]) -> Tuple[List[Any], List[List[int]]]:
        from ..ops.fused import build_union_plan

        return build_union_plan(plans)

    def _shared_boundary_probe(
        self, subs: List[_Submission], overdrawn: set
    ) -> Callable[[Dict[str, Any]], Optional[str]]:
        """Pro-rata quota enforcement for one shared scan: each newly
        committed partition's bytes (the UNION read, approximated by
        the widest participant's prediction) split across participants
        proportional to their own solo demand. An overdrawn tenant is
        marked and dropped at fan-out (DQ406) while the scan continues
        for the others; the scan itself stops only when every
        participant is overdrawn."""
        from .sharing import prorata_weights

        predicted = []
        for s in subs:
            p = 0.0
            if s.cost is not None and s.cost.predicted_scan_bytes is not None:
                p = float(s.cost.predicted_scan_bytes)
            predicted.append(p)
        _union_bytes, shares = prorata_weights(predicted)
        charged = {"parts": 0}

        def probe(progress: Dict[str, Any]) -> Optional[str]:
            done = int(progress.get("partitions_done", 0))
            scanned = done - int(progress.get("partitions_cached", 0))
            total = int(progress.get("partitions_total", 0)) or 1
            new = scanned - charged["parts"]
            if new > 0:
                charged["parts"] = scanned
                for s, share in zip(subs, shares):
                    if s.tenant in overdrawn:
                        continue
                    charge = new * share / total
                    if charge > 0:
                        self.ledger.charge_scan(s.tenant, charge)
                        self.telemetry.charge_tenant_bytes(s.tenant, charge)
            for s in subs:
                if s.tenant in overdrawn:
                    continue
                over = self.ledger.over_scan_budget(s.tenant)
                if not over:
                    quota = self.ledger.quota(s.tenant)
                    if quota.state_disk_bytes is not None:
                        usage = self._state_disk_usage(s.tenant, s.dataset)
                        over = (
                            usage is not None
                            and usage > quota.state_disk_bytes
                        )
                if over:
                    overdrawn.add(s.tenant)
            if all(s.tenant in overdrawn for s in subs):
                return "quota"
            return None

        return probe

    def _on_cancelled(self, sub: _Submission, exc: RunCancelled) -> None:
        handle = sub.handle
        if exc.reason == "preempted":
            # neutral for the breaker: the run said nothing about the
            # dataset; re-queue at the HEAD so it resumes next
            self.breakers.record_neutral(sub.tenant, sub.dataset)
            self.telemetry.count("preempted")
            handle.preemptions += 1
            with self._cv:
                if self._accepting and not self._stopping:
                    sub.controller = None
                    handle.status = "queued"
                    self._queues[sub.tier].appendleft(sub)
                    self._cv.notify_all()
                    return
                # preempted during drain: treat as drained
                self._decrement_pending_locked(sub)
                self._finalize_locked_handle(
                    handle, "drained", DQ_DRAINED,
                    "preempted while service drained; committed partition "
                    "states resume the run after restart",
                )
            return
        if exc.reason == "quota":
            self.breakers.record_neutral(sub.tenant, sub.dataset)
            self.telemetry.count("quota_stops")
            status = "quota"
        elif exc.reason == "drain":
            self.breakers.record_neutral(sub.tenant, sub.dataset)
            self.telemetry.count("drained")
            status = "drained"
        else:
            self.breakers.record_neutral(sub.tenant, sub.dataset)
            status = "cancelled"
        handle.error = exc
        with self._cv:
            self._decrement_pending_locked(sub)
            self._finalize_locked_handle(handle, status, exc.code, str(exc))
        publish_event(
            "service." + status, tenant=sub.tenant, dataset=sub.dataset,
            code=exc.code,
        )

    def _boundary_probe(
        self, sub: _Submission
    ) -> Callable[[Dict[str, Any]], Optional[str]]:
        """Per-partition quota enforcement. Charges the tenant's
        sliding window for each newly committed partition (estimated
        from the EXPLAIN cost split evenly across partitions) and
        stops the run with DQ406 once overdrawn — after the partition
        committed, so progress is never lost."""
        cost = sub.cost
        predicted = 0.0
        if cost is not None and cost.predicted_scan_bytes is not None:
            predicted = float(cost.predicted_scan_bytes)
        charged = {"parts": 0}

        def probe(progress: Dict[str, Any]) -> Optional[str]:
            done = int(progress.get("partitions_done", 0))
            # charge only SCANNED partitions — cache hits read no data,
            # so they never count against the tenant's scan window
            scanned = done - int(progress.get("partitions_cached", 0))
            # the static plan may not know the partition split; the
            # runtime progress dict always does on partitioned runs
            total = int(progress.get("partitions_total", 0)) or 1
            per_part = predicted / total
            new = scanned - charged["parts"]
            if new > 0 and per_part > 0:
                charge = new * per_part
                self.ledger.charge_scan(sub.tenant, charge)
                self.telemetry.charge_tenant_bytes(sub.tenant, charge)
                charged["parts"] = scanned
            if self.ledger.over_scan_budget(sub.tenant):
                return "quota"
            quota = self.ledger.quota(sub.tenant)
            if quota.state_disk_bytes is not None:
                usage = self._state_disk_usage(sub.tenant, sub.dataset)
                if usage is not None and usage > quota.state_disk_bytes:
                    return "quota"
            return None

        return probe

    # ------------------------------------------------------------------
    # introspection & telemetry

    def queue_depths(self) -> Dict[str, int]:
        with self._cv:
            return {tier: len(q) for tier, q in self._queues.items()}

    def running_count(self) -> int:
        with self._cv:
            return len(self._running)

    def telemetry_snapshot(self) -> Dict[str, float]:
        depths = self.queue_depths()
        return self.telemetry.snapshot(
            queue_depths=depths,
            running=self.running_count(),
            workers=self._workers_n,
            breaker_open=self.breakers.open_count(),
            breaker_transitions=self.breakers.transitions(),
        )

    def publish_telemetry(self) -> Optional[Dict[str, float]]:
        if self._metrics_repository is None:
            return None
        record = self.telemetry_snapshot()
        publish_telemetry(self._metrics_repository, record)
        return record

    # ------------------------------------------------------------------
    # drain & shutdown

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Graceful shutdown: stop intake, return queued work DQ414,
        soft-cancel running work so in-flight partitions commit, then
        join every thread. Escalates to hard cancel at the timeout."""
        if timeout_s is None:
            timeout_s = runtime.service_drain_s()
        with self._cv:
            already = self._stopping and not self._accepting
            self._accepting = False
            queued: List[_Submission] = []
            for tier in TIERS:
                q = self._queues[tier]
                queued.extend(q)
                q.clear()
            for sub in queued:
                self.telemetry.count("drained")
                self._decrement_pending_locked(sub)
                self._finalize_locked_handle(
                    sub.handle, "drained", DQ_DRAINED,
                    "returned unrun by graceful drain; committed partition "
                    "states resume the run after restart",
                )
            for sub in self._running:
                if sub.controller is not None:
                    sub.controller.cancel_at_boundary("drain")
            self._cv.notify_all()
        if already:
            return
        publish_event("service.drain", name=self._name, queued=len(queued))
        deadline = self._clock() + float(timeout_s)
        with self._cv:
            while self._running and self._clock() < deadline:
                self._cv.wait(timeout=0.1)
            # past the timeout: escalate soft to hard cancel
            for sub in self._running:
                if sub.controller is not None:
                    sub.controller.cancel("drain")
            while self._running:
                self._cv.wait(timeout=0.1)
        try:
            self.publish_telemetry()
        except Exception:  # fault-ok: telemetry must not block drain
            pass
        self._shutdown_threads()

    def _shutdown_threads(self) -> None:
        self._stop_event.set()
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=10.0)
        self._scheduler.join(timeout=10.0)

    def close(self) -> None:
        self.drain()

    def __enter__(self) -> "DQService":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    # ------------------------------------------------------------------
    # SIGTERM integration

    def install_sigterm(self) -> None:
        """Drain on SIGTERM. The handler does not exit the process —
        the host decides what happens after the pool is quiet."""
        def _handler(signum: int, frame: Any) -> None:
            self.drain()

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None


__all__ = [
    "DEFAULT_QUEUE_LIMITS",
    "TIERS",
    "DQService",
    "SubmissionHandle",
]

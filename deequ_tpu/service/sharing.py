"""Fleet-level scan sharing: one superset scan per table, proven safe.

The DQService isolates tenants but — before this module — scanned the
same table once per tenant. The enabler for sharing is static: the
plan-subsumption prover (lint/subsume.py) proves each participant's
suite CONTAINED in the union plan the group synthesizes
(ops/fused.build_union_plan), so ONE fused scan computes every
participant's states and the fan-out is a pure selection over the
semigroup — bit-identical to a solo run per tenant.

What lives here (service/service.py orchestrates around it):

* ``dataset_fingerprint`` — the grouping key. Content-based for
  partitioned sources (the hash of the partition fingerprints the
  state cache already keys on), object identity for a directly
  submitted in-memory table. ``None`` means "cannot prove same data"
  and the submission always scans solo.
* ``plan_share_group`` — the prover gate: builds the union plan,
  proves each candidate contained (environment components from the
  live runtime knobs on BOTH sides, so a fold-variant or dtype flip
  can never be silently merged), and splits participants from
  declines with their DQ322-style fall-off reasons.
* ``FanoutStateRepository`` — per-tenant state persistence for the
  shared scan: every committed partition saves the union states under
  the shared dataset AND each tenant's analyzer subset under the
  tenant's own dataset with the tenant's own solo plan signature — so
  a later solo run (or a re-formed group after preemption) resumes
  from cache. Loads assemble the union from per-tenant entries when
  the shared entry is missing, so a differently composed group still
  resumes committed partitions.
* ``ForensicsFanout`` — one ForensicsCapture per tenant behind the
  fused pass's single forensics hook: reservoirs stay isolated per
  tenant (and their RNG seeds are content-derived per constraint, so
  each tenant's samples are bit-identical to its solo run).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..ops import runtime

#: dataset name shared-scan state envelopes are keyed under
SHARED_DATASET_PREFIX = "shared/"


# -- grouping key -------------------------------------------------------------


def dataset_fingerprint(data: Any, table: Any) -> Optional[str]:
    """The sharing group key for one submission, or None when equality
    of the underlying data cannot be established statically.

    ``data`` is what the caller submitted (a Table or a factory),
    ``table`` the opened Table. Partitioned sources fingerprint by
    content — the digest of their partition fingerprints, stable
    across re-opens of the same files. A directly submitted in-memory
    table keys on object identity (two tenants passing the SAME Table
    object provably verify the same data); a factory-opened in-memory
    table has no stable identity across opens and never shares."""
    parts_fn = getattr(table, "partitions", None)
    if parts_fn is not None:
        try:
            h = hashlib.sha256()
            n = 0
            for part in parts_fn():
                h.update(str(part.fingerprint).encode("utf-8") + b"\x00")
                n += 1
            if n:
                return "parts:" + h.hexdigest()[:32]
        except Exception:  # noqa: BLE001 — unknowable identity = no sharing
            return None
    if not callable(data) and data is table:
        return f"obj:{id(table)}"
    return None


def shared_dataset_name(fingerprint: str) -> str:
    return SHARED_DATASET_PREFIX + fingerprint.replace("/", "_")[:44]


# -- the prover gate ----------------------------------------------------------


def current_plan_env(table: Any, batch_size: Optional[int] = None):
    """The live runtime's plan-signature components as a
    `lint.subsume.PlanEnv` — the same fields
    `repository.states.plan_signature_for` hashes."""
    import numpy as np

    from ..lint.subsume import PlanEnv

    batch_rows = getattr(table, "batch_rows", None)
    return PlanEnv(
        placement=runtime.placement_mode(),
        compute_dtype=np.dtype(runtime.compute_dtype()).name,
        batch_size=batch_size,
        batch_rows=int(batch_rows) if batch_rows else None,
        fold_variant=runtime.fold_signature_variant(),
    )


def submission_plan(checks: Sequence[Any], analyzers: Sequence[Any]) -> List[Any]:
    """One submission's deduplicated analyzer plan — the same
    collection order the verification suite uses (required analyzers
    first, then each check's)."""
    from ..lint.explain import _plan_analyzers

    return _plan_analyzers(analyzers, checks)


def plan_share_group(
    plans: Sequence[List[Any]],
    table: Any,
) -> Tuple[List[Any], List[Any], List[Optional[str]]]:
    """Prove a group of submission plans shareable over ``table``.

    Returns ``(union, proofs, declines)``: the superset analyzer list,
    one `SubsumptionProof` per plan, and per-plan decline reasons
    (None = proven CONTAINED and safe to share). A plan declines when
    its proof is anything but exact CONTAINED — the union is built by
    engine-identity dedup, so equivalent-but-respelled wheres stay
    separate members and every participant should prove exact; any
    residual or mismatch here is a real incompatibility."""
    from ..lint.schema import SchemaInfo
    from ..lint.subsume import CONTAINED, prove_subsumption
    from ..ops.fused import build_union_plan

    union, _memberships = build_union_plan(plans)
    try:
        schema = SchemaInfo.from_table(table)
    except Exception:  # noqa: BLE001 — prover degrades to structural
        schema = None
    env = current_plan_env(table)
    proofs: List[Any] = []
    declines: List[Optional[str]] = []
    for plan in plans:
        proof = prove_subsumption(
            plan, union, schema, suite_env=env, scan_env=env
        )
        proofs.append(proof)
        if proof.verdict == CONTAINED:
            declines.append(None)
        else:
            declines.append(proof.summary())
    return union, proofs, declines


# -- per-tenant state fan-out -------------------------------------------------


class TenantStatePlan:
    """One tenant's slice of the shared scan's state persistence: the
    dataset its envelopes are keyed under, the scan-shareable analyzer
    subset a SOLO run of this tenant would fold, and that solo run's
    plan signature."""

    def __init__(self, dataset: str, analyzers: Sequence[Any], table: Any) -> None:
        from ..repository.states import plan_signature_for

        self.dataset = dataset
        self.analyzers = scan_shareable_subset(analyzers, table)
        self.signature = plan_signature_for(self.analyzers, table)


def scan_shareable_subset(analyzers: Sequence[Any], table: Any) -> List[Any]:
    """The sublist of ``analyzers`` a solo run's FusedScanPass would
    fold — mirrors the runner's own filtering (dedupe, precondition
    check, grouping split, scan-shareable only), so the signature
    computed over it matches the solo run's exactly."""
    from ..analyzers.base import Preconditions, ScanShareableAnalyzer
    from ..analyzers.grouping import GroupingAnalyzer

    seen: set = set()
    subset: List[Any] = []
    for a in analyzers:
        if a in seen:
            continue
        seen.add(a)
        if not isinstance(a, ScanShareableAnalyzer) or isinstance(
            a, GroupingAnalyzer
        ):
            continue
        try:
            if Preconditions.find_first_failing(table, a.preconditions()):
                continue
        except Exception:  # noqa: BLE001 — failing precondition = no fold
            continue
        subset.append(a)
    return subset


class FanoutStateRepository:
    """StateRepository facade for one shared scan.

    The fused pass talks to it exactly like any repository — keyed by
    the SHARED dataset and the union plan's signature. Saves
    additionally fan each tenant's analyzer subset out under the
    tenant's own (dataset, solo signature), so the shared scan warms
    every participant's solo cache; loads fall back to assembling the
    union from per-tenant entries, so a re-formed group (different
    participants after a preemption) still resumes every partition any
    earlier attempt committed."""

    def __init__(self, inner: Any, tenants: Sequence[TenantStatePlan]) -> None:
        self.inner = inner
        self.tenants = list(tenants)

    # -- cache surface (duck-typed StateRepository) --------------------------

    def has_states(self, dataset: str, fingerprint: str, signature: str) -> bool:
        if self.inner.has_states(dataset, fingerprint, signature):
            return True
        return bool(self.tenants) and all(
            self.inner.has_states(t.dataset, fingerprint, t.signature)
            for t in self.tenants
        )

    def load_states(
        self,
        dataset: str,
        fingerprint: str,
        signature: str,
        analyzers: Sequence[Any],
    ) -> Optional[List[Any]]:
        states = self.inner.load_states(dataset, fingerprint, signature, analyzers)
        if states is not None:
            return states
        # assemble the union from per-tenant solo entries
        by_analyzer: Dict[Any, Any] = {}
        for t in self.tenants:
            if not t.analyzers:
                continue
            loaded = self.inner.load_states(
                t.dataset, fingerprint, t.signature, t.analyzers
            )
            if loaded is None:
                continue
            for a, s in zip(t.analyzers, loaded):
                by_analyzer.setdefault(a, s)
        if not by_analyzer:
            return None
        if any(a not in by_analyzer for a in analyzers):
            return None
        return [by_analyzer[a] for a in analyzers]

    def save_states(
        self,
        dataset: str,
        fingerprint: str,
        signature: str,
        pairs: Sequence[Tuple[Any, Any]],
    ) -> bool:
        saved = self.inner.save_states(dataset, fingerprint, signature, pairs)
        states = {a: s for a, s in pairs}
        for t in self.tenants:
            if not t.analyzers:
                continue
            if any(a not in states for a in t.analyzers):
                continue  # best-effort: never a partial tenant envelope
            self.inner.save_states(
                t.dataset,
                fingerprint,
                t.signature,
                [(a, states[a]) for a in t.analyzers],
            )
        return saved

    def disk_usage(self, dataset: str) -> Optional[int]:
        return self.inner.disk_usage(dataset)


# -- per-tenant forensics fan-out ---------------------------------------------


class ForensicsFanout:
    """One ForensicsCapture per participant behind the single forensics
    hook the fused pass drives. Every hook fans out; reservoirs and
    coordinate state stay per-tenant, and because reservoir seeds are
    content-derived per constraint (observe/forensics._batch_seed),
    each tenant's samples are bit-identical to its solo run."""

    def __init__(self, captures: Sequence[Any]) -> None:
        self.captures = list(captures)

    def note_plan_signature(self, signature: str) -> None:
        for c in self.captures:
            c.note_plan_signature(signature)

    def note_partition(self, name: str, fingerprint: str, mode: str) -> None:
        for c in self.captures:
            c.note_partition(name, fingerprint, mode)

    def enter_partition(self, name: str, fingerprint: str) -> "ForensicsFanout":
        for c in self.captures:
            c.enter_partition(name, fingerprint)
        return self

    def note_table(self, source: Any) -> None:
        for c in self.captures:
            c.note_table(source)

    def note_decode_plan(self, plan: Any) -> None:
        for c in self.captures:
            c.note_decode_plan(plan)

    def capture_batch(self, batch: Any, row_offset: int) -> None:
        for c in self.captures:
            c.capture_batch(batch, row_offset)


# -- pro-rata quota split -----------------------------------------------------


def prorata_weights(predicted: Sequence[float]) -> Tuple[float, List[float]]:
    """Split one shared scan's bytes across participants.

    ``predicted`` is each participant's own solo predicted scan bytes
    (its EXPLAIN cost). The shared scan reads the union of columns
    once — approximated by the WIDEST participant's prediction — and
    each participant is charged its pro-rata share of that single
    read, proportional to its own demand (even split when no
    prediction is available). Returns ``(union_bytes, shares)`` with
    ``sum(shares) == union_bytes``: together the tenants pay for one
    scan, not K."""
    n = len(predicted)
    if n == 0:
        return 0.0, []
    union_bytes = max(float(p) for p in predicted)
    total = sum(float(p) for p in predicted)
    if union_bytes <= 0.0 or total <= 0.0:
        return 0.0, [0.0] * n
    return union_bytes, [union_bytes * float(p) / total for p in predicted]


__all__ = [
    "FanoutStateRepository",
    "ForensicsFanout",
    "SHARED_DATASET_PREFIX",
    "TenantStatePlan",
    "current_plan_env",
    "dataset_fingerprint",
    "plan_share_group",
    "prorata_weights",
    "scan_shareable_subset",
    "shared_dataset_name",
    "submission_plan",
]

"""Fleet telemetry for the DQ service.

Counters are plain locked integers — the service's hot paths touch
them under their own locks already, so the cost here is one more
uncontended acquire. ``snapshot()`` flattens everything into the
``engine.service.*`` float namespace so the existing ``EngineMetric``
repository machinery (and the sentinel's watched series) persist and
trend service health exactly like any other engine metric.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Mapping, Optional

from ..repository.engine import engine_result_key, persist_engine_record

PREFIX = "engine.service."

#: counter names every snapshot carries, even at zero
COUNTERS = (
    "submitted",
    "admitted",
    "rejected",
    "shed",
    "preempted",
    "drained",
    "quota_stops",
    "completed",
    "failed",
    "queue_faults",
    "worker_faults",
    "admission_faults",
    # fleet-wide scan sharing (service/sharing.py)
    "shared_scans",
    "shared_participants",
    "sharing_declined",
)


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in name)


class ServiceTelemetry:
    """Thread-safe counters + per-tenant scan-bytes accumulators."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {name: 0 for name in COUNTERS}
        self._tenant_bytes: Dict[str, float] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def value(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def charge_tenant_bytes(self, tenant: str, nbytes: float) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self._tenant_bytes[tenant] = (
                self._tenant_bytes.get(tenant, 0.0) + float(nbytes)
            )

    def snapshot(
        self,
        *,
        queue_depths: Mapping[str, int],
        running: int,
        workers: int,
        breaker_open: int,
        breaker_transitions: int,
    ) -> Dict[str, float]:
        """One flat ``engine.service.*`` record, ready to persist."""
        with self._lock:
            counts = dict(self._counts)
            tenant_bytes = dict(self._tenant_bytes)
        record: Dict[str, float] = {}
        for name, value in counts.items():
            record[PREFIX + name] = float(value)
        for tier, depth in queue_depths.items():
            record[PREFIX + f"queue_depth.{tier}"] = float(depth)
        record[PREFIX + "running"] = float(running)
        record[PREFIX + "workers"] = float(workers)
        record[PREFIX + "breaker_open"] = float(breaker_open)
        record[PREFIX + "breaker_transitions"] = float(breaker_transitions)
        submitted = counts.get("submitted", 0)
        if submitted > 0:
            record[PREFIX + "shed_ratio"] = counts.get("shed", 0) / submitted
        for tenant, nbytes in tenant_bytes.items():
            record[PREFIX + f"tenant.{_sanitize(tenant)}.bytes_scanned"] = nbytes
        return record


def publish(
    repository: Any,
    record: Dict[str, float],
    *,
    suite: str = "service",
    dataset: str = "fleet",
    tags: Optional[Dict[str, str]] = None,
) -> None:
    """Persist one service snapshot through the EngineMetric repository."""
    key = engine_result_key(
        suite=suite,
        dataset=dataset,
        tags=dict(tags or {"component": "service"}),
    )
    persist_engine_record(repository, record, key, instance="service")


__all__ = ["COUNTERS", "PREFIX", "ServiceTelemetry", "publish"]

from deequ_tpu.suggestions.rules import (
    DEFAULT_RULES,
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    ConstraintRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    Rules,
    UniqueIfApproximatelyUniqueRule,
)
from deequ_tpu.suggestions.suggestion import ConstraintSuggestion
from deequ_tpu.suggestions.runner import (
    ConstraintSuggestionResult,
    ConstraintSuggestionRunner,
)


__all__ = [
    "Rules",
    "DEFAULT_RULES",
    "ConstraintRule",
    "CompleteIfCompleteRule",
    "RetainCompletenessRule",
    "RetainTypeRule",
    "CategoricalRangeRule",
    "FractionalCategoricalRangeRule",
    "NonNegativeNumbersRule",
    "UniqueIfApproximatelyUniqueRule",
    "ConstraintSuggestion",
    "ConstraintSuggestionResult",
    "ConstraintSuggestionRunner",
]

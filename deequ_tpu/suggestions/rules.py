"""Constraint-suggestion rules: profile -> candidate constraint.

reference: suggestions/rules/*.scala (8 rules; DEFAULT = 6,
ConstraintSuggestionRunner.scala:29-35). Trigger conditions, CI formulas
(z=1.96, rounded DOWN to 2 decimals) and descriptions mirror the
reference; generated code snippets use this framework's Python DSL.
"""

from __future__ import annotations

import math
from typing import List

from deequ_tpu.analyzers.scan import DataTypeInstances
from deequ_tpu.checks.check import is_one
from deequ_tpu.constraints.constrainable_data_types import ConstrainableDataTypes
from deequ_tpu.constraints import constraint as C
from deequ_tpu.profiles.column_profile import ColumnProfile, NumericColumnProfile
from deequ_tpu.suggestions.suggestion import ConstraintSuggestion

NULL_FIELD_REPLACEMENT = "NullValue"


def _floor_2dp(value: float) -> float:
    """BigDecimal.setScale(2, DOWN) (reference: RetainCompletenessRule.scala:41)."""
    return math.floor(value * 100) / 100


class ConstraintRule:
    rule_description: str = ""

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        raise NotImplementedError

    def candidate(self, profile: ColumnProfile, num_records: int) -> ConstraintSuggestion:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class CompleteIfCompleteRule(ConstraintRule):
    rule_description = (
        "If a column is complete in the sample, we suggest a NOT NULL constraint"
    )

    def should_be_applied(self, profile, num_records) -> bool:
        return profile.completeness == 1.0

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        constraint = C.completeness_constraint(profile.column, is_one)
        return ConstraintSuggestion(
            constraint,
            profile.column,
            f"Completeness: {profile.completeness}",
            f"'{profile.column}' is not null",
            self,
            f'.is_complete("{profile.column}")',
        )


class RetainCompletenessRule(ConstraintRule):
    rule_description = (
        "If a column is incomplete in the sample, we model its completeness "
        "as a binomial variable, estimate a confidence interval and use this "
        "to define a lower bound for the completeness"
    )

    def should_be_applied(self, profile, num_records) -> bool:
        return 0.2 < profile.completeness < 1.0

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        p = profile.completeness
        n = max(num_records, 1)
        z = 1.96
        target = _floor_2dp(p - z * math.sqrt(p * (1 - p) / n))
        constraint = C.completeness_constraint(
            profile.column, lambda v, t=target: v >= t
        )
        bound_pct = int((1.0 - target) * 100)
        return ConstraintSuggestion(
            constraint,
            profile.column,
            f"Completeness: {profile.completeness}",
            f"'{profile.column}' has less than {bound_pct}% missing values",
            self,
            f'.has_completeness("{profile.column}", lambda v: v >= {target}, '
            f'hint="It should be above {target}!")',
        )


class RetainTypeRule(ConstraintRule):
    rule_description = "If we detect a non-string type, we suggest a type constraint"

    def should_be_applied(self, profile, num_records) -> bool:
        testable = profile.data_type in (
            DataTypeInstances.INTEGRAL,
            DataTypeInstances.FRACTIONAL,
            DataTypeInstances.BOOLEAN,
        )
        return profile.is_data_type_inferred and testable

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        type_to_check = {
            DataTypeInstances.FRACTIONAL: ConstrainableDataTypes.FRACTIONAL,
            DataTypeInstances.INTEGRAL: ConstrainableDataTypes.INTEGRAL,
            DataTypeInstances.BOOLEAN: ConstrainableDataTypes.BOOLEAN,
        }[profile.data_type]
        constraint = C.data_type_constraint(profile.column, type_to_check, is_one)
        return ConstraintSuggestion(
            constraint,
            profile.column,
            f"DataType: {profile.data_type}",
            f"'{profile.column}' has type {profile.data_type}",
            self,
            f'.has_data_type("{profile.column}", ConstrainableDataTypes.'
            f"{type_to_check.name})",
        )


class CategoricalRangeRule(ConstraintRule):
    rule_description = (
        "If we see a categorical range for a column, we suggest an IS IN (...) constraint"
    )

    def should_be_applied(self, profile, num_records) -> bool:
        if profile.histogram is None or profile.data_type != DataTypeInstances.STRING:
            return False
        entries = profile.histogram.values
        if not entries:
            return False
        num_unique = sum(1 for v in entries.values() if v.absolute == 1)
        return num_unique / len(entries) <= 0.1

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        by_popularity = sorted(
            (
                (key, value)
                for key, value in profile.histogram.values.items()
                if key != NULL_FIELD_REPLACEMENT
            ),
            key=lambda kv: kv[1].absolute,
            reverse=True,
        )
        categories_sql = ", ".join(
            "'" + key.replace("'", "''") + "'" for key, _ in by_popularity
        )
        categories_code = ", ".join(
            '"' + key.replace("\\", "\\\\").replace('"', '\\"') + '"'
            for key, _ in by_popularity
        )
        description = f"'{profile.column}' has value range {categories_sql}"
        column_condition = f"`{profile.column}` IN ({categories_sql})"
        constraint = C.compliance_constraint(description, column_condition, is_one)
        return ConstraintSuggestion(
            constraint,
            profile.column,
            "Compliance: 1",
            description,
            self,
            f'.is_contained_in("{profile.column}", [{categories_code}])',
        )


class FractionalCategoricalRangeRule(ConstraintRule):
    def __init__(self, target_data_coverage_fraction: float = 0.9):
        self.target_data_coverage_fraction = target_data_coverage_fraction

    rule_description = (
        "If we see a categorical range for most values in a column, we "
        "suggest an IS IN (...) constraint that should hold for most values"
    )

    def _top_categories(self, profile):
        sorted_values = sorted(
            profile.histogram.values.items(), key=lambda kv: kv[1].ratio, reverse=True
        )
        coverage = 0.0
        out = {}
        for key, value in sorted_values:
            if coverage < self.target_data_coverage_fraction:
                coverage += value.ratio
                out[key] = value
        return out

    def should_be_applied(self, profile, num_records) -> bool:
        if profile.histogram is None or profile.data_type != DataTypeInstances.STRING:
            return False
        entries = profile.histogram.values
        if not entries:
            return False
        num_unique = sum(1 for v in entries.values() if v.absolute == 1)
        unique_ratio = num_unique / len(entries)
        top = self._top_categories(profile)
        ratio_sums = sum(v.ratio for v in top.values())
        return unique_ratio <= 0.4 and ratio_sums < 1

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        top = self._top_categories(profile)
        ratio_sums = sum(v.ratio for v in top.values())
        by_popularity = sorted(
            ((k, v) for k, v in top.items() if k != NULL_FIELD_REPLACEMENT),
            key=lambda kv: kv[1].absolute,
            reverse=True,
        )
        categories_sql = ", ".join(
            "'" + key.replace("'", "''") + "'" for key, _ in by_popularity
        )
        categories_code = ", ".join(
            '"' + key.replace("\\", "\\\\").replace('"', '\\"') + '"'
            for key, _ in by_popularity
        )
        p = ratio_sums
        n = max(num_records, 1)
        z = 1.96
        target = _floor_2dp(p - z * math.sqrt(p * (1 - p) / n))
        description = (
            f"'{profile.column}' has value range {categories_sql} for at "
            f"least {target * 100}% of values"
        )
        column_condition = f"`{profile.column}` IN ({categories_sql})"
        hint = f"It should be above {target}!"
        constraint = C.compliance_constraint(
            description, column_condition, lambda v, t=target: v >= t, hint=hint
        )
        return ConstraintSuggestion(
            constraint,
            profile.column,
            f"Compliance: {ratio_sums}",
            description,
            self,
            f'.is_contained_in("{profile.column}", [{categories_code}], '
            f'lambda v: v >= {target}, hint="{hint}")',
        )

    def __repr__(self) -> str:
        return f"FractionalCategoricalRangeRule({self.target_data_coverage_fraction})"


class NonNegativeNumbersRule(ConstraintRule):
    rule_description = (
        "If we see only non-negative numbers in a column, we suggest a "
        "corresponding constraint"
    )

    def should_be_applied(self, profile, num_records) -> bool:
        return (
            isinstance(profile, NumericColumnProfile)
            and profile.minimum is not None
            and profile.minimum >= 0.0
        )

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        description = f"'{profile.column}' has no negative values"
        constraint = C.compliance_constraint(
            description, f"{profile.column} >= 0", is_one
        )
        minimum = (
            str(profile.minimum)
            if isinstance(profile, NumericColumnProfile) and profile.minimum is not None
            else "Error while calculating minimum!"
        )
        return ConstraintSuggestion(
            constraint,
            profile.column,
            f"Minimum: {minimum}",
            description,
            self,
            f'.is_non_negative("{profile.column}")',
        )


class UniqueIfApproximatelyUniqueRule(ConstraintRule):
    rule_description = (
        "If the ratio of approximate num distinct values in a column is "
        "close to the number of records (within the error of the HLL "
        "sketch), we suggest a UNIQUE constraint"
    )

    def should_be_applied(self, profile, num_records) -> bool:
        if num_records == 0:
            return False
        approx_distinctness = profile.approximate_num_distinct_values / num_records
        return profile.completeness == 1.0 and abs(1.0 - approx_distinctness) <= 0.08

    def candidate(self, profile, num_records) -> ConstraintSuggestion:
        constraint = C.uniqueness_constraint([profile.column], is_one)
        approx_distinctness = profile.approximate_num_distinct_values / max(num_records, 1)
        return ConstraintSuggestion(
            constraint,
            profile.column,
            f"ApproxDistinctness: {approx_distinctness}",
            f"'{profile.column}' is unique",
            self,
            f'.is_unique("{profile.column}")',
        )


def DEFAULT_RULES() -> List[ConstraintRule]:
    """reference: ConstraintSuggestionRunner.scala:29-35 — 6 of the 8 rules
    (UniqueIfApproximatelyUnique and the non-default variant excluded)."""
    return [
        CompleteIfCompleteRule(),
        RetainCompletenessRule(),
        RetainTypeRule(),
        CategoricalRangeRule(),
        FractionalCategoricalRangeRule(),
        NonNegativeNumbersRule(),
    ]


class Rules:
    """Reference-shaped access: `Rules.DEFAULT`
    (reference: suggestions/ConstraintSuggestionRunner.scala:29-35).
    Rules are stateless, so sharing the instances is safe; the tuple
    keeps the default set immutable."""

    DEFAULT = tuple(DEFAULT_RULES())

"""ConstraintSuggestionRunner: profile data, apply rules per column,
optionally evaluate suggestions on a held-out split.

reference: suggestions/ConstraintSuggestionRunner.scala:58-322 +
ConstraintSuggestionRunBuilder.scala:78-289.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from deequ_tpu.checks.check import Check, CheckLevel
from deequ_tpu.data.table import Table
from deequ_tpu.profiles.column_profile import ColumnProfile
from deequ_tpu.profiles.column_profiler import (
    DEFAULT_CARDINALITY_THRESHOLD,
    ColumnProfiler,
)
from deequ_tpu.suggestions.rules import ConstraintRule
from deequ_tpu.suggestions.suggestion import ConstraintSuggestion, suggestions_to_json


@dataclass
class ConstraintSuggestionResult:
    """reference: suggestions/ConstraintSuggestionResult.scala:30."""

    column_profiles: Dict[str, ColumnProfile]
    num_records: int
    constraint_suggestions: Dict[str, List[ConstraintSuggestion]]
    verification_result: Optional[object] = None

    def all_suggestions(self) -> List[ConstraintSuggestion]:
        return [s for group in self.constraint_suggestions.values() for s in group]

    def suggestions_as_json(self) -> str:
        return suggestions_to_json(self.all_suggestions())


class ConstraintSuggestionRunner:
    @staticmethod
    def on_data(data: Table) -> "ConstraintSuggestionRunBuilder":
        return ConstraintSuggestionRunBuilder(data)


class ConstraintSuggestionRunBuilder:
    def __init__(self, data: Table):
        self._data = data
        self._rules: List[ConstraintRule] = []
        self._print_status_updates = False
        self._test_set_ratio: Optional[float] = None
        self._test_set_split_seed: Optional[int] = None
        self._low_cardinality_histogram_threshold = DEFAULT_CARDINALITY_THRESHOLD
        self._restrict_to_columns: Optional[Sequence[str]] = None
        self._metrics_repository = None
        self._reuse_key = None
        self._fail_if_results_missing = False
        self._save_key = None
        self._save_column_profiles_json_path: Optional[str] = None
        self._save_constraint_suggestions_json_path: Optional[str] = None
        self._save_evaluation_results_json_path: Optional[str] = None
        self._overwrite_output_files = False

    def add_constraint_rule(self, rule: ConstraintRule) -> "ConstraintSuggestionRunBuilder":
        self._rules.append(rule)
        return self

    def add_constraint_rules(self, rules) -> "ConstraintSuggestionRunBuilder":
        if callable(rules):
            rules = rules()
        self._rules.extend(rules)
        return self

    def print_status_updates(self, value: bool) -> "ConstraintSuggestionRunBuilder":
        self._print_status_updates = value
        return self

    def use_train_test_split_with_test_set_ratio(
        self, ratio: float, seed: Optional[int] = None
    ) -> "ConstraintSuggestionRunBuilder":
        """reference: ConstraintSuggestionRunner.scala:127-148."""
        if not (0.0 < ratio < 1.0):
            raise ValueError("Test set ratio must be in (0, 1)")
        self._test_set_ratio = ratio
        self._test_set_split_seed = seed
        return self

    def with_low_cardinality_histogram_threshold(
        self, threshold: int
    ) -> "ConstraintSuggestionRunBuilder":
        self._low_cardinality_histogram_threshold = threshold
        return self

    def restrict_to_columns(self, columns) -> "ConstraintSuggestionRunBuilder":
        self._restrict_to_columns = columns
        return self

    def use_repository(self, repository) -> "ConstraintSuggestionRunBuilder":
        self._metrics_repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key, fail_if_results_missing: bool = False
    ) -> "ConstraintSuggestionRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key) -> "ConstraintSuggestionRunBuilder":
        self._save_key = key
        return self

    def save_column_profiles_json_to_path(
        self, path: str
    ) -> "ConstraintSuggestionRunBuilder":
        """reference: ConstraintSuggestionRunBuilder.scala:243-249."""
        self._save_column_profiles_json_path = path
        return self

    def save_constraint_suggestions_json_to_path(
        self, path: str
    ) -> "ConstraintSuggestionRunBuilder":
        """reference: ConstraintSuggestionRunBuilder.scala:256-262."""
        self._save_constraint_suggestions_json_path = path
        return self

    def save_evaluation_results_json_to_path(
        self, path: str
    ) -> "ConstraintSuggestionRunBuilder":
        """reference: ConstraintSuggestionRunBuilder.scala:269-275."""
        self._save_evaluation_results_json_path = path
        return self

    def overwrite_output_files(self, value: bool) -> "ConstraintSuggestionRunBuilder":
        """reference: ConstraintSuggestionRunBuilder.scala:283-286."""
        self._overwrite_output_files = value
        return self

    def run(self) -> ConstraintSuggestionResult:
        """reference: ConstraintSuggestionRunner.scala:62-125."""
        # optional train/test split
        if self._test_set_ratio is not None:
            train_ratio = 1.0 - self._test_set_ratio
            train, test = self._data.random_split(
                [train_ratio, self._test_set_ratio], seed=self._test_set_split_seed
            )
        else:
            train, test = self._data, None

        if self._print_status_updates:
            print("### SUGGESTIONS: Profiling the data...")
        profiles = ColumnProfiler.profile(
            train,
            restrict_to_columns=self._restrict_to_columns,
            print_status_updates=self._print_status_updates,
            low_cardinality_histogram_threshold=self._low_cardinality_histogram_threshold,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_in_metrics_repository_using_key=self._save_key,
        )

        # apply rules per column (reference: :193-208)
        suggestions: Dict[str, List[ConstraintSuggestion]] = {}
        for name, profile in profiles.profiles.items():
            for rule in self._rules:
                if rule.should_be_applied(profile, profiles.num_records):
                    suggestions.setdefault(name, []).append(
                        rule.candidate(profile, profiles.num_records)
                    )

        # optionally evaluate on the test split (reference: :283-313)
        verification_result = None
        if test is not None and suggestions:
            from deequ_tpu.verification.suite import VerificationSuite

            check = Check(CheckLevel.WARNING, "generated constraints")
            for group in suggestions.values():
                for suggestion in group:
                    check = check.add_constraint(suggestion.constraint)
            verification_result = VerificationSuite.do_verification_run(test, [check])

        result = ConstraintSuggestionResult(
            profiles.profiles, profiles.num_records, suggestions, verification_result
        )

        # JSON file outputs (reference: ConstraintSuggestionRunner.scala:220-281)
        from deequ_tpu.core.fileio import write_text_output
        from deequ_tpu.suggestions.suggestion import evaluation_results_to_json

        if self._save_column_profiles_json_path is not None:
            write_text_output(
                self._save_column_profiles_json_path,
                profiles.to_json(),
                self._overwrite_output_files,
            )
        if self._save_constraint_suggestions_json_path is not None:
            write_text_output(
                self._save_constraint_suggestions_json_path,
                result.suggestions_as_json(),
                self._overwrite_output_files,
            )
        if self._save_evaluation_results_json_path is not None:
            write_text_output(
                self._save_evaluation_results_json_path,
                evaluation_results_to_json(
                    result.all_suggestions(), verification_result
                ),
                self._overwrite_output_files,
            )
        return result

"""ConstraintSuggestion model + JSON export.

reference: suggestions/ConstraintSuggestion.scala:25-115. The
`code_for_constraint` strings are Python DSL snippets (the reference emits
Scala snippets — same role, native surface).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from deequ_tpu.constraints.constraint import Constraint
    from deequ_tpu.suggestions.rules import ConstraintRule


@dataclass
class ConstraintSuggestion:
    constraint: "Constraint"
    column_name: str
    current_value: str
    description: str
    suggesting_rule: "ConstraintRule"
    code_for_constraint: str


def _shared_properties(suggestion: ConstraintSuggestion) -> dict:
    return {
        "constraint_name": repr(suggestion.constraint),
        "column_name": suggestion.column_name,
        "current_value": suggestion.current_value,
        "description": suggestion.description,
        "suggesting_rule": repr(suggestion.suggesting_rule),
        "rule_description": suggestion.suggesting_rule.rule_description,
        "code_for_constraint": suggestion.code_for_constraint,
    }


def suggestions_to_json(suggestions: List[ConstraintSuggestion]) -> str:
    """reference: ConstraintSuggestion.scala:42+."""
    return json.dumps(
        {"constraint_suggestions": [_shared_properties(s) for s in suggestions]},
        indent=2,
    )


def evaluation_results_to_json(
    suggestions: List[ConstraintSuggestion], verification_result
) -> str:
    """Per-suggestion evaluation status on the held-out split; "Unknown"
    where no constraint result lines up (no split was evaluated, or fewer
    results than suggestions) — reference:
    ConstraintSuggestion.scala:61-100."""
    statuses: List[str] = []
    if verification_result is not None and verification_result.check_results:
        first_check = next(iter(verification_result.check_results.values()))
        statuses = [
            cr.status.name.capitalize() for cr in first_check.constraint_results
        ]
    out = []
    for i, suggestion in enumerate(suggestions):
        entry = _shared_properties(suggestion)
        entry["constraint_result_on_test_set"] = (
            statuses[i] if i < len(statuses) else "Unknown"
        )
        out.append(entry)
    return json.dumps({"constraint_suggestions": out}, indent=2)

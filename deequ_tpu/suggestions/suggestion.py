"""ConstraintSuggestion model + JSON export.

reference: suggestions/ConstraintSuggestion.scala:25-115. The
`code_for_constraint` strings are Python DSL snippets (the reference emits
Scala snippets — same role, native surface).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from deequ_tpu.constraints.constraint import Constraint
    from deequ_tpu.suggestions.rules import ConstraintRule


@dataclass
class ConstraintSuggestion:
    constraint: "Constraint"
    column_name: str
    current_value: str
    description: str
    suggesting_rule: "ConstraintRule"
    code_for_constraint: str


def suggestions_to_json(suggestions: List[ConstraintSuggestion]) -> str:
    """reference: ConstraintSuggestion.scala:42+."""
    out = []
    for suggestion in suggestions:
        out.append(
            {
                "constraint_name": repr(suggestion.constraint),
                "column_name": suggestion.column_name,
                "current_value": suggestion.current_value,
                "description": suggestion.description,
                "suggesting_rule": repr(suggestion.suggesting_rule),
                "rule_description": suggestion.suggesting_rule.rule_description,
                "code_for_constraint": suggestion.code_for_constraint,
            }
        )
    return json.dumps({"constraint_suggestions": out}, indent=2)

"""Deterministic test harnesses for the engine (fault injection)."""

from deequ_tpu.testing import faults

__all__ = ["faults"]

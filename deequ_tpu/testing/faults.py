"""Seed-driven chaos harness: deterministic fault injection at named
points in the engine's execution stack (ISSUE 13 tentpole).

The failure surface the engine owns end-to-end — readahead preads,
page decompress/decode, stage workers, state-repository IO — was only
reachable by accident (PR 11's intermittent readahead deadlock, the
corrupt-varint overflows). This module makes every one of those
failures reproducible on demand: product code calls
`faults.fault_point("<name>")` at each seam, and an armed fault plan
decides — deterministically, from `(seed, point, occurrence index)` —
whether that occurrence fails.

Disabled path: `fault_point` is a module-global `None` check plus a
function call, nothing else — cheap enough for per-chunk call sites
(bounded analytically in tests/test_observe_overhead.py alongside the
tracing and forensics guards).

Spec grammar (`DEEQU_TPU_FAULTS` or `install(spec)`), comma-separated:

    seed=7,stall=0.05,read.pread:0.5:3,decode.worker:1.0:1

  * `seed=N` — base seed for the per-occurrence hash (default 0);
  * `stall=S` — sleep seconds for the latency/stall kinds (default 0.02);
  * `name:rate[:count]` — arm point `name`: each occurrence injects
    independently with probability `rate`; `count` caps total
    injections at that point (a transient fault: the first `count`
    qualifying occurrences fail, later retries succeed). No `count`
    with rate 1.0 models a persistent fault.

Every point name is registered in `FAULT_POINTS`; the repo linter
(tools/lint.py FAULTS rule) rejects a `fault_point("...")` call site
whose literal is not registered here, so the harness can never drift
from the product code it exercises.

Injection behavior is keyed by the point's kind:

  * raise-kind points raise `InjectedFaultError` (an `OSError`
    subclass, so transient-IO retry paths treat it as retryable);
  * sleep-kind points block the calling thread for `stall` seconds
    (latency spikes and stage stalls) and return None;
  * data-kind points return a directive string (`"short"`, `"corrupt"`,
    `"fail"`) the call site applies to its own data — the harness never
    touches buffers itself.

Determinism: occurrence `i` at point `p` under seed `s` injects iff
`random.Random(f"{s}:{p}:{i}").random() < rate`. The occurrence counter
is per-point and process-global (lock-guarded), so a fixed spec over a
fixed workload injects the same schedule every run regardless of thread
interleaving of OTHER points. (Which thread hits occurrence `i` may
vary under races — bit-identity of RESULTS under faults is the contract
the chaos differential pins, not the per-thread schedule.)
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Dict, Iterator, Optional, Tuple

ENV_KNOB = "DEEQU_TPU_FAULTS"

#: every injectable point, name -> kind. Kinds: "raise" (the point
#: raises InjectedFaultError), "sleep" (the point blocks for the plan's
#: stall seconds), "data" (the point returns a directive the call site
#: applies: read.short -> "short", read.corrupt -> "corrupt",
#: decode.chunk / decode.runs -> "fail", shard.merge -> "corrupt",
#: shard.host_loss -> "lost").
FAULT_KINDS: Dict[str, str] = {
    # readahead pool / object-store fetch path (data/source.py)
    "read.pread": "raise",     # transient/persistent pread / ranged-GET error
    "read.short": "data",      # short read: the fetch returns truncated data
    "read.latency": "sleep",   # latency spike in the fetch slot
    "read.corrupt": "data",    # corrupt page bytes reach the decoder
    # native page decode (data/source.py decode side)
    "decode.chunk": "data",    # one column chunk fails to decode
    "decode.runs": "data",     # a run-length stream corrupts mid-chunk
    "decode.worker": "raise",  # a decode worker dies mid-unit
    # staged stream pipeline (ops/pipeline.py)
    "pipeline.stage": "raise",  # the stage worker raises mid-batch
    "pipeline.stall": "sleep",  # the stage worker wedges on one batch
    # state repository (repository/states.py, windows/segments.py)
    "state.save": "raise",     # the per-partition state commit fails
    "state.load": "raise",     # a cached-state read fails
    "state.segment": "raise",  # a DQSG segment envelope read/write fails
    # DQ service (service/): the fleet-scale execution layer
    "service.worker": "raise",     # a pool worker dies executing a run
    "service.scheduler": "sleep",  # the scheduler housekeeping tick wedges
    "service.admission": "raise",  # admission bookkeeping fails mid-submit
    "service.queue": "raise",      # a tier-queue pop fails (corruption)
    # sharded streaming scan (parallel/shard.py, parallel/multihost.py)
    "shard.assign": "raise",       # the shard planner fails mid-plan
    "shard.merge": "data",         # one gathered partition entry corrupts
    "shard.host_loss": "data",     # a whole shard's envelope is lost
}

FAULT_POINTS = frozenset(FAULT_KINDS)

DEFAULT_STALL_S = 0.02


class InjectedFaultError(OSError):
    """A fault the harness injected. Subclasses OSError so the engine's
    transient-IO retry paths handle it exactly like a real pread/GET
    failure — nothing in product code special-cases injection."""

    def __init__(self, point: str, occurrence: int) -> None:
        super().__init__(f"injected fault at {point} (occurrence {occurrence})")
        self.point = point
        self.occurrence = occurrence


class FaultSpecError(ValueError):
    """The DEEQU_TPU_FAULTS spec string does not parse."""


class FaultPlan:
    """One armed injection schedule: per-point rates/budgets plus the
    occurrence counters that make the schedule deterministic."""

    def __init__(
        self,
        specs: Dict[str, Tuple[float, Optional[int]]],
        *,
        seed: int = 0,
        stall_s: float = DEFAULT_STALL_S,
    ) -> None:
        for name in specs:
            if name not in FAULT_POINTS:
                raise FaultSpecError(
                    f"unknown fault point {name!r} (registered: "
                    f"{', '.join(sorted(FAULT_POINTS))})"
                )
        self.specs = dict(specs)
        self.seed = int(seed)
        self.stall_s = float(stall_s)
        self._lock = threading.Lock()
        self._occurrences: Dict[str, int] = {}
        #: point -> injections actually fired (tests/bench assert on it)
        self.injected: Dict[str, int] = {}

    def decide(self, point: str) -> Optional[str]:
        """One occurrence at `point`: None (pass through) or the point's
        kind-directive when this occurrence injects."""
        spec = self.specs.get(point)
        if spec is None:
            return None
        rate, budget = spec
        with self._lock:
            i = self._occurrences.get(point, 0)
            self._occurrences[point] = i + 1
            fired = self.injected.get(point, 0)
            if budget is not None and fired >= budget:
                return None
            if random.Random(f"{self.seed}:{point}:{i}").random() >= rate:
                return None
            self.injected[point] = fired + 1
        kind = FAULT_KINDS[point]
        if kind == "raise":
            raise InjectedFaultError(point, i)
        if kind == "sleep":
            time.sleep(self.stall_s)
            return None
        # data kind: the call site applies the directive to its buffers
        return {
            "read.short": "short",
            "read.corrupt": "corrupt",
            "decode.chunk": "fail",
            "decode.runs": "fail",
            "shard.merge": "corrupt",
            "shard.host_loss": "lost",
        }[point]


def parse_spec(spec: str) -> FaultPlan:
    """Parse a DEEQU_TPU_FAULTS spec string into a FaultPlan."""
    seed = 0
    stall_s = DEFAULT_STALL_S
    specs: Dict[str, Tuple[float, Optional[int]]] = {}
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        if token.startswith("seed="):
            seed = int(token[len("seed="):])
            continue
        if token.startswith("stall="):
            stall_s = float(token[len("stall="):])
            continue
        parts = token.split(":")
        if len(parts) not in (2, 3):
            raise FaultSpecError(
                f"bad fault token {token!r}: expected name:rate[:count]"
            )
        name = parts[0].strip()
        try:
            rate = float(parts[1])
            count = int(parts[2]) if len(parts) == 3 else None
        except ValueError as e:
            raise FaultSpecError(f"bad fault token {token!r}: {e}") from e
        if not (0.0 <= rate <= 1.0):
            raise FaultSpecError(f"rate out of [0,1] in {token!r}")
        specs[name] = (rate, count)
    return FaultPlan(specs, seed=seed, stall_s=stall_s)


# the armed plan; None (the overwhelmingly common case) short-circuits
# fault_point to a single global read. Written only by install()/_disarm
# under _install_lock; racing readers see either None or a full plan.
_PLAN: Optional[FaultPlan] = None
_install_lock = threading.Lock()


def fault_point(point: str) -> Optional[str]:
    """One occurrence at a named fault seam. Returns None (no fault) or
    a data directive; raises InjectedFaultError for raise-kind points;
    sleeps for sleep-kind points. Product call sites must use a string
    literal registered in FAULT_POINTS (lint-enforced)."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.decide(point)


def active_plan() -> Optional[FaultPlan]:
    """The armed FaultPlan, or None."""
    return _PLAN


@contextlib.contextmanager
def install(spec: str) -> Iterator[FaultPlan]:
    """Arm a fault plan for the duration of the block (tests)."""
    global _PLAN
    plan = parse_spec(spec)
    with _install_lock:
        previous = _PLAN
        _PLAN = plan
    try:
        yield plan
    finally:
        with _install_lock:
            _PLAN = previous


def install_from_env() -> Optional[FaultPlan]:
    """Arm from DEEQU_TPU_FAULTS (subprocess / `make chaos` entry).
    Returns the armed plan, or None when the knob is unset/empty."""
    global _PLAN
    raw = os.environ.get(ENV_KNOB, "").strip()
    if not raw:
        return None
    plan = parse_spec(raw)
    with _install_lock:
        _PLAN = plan
    return plan


# a process started with the knob set is armed from import — the
# SIGKILL/resume and `make chaos` subprocesses need no harness code
install_from_env()

__all__ = [
    "DEFAULT_STALL_S",
    "ENV_KNOB",
    "FAULT_KINDS",
    "FAULT_POINTS",
    "FaultPlan",
    "FaultSpecError",
    "InjectedFaultError",
    "active_plan",
    "fault_point",
    "install",
    "install_from_env",
    "parse_spec",
]

from deequ_tpu.verification.suite import VerificationSuite
from deequ_tpu.verification.result import VerificationResult

__all__ = ["VerificationSuite", "VerificationResult"]

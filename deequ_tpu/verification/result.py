"""VerificationResult: status + per-check constraint results + metrics,
with DataFrame/JSON exporters.

reference: VerificationResult.scala:33-119.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List

from deequ_tpu.checks.check import Check, CheckResult, CheckStatus
from deequ_tpu.core.metrics import Metric
from deequ_tpu.runners.context import AnalyzerContext

if TYPE_CHECKING:
    from deequ_tpu.analyzers.base import Analyzer


@dataclass
class VerificationResult:
    status: CheckStatus
    check_results: Dict[Check, CheckResult]
    metrics: Dict["Analyzer", Metric]
    # plan-validation diagnostics attached in lenient mode
    # (deequ_tpu.lint.Diagnostic items); empty when validation is off or
    # the plan is clean
    validation_warnings: List = field(default_factory=list)
    # observability: the run's RunTrace (deequ_tpu.observe) when tracing
    # was enabled via with_tracing(...) or DEEQU_TPU_TRACE, else None
    run_trace: object = None
    # static cost prediction (lint/cost.PlanCost) from the validation
    # pass; None when validation is off
    plan_cost: object = None
    # failure forensics (observe/forensics.ForensicsReport): sampled
    # violating rows + metric provenance when capture was enabled via
    # with_forensics(...) or DEEQU_TPU_FORENSICS, else None
    forensics_report: object = None

    def forensics(self):
        """The run's ForensicsReport — per-constraint sampled violating
        rows with (partition, row group, row index, value) coordinates
        plus plan/partition provenance — or None when forensics capture
        was off (the default)."""
        return self.forensics_report

    # -- metric exporters (reference: VerificationResult.scala:40-72) --------

    def success_metrics_as_rows(self, for_analyzers=None) -> List[Dict[str, object]]:
        return AnalyzerContext(self.metrics).success_metrics_as_rows(for_analyzers)

    def success_metrics_as_table(self, for_analyzers=None):
        return AnalyzerContext(self.metrics).success_metrics_as_table(for_analyzers)

    def success_metrics_as_json(self, for_analyzers=None) -> str:
        return AnalyzerContext(self.metrics).success_metrics_as_json(for_analyzers)

    # -- check exporters (reference: VerificationResult.scala:74-117) --------

    def check_results_as_rows(self, for_checks=None) -> List[Dict[str, object]]:
        include = set(id(c) for c in for_checks) if for_checks else None
        rows: List[Dict[str, object]] = []
        for check, result in self.check_results.items():
            if include is not None and id(check) not in include:
                continue
            for cr in result.constraint_results:
                rows.append(
                    {
                        "check": check.description,
                        "check_level": check.level.value,
                        "check_status": result.status.value,
                        "constraint": repr(cr.constraint),
                        "constraint_status": cr.status.value,
                        "constraint_message": cr.message or "",
                    }
                )
        return rows

    def check_results_as_table(self, for_checks=None):
        from deequ_tpu.data.table import Table

        rows = self.check_results_as_rows(for_checks)
        return Table.from_pydict(
            {
                "check": [r["check"] for r in rows],
                "check_level": [r["check_level"] for r in rows],
                "check_status": [r["check_status"] for r in rows],
                "constraint": [r["constraint"] for r in rows],
                "constraint_status": [r["constraint_status"] for r in rows],
                "constraint_message": [r["constraint_message"] for r in rows],
            }
        )

    def check_results_as_json(self, for_checks=None) -> str:
        return json.dumps(self.check_results_as_rows(for_checks))

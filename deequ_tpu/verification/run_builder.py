"""Fluent builder for verification runs.

reference: VerificationRunBuilder.scala:28-308 (incl. the repository
variant's options and addAnomalyCheck).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.checks.check import Check, CheckLevel
from deequ_tpu.verification.result import VerificationResult
from deequ_tpu.verification.suite import VerificationSuite

if TYPE_CHECKING:
    from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister
    from deequ_tpu.data.table import Table
    from deequ_tpu.repository.base import MetricsRepository, ResultKey


@dataclass
class AnomalyCheckConfig:
    """reference: VerificationRunBuilder.scala:303."""

    level: CheckLevel
    description: str
    with_tag_values: Optional[Dict[str, str]] = None
    after_date: Optional[int] = None
    before_date: Optional[int] = None


class VerificationRunBuilder:
    def __init__(self, data: "Table"):
        self._data = data
        self._checks: List[Check] = []
        self._required_analyzers: List[Analyzer] = []
        self._metrics_repository: Optional["MetricsRepository"] = None
        self._reuse_key: Optional["ResultKey"] = None
        self._fail_if_results_missing = False
        self._save_key: Optional["ResultKey"] = None
        self._aggregate_with: Optional["StateLoader"] = None
        self._save_states_with: Optional["StatePersister"] = None
        self._engine: str = "auto"
        self._mesh = None
        self._state_repository = None
        self._dataset_name: str = "default"
        self._validation: Optional[str] = None
        self._tracing = None
        self._forensics: Optional[bool] = None
        self._forensics_max_samples: int = 10
        self._controller = None
        self._deadline_s: Optional[float] = None
        self._save_check_results_json_path: Optional[str] = None
        self._save_success_metrics_json_path: Optional[str] = None
        self._overwrite_output_files = False

    def with_engine(self, engine: str, mesh=None) -> "VerificationRunBuilder":
        """"auto" (mesh when >1 device), "single", or "distributed"."""
        self._engine = engine
        self._mesh = mesh
        return self

    def explain(self, **kwargs):
        """EXPLAIN the planned verification without scanning a row: the
        static cost/effect prediction plus DQ3xx performance
        diagnostics, as an `ExplainResult` (render with `str(...)`)."""
        from deequ_tpu.lint.explain import explain_plan

        if self._deadline_s is not None:
            kwargs.setdefault("deadline_s", self._deadline_s)
        return explain_plan(
            self._data,
            analyzers=self._required_analyzers,
            checks=self._checks,
            **kwargs,
        )

    def with_plan_validation(self, mode: str) -> "VerificationRunBuilder":
        """Plan-time static analysis mode: "strict" raises one aggregated
        PlanValidationError before any scan, "lenient" (default) attaches
        diagnostics to the result, "off" skips the pass."""
        self._validation = mode
        return self

    def with_tracing(self, trace=True) -> "VerificationRunBuilder":
        """Run observability (deequ_tpu.observe): True records a
        hierarchical span tree (plan / dispatch / transfer / merge /
        constraint eval) attached as `result.run_trace`; a str
        additionally writes the Chrome-trace JSON to that path (load in
        Perfetto); False forces tracing off regardless of the
        DEEQU_TPU_TRACE env knob."""
        self._tracing = trace
        return self

    def with_forensics(
        self, enabled: bool = True, max_samples: int = 10
    ) -> "VerificationRunBuilder":
        """Failure forensics (deequ_tpu.observe.forensics): capture a
        bounded deterministic sample of violating rows — with
        (partition, row group, row index, offending values)
        coordinates — for every row-level-capable constraint, plus a
        provenance record per run (plan signature, scanned-vs-cached
        partitions, row groups pruned, decode routing). Attached as
        `result.forensics()`; persisted as an audit trail when a
        metrics repository and save key are set. Off by default (also
        reachable via DEEQU_TPU_FORENSICS=1); metrics and check
        outcomes are bit-identical either way."""
        self._forensics = bool(enabled)
        self._forensics_max_samples = int(max_samples)
        return self

    def with_controller(self, controller) -> "VerificationRunBuilder":
        """Cooperative run control (deequ_tpu.core.controller): attach a
        `RunController` whose `cancel()` any thread may call; the run
        honors it at batch granularity and raises `RunCancelled`
        (DQ401) carrying progress after every stage thread joined. With
        a partitioned source and a state repository, committed
        partitions resume from cache on the rerun."""
        self._controller = controller
        return self

    def with_deadline(self, seconds: float) -> "VerificationRunBuilder":
        """Bound the run's wall time: past `seconds` the next batch
        check raises `RunCancelled` (DQ402). Equivalent to
        `with_controller(RunController(deadline_s=seconds))`; EXPLAIN
        renders the knob and DQ318 warns when the source has no
        partition boundaries to resume from."""
        self._deadline_s = float(seconds)
        return self

    def add_check(self, check: Check) -> "VerificationRunBuilder":
        self._checks.append(check)
        return self

    def add_checks(self, checks: Sequence[Check]) -> "VerificationRunBuilder":
        self._checks.extend(checks)
        return self

    def add_required_analyzer(self, analyzer: Analyzer) -> "VerificationRunBuilder":
        self._required_analyzers.append(analyzer)
        return self

    def add_required_analyzers(self, analyzers: Sequence[Analyzer]) -> "VerificationRunBuilder":
        self._required_analyzers.extend(analyzers)
        return self

    def aggregate_with(self, loader: "StateLoader") -> "VerificationRunBuilder":
        self._aggregate_with = loader
        return self

    def save_states_with(self, persister: "StatePersister") -> "VerificationRunBuilder":
        self._save_states_with = persister
        return self

    def with_state_repository(
        self, repository, dataset: str = "default"
    ) -> "VerificationRunBuilder":
        """Persist and reuse per-partition analyzer states across runs.

        With a `StateRepository` attached and a partitioned source
        (`Table.scan_parquet_dataset`), the verification scan loads
        cached states for unchanged partitions and scans only new or
        modified ones — results stay bit-identical to a full rescan.
        `dataset` namespaces the cache entries."""
        self._state_repository = repository
        self._dataset_name = dataset
        return self

    def use_repository(self, repository: "MetricsRepository") -> "VerificationRunBuilder":
        """reference: VerificationRunBuilder.scala:114-117 — unlocks the
        repository-backed options below."""
        self._metrics_repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key: "ResultKey", fail_if_results_missing: bool = False
    ) -> "VerificationRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key: "ResultKey") -> "VerificationRunBuilder":
        self._save_key = key
        return self

    def add_anomaly_check(
        self,
        anomaly_detection_strategy,
        analyzer: Analyzer,
        anomaly_check_config: Optional[AnomalyCheckConfig] = None,
    ) -> "VerificationRunBuilder":
        """reference: VerificationRunBuilder.scala:194-210."""
        if self._metrics_repository is None:
            raise ValueError(
                "addAnomalyCheck requires a repository — call use_repository first"
            )
        config = anomaly_check_config or AnomalyCheckConfig(
            CheckLevel.WARNING,
            f"Anomaly check for {analyzer!r}",
        )
        check = Check(config.level, config.description).is_newest_point_non_anomalous(
            self._metrics_repository,
            anomaly_detection_strategy,
            analyzer,
            config.with_tag_values,
            config.after_date,
            config.before_date,
        )
        self._checks.append(check)
        return self

    def save_check_results_json_to_path(self, path: str) -> "VerificationRunBuilder":
        """reference: VerificationRunBuilder.scala:226-231."""
        self._save_check_results_json_path = path
        return self

    def save_success_metrics_json_to_path(self, path: str) -> "VerificationRunBuilder":
        """reference: VerificationRunBuilder.scala:239-244."""
        self._save_success_metrics_json_path = path
        return self

    def overwrite_output_files(self, value: bool) -> "VerificationRunBuilder":
        """Whether previous files with identical names should be
        overwritten (reference: VerificationRunBuilder.scala:253-256 —
        where the reference's self-assignment bug makes the option a
        no-op; here it works)."""
        self._overwrite_output_files = value
        return self

    def run(self) -> VerificationResult:
        result = VerificationSuite.do_verification_run(
            self._data,
            self._checks,
            self._required_analyzers,
            aggregate_with=self._aggregate_with,
            save_states_with=self._save_states_with,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_or_append_results_with_key=self._save_key,
            engine=self._engine,
            mesh=self._mesh,
            validation=self._validation,
            tracing=self._tracing,
            state_repository=self._state_repository,
            dataset_name=self._dataset_name,
            forensics=self._forensics,
            forensics_max_samples=self._forensics_max_samples,
            controller=self._controller,
            deadline_s=self._deadline_s,
        )
        # JSON file outputs (reference: VerificationSuite.scala:146-172)
        from deequ_tpu.core.fileio import write_text_output

        if self._save_check_results_json_path is not None:
            write_text_output(
                self._save_check_results_json_path,
                result.check_results_as_json(),
                self._overwrite_output_files,
            )
        if self._save_success_metrics_json_path is not None:
            write_text_output(
                self._save_success_metrics_json_path,
                result.success_metrics_as_json(),
                self._overwrite_output_files,
            )
        return result

"""VerificationSuite: orchestrates a verification run.

reference: VerificationSuite.scala:49-281. Collects required analyzers from
checks, runs one (fused) analysis, evaluates checks, persists results.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from deequ_tpu import observe
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.checks.check import Check, CheckResult, CheckStatus
from deequ_tpu.ops.runtime import forensics_enabled as runtime_forensics_enabled
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.runners.context import AnalyzerContext
from deequ_tpu.verification.result import VerificationResult

if TYPE_CHECKING:
    from deequ_tpu.analyzers.state_provider import StateLoader, StatePersister
    from deequ_tpu.data.table import Table
    from deequ_tpu.repository.base import MetricsRepository, ResultKey


class VerificationSuite:
    @staticmethod
    def on_data(data: "Table"):
        from deequ_tpu.verification.run_builder import VerificationRunBuilder

        return VerificationRunBuilder(data)

    # reference: VerificationSuite.scala:80-104 (deprecated run shortcut)
    def run(
        self,
        data: "Table",
        checks: Sequence[Check],
        required_analyzers: Sequence[Analyzer] = (),
    ) -> VerificationResult:
        return self.do_verification_run(data, checks, required_analyzers)

    @staticmethod
    def do_verification_run(
        data: "Table",
        checks: Sequence[Check],
        required_analyzers: Sequence[Analyzer] = (),
        aggregate_with: Optional["StateLoader"] = None,
        save_states_with: Optional["StatePersister"] = None,
        metrics_repository: Optional["MetricsRepository"] = None,
        reuse_existing_results_for_key: Optional["ResultKey"] = None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key: Optional["ResultKey"] = None,
        engine: str = "auto",
        mesh=None,
        validation: Optional[str] = None,
        tracing=None,
        state_repository=None,
        dataset_name: str = "default",
        forensics: Optional[bool] = None,
        forensics_max_samples: int = 10,
        controller=None,
        deadline_s: Optional[float] = None,
    ) -> VerificationResult:
        """reference: VerificationSuite.scala:107-144.

        `validation` — plan-time static analysis mode: "strict" raises one
        aggregated PlanValidationError before any kernel dispatch,
        "lenient" (default) attaches diagnostics to the result, "off"
        skips. Defaults to env DEEQU_TPU_VALIDATE, then lenient.

        `tracing` — run observability (deequ_tpu.observe): True records
        a span tree, a str additionally names the Chrome-trace output
        path, None defers to the DEEQU_TPU_TRACE env knob, False forces
        off. The finished trace attaches as `result.run_trace`.

        `state_repository` / `dataset_name` — incremental computation:
        with a `StateRepository` and a partitioned source, unchanged
        partitions load their folded analyzer states from the cache
        instead of rescanning (see runners.AnalysisRunner).

        `forensics` — failure forensics (deequ_tpu.observe.forensics):
        True captures a bounded deterministic sample of violating rows
        per row-level-capable constraint plus metric provenance,
        attached as `result.forensics()` and persisted as an audit
        trail when a repository + save key are set; False forces off;
        None (default) defers to the DEEQU_TPU_FORENSICS env knob.
        Metrics are bit-identical either way.

        `controller` / `deadline_s` — cooperative run control
        (deequ_tpu.core.controller): a `RunController` is honored at
        batch granularity; `cancel()` or a tripped deadline raises
        `RunCancelled` (DQ401/DQ402) carrying the run's progress after
        every stage thread and file descriptor joined. `deadline_s`
        without a controller constructs one. With a partitioned source
        and a `state_repository`, every partition committed before the
        cancel loads from cache on the rerun — resumable by default.
        """
        if controller is None and deadline_s is not None:
            from deequ_tpu.core.controller import RunController

            controller = RunController(deadline_s=deadline_s)
        with observe.traced_run(
            "verification_suite", enable=tracing, checks=len(checks)
        ) as run:
            analyzers: List[Analyzer] = list(required_analyzers)
            for check in checks:
                analyzers.extend(check.required_analyzers())

            capture = None
            enable_forensics = (
                forensics
                if forensics is not None
                else runtime_forensics_enabled()
            )
            if enable_forensics and mesh is None:
                # mesh runs shard batches across devices: no ordered
                # per-batch host fold to hook, so capture degrades to off
                # (documented fallback, mirrors the state-cache rule)
                from deequ_tpu.observe.forensics import ForensicsCapture

                capture = ForensicsCapture(
                    checks, max_samples=forensics_max_samples
                )

            with observe.span("plan_validate", cat="plan"):
                validation_diagnostics, plan_cost = (
                    VerificationSuite._validate_plan(
                        data,
                        checks,
                        required_analyzers,
                        validation,
                        state_repository=state_repository,
                        dataset_name=dataset_name,
                        deadline_s=deadline_s,
                    )
                )

            analysis_results = AnalysisRunner.do_analysis_run(
                data,
                analyzers,
                aggregate_with=aggregate_with,
                save_states_with=save_states_with,
                metrics_repository=metrics_repository,
                reuse_existing_results_for_key=reuse_existing_results_for_key,
                fail_if_results_missing=fail_if_results_missing,
                # NOT forwarded: results are saved AFTER check evaluation, so
                # anomaly-check assertions querying the repository see only
                # prior history, not this run's own metrics
                # (reference: VerificationSuite.scala:121-139 passes
                # saveOrAppendResultsWithKey = None into the runner and saves
                # post-evaluate)
                save_or_append_results_with_key=None,
                engine=engine,
                mesh=mesh,
                # the suite already validated the full plan (checks included);
                # don't lint the bare analyzer list a second time
                validation="off",
                state_repository=state_repository,
                dataset_name=dataset_name,
                forensics=capture,
                controller=controller,
            )

            verification_result = VerificationSuite.evaluate(
                checks, analysis_results
            )
            verification_result.validation_warnings = validation_diagnostics
            verification_result.plan_cost = plan_cost

            save_context = analysis_results
            if capture is not None:
                report = capture.finalize(verification_result.check_results)
                verification_result.forensics_report = report
                if (
                    metrics_repository is not None
                    and save_or_append_results_with_key is not None
                ):
                    # the audit trail persists through the SAME repository
                    # save as the metrics it explains (repository/audit.py)
                    from deequ_tpu.repository.audit import audit_entry_for

                    record, metric = audit_entry_for(report)
                    save_context = analysis_results + AnalyzerContext(
                        {record: metric}
                    )

            if (
                metrics_repository is not None
                and save_or_append_results_with_key is not None
            ):
                AnalysisRunner._save_or_append(
                    metrics_repository,
                    save_or_append_results_with_key,
                    save_context,
                )
        if run:
            verification_result.run_trace = run.trace

        return verification_result

    @staticmethod
    def _validate_plan(
        data,
        checks,
        required_analyzers,
        validation,
        state_repository=None,
        dataset_name: str = "default",
        deadline_s=None,
    ):
        """Static plan analysis before any scan -> (diagnostics,
        PlanCost | None). Strict mode propagates the aggregated
        PlanValidationError; otherwise the linter must never break a
        run — any internal failure is swallowed."""
        from deequ_tpu.lint import PlanValidationError, SchemaInfo, validate_plan
        from deequ_tpu.lint.planlint import resolve_validation_mode

        mode = resolve_validation_mode(validation)
        if mode == "off":
            return [], None
        try:
            schema = SchemaInfo.from_table(data)
            partitions = None
            if getattr(data, "partitions", None) is not None:
                analyzers: List[Analyzer] = list(required_analyzers)
                for check in checks:
                    analyzers.extend(check.required_analyzers())
                cache = None
                if state_repository is not None:
                    from deequ_tpu.repository.states import StateCacheContext

                    cache = StateCacheContext(state_repository, dataset_name)
                partitions = AnalysisRunner._predict_partitions(
                    data, analyzers, cache
                )
            report = validate_plan(
                schema,
                checks,
                required_analyzers,
                mode=mode,
                num_rows=int(data.num_rows),
                partitions=partitions,
                deadline_s=deadline_s,
            )
            return list(report.diagnostics), report.plan_cost
        except PlanValidationError:
            raise
        except Exception:  # noqa: BLE001
            return [], None

    @staticmethod
    def run_on_aggregated_states(
        schema_table: "Table",
        checks: Sequence[Check],
        state_loaders: Sequence["StateLoader"],
        required_analyzers: Sequence[Analyzer] = (),
        save_states_with: Optional["StatePersister"] = None,
        metrics_repository: Optional["MetricsRepository"] = None,
        save_or_append_results_with_key: Optional["ResultKey"] = None,
    ) -> VerificationResult:
        """reference: VerificationSuite.scala:208-229."""
        analyzers: List[Analyzer] = list(required_analyzers)
        for check in checks:
            analyzers.extend(check.required_analyzers())

        analysis_results = AnalysisRunner.run_on_aggregated_states(
            schema_table,
            analyzers,
            state_loaders,
            save_states_with=save_states_with,
            metrics_repository=metrics_repository,
            # saved after evaluation, same as do_verification_run: anomaly
            # assertions must not see this run's own metrics as history
            save_or_append_results_with_key=None,
        )
        verification_result = VerificationSuite.evaluate(checks, analysis_results)
        if metrics_repository is not None and save_or_append_results_with_key is not None:
            AnalysisRunner._save_or_append(
                metrics_repository, save_or_append_results_with_key, analysis_results
            )
        return verification_result

    @staticmethod
    def is_check_applicable_to_data(check: Check, schema, num_records: int = 1000):
        """Dry-run the check's analyzers on generated data matching the
        schema (reference: VerificationSuite.scala:238-261)."""
        from deequ_tpu.applicability.applicability import Applicability

        return Applicability().is_applicable(check, schema, num_records)

    @staticmethod
    def evaluate(
        checks: Sequence[Check], analysis_context: AnalyzerContext
    ) -> VerificationResult:
        """reference: VerificationSuite.scala:263-281 — overall status is
        the max severity over check statuses."""
        with observe.span(
            "constraint_eval", cat="constraint", checks=len(checks)
        ):
            check_results: Dict[Check, CheckResult] = {
                check: check.evaluate(analysis_context) for check in checks
            }
        if check_results:
            status = max(
                (r.status for r in check_results.values()), key=lambda s: s.severity
            )
        else:
            status = CheckStatus.SUCCESS
        return VerificationResult(status, check_results, dict(analysis_context.metric_map))

"""Temporal state algebra: windowed metrics over partition time.

The semigroup of mergeable sufficient statistics (PAPER.md §0) makes
metrics over ANY span of data a pure state merge — this package turns
that algebra into a first-class time axis. `WindowSpec` (tumbling,
sliding, last-N) compiles a window query into a merge tree over
`StateRepository` entries; precomputed power-of-two segment states
(`DQSG` envelopes, `segments.py`) resolve any window in O(log
#partitions) repository loads with zero data rows read; and
`WindowQuery` (`query.py`) executes the tree bit-identically to a full
rescan of the same partitions.
"""

from deequ_tpu.windows.spec import (
    LastN,
    Sliding,
    Timeline,
    Tumbling,
    WindowFrame,
    WindowSpec,
    default_bucket_for,
)
from deequ_tpu.windows.segments import (
    SEGMENT_FORMAT_VERSION,
    SEGMENT_MAGIC,
    Segment,
    SegmentStore,
    aligned_cover,
    decode_segment,
    encode_segment,
    span_fingerprint,
)
from deequ_tpu.windows.query import (
    SpanResolution,
    WindowPlan,
    WindowQuery,
)

__all__ = [
    "SEGMENT_FORMAT_VERSION",
    "SEGMENT_MAGIC",
    "LastN",
    "Segment",
    "SegmentStore",
    "Sliding",
    "SpanResolution",
    "Timeline",
    "Tumbling",
    "WindowFrame",
    "WindowPlan",
    "WindowQuery",
    "WindowSpec",
    "aligned_cover",
    "decode_segment",
    "default_bucket_for",
    "encode_segment",
    "span_fingerprint",
]

"""Window queries: metrics over a partition-time window as an O(log n)
segment merge, bit-identical to a full rescan, with zero data rows read
when the repository is warm.

Execution shape:

  1. resolve the window spec against the dataset's timeline
     (`spec.Timeline.derive` — layout dates or positional buckets);
  2. decompose the window's bucket range into the canonical aligned
     power-of-two cover (`segments.aligned_cover`) and address each
     span by its content fingerprint — late or re-stated partitions
     changed exactly the covering spans' keys, so staleness is
     impossible by construction;
  3. load each span's `DQSG` segment (one repository round-trip per
     span); a missing/corrupt span rebuilds from per-partition `DQST`
     states and is re-published, and partitions with no usable state at
     all are rescanned through the ordinary `AnalysisRunner` path
     (which re-commits their states);
  4. merge every member partition's states sequentially in global name
     order through the same `merge_states` semigroup surface the fused
     scan uses — the merge tree is identical to the engine's, so the
     answer is bit-identical to scanning the window's partitions.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

from deequ_tpu import observe
from deequ_tpu.lint.diagnostics import Diagnostic, Severity
from deequ_tpu.observe import counters as _counters
from deequ_tpu.repository.states import (
    StateDecodeError,
    decode_states,
    merge_states,
    plan_signature_for,
)
from deequ_tpu.windows.segments import (
    SegmentStore,
    aligned_cover,
    segment_key,
    span_fingerprint,
)
from deequ_tpu.windows.spec import Timeline, WindowFrame, WindowSpec

__all__ = ["SpanResolution", "WindowPlan", "WindowQuery"]

WindowLike = Union[WindowSpec, WindowFrame]


@dataclass(frozen=True)
class SpanResolution:
    """One cover span's resolution: which aligned span, its content
    fingerprint, its member partition indices, and whether a segment
    envelope for it already exists in the repository."""

    level: int
    start: int
    fingerprint: str
    indices: Tuple[int, ...]
    hit: bool

    @property
    def span(self) -> Tuple[int, int]:
        return (self.start, self.start + (1 << self.level))


@dataclass
class WindowPlan:
    """The compiled merge tree of one window query: resolved frame,
    cover spans with hit/miss verdicts, partitions that must rescan
    (no usable per-partition state), and the byte accounting EXPLAIN
    and admission consume."""

    frame: WindowFrame
    spec_text: str
    signature: str
    spans: List[SpanResolution] = field(default_factory=list)
    #: partition names with no usable per-partition state entry — these
    #: rescan (and re-commit states) before the merge can run
    partitions_rescanned: Tuple[str, ...] = ()
    rescan_paths: Tuple[str, ...] = ()
    predicted_scan_bytes: float = 0.0
    saved_window_bytes: float = 0.0
    diagnostics: List[Diagnostic] = field(default_factory=list)

    @property
    def segments_merged(self) -> int:
        return len(self.spans)

    @property
    def segment_hits(self) -> int:
        return sum(1 for s in self.spans if s.hit)

    @property
    def segment_misses(self) -> int:
        return sum(1 for s in self.spans if not s.hit)

    def summary(self) -> str:
        return (
            f"{self.spec_text} -> {self.segments_merged} segment "
            f"merges ({self.segment_hits} warm), "
            f"{len(self.partitions_rescanned)} partitions rescanned"
        )


class WindowQuery:
    """Windowed metrics over a partitioned source through the
    repository's state algebra.

    `analyzers` must be scan-shareable, non-grouping analyzers — the
    family whose states the partitioned fused pass commits per
    partition — given in the SAME order the filling scans used, so the
    plan signature (and therefore every state entry) matches.
    """

    def __init__(
        self,
        source: Any,
        analyzers: Sequence[Any],
        *,
        repository: Any,
        dataset: str,
        extractor: Optional[Callable[[str], Optional[int]]] = None,
        batch_size: Optional[int] = None,
    ) -> None:
        from deequ_tpu.analyzers.base import ScanShareableAnalyzer
        from deequ_tpu.analyzers.grouping import GroupingAnalyzer

        seen: set = set()
        unique: List[Any] = []
        for a in analyzers:
            if a in seen:
                continue
            seen.add(a)
            unique.append(a)
        for a in unique:
            if isinstance(a, GroupingAnalyzer) or not isinstance(
                a, ScanShareableAnalyzer
            ):
                raise ValueError(
                    f"window queries need scan-shareable, non-grouping "
                    f"analyzers (their states are committed per "
                    f"partition); {a!r} is not"
                )
        if not unique:
            raise ValueError("window query needs at least one analyzer")
        self.analyzers: Tuple[Any, ...] = tuple(unique)
        self._source = source
        self._repository = repository
        self._dataset = dataset
        self._extractor = extractor
        self._batch_size = batch_size

    # -- plan ----------------------------------------------------------------

    def signature(self) -> str:
        """The live plan signature — the exact key
        `FusedScanPass._run_partitioned` computes for these analyzers
        over this source under the current runtime knobs."""
        return plan_signature_for(
            list(self.analyzers), self._source, self._batch_size
        )

    def timeline(self) -> Timeline:
        return Timeline.derive(self._source.partitions(), self._extractor)

    def _frame(self, window: WindowLike, timeline: Timeline) -> WindowFrame:
        if isinstance(window, WindowFrame):
            return window
        return window.resolve(timeline)

    def plan(
        self, window: WindowLike, *, timeline: Optional[Timeline] = None
    ) -> WindowPlan:
        """Compile the window into its merge tree and classify every
        span (segment hit / rebuild) and member partition (state present
        / rescan) — without reading a row or moving a byte."""
        parts = self._source.partitions()
        if timeline is None:
            timeline = Timeline.derive(parts, self._extractor)
        frame = self._frame(window, timeline)
        signature = self.signature()
        spec_text = (
            window.describe()
            if isinstance(window, WindowSpec)
            else frame.label
        )
        plan = WindowPlan(frame=frame, spec_text=spec_text, signature=signature)
        if not frame.indices:
            return plan

        store = SegmentStore(self._repository, self._dataset, signature)
        cover_lo = timeline.buckets[frame.indices[0]]
        cover_hi = timeline.buckets[frame.indices[-1]] + 1
        member_set = frozenset(frame.indices)
        for level, start in aligned_cover(cover_lo, cover_hi):
            end = start + (1 << level)
            idx = tuple(
                i
                for i in frame.indices
                if start <= timeline.buckets[i] < end
            )
            if not idx:
                continue  # sparse timeline: the span covers no partition
            members = [(timeline.buckets[i], parts[i].fingerprint) for i in idx]
            fp = span_fingerprint(level, start, members)
            plan.spans.append(
                SpanResolution(
                    level=level, start=start, fingerprint=fp, indices=idx,
                    hit=store.has(level, fp),
                )
            )

        # partitions needing a rescan: members of MISSED spans with no
        # per-partition state entry (a hit span carries its members'
        # states inside the segment envelope)
        needed = sorted(
            {i for s in plan.spans if not s.hit for i in s.indices}
        )
        rescan_names: List[str] = []
        rescan_paths: List[str] = []
        rescan_bytes = 0.0
        member_bytes = 0.0
        for i in frame.indices:
            try:
                nbytes = float(os.path.getsize(parts[i].path))
            except OSError:
                nbytes = 0.0
            member_bytes += nbytes
            if i in set(needed) and not self._repository.has_states(
                self._dataset, parts[i].fingerprint, signature
            ):
                rescan_names.append(parts[i].name)
                rescan_paths.append(parts[i].path)
                rescan_bytes += nbytes
        assert member_set  # non-empty frame reaches here
        plan.partitions_rescanned = tuple(rescan_names)
        plan.rescan_paths = tuple(rescan_paths)
        plan.predicted_scan_bytes = rescan_bytes
        plan.saved_window_bytes = member_bytes - rescan_bytes

        missed = [s for s in plan.spans if not s.hit]
        if missed:
            named = ", ".join(
                f"[{s.span[0]},{s.span[1]})" for s in missed[:6]
            )
            if len(missed) > 6:
                named += f", ... ({len(missed) - 6} more)"
            plan.diagnostics.append(
                Diagnostic(
                    code="DQ323",
                    severity=Severity.WARNING,
                    message=(
                        f"window not resolvable from precomputed segments: "
                        f"{len(missed)} of {len(plan.spans)} cover span(s) "
                        f"invalidated or cold ({named}); "
                        f"{len(rescan_names)} partition(s) rescan, the rest "
                        "rebuild from per-partition states"
                    ),
                    source=spec_text,
                    span=(0, len(spec_text)),
                    subject=f"dataset {self._dataset!r}",
                )
            )
        return plan

    # -- execution -----------------------------------------------------------

    def _rescan(self, paths: Sequence[str]) -> None:
        """Scan exactly `paths` through the ordinary runner with the
        repository attached — the partitioned fused pass re-commits one
        state envelope per partition as it goes."""
        from deequ_tpu.runners.analysis_runner import AnalysisRunner

        AnalysisRunner.do_analysis_run(
            self._source.subset(list(paths)),
            list(self.analyzers),
            state_repository=self._repository,
            dataset_name=self._dataset,
        )

    def _assemble(
        self,
        plan: WindowPlan,
        parts: Sequence[Any],
        timeline: Timeline,
        *,
        warm: bool,
    ) -> Tuple[List[Tuple[str, int, bytes]], int, int]:
        """Per-partition DQST blobs for every frame member in global
        name order, via segments where possible. Returns (entries,
        segment_hits, segments_built)."""
        store = SegmentStore(self._repository, self._dataset, plan.signature)
        entries_all: List[Tuple[str, int, bytes]] = []
        hits = 0
        built = 0
        for res in plan.spans:
            seg = store.load(res.level, res.fingerprint) if res.hit else None
            expected = [parts[i].name for i in res.indices]
            if seg is not None and [e[0] for e in seg.entries] == expected:
                hits += 1
                entries_all.extend(seg.entries)
                continue
            entries: List[Tuple[str, int, bytes]] = []
            for i in res.indices:
                blob = self._repository.get_blob(
                    self._dataset, plan.signature, parts[i].fingerprint
                )
                if blob is None:
                    raise KeyError(
                        f"no cached states for dataset {self._dataset!r} "
                        f"partition {parts[i].name!r} under signature "
                        f"{plan.signature!r}"
                    )
                entries.append((parts[i].name, timeline.buckets[i], blob))
            if warm:
                store.save(res.level, res.start, res.fingerprint, entries)
            built += 1
            entries_all.extend(entries)
        return entries_all, hits, built

    def _merge(self, entries: Sequence[Tuple[str, int, bytes]]) -> List[Any]:
        """Sequential left-fold over per-partition states in global
        name order — the engine's merge tree exactly."""
        merged: List[Any] = [None] * len(self.analyzers)
        for _name, _bucket, blob in entries:
            states = decode_states(blob, self.analyzers)
            merged = [merge_states(m, s) for m, s in zip(merged, states)]
        return merged

    def _unusable_paths(
        self, plan: WindowPlan, parts: Sequence[Any]
    ) -> List[str]:
        """Frame members whose per-partition state entry is missing or
        does not decode — the degrade-to-rescan set."""
        bad: List[str] = []
        for i in plan.frame.indices:
            blob = self._repository.get_blob(
                self._dataset, plan.signature, parts[i].fingerprint
            )
            if blob is None:
                bad.append(parts[i].path)
                continue
            try:
                decode_states(blob, self.analyzers)
            except StateDecodeError:
                bad.append(parts[i].path)
        return bad

    def _resolve_states(
        self, window: WindowLike, *, warm: bool
    ) -> Tuple[WindowPlan, List[Any]]:
        """Plan + merged states, with the two recovery ladders armed:
        missing states rescan up front, and any defect discovered
        during assembly/merge (corrupt segment member, truncated
        partition envelope) degrades to one targeted rescan-and-retry —
        never a wrong answer, never an unbounded loop."""
        parts = self._source.partitions()
        timeline = Timeline.derive(parts, self._extractor)
        for attempt in (0, 1):
            plan = self.plan(window, timeline=timeline)
            with observe.span(
                "window", cat="window", op="resolve",
                spec=plan.spec_text,
                partitions=len(plan.frame.indices),
                segments=plan.segments_merged,
            ) as sp:
                if plan.rescan_paths:
                    self._rescan(plan.rescan_paths)
                try:
                    entries, hits, built = self._assemble(
                        plan, parts, timeline, warm=warm
                    )
                    merged = self._merge(entries)
                except (KeyError, StateDecodeError):
                    if attempt:
                        raise
                    bad = self._unusable_paths(plan, parts)
                    if not bad:
                        raise
                    self._rescan(bad)
                    continue
                sp.set(hits=hits, built=built)
                _counters.record_window(
                    segments=plan.segments_merged,
                    hits=hits,
                    built=built,
                    rescanned=len(plan.partitions_rescanned),
                    partitions=len(plan.frame.indices),
                )
                return plan, merged
        raise AssertionError("unreachable")  # pragma: no cover

    def run(self, window: WindowLike, *, warm: bool = True, tracing=None):
        """Metrics over the window as an `AnalyzerContext` — the same
        object a scan produces, computed purely from merged states.
        `warm=True` (default) re-publishes any cover segment that had
        to be rebuilt, so the next query over the same range is pure
        segment loads. The compiled `WindowPlan` attaches to the
        returned context as `window_plan`."""
        from deequ_tpu.runners.context import AnalyzerContext

        with observe.traced_run(
            "window_query", enable=tracing, analyzers=len(self.analyzers)
        ) as run:
            plan, merged = self._resolve_states(window, warm=warm)
            metrics = {
                analyzer: analyzer.compute_metric_from(state)
                for analyzer, state in zip(self.analyzers, merged)
            }
            context = AnalyzerContext(metrics)
        context.window_plan = plan
        context.validation_warnings = list(plan.diagnostics)
        if run.trace is not None:
            context.run_trace = run.trace
        return context

    def states(self, window: WindowLike, *, warm: bool = True):
        """The window's merged states as a `StateBag` — the two-sample
        input of the drift check family (`checks/drift.py`), with the
        plan signature carried along so baseline/current mismatches are
        detectable (DQ324)."""
        from deequ_tpu.analyzers.drift import StateBag

        plan, merged = self._resolve_states(window, warm=warm)
        return StateBag.from_pairs(
            list(zip(self.analyzers, merged)),
            signature=plan.signature,
            label=plan.frame.label,
        )

    # -- admission / EXPLAIN -------------------------------------------------

    def admission_cost(self, window: WindowLike):
        """A `PlanCost` for this window query, costed like any other
        submission: the predicted scan bytes are the rescan partitions'
        file bytes ONLY (near zero on a warm repository), and the
        window fields feed EXPLAIN's `windows:` line and the
        `drift.window_*` pins."""
        from deequ_tpu.lint.cost import analyze_plan
        from deequ_tpu.lint.schema import SchemaInfo

        parts = self._source.partitions()
        timeline = Timeline.derive(parts, self._extractor)
        plan = self.plan(window, timeline=timeline)
        rescan = set(plan.partitions_rescanned)
        records = []
        num_rows = 0
        member_paths = []
        for i in plan.frame.indices:
            member_paths.append(parts[i].path)
            try:
                nbytes = int(os.path.getsize(parts[i].path))
            except OSError:
                nbytes = 0
            records.append(
                {"cached": parts[i].name not in rescan, "bytes": nbytes}
            )
        if member_paths:
            num_rows = int(self._source.subset(member_paths).num_rows)
        schema = SchemaInfo.from_table(self._source)
        cost = analyze_plan(
            list(self.analyzers),
            schema,
            num_rows=num_rows,
            batch_size=self._batch_size,
            streaming=True,
            stream_batch_rows=getattr(self._source, "batch_rows", None),
            partitions=records,
        )
        cost.window_spec = plan.spec_text
        cost.window_segments_merged = plan.segments_merged
        cost.window_partitions_rescanned = len(plan.partitions_rescanned)
        cost.saved_window_bytes = plan.saved_window_bytes
        return cost


# re-exported for callers that build covers by hand (tests, tools)
_ = segment_key

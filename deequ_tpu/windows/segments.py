"""Precomputed segment states: power-of-two spans of per-partition DQST
envelopes, persisted through the `StateRepository` under a versioned
`DQSG` envelope keyed by ``(dataset, plan_signature, level, span
fingerprint)``.

Design note — why a segment carries per-partition blobs, not one
pre-merged state: the engine's fold is a sequential left-fold in
partition NAME order, and float addition and KLL merges are not
associative. A pre-merged segment would change the merge tree and
forfeit bit-identity with a full rescan. So a `DQSG` envelope bundles
the span's per-partition `DQST` envelopes (the exact bytes the scan
committed), and a window query still merges partition-by-partition in
global name order — bit-identical by construction. The win is IO
shape, not arithmetic: any window resolves in O(log #partitions)
repository round-trips instead of one per partition, with zero data
rows read either way. (This is the associativity trick of the
compiler-first O(1)-caching framing in PAPERS.md, applied to the
envelope level where it is sound.)

Invalidation is content-keyed: a span's fingerprint hashes its member
``(bucket, partition fingerprint)`` pairs in merge order, so a late or
re-stated partition CHANGES the key of exactly the O(log n) spans
covering its bucket — stale segments are simply never looked up again,
and the fresh keys rebuild lazily from per-partition states. Corrupt,
truncated, or version-bumped entries degrade identically: a DQ323
RuntimeWarning and a rebuild from per-partition states — never a wrong
answer. Writes ride the repository's existing tmp+rename+flock path.
"""

from __future__ import annotations

import hashlib
import struct
import warnings
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from deequ_tpu.repository.states import StateDecodeError
from deequ_tpu.testing import faults

__all__ = [
    "SEGMENT_FORMAT_VERSION",
    "SEGMENT_MAGIC",
    "Segment",
    "SegmentStore",
    "aligned_cover",
    "decode_segment",
    "encode_segment",
    "segment_key",
    "span_fingerprint",
]

#: envelope magic — "DeeQu SeGment"; bump SEGMENT_FORMAT_VERSION when
#: this layout changes (the inner DQST blobs carry their own version)
SEGMENT_MAGIC = b"DQSG"
SEGMENT_FORMAT_VERSION = 1

_DIGEST = hashlib.sha256
_DIGEST_LEN = 32


def span_fingerprint(
    level: int, start: int, members: Sequence[Tuple[int, str]]
) -> str:
    """Content key of one span: the level, the absolute span start, and
    every member's ``(bucket, partition fingerprint)`` in merge order.
    Any membership or content change yields a different key, so stale
    segment entries self-invalidate by never being addressed again."""
    h = _DIGEST()
    h.update(SEGMENT_MAGIC)
    h.update(struct.pack(">IIq", SEGMENT_FORMAT_VERSION, int(level), int(start)))
    for bucket, fingerprint in members:
        h.update(struct.pack(">q", int(bucket)))
        h.update(fingerprint.encode("utf-8") + b"\x00")
    return h.hexdigest()[:32]


def segment_key(level: int, fingerprint: str) -> str:
    """The repository key a segment lives under (the `fingerprint` slot
    of the ``(dataset, signature, fingerprint)`` triple). The `seg-`
    prefix keeps segment entries disjoint from partition fingerprints
    (which are bare hex)."""
    return f"seg-L{int(level):02d}-{fingerprint}"


@dataclass
class Segment:
    """One decoded segment: which span, under which plan signature, and
    the member partitions' DQST envelopes in merge (name) order."""

    level: int
    start: int
    signature: str
    #: (partition name, bucket, DQST envelope bytes) in merge order
    entries: List[Tuple[str, int, bytes]]

    @property
    def span(self) -> Tuple[int, int]:
        return (self.start, self.start + (1 << self.level))


def encode_segment(
    level: int,
    start: int,
    signature: str,
    entries: Sequence[Tuple[str, int, bytes]],
) -> bytes:
    """Serialize one span's per-partition envelopes:

        DQSG | version u32 | level u32 | start i64 |
          sig_len u32 | signature utf8 | count u32 |
          ( name_len u32 | name utf8 | bucket i64 |
            blob_len u32 | DQST blob )*
        | sha256(previous bytes)

    Each entry's blob is a complete self-validated `encode_states`
    envelope — byte-identical to what the scan committed per partition,
    so a window merge decodes members exactly as `merge_range` would
    load them one by one."""
    body = bytearray()
    body += SEGMENT_MAGIC
    body += struct.pack(">I", SEGMENT_FORMAT_VERSION)
    body += struct.pack(">Iq", int(level), int(start))
    sig_b = signature.encode("utf-8")
    body += struct.pack(">I", len(sig_b)) + sig_b
    body += struct.pack(">I", len(entries))
    for name, bucket, blob in entries:
        name_b = name.encode("utf-8")
        body += struct.pack(">I", len(name_b)) + name_b
        body += struct.pack(">q", int(bucket))
        body += struct.pack(">I", len(blob)) + blob
    return bytes(body) + _DIGEST(bytes(body)).digest()


def decode_segment(blob: bytes) -> Segment:
    """Inverse of `encode_segment`, validated end to end: digest first
    (corruption), then magic/version (format drift), then per-entry
    bounds (truncation). Any defect raises `StateDecodeError` — the
    caller rebuilds the span from per-partition states."""
    header = len(SEGMENT_MAGIC)
    if len(blob) < header + 4 + _DIGEST_LEN:
        raise StateDecodeError("truncated segment envelope")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if _DIGEST(body).digest() != digest:
        raise StateDecodeError("segment envelope digest mismatch")
    if body[:header] != SEGMENT_MAGIC:
        raise StateDecodeError("bad segment magic")
    off = header
    try:
        (version,) = struct.unpack_from(">I", body, off)
        off += 4
        if version != SEGMENT_FORMAT_VERSION:
            raise StateDecodeError(
                f"segment format version {version} != {SEGMENT_FORMAT_VERSION}"
            )
        level, start = struct.unpack_from(">Iq", body, off)
        off += 12
        (sig_len,) = struct.unpack_from(">I", body, off)
        off += 4
        signature = body[off : off + sig_len].decode("utf-8")
        off += sig_len
        (count,) = struct.unpack_from(">I", body, off)
        off += 4
        entries: List[Tuple[str, int, bytes]] = []
        for _ in range(count):
            (name_len,) = struct.unpack_from(">I", body, off)
            off += 4
            name = body[off : off + name_len].decode("utf-8")
            if len(name.encode("utf-8")) != name_len:
                raise StateDecodeError("truncated segment entry name")
            off += name_len
            (bucket,) = struct.unpack_from(">q", body, off)
            off += 8
            (blob_len,) = struct.unpack_from(">I", body, off)
            off += 4
            entry = body[off : off + blob_len]
            if len(entry) != blob_len:
                raise StateDecodeError("truncated segment entry payload")
            off += blob_len
            entries.append((name, int(bucket), bytes(entry)))
    except struct.error as e:
        raise StateDecodeError(f"truncated segment envelope: {e}") from e
    if off != len(body):
        raise StateDecodeError("trailing bytes after last segment entry")
    return Segment(
        level=int(level), start=int(start), signature=signature,
        entries=entries,
    )


def aligned_cover(lo: int, hi: int) -> List[Tuple[int, int]]:
    """Greedy decomposition of ``[lo, hi)`` into aligned power-of-two
    spans ``(level, start)`` — each span starts at a multiple of its own
    size. At most 2·log2(hi-lo) spans, ascending; the canonical
    segment-tree cover, so every query over the same range addresses
    the same segment keys."""
    if lo < 0:
        raise ValueError(f"aligned cover needs lo >= 0, got {lo}")
    spans: List[Tuple[int, int]] = []
    cur = int(lo)
    hi = int(hi)
    while cur < hi:
        remaining = hi - cur
        if cur == 0:
            level = remaining.bit_length() - 1
        else:
            align = (cur & -cur).bit_length() - 1
            level = min(align, remaining.bit_length() - 1)
        spans.append((level, cur))
        cur += 1 << level
    return spans


def _warn_segment(dataset: str, key: str, reason: str) -> None:
    """The DQ323 lenient warning: the window stays answerable — the
    span rebuilds from per-partition states — but the operator sees
    exactly which segment entry degraded."""
    warnings.warn(
        f"DQ323: segment entry {key!r} for dataset {dataset!r} is "
        f"unusable ({reason}); the span falls back to per-partition "
        "states and will be rewritten",
        RuntimeWarning,
        stacklevel=3,
    )


class SegmentStore:
    """Segment persistence over a `StateRepository`: the same backends,
    the same ``(dataset, signature, key)`` addressing, the same atomic
    tmp+rename+flock write path — segments are just one more kind of
    envelope in the store."""

    def __init__(self, repository: Any, dataset: str, signature: str) -> None:
        self.repository = repository
        self.dataset = dataset
        self.signature = signature

    def has(self, level: int, fingerprint: str) -> bool:
        return bool(
            self.repository.has_blob(
                self.dataset, self.signature, segment_key(level, fingerprint)
            )
        )

    def load(self, level: int, fingerprint: str) -> Optional[Segment]:
        """The decoded segment, or None on any miss or defect (DQ323
        lenient warning) — never a wrong answer."""
        key = segment_key(level, fingerprint)
        try:
            faults.fault_point("state.segment")
            blob = self.repository.get_blob(self.dataset, self.signature, key)
        except Exception as e:  # noqa: BLE001 — unreadable entry = miss
            _warn_segment(self.dataset, key, f"unreadable: {e}")
            return None
        if blob is None:
            return None
        try:
            segment = decode_segment(blob)
        except StateDecodeError as e:
            _warn_segment(self.dataset, key, str(e))
            return None
        if segment.signature != self.signature:
            _warn_segment(
                self.dataset, key,
                f"plan signature {segment.signature!r} != {self.signature!r}",
            )
            return None
        return segment

    def save(
        self,
        level: int,
        start: int,
        fingerprint: str,
        entries: Sequence[Tuple[str, int, bytes]],
    ) -> bool:
        """Best-effort atomic publish, like `save_states`: a failed
        write never breaks the query — the span just stays cold."""
        blob = encode_segment(level, start, self.signature, entries)
        try:
            faults.fault_point("state.segment")
            self.repository.put_blob(
                self.dataset, self.signature, segment_key(level, fingerprint),
                blob,
            )
        except Exception:  # noqa: BLE001 — cache write must never break a query
            return False
        return True

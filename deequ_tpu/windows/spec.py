"""Window specifications over partition time.

A partitioned dataset's time axis is derived from its layout: partition
basenames carrying a date (`part-2026-08-01.parquet`, `20260801.pq`)
map to epoch-day *buckets*; datasets without a recognizable date fall
back to positional buckets (partition index in name order). An explicit
``extractor`` overrides both. Every window below compiles to a
half-open bucket range ``[lo, hi)`` plus the member partition indices —
the unit `windows/segments.py` decomposes into power-of-two spans.

Bucket order must agree with partition *name* order (the engine's
deterministic merge order): a timeline whose buckets decrease along the
name-sorted layout is rejected, because a window over it would not be a
contiguous name-order range and the merge could not be bit-identical to
a rescan.
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

__all__ = [
    "LastN",
    "Sliding",
    "Timeline",
    "Tumbling",
    "WindowFrame",
    "WindowSpec",
    "default_bucket_for",
]

#: dataset-layout date forms, tried in order: ISO `YYYY-MM-DD`, then a
#: bare `YYYYMMDD` run of 8 digits
_DATE_RES = (
    re.compile(r"(\d{4})-(\d{2})-(\d{2})"),
    re.compile(r"(?<!\d)(\d{4})(\d{2})(\d{2})(?!\d)"),
)


def default_bucket_for(name: str) -> Optional[int]:
    """Epoch-day bucket from a partition basename, or None when the
    name carries no valid date. Proleptic-Gregorian ordinal days
    (`datetime.date.toordinal`), so "last 7 days" is exact calendar
    arithmetic with no timezone involved."""
    for pattern in _DATE_RES:
        m = pattern.search(name)
        if m is None:
            continue
        try:
            year, month, day = (int(g) for g in m.groups())
            return datetime.date(year, month, day).toordinal()
        except ValueError:
            continue
    return None


@dataclass(frozen=True)
class WindowFrame:
    """One resolved window: a half-open bucket range plus the member
    partition indices (positions into the timeline, name order)."""

    label: str
    lo: int  # inclusive bucket
    hi: int  # exclusive bucket
    indices: Tuple[int, ...]

    def shifted(self, delta: int, timeline: "Timeline") -> "WindowFrame":
        """The same window `delta` buckets earlier (week-over-week
        baselines: `frame.shifted(7, timeline)`)."""
        return timeline.frame(
            self.lo - delta, self.hi - delta,
            label=f"{self.label} shifted -{delta}",
        )


@dataclass(frozen=True)
class Timeline:
    """The dataset's partition→bucket assignment, in partition name
    order. `axis` records where the buckets came from: 'date' (layout
    or extractor yields calendar days) or 'index' (positional)."""

    names: Tuple[str, ...]
    buckets: Tuple[int, ...]
    axis: str = "date"

    def __post_init__(self) -> None:
        if len(self.names) != len(self.buckets):
            raise ValueError("timeline names and buckets differ in length")
        for prev, cur in zip(self.buckets, self.buckets[1:]):
            if cur < prev:
                raise ValueError(
                    "partition buckets must be non-decreasing in name "
                    "order (windows are contiguous name-order ranges); "
                    f"got {prev} then {cur} in {self.names!r}"
                )

    @classmethod
    def derive(
        cls,
        partitions: Sequence,
        extractor: Optional[Callable[[str], Optional[int]]] = None,
    ) -> "Timeline":
        """Timeline from `Partition` objects (anything with `.name`).
        An explicit `extractor` must bucket every partition (error
        otherwise); the layout-derived default degrades to positional
        buckets when any name lacks a date."""
        names = tuple(p.name for p in partitions)
        if extractor is not None:
            buckets = []
            for name in names:
                b = extractor(name)
                if b is None:
                    raise ValueError(
                        f"window bucket extractor returned None for "
                        f"partition {name!r}"
                    )
                buckets.append(int(b))
            return cls(names, tuple(buckets), axis="date")
        derived = [default_bucket_for(name) for name in names]
        if names and all(b is not None for b in derived):
            return cls(names, tuple(int(b) for b in derived), axis="date")
        return cls(names, tuple(range(len(names))), axis="index")

    def __len__(self) -> int:
        return len(self.names)

    @property
    def min_bucket(self) -> int:
        return self.buckets[0] if self.buckets else 0

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1] if self.buckets else 0

    def indices_in(self, lo: int, hi: int) -> Tuple[int, ...]:
        """Partition indices whose bucket falls in ``[lo, hi)``."""
        return tuple(
            i for i, b in enumerate(self.buckets) if lo <= b < hi
        )

    def frame(self, lo: int, hi: int, *, label: str = "") -> WindowFrame:
        return WindowFrame(
            label=label or f"buckets [{lo}, {hi})",
            lo=int(lo),
            hi=int(hi),
            indices=self.indices_in(lo, hi),
        )


class WindowSpec:
    """A window family over a timeline. `resolve` yields the LATEST
    window (the one a per-ingest-tick suite watches); `series` yields
    every window the timeline holds, ascending."""

    def describe(self) -> str:
        raise NotImplementedError

    def resolve(self, timeline: Timeline) -> WindowFrame:
        raise NotImplementedError

    def series(self, timeline: Timeline) -> List[WindowFrame]:
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.describe()


@dataclass(frozen=True, repr=False)
class Tumbling(WindowSpec):
    """Non-overlapping windows of `size` buckets aligned at `origin`
    (+ k·size). A daily layout with size=7, origin on a Monday gives
    calendar weeks."""

    size: int
    origin: int = 0

    def __post_init__(self) -> None:
        if self.size < 1:
            raise ValueError(f"tumbling window size must be >= 1, got {self.size}")

    def describe(self) -> str:
        return f"tumbling({self.size})"

    def _aligned_lo(self, bucket: int) -> int:
        return self.origin + ((bucket - self.origin) // self.size) * self.size

    def series(self, timeline: Timeline) -> List[WindowFrame]:
        if not len(timeline):
            return []
        frames = []
        lo = self._aligned_lo(timeline.min_bucket)
        while lo <= timeline.max_bucket:
            frame = timeline.frame(
                lo, lo + self.size, label=f"{self.describe()}[{lo}]"
            )
            if frame.indices:
                frames.append(frame)
            lo += self.size
        return frames

    def resolve(self, timeline: Timeline) -> WindowFrame:
        lo = self._aligned_lo(timeline.max_bucket)
        return timeline.frame(
            lo, lo + self.size, label=f"{self.describe()}[{lo}]"
        )


@dataclass(frozen=True, repr=False)
class Sliding(WindowSpec):
    """Windows of `size` buckets advancing by `step`, anchored so the
    latest window ENDS at the newest bucket (a 7-day sliding window is
    always "the last 7 days as of the latest partition")."""

    size: int
    step: int = 1

    def __post_init__(self) -> None:
        if self.size < 1 or self.step < 1:
            raise ValueError(
                f"sliding window needs size/step >= 1, got "
                f"size={self.size} step={self.step}"
            )

    def describe(self) -> str:
        return f"sliding({self.size}, step={self.step})"

    def resolve(self, timeline: Timeline) -> WindowFrame:
        hi = timeline.max_bucket + 1
        return timeline.frame(
            hi - self.size, hi, label=f"{self.describe()}[{hi - self.size}]"
        )

    def series(self, timeline: Timeline) -> List[WindowFrame]:
        if not len(timeline):
            return []
        frames = []
        ends = []
        end = timeline.max_bucket + 1
        while end > timeline.min_bucket:
            ends.append(end)
            end -= self.step
        for end in reversed(ends):
            frame = timeline.frame(
                end - self.size, end,
                label=f"{self.describe()}[{end - self.size}]",
            )
            if frame.indices:
                frames.append(frame)
        return frames


@dataclass(frozen=True, repr=False)
class LastN(WindowSpec):
    """The trailing window: the last `n` days (bucket arithmetic) or
    the last `n` partitions (positional, layout-agnostic)."""

    n: int
    unit: str = "days"  # 'days' | 'partitions'

    def __post_init__(self) -> None:
        if self.n < 1:
            raise ValueError(f"last-N window needs n >= 1, got {self.n}")
        if self.unit not in ("days", "partitions"):
            raise ValueError(f"last-N unit must be 'days' or 'partitions', got {self.unit!r}")

    def describe(self) -> str:
        return f"last({self.n} {self.unit})"

    def resolve(self, timeline: Timeline) -> WindowFrame:
        if self.unit == "days":
            hi = timeline.max_bucket + 1
            return timeline.frame(
                hi - self.n, hi, label=self.describe()
            )
        indices = tuple(range(max(0, len(timeline) - self.n), len(timeline)))
        if not indices:
            return WindowFrame(self.describe(), 0, 0, ())
        lo = timeline.buckets[indices[0]]
        hi = timeline.buckets[indices[-1]] + 1
        return WindowFrame(self.describe(), lo, hi, indices)

    def series(self, timeline: Timeline) -> List[WindowFrame]:
        return [self.resolve(timeline)] if len(timeline) else []

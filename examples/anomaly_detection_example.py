"""Anomaly check on metric history
(reference: examples/AnomalyDetectionExample.scala:29-92).

We compute the Size metric every 'day'; today's data more than doubled in
size, so a RateOfChangeStrategy(max_rate_increase=2.0) anomaly check fails
the verification.
"""

import time

from example_utils import Item, items_as_table

from deequ_tpu import CheckStatus, VerificationSuite
from deequ_tpu.analyzers import Size
from deequ_tpu.anomaly.strategies import RateOfChangeStrategy
from deequ_tpu.repository.base import ResultKey
from deequ_tpu.repository.memory import InMemoryMetricsRepository


def main() -> None:
    metrics_repository = InMemoryMetricsRepository()
    now_ms = int(time.time() * 1000)

    # Yesterday, the data had only two rows
    yesterdays_key = ResultKey(now_ms - 24 * 60 * 1000)
    yesterdays_dataset = items_as_table(
        Item(1, "Thingy A", "awesome thing.", "high", 0),
        Item(2, "Thingy B", "available at http://thingb.com", None, 0),
    )
    (
        VerificationSuite()
        .on_data(yesterdays_dataset)
        .use_repository(metrics_repository)
        .save_or_append_result(yesterdays_key)
        .add_anomaly_check(RateOfChangeStrategy(max_rate_increase=2.0), Size())
        .run()
    )

    # Today the data has five rows — more than doubled
    todays_dataset = items_as_table(
        Item(1, "Thingy A", "awesome thing.", "high", 0),
        Item(2, "Thingy B", "available at http://thingb.com", None, 0),
        Item(3, None, None, "low", 5),
        Item(4, "Thingy D", "checkout https://thingd.ca", "low", 10),
        Item(5, "Thingy E", None, "high", 12),
    )
    todays_key = ResultKey(now_ms)
    verification_result = (
        VerificationSuite()
        .on_data(todays_dataset)
        .use_repository(metrics_repository)
        .save_or_append_result(todays_key)
        .add_anomaly_check(RateOfChangeStrategy(max_rate_increase=2.0), Size())
        .run()
    )

    if verification_result.status != CheckStatus.SUCCESS:
        print("Anomaly detected in the Size() metric!")
        for row in (
            metrics_repository.load()
            .for_analyzers([Size()])
            .get_success_metrics_as_rows()
        ):
            print(row)


if __name__ == "__main__":
    main()

"""The canonical Item demo from the reference README.

Expected outcome (reference: README.md:113-119, examples/BasicExample.scala):
the error-level check fails on Completeness(name)=0.8, the warning-level
check fails on containsURL(description)=0.4 — the run reports the failed
constraints.
"""

from example_utils import Item, items_as_table

from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite
from deequ_tpu.constraints.constraint import ConstraintStatus


def main() -> None:
    data = items_as_table(
        Item(1, "Thingy A", "awesome thing.", "high", 0),
        Item(2, "Thingy B", "available at http://thingb.com", None, 0),
        Item(3, None, None, "low", 5),
        Item(4, "Thingy D", "checkout https://thingd.ca", "low", 10),
        Item(5, "Thingy E", None, "high", 12),
    )

    verification_result = (
        VerificationSuite()
        .on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "integrity checks")
            # we expect 5 records
            .has_size(lambda size: size == 5)
            # 'id' should never be NULL
            .is_complete("id")
            # 'id' should not contain duplicates
            .is_unique("id")
            # 'name' should never be NULL
            .is_complete("name")
            # 'priority' should only contain the values "high" and "low"
            .is_contained_in("priority", ["high", "low"])
            # 'numViews' should not contain negative values
            .is_non_negative("numViews")
        )
        .add_check(
            Check(CheckLevel.WARNING, "distribution checks")
            # at least half of the 'description's should contain a url
            .contains_url("description", lambda ratio: ratio >= 0.5)
            # half of the items should have less than 10 'numViews'
            .has_approx_quantile("numViews", 0.5, lambda median: median <= 10)
        )
        .run()
    )

    if verification_result.status == CheckStatus.SUCCESS:
        print("The data passed the test, everything is fine!")
    else:
        print(
            "We found errors in the data, the following constraints were "
            "not satisfied:\n"
        )
        for check_result in verification_result.check_results.values():
            for result in check_result.constraint_results:
                if result.status != ConstraintStatus.SUCCESS:
                    print(f"{result.constraint} failed: {result.message}")


if __name__ == "__main__":
    main()

"""Automatic constraint suggestion from a profile
(reference: examples/ConstraintSuggestionExample.scala:26-70).

Profiles the data, applies the default rule set, and prints each
suggested constraint with its generated code string.
"""

import numpy as np

from example_utils import Table  # noqa: F401  (path bootstrap)

from deequ_tpu import Table
from deequ_tpu.suggestions.rules import Rules
from deequ_tpu.suggestions.runner import ConstraintSuggestionRunner


def main() -> None:
    data = Table.from_numpy(
        {
            "name": np.array(
                ["thingA", "thingA", "thingB", "thingC", "thingD", "thingC",
                 "thingC", "thingE"] * 2,
                dtype=object,
            ),
            "count": np.array(
                ["13.0", "5", None, None, "1.0", "7.0", "24", "20",
                 "13.0", "5", None, None, "1.0", "17.0", "22", "23"],
                dtype=object,
            ),
            "status": np.array(
                ["IN_TRANSIT", "DELAYED", "DELAYED", "IN_TRANSIT", "DELAYED",
                 "UNKNOWN", "UNKNOWN", "DELAYED"] * 2,
                dtype=object,
            ),
            "valuable": np.array(
                ["true", "false", None, "false", "true", None, None, "false"] * 2,
                dtype=object,
            ),
        }
    )

    suggestion_result = (
        ConstraintSuggestionRunner()
        .on_data(data)
        .add_constraint_rules(Rules.DEFAULT)
        .run()
    )

    # Heuristic suggestions: always review before deploying
    for column, suggestions in suggestion_result.constraint_suggestions.items():
        for suggestion in suggestions:
            print(
                f"Constraint suggestion for '{column}':\t{suggestion.description}\n"
                f"The corresponding code is {suggestion.code_for_constraint}\n"
            )


if __name__ == "__main__":
    main()

"""Single-line profiling of raw (mostly string) data
(reference: examples/DataProfilingExample.scala:26-77).

The profiler runs its three passes, infers that the string column 'count'
is numeric, and computes full descriptive statistics plus value
distributions for low-cardinality columns.
"""

import numpy as np

from example_utils import Table  # noqa: F401  (path bootstrap)

from deequ_tpu import Table
from deequ_tpu.profiles.column_profile import NumericColumnProfile
from deequ_tpu.profiles.runner import ColumnProfilerRunner


def raw_data() -> Table:
    """reference: DataProfilingExample.scala:28-40 (RawData rows)."""
    return Table.from_numpy(
        {
            "name": np.array(
                ["thingA", "thingA", "thingB", "thingC", "thingD", "thingC",
                 "thingC", "thingE"],
                dtype=object,
            ),
            "count": np.array(
                ["13.0", "5", None, None, "1.0", "7.0", "20", "20"], dtype=object
            ),
            "status": np.array(
                ["IN_TRANSIT", "DELAYED", "DELAYED", "IN_TRANSIT", "DELAYED",
                 "UNKNOWN", "UNKNOWN", "DELAYED"],
                dtype=object,
            ),
            "valuable": np.array(
                ["true", "false", None, "false", "true", None, None, "false"],
                dtype=object,
            ),
        }
    )


def main() -> None:
    result = ColumnProfilerRunner().on_data(raw_data()).run()

    for name, profile in result.profiles.items():
        print(
            f"Column '{name}':\n"
            f"\tcompleteness: {profile.completeness}\n"
            f"\tapproximate number of distinct values: "
            f"{profile.approximate_num_distinct_values}\n"
            f"\tdatatype: {profile.data_type}\n"
        )

    count_profile = result.profiles["count"]
    assert isinstance(count_profile, NumericColumnProfile)
    print(
        "Statistics of 'count':\n"
        f"\tminimum: {count_profile.minimum}\n"
        f"\tmaximum: {count_profile.maximum}\n"
        f"\tmean: {count_profile.mean}\n"
        f"\tstandard deviation: {count_profile.std_dev}\n"
    )

    status_profile = result.profiles["status"]
    print("Value distribution in 'status':")
    if status_profile.histogram is not None:
        for key, entry in status_profile.histogram.values.items():
            print(f"\t{key} occurred {int(entry.absolute)} times "
                  f"(ratio is {entry.ratio})")


if __name__ == "__main__":
    main()

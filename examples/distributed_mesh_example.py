"""TPU-native extra: the same verification on a device mesh.

The reference delegates partition parallelism to Spark executors
(reference: SURVEY.md §2.10); here the equivalent is a
`jax.sharding.Mesh` — rows shard across devices, each device runs the
same fused reduction, and states merge in-graph with collectives over
ICI. On one host this runs on a virtual CPU mesh; on a TPU pod slice the
identical code spans real chips.

Run:  python examples/distributed_mesh_example.py
"""

import example_utils  # noqa: F401  (path bootstrap)

import jax

if jax.default_backend() == "cpu" and len(jax.devices()) == 1:
    # single-CPU dev box: fake an 8-device mesh (same recipe as the tests)
    jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402

from deequ_tpu import Table  # noqa: E402
from deequ_tpu.analyzers import (  # noqa: E402
    ApproxCountDistinct,
    Completeness,
    Mean,
    Size,
    StandardDeviation,
)
from deequ_tpu.parallel.distributed import data_mesh, run_distributed_analysis  # noqa: E402
from deequ_tpu.runners.analysis_runner import AnalysisRunner  # noqa: E402


def main() -> None:
    rng = np.random.default_rng(0)
    n = 100_000
    x = rng.normal(42.0, 5.0, n)
    x[:: 101] = np.nan
    table = Table.from_numpy({"x": x, "id": rng.integers(0, n, n)})

    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        StandardDeviation("x"),
        ApproxCountDistinct("id"),
    ]

    mesh = data_mesh()
    print(f"Mesh: {mesh.shape} over {len(jax.devices())} {jax.devices()[0].platform} device(s)\n")

    distributed = run_distributed_analysis(table, analyzers, mesh=mesh)
    single = AnalysisRunner.on_data(table).add_analyzers(analyzers).run()

    print(f"{'analyzer':45s} {'mesh':>18s} {'single-device':>18s}")
    for a in analyzers:
        d = distributed.metric_map[a].value.get()
        s = single.metric_map[a].value.get()
        print(f"{a!r:45s} {d:18.8f} {s:18.8f}")
        assert abs(d - s) <= 1e-6 * max(1.0, abs(s)), (a, d, s)
    print("\nMesh metrics equal single-device metrics — the state semigroup "
          "makes the merge exact.")


if __name__ == "__main__":
    main()

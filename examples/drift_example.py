"""Windowed metrics and week-over-week drift detection.

A daily-partitioned dataset accumulates one parquet file per day. An
ordinary analysis run commits each partition's analyzer STATES to a
repository as it scans — after that, any time window (last 7 days, this
week vs last week) is answered by merging a handful of precomputed
segment states (deequ_tpu/windows/) with ZERO data rows read, and a
`DriftCheck` compares two windows state-vs-state: KS distance between
quantile sketches, cardinality ratios between HLLs, completeness and
moment deltas — no rescans of either side.

The script bootstraps two stable weeks, shows the warm window query
resolving from segments, then injects a skewed day and watches the
week-over-week drift check fail.
"""

import datetime
import os
import tempfile

import numpy as np

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    StandardDeviation,
)
from deequ_tpu.checks import CheckLevel, DriftCheck
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.repository.states import FileSystemStateRepository
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.windows import Sliding, WindowQuery

DAY0 = datetime.date(2026, 6, 1)

ANALYZERS = [
    Size(),
    Completeness("latency_ms"),
    Mean("latency_ms"),
    StandardDeviation("latency_ms"),
    ApproxQuantile("latency_ms", 0.5),
    ApproxCountDistinct("endpoint"),
]


def write_day(dir_path: str, day_index: int, *, skewed: bool = False) -> None:
    """One day of request-latency telemetry; a skewed day models a
    regression (slower, spikier, nullier, new endpoints)."""
    rng = np.random.default_rng(100 + day_index)
    n = 2_000
    mean, scale, nulls, endpoints = (
        (240.0, 80.0, 0.25, 900) if skewed else (120.0, 25.0, 0.02, 150)
    )
    latency = rng.normal(mean, scale, n)
    latency[rng.random(n) < nulls] = np.nan
    table = Table.from_pydict(
        {
            "latency_ms": list(latency),
            "endpoint": [int(v) for v in rng.integers(0, endpoints, n)],
        },
        types={"latency_ms": ColumnType.DOUBLE, "endpoint": ColumnType.LONG},
    )
    day = DAY0 + datetime.timedelta(days=day_index)
    table.to_parquet(
        os.path.join(dir_path, f"requests-{day.isoformat()}.parquet")
    )


def main() -> None:
    with tempfile.TemporaryDirectory() as workdir:
        data_dir = os.path.join(workdir, "requests")
        os.makedirs(data_dir)
        for i in range(14):  # two stable weeks
            write_day(data_dir, i)

        repository = FileSystemStateRepository(os.path.join(workdir, "states"))

        # the nightly scan: computes metrics AND commits per-partition
        # states — the only pass that ever reads data rows
        source = Table.scan_parquet_dataset(data_dir)
        AnalysisRunner.do_analysis_run(
            source, ANALYZERS, state_repository=repository,
            dataset_name="requests",
        )

        query = WindowQuery(
            source, ANALYZERS, repository=repository, dataset="requests"
        )
        window = Sliding(7)  # "the last 7 days", resolved per query

        context = query.run(window)  # publishes the segment covers
        plan = context.window_plan
        print(f"window plan: {plan.summary()}")
        print("last-7-days metrics (zero rows read on the warm path):")
        for analyzer, metric in context.metric_map.items():
            print(f"\t{analyzer!r}: {metric.value.get():.4f}")

        check = (
            DriftCheck(CheckLevel.ERROR, "week-over-week regression gate")
            .has_no_quantile_drift("latency_ms", max_quantile_shift=0.15)
            .has_no_mean_drift("latency_ms", max_relative_delta=0.10)
            .has_no_completeness_drift("latency_ms", max_delta=0.05)
            .has_no_cardinality_drift("endpoint", max_ratio_drift=0.50)
        )

        def week_over_week() -> None:
            timeline = query.timeline()
            this_week = window.resolve(timeline)
            last_week = this_week.shifted(7, timeline)
            result = check.evaluate(
                current=query.states(this_week),
                baseline=query.states(last_week),
            )
            print(f"drift status: {result.status.name}")
            for r in result.constraint_results:
                value = "-" if r.value is None else f"{r.value:.4f}"
                print(f"\t[{r.status.name:7s}] {r.constraint.description}"
                      f" (observed {value})")

        print("\nweek over week, both weeks stable:")
        week_over_week()

        # day 14 ships a regression: slower, spikier, nullier, and
        # hitting endpoints nobody saw last week
        write_day(data_dir, 14, skewed=True)
        source = Table.scan_parquet_dataset(data_dir)
        AnalysisRunner.do_analysis_run(
            source, ANALYZERS, state_repository=repository,
            dataset_name="requests",
        )
        query = WindowQuery(
            source, ANALYZERS, repository=repository, dataset="requests"
        )

        print("\nweek over week after the skewed day landed:")
        week_over_week()


if __name__ == "__main__":
    main()

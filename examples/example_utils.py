"""Shared helpers for the runnable examples.

The reference ships Item/Manufacturer case classes and a local-SparkSession
loan pattern (reference: examples/ExampleUtils.scala:23-47,
examples/entities.scala:19-31). Here a Table is built directly from the
entity tuples — there is no session to manage; JAX owns the device.

Run any example from the repo root:  python examples/basic_example.py
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deequ_tpu import Table  # noqa: E402


@dataclass
class Item:
    """reference: examples/entities.scala:19-25."""

    id: int
    name: Optional[str]
    description: Optional[str]
    priority: Optional[str]
    numViews: int


@dataclass
class Manufacturer:
    """reference: examples/entities.scala:27-31."""

    id: int
    name: Optional[str]
    countryCode: Optional[str]


def items_as_table(*items: Item) -> Table:
    """reference: ExampleUtils.itemsAsDataframe (ExampleUtils.scala:39-42)."""
    return Table.from_numpy(
        {
            "id": np.array([it.id for it in items], dtype=np.int64),
            "name": np.array([it.name for it in items], dtype=object),
            "description": np.array([it.description for it in items], dtype=object),
            "priority": np.array([it.priority for it in items], dtype=object),
            "numViews": np.array([it.numViews for it in items], dtype=np.int64),
        }
    )


def manufacturers_as_table(*ms: Manufacturer) -> Table:
    """reference: ExampleUtils.manufacturersAsDataframe (ExampleUtils.scala:44-46)."""
    return Table.from_numpy(
        {
            "id": np.array([m.id for m in ms], dtype=np.int64),
            "name": np.array([m.name for m in ms], dtype=object),
            "countryCode": np.array([m.countryCode for m in ms], dtype=object),
        }
    )

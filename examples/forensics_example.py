"""TPU-native extra: failure forensics — from a red check to the rows.

The metric algebra deliberately forgets row identity: a failed
constraint reports "completeness 0.997" and nothing else. With
`.with_forensics()` the same fused scan (no second pass, no extra
decode) keeps a bounded deterministic sample of the violating rows
with full coordinates — (partition, row group, row index, offending
values) — plus the run's provenance (plan signature, scanned vs
cache-merged partitions, row groups pruned). Attach a metrics
repository and the report persists as a tamper-evident audit trail
next to the metrics it explains.

Run:  python examples/forensics_example.py
"""

import tempfile
from pathlib import Path

import example_utils  # noqa: F401  (path bootstrap)
import numpy as np

from deequ_tpu import Check, CheckLevel, CheckStatus, Table, VerificationSuite
from deequ_tpu.repository.audit import load_audit_trail
from deequ_tpu.repository.base import ResultKey
from deequ_tpu.repository.fs import FileSystemMetricsRepository


def write_partitions(data_dir: Path, parts: int = 3, n: int = 10_000) -> None:
    """A partitioned dataset where partition 1 hides a few bad rows."""
    for p in range(parts):
        rng = np.random.default_rng(100 + p)
        email = np.array([f"user{i}@example.com" for i in range(n)], dtype=object)
        amount = rng.uniform(1.0, 500.0, n)
        if p == 1:  # the upstream bug lives in one partition
            email[[17, 4242]] = None
            amount[[9000, 9001]] = [-3.5, -120.0]
        Table.from_pydict({"email": email, "amount": amount}).to_parquet(
            str(data_dir / f"events-{p}.parquet"), row_group_size=2048
        )


def main() -> None:
    tmp = Path(tempfile.mkdtemp())
    data_dir = tmp / "events"
    data_dir.mkdir()
    write_partitions(data_dir)

    repo = FileSystemMetricsRepository(str(tmp / "metrics.json"))
    key = ResultKey(20260805, {"pipeline": "events"})

    result = (
        VerificationSuite()
        .on_data(Table.scan_parquet_dataset(str(data_dir)))
        .add_check(
            Check(CheckLevel.ERROR, "event hygiene")
            .is_complete("email")
            .has_min("amount", lambda v: v >= 0.0)
        )
        .with_forensics(max_samples=5)
        .use_repository(repo)
        .save_or_append_result(key)
        .run()
    )

    assert result.status == CheckStatus.ERROR, result.status
    print("The check went red. Which rows? Ask the forensics report:\n")
    report = result.forensics()
    print(report.render())

    print("\nTriage: every sampled violation points into events-1.parquet —")
    print("one bad partition, not a fleet-wide problem.")
    for entry in report.failed():
        for sample in entry.samples:
            print(
                f"\t{entry.kind}: {sample.partition} rg={sample.row_group}"
                f" row={sample.row_index} values={sample.values}"
            )

    # the trail persisted with the metrics — a later session (or another
    # operator) can pull the same evidence straight from the repository
    replayed = load_audit_trail(repo, key)
    assert replayed.to_dict() == report.to_dict()
    print("\nAudit trail round-tripped through the metrics repository.")


if __name__ == "__main__":
    main()

"""TPU-native extra: uniqueness over a near-unique key at bounded memory.

The reference handles high-cardinality group-bys by caching the
frequencies DataFrame at MEMORY_AND_DISK (reference:
runners/AnalysisRunner.scala:75,479-483). Here the frequency fold spills
group counts to hash-partitioned disk files once the in-RAM group count
crosses `DEEQU_TPU_MAX_GROUPS_IN_MEMORY` (default 2M) — so primary-key
checks over billions of distinct values run in constant host memory,
streamed straight off Parquet.

Run:  python examples/high_cardinality_spill_example.py
"""

import os
import tempfile
from pathlib import Path

import example_utils  # noqa: F401  (path bootstrap)

from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite
from deequ_tpu.data.source import ParquetSource


def write_orders(path: str, n: int = 200_000, chunk: int = 50_000) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    schema = pa.schema([("order_id", pa.string()), ("region", pa.string())])
    with pq.ParquetWriter(path, schema) as writer:
        for start in range(0, n, chunk):
            m = min(chunk, n - start)
            writer.write_table(
                pa.table(
                    {
                        "order_id": [f"ord-{i:09d}" for i in range(start, start + m)],
                        "region": [["eu", "us", "apac"][i % 3] for i in range(start, start + m)],
                    },
                    schema=schema,
                )
            )


def main() -> None:
    # tiny cap so this demo actually exercises the spill at example
    # scale; restored afterwards so in-process callers (the example
    # smoke tests) keep their own configuration
    previous_cap = os.environ.get("DEEQU_TPU_MAX_GROUPS_IN_MEMORY")
    os.environ["DEEQU_TPU_MAX_GROUPS_IN_MEMORY"] = "20000"
    try:
        _run()
    finally:
        if previous_cap is None:
            del os.environ["DEEQU_TPU_MAX_GROUPS_IN_MEMORY"]
        else:
            os.environ["DEEQU_TPU_MAX_GROUPS_IN_MEMORY"] = previous_cap


def _run() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        path = str(Path(tmp) / "orders.parquet")
        write_orders(path)

        # 200k distinct order ids against a 20k in-RAM group cap: the
        # fold spills to disk and every metric still comes out exact
        result = (
            VerificationSuite.on_data(ParquetSource(path, batch_rows=1 << 15))
            .add_check(
                Check(CheckLevel.ERROR, "key integrity")
                .is_unique("order_id")
                .has_number_of_distinct_values("order_id", lambda v: v == 200_000)
                .has_uniqueness(["region"], lambda v: v == 0.0)
            )
            .run()
        )
        assert result.status == CheckStatus.SUCCESS, result.check_results_as_json()
        print("high-cardinality verification:", result.status.name)
        for row in result.check_results_as_rows():
            print(f"  {row['constraint']}: {row['constraint_status']}")


if __name__ == "__main__":
    main()

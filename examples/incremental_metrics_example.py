"""Incremental metrics on a growing dataset
(reference: examples/IncrementalMetricsExample.scala:24-72).

The first run persists each analyzer's internal state; the second run
computes updated whole-dataset metrics from the new rows PLUS the stored
states — without ever touching the first dataset again. This is the
semigroup state algebra (reference: analyzers/Analyzer.scala:34-48) that
maps to collective merges on a device mesh.
"""

from example_utils import Item, items_as_table

from deequ_tpu.analyzers import ApproxCountDistinct, Completeness, Size
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def main() -> None:
    data = items_as_table(
        Item(1, "Thingy A", "awesome thing.", "high", 0),
        Item(2, "Thingy B", "available tomorrow", "low", 0),
        Item(3, "Thing C", None, None, 5),
    )
    more_data = items_as_table(
        Item(4, "Thingy D", None, "low", 10),
        Item(5, "Thingy E", None, "high", 12),
    )

    analyzers = [
        Size(),
        ApproxCountDistinct("id"),
        Completeness("name"),
        Completeness("description"),
    ]

    state_store = InMemoryStateProvider()

    # persist the internal state of the computation
    metrics_for_data = AnalysisRunner.do_analysis_run(
        data, analyzers, save_states_with=state_store
    )

    # update the metrics from the stored states without re-reading `data`
    metrics_after_adding_more_data = AnalysisRunner.do_analysis_run(
        more_data, analyzers, aggregate_with=state_store
    )

    print("Metrics for the first 3 records:\n")
    for analyzer, metric in metrics_for_data.metric_map.items():
        print(f"\t{analyzer!r}: {metric.value.get()}")

    print("\nMetrics after adding 2 more records:\n")
    for analyzer, metric in metrics_after_adding_more_data.metric_map.items():
        print(f"\t{analyzer!r}: {metric.value.get()}")


if __name__ == "__main__":
    main()

"""TPU-native extra: the sharded streaming scan across REAL processes.

Each spawned interpreter owns a rendezvous-assigned range of the
dataset's partitions (`parallel.plan_shards` — a pure function of the
partition fingerprints, so every process computes the same plan with no
coordination round), folds its range through the streamed scan, and
allgathers only the folded state envelopes — rows never cross process
boundaries. The merge folds every shard's states in global partition
order, which is what makes the sharded answer BIT-identical to a solo
pass, not just close.

Run:  python examples/mesh_example.py
"""

import json
import os
import tempfile
import textwrap

import example_utils  # noqa: F401  (path bootstrap)
import numpy as np

N_PARTS = 6
ROWS_PER_PART = 3000

WORKER = textwrap.dedent(
    """
    import json, os, sys, time

    os.environ["JAX_PLATFORMS"] = "cpu"
    rank, _port, tmpdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    data_dir, n_shards = sys.argv[4], int(sys.argv[5])
    os.environ["DEEQU_TPU_SHARD"] = str(rank)

    from deequ_tpu.analyzers.scan import Completeness, Mean, Minimum, Sum
    from deequ_tpu.data.source import PartitionedParquetSource
    from deequ_tpu.parallel import plan_shards, run_sharded_analysis

    # loopback allgather: each rank publishes its envelope as a file and
    # polls for its peers' — on a TPU pod this is jax's process_allgather,
    # the byte streams and the merge are identical either way
    _round = [0]

    def gather(payload):
        r = _round[0]
        _round[0] += 1
        gdir = os.path.join(tmpdir, f"gather-{r}")
        os.makedirs(gdir, exist_ok=True)
        tmp = os.path.join(gdir, f"{rank}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(gdir, f"{rank}.bin"))
        out = []
        for i in range(n_shards):
            p = os.path.join(gdir, f"{i}.bin")
            deadline = time.time() + 120
            while not os.path.exists(p):
                if time.time() > deadline:
                    raise TimeoutError(f"peer {i} missing in round {r}")
                time.sleep(0.01)
            with open(p, "rb") as f:
                out.append(f.read())
        return out

    src = PartitionedParquetSource(
        sorted(
            os.path.join(data_dir, f)
            for f in os.listdir(data_dir)
            if f.endswith(".parquet")
        )
    )
    analyzers = [Mean("price"), Sum("qty"), Minimum("price"), Completeness("price")]
    ctx = run_sharded_analysis(
        src, analyzers, shard=rank, num_shards=n_shards, gather=gather
    )
    mine = plan_shards(src.partitions(), n_shards).assignment(rank)
    out = {
        "my_partitions": list(mine.names),
        "metrics": {str(a): ctx.metric_map[a].value.get() for a in analyzers},
    }
    print("RESULT:" + json.dumps(out), flush=True)
    """
)


def write_dataset(root: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(21)
    for i in range(N_PARTS):
        price = rng.lognormal(3.0, 1.0, ROWS_PER_PART)
        price[:: 17 + i] = np.nan
        pq.write_table(
            pa.table(
                {
                    "price": pa.array(price, mask=np.isnan(price)),
                    "qty": rng.integers(1, 100, ROWS_PER_PART).astype("float64"),
                }
            ),
            os.path.join(root, f"events-{i:02d}.parquet"),
            row_group_size=1000,
        )


def main() -> None:
    from deequ_tpu.analyzers.scan import Completeness, Mean, Minimum, Sum
    from deequ_tpu.data.source import PartitionedParquetSource
    from deequ_tpu.parallel.procspawn import WorkerFailure, run_worker_processes
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    with tempfile.TemporaryDirectory() as data_dir:
        write_dataset(data_dir)

        # the reference answer: one process scans everything
        src = PartitionedParquetSource(
            sorted(
                os.path.join(data_dir, f)
                for f in os.listdir(data_dir)
                if f.endswith(".parquet")
            )
        )
        analyzers = [
            Mean("price"),
            Sum("qty"),
            Minimum("price"),
            Completeness("price"),
        ]
        solo = AnalysisRunner.do_analysis_run(src, analyzers)
        solo_metrics = {
            str(a): solo.metric_map[a].value.get() for a in analyzers
        }

        print(f"dataset: {N_PARTS} partitions x {ROWS_PER_PART} rows")
        try:
            results = run_worker_processes(
                WORKER, 2, extra_args=[data_dir, "2"], timeout=240.0
            )
        except WorkerFailure as exc:
            if exc.runtime_unavailable:
                # no room to spawn interpreters here — the solo numbers
                # above are the same answer the mesh would have produced
                print("mesh spawn unavailable on this host:", exc)
                print("solo metrics:", solo_metrics)
                return
            raise

        for rank, res in enumerate(results):
            print(f"shard {rank} scanned: {', '.join(res['my_partitions'])}")
        for name, value in sorted(solo_metrics.items()):
            print(f"  {name}: {value}")
        identical = all(r["metrics"] == solo_metrics for r in results)
        print(f"sharded == solo, bit for bit: {identical}")
        if not identical:
            raise SystemExit("sharded run diverged from solo!")


if __name__ == "__main__":
    main()

"""Storing and querying computed metrics in a repository
(reference: examples/MetricsRepositoryExample.scala:29-90).

Metrics land in a JSON file on disk (the FileSystem repository also
serves object storage paths), keyed by timestamp + tags, and are queried
back by key, time window, and tag value.
"""

import tempfile
import time
from pathlib import Path

from example_utils import Item, items_as_table

from deequ_tpu import Check, CheckLevel, VerificationSuite
from deequ_tpu.analyzers import Completeness
from deequ_tpu.repository.base import ResultKey
from deequ_tpu.repository.fs import FileSystemMetricsRepository


def main() -> None:
    data = items_as_table(
        Item(1, "Thingy A", "awesome thing.", "high", 0),
        Item(2, "Thingy B", "available at http://thingb.com", None, 0),
        Item(3, None, None, "low", 5),
        Item(4, "Thingy D", "checkout https://thingd.ca", "low", 10),
        Item(5, "Thingy E", None, "high", 12),
    )

    # A json file in which the computed metrics will be stored
    metrics_file = str(Path(tempfile.mkdtemp()) / "metrics.json")
    repository = FileSystemMetricsRepository(metrics_file)

    # The key under which we store the results: a timestamp plus
    # arbitrary key-value tags
    now_ms = int(time.time() * 1000)
    result_key = ResultKey(now_ms, {"tag": "repositoryExample"})

    (
        VerificationSuite()
        .on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "integrity checks")
            .has_size(lambda size: size == 5)
            .is_complete("id")
            .is_complete("name")
            .is_contained_in("priority", ["high", "low"])
            .is_non_negative("numViews")
        )
        .use_repository(repository)
        .save_or_append_result(result_key)
        .run()
    )

    # Load the metric for a particular analyzer stored under our key
    completeness_of_name = (
        repository.load_by_key(result_key).metric(Completeness("name")).value.get()
    )
    print(f"The completeness of the name column is: {completeness_of_name}")

    # Query the repository for all metrics from the last 10 minutes as json
    json_metrics = (
        repository.load().after(now_ms - 10 * 60 * 1000).get_success_metrics_as_json()
    )
    print(f"Metrics from the last 10 minutes:\n{json_metrics}")

    # Query by tag value; the row form is the DataFrame analogue
    for row in (
        repository.load()
        .with_tag_values({"tag": "repositoryExample"})
        .get_success_metrics_as_rows()
    ):
        print(row)


if __name__ == "__main__":
    main()

"""Resumable runs: cancel a partitioned analysis mid-flight, then rerun
at the cost of only the partitions the first run never finished.

Every partition that completes commits its folded analyzer states to
the `StateRepository` BEFORE the run moves on, so a cancel (explicit,
deadline, or the stall watchdog — and equally a crash or SIGKILL)
loses at most the partition in flight. The rerun loads the committed
states from the repository and scans the remainder; the semigroup
state merge makes the final metrics bit-identical to an uninterrupted
full scan.
"""

import tempfile
from pathlib import Path

import numpy as np

from deequ_tpu.analyzers import Completeness, Mean, Size
from deequ_tpu.core.controller import RunCancelled, RunController
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.repository.states import FileSystemStateRepository
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def write_partitions(data_dir: Path, n_parts: int = 3) -> None:
    rng = np.random.default_rng(7)
    for i in range(n_parts):
        n = 400 + 50 * i
        x = rng.normal(10.0, 2.0, n)
        x[rng.random(n) < 0.05] = np.nan
        Table.from_pydict(
            {"x": list(x)}, types={"x": ColumnType.DOUBLE}
        ).to_parquet(str(data_dir / f"part-{i}.parquet"), row_group_size=128)


class CancelAfterFirstCommit(FileSystemStateRepository):
    """Stands in for an operator's ctrl-C (or a deadline, or a crash):
    trips the controller the moment the first partition commits."""

    def __init__(self, base_path: str, controller: RunController) -> None:
        super().__init__(base_path)
        self._controller = controller

    def _put(self, dataset, signature, fingerprint, blob):
        super()._put(dataset, signature, fingerprint, blob)
        self._controller.cancel()


def main() -> None:
    analyzers = [Size(), Mean("x"), Completeness("x")]
    with tempfile.TemporaryDirectory() as tmp:
        data_dir = Path(tmp) / "dataset"
        data_dir.mkdir()
        write_partitions(data_dir)
        cache_dir = str(Path(tmp) / "state-cache")

        # first attempt: cancelled right after the first partition commits
        controller = RunController()
        repository = CancelAfterFirstCommit(cache_dir, controller)
        try:
            AnalysisRunner.do_analysis_run(
                Table.scan_parquet_dataset(str(data_dir)), analyzers,
                state_repository=repository, dataset_name="resume-demo",
                controller=controller,
            )
        except RunCancelled as cancelled:
            print(f"first attempt ended early: {cancelled}")

        # the rerun resumes: committed partitions load from the cache,
        # only the remainder is scanned, metrics match a full clean scan
        resumed = AnalysisRunner.do_analysis_run(
            Table.scan_parquet_dataset(str(data_dir)), analyzers,
            state_repository=FileSystemStateRepository(cache_dir),
            dataset_name="resume-demo", tracing=True,
        )
        counters = resumed.run_trace.counters
        print(
            f"rerun: {counters['partitions_cached']} partition(s) from "
            f"cache, {counters['partitions_scanned']} scanned"
        )
        print("\nResumed metrics (bit-identical to an uninterrupted run):\n")
        for analyzer, metric in resumed.metric_map.items():
            print(f"\t{analyzer!r}: {metric.value.get()}")


if __name__ == "__main__":
    main()

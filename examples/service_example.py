"""Fleet-service demo: run deequ_tpu as a long-lived multi-tenant
service with admission control, preemptive scheduling, and circuit
breakers.

Three things happen on one single-worker pool (one worker makes the
preemption story visible — with spare workers interactive checks just
take a free slot):

  1. a batch tenant submits a HEAVY partitioned profile;
  2. an interactive tenant's small checks arrive while it runs — each
     one preempts the heavy run at a partition boundary (DQ405), runs
     immediately, and the heavy run resumes from its committed
     partition states, finishing bit-identically;
  3. a third tenant keeps submitting a corrupt dataset until its
     per-(tenant, dataset) circuit breaker opens (DQ413) — after which
     the service rejects at admission without touching the data.

Run directly or via `PYTHONPATH=.:examples python examples/service_example.py`.
"""

import os
import tempfile

import numpy as np

from deequ_tpu import Check, CheckLevel
from deequ_tpu.data.table import Table
from deequ_tpu.repository.states import FileSystemStateRepository
from deequ_tpu.service import DQService


def write_dataset(root: str, partitions: int, rows_per_part: int) -> str:
    rng = np.random.default_rng(7)
    data_dir = os.path.join(root, "events")
    os.makedirs(data_dir)
    for i in range(partitions):
        Table.from_pydict(
            {
                "price": rng.lognormal(3.0, 1.0, rows_per_part),
                "quantity": rng.integers(1, 50, rows_per_part).astype(
                    np.float64
                ),
            }
        ).to_parquet(
            os.path.join(data_dir, f"part-{i:03d}.parquet"),
            row_group_size=max(4096, rows_per_part // 4),
        )
    return data_dir


def heavy_check() -> Check:
    return (
        Check(CheckLevel.ERROR, "nightly profile")
        .has_size(lambda s: s > 0)
        .is_complete("price")
        .has_mean("price", lambda m: m > 0)
        .has_standard_deviation("price", lambda s: s > 0)
        .is_complete("quantity")
    )


def interactive_check() -> Check:
    return (
        Check(CheckLevel.ERROR, "freshness probe")
        .has_size(lambda s: s > 0)
        .is_complete("price")
    )


def main() -> None:
    work = tempfile.mkdtemp(prefix="dq_service_demo_")
    data_dir = write_dataset(work, partitions=32, rows_per_part=50_000)
    probe = Table.from_pydict(
        {"price": np.random.default_rng(1).lognormal(3.0, 1.0, 10_000)}
    )
    corrupt = os.path.join(work, "corrupt.parquet")
    with open(corrupt, "wb") as fh:
        fh.write(b"not parquet at all")

    # demo datasets are far below the production tier boundaries; pin
    # them down (the operator override documented in lint/cost.py) so
    # the 1.6M-row profile classifies as heavy and the probes stay
    # interactive
    saved_tiers = {
        k: os.environ.get(k)
        for k in (
            "DEEQU_TPU_TIER_INTERACTIVE_BYTES",
            "DEEQU_TPU_TIER_HEAVY_BYTES",
        )
    }
    os.environ["DEEQU_TPU_TIER_INTERACTIVE_BYTES"] = str(1 << 20)
    os.environ["DEEQU_TPU_TIER_HEAVY_BYTES"] = str(4 << 20)

    states = FileSystemStateRepository(os.path.join(work, "states"))
    with DQService(
        workers=1, state_repository=states, breaker_threshold=2
    ) as svc:
        # 1. the heavy profile occupies the pool
        heavy = svc.submit(
            "batch-tenant",
            "events",
            lambda: Table.scan_parquet_dataset(data_dir),
            checks=[heavy_check()],
        )
        print(f"heavy admitted: tier={heavy.tier}")

        # 2. interactive probes preempt it at partition boundaries
        for i in range(3):
            h = svc.submit(
                "interactive-tenant",
                f"probe-{i}",
                probe,
                checks=[interactive_check()],
            )
            h.wait(timeout=120)
            print(f"probe-{i}: {h.status} (tier={h.tier})")

        heavy.wait(timeout=600)
        print(
            f"heavy: {heavy.status} after {heavy.preemptions} "
            f"preemption(s), {heavy.attempts} attempt(s) — resumed from "
            f"committed states"
        )

        # 3. a corrupt dataset trips its tenant's breaker
        for i in range(3):
            h = svc.submit(
                "flaky-tenant",
                "corrupt",
                lambda: Table.scan_parquet(corrupt),
                checks=[interactive_check()],
            )
            h.wait(timeout=60)
            print(f"corrupt submit {i}: {h.status} code={h.code or '-'}")
        print(
            "breaker for (flaky-tenant, corrupt):",
            svc.breakers.state("flaky-tenant", "corrupt"),
        )

        print("\nservice telemetry:")
        snap = svc.telemetry_snapshot()
        for key in sorted(snap):
            if snap[key]:
                print(f"  {key} = {snap[key]}")

    for key, value in saved_tiers.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value


if __name__ == "__main__":
    main()

"""Fleet-wide scan sharing demo: several tenants submit suites over
the SAME table, and the service plans ONE proven superset scan for the
whole group instead of one scan per tenant.

What happens on a single-worker DQService:

  1. three tenants submit different check suites against the same
     partitioned parquet dataset (identified by its content
     fingerprint, so re-opened handles still group);
  2. the scheduler collects them into a share group, the
     plan-subsumption prover certifies "suite ⊆ union scan" for every
     member (CONTAINED, with a machine-checkable proof object), and the
     union plan runs ONCE;
  3. the folded states fan back out over the analyzer state semigroup —
     each tenant's metrics and check verdicts are bit-identical to a
     solo run — and each tenant is charged only its pro-rata share of
     the single scan's bytes.

Run directly or via `PYTHONPATH=.:examples python examples/sharing_example.py`.
"""

import os
import tempfile
import time

import numpy as np

from deequ_tpu import Check, CheckLevel
from deequ_tpu.data.table import Table
from deequ_tpu.service import DQService


def write_dataset(root: str, partitions: int = 3, rows_per_part: int = 20000) -> str:
    rng = np.random.default_rng(17)
    data_dir = os.path.join(root, "orders")
    os.makedirs(data_dir)
    for i in range(partitions):
        Table.from_pydict(
            {
                "price": rng.lognormal(3.0, 1.0, rows_per_part),
                "quantity": rng.integers(1, 50, rows_per_part).astype(np.float64),
                "rating": rng.uniform(0.0, 5.0, rows_per_part),
            }
        ).to_parquet(os.path.join(data_dir, f"part-{i:02d}.parquet"))
    return data_dir


def tenant_suites():
    return {
        "billing": Check(CheckLevel.ERROR, "billing-dq")
        .is_complete("price")
        .has_mean("price", lambda m: m > 0),
        "inventory": Check(CheckLevel.ERROR, "inventory-dq")
        .is_complete("quantity")
        .has_mean("quantity", lambda m: m > 0)
        .has_mean("price", lambda m: m > 0),
        "reviews": Check(CheckLevel.ERROR, "reviews-dq")
        .has_size(lambda n: n > 0)
        .has_standard_deviation("rating", lambda s: s > 0),
    }


def main() -> None:
    with tempfile.TemporaryDirectory(prefix="sharing_example_") as work:
        data_dir = write_dataset(work)

        def open_table():
            return Table.scan_parquet_dataset(data_dir)

        suites = tenant_suites()
        with DQService(workers=1) as svc:
            # occupy the single worker briefly so all three submissions
            # queue up and the scheduler can group them into one scan
            gate = Check(CheckLevel.ERROR, "gate").has_size(
                lambda n: (time.sleep(0.5) or n >= 0)
            )
            blocker = svc.submit(
                "warmup", "gate", Table.from_pydict({"k": [1.0]}), checks=[gate]
            )
            time.sleep(0.2)

            handles = {
                tenant: svc.submit(tenant, "orders", open_table, checks=[check])
                for tenant, check in suites.items()
            }
            blocker.wait(60)
            for tenant, handle in handles.items():
                if not handle.wait(120) or handle.status != "done":
                    raise SystemExit(f"{tenant}: {handle.status} ({handle.reason})")

            print(f"shared scans run: {svc.telemetry.value('shared_scans')}")
            for tenant, handle in handles.items():
                info = handle.sharing or {}
                if info.get("shared"):
                    proof = info["proof"]
                    drift = info["drift"]
                    print(
                        f"  {tenant:<10} {handle.result.status.name:<7} "
                        f"shared with {info['participants']} tenants — "
                        f"proof {proof['verdict']}, "
                        f"drift {sum(drift.values())}"
                    )
                else:
                    print(
                        f"  {tenant:<10} {handle.result.status.name:<7} solo "
                        f"({info.get('reason', 'no group formed')})"
                    )
            charges = {
                t: round(svc.ledger.bytes_total(t)) for t in suites
            }
            print(f"pro-rata scan charges (bytes): {charges}")


if __name__ == "__main__":
    main()

"""TPU-native extra: out-of-core verification over on-disk Parquet.

The reference scales to "billions of rows" by leaning on Spark's
partitioned storage (reference: README.md:43). Here `Table.scan_parquet`
streams Arrow record batches through the fused pass with constant host
memory — the profiler and VerificationSuite never materialize the file.

Run:  python examples/streaming_parquet_example.py
"""

import tempfile
from pathlib import Path

import example_utils  # noqa: F401  (path bootstrap)
import numpy as np

from deequ_tpu import Check, CheckLevel, CheckStatus, Table, VerificationSuite


def write_parquet(path: str, n: int = 500_000, chunk: int = 100_000) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    schema = pa.schema(
        [("price", pa.float64()), ("qty", pa.int64()), ("category", pa.string())]
    )
    with pq.ParquetWriter(path, schema) as writer:
        for start in range(0, n, chunk):
            m = min(chunk, n - start)
            price = rng.lognormal(3.0, 1.0, m)
            price[rng.random(m) < 0.01] = np.nan
            writer.write_table(
                pa.table(
                    {
                        "price": price,
                        "qty": rng.integers(1, 100, m),
                        "category": rng.choice(["a", "b", "c", "d"], m),
                    },
                    schema=schema,
                )
            )


def main() -> None:
    path = str(Path(tempfile.mkdtemp()) / "items.parquet")
    write_parquet(path)

    # a STREAMED table: batches flow from disk through the fused pass
    table = Table.scan_parquet(path)

    result = (
        VerificationSuite()
        .on_data(table)
        .add_check(
            Check(CheckLevel.ERROR, "stream checks")
            .has_size(lambda s: s == 500_000)
            .has_completeness("price", lambda c: c > 0.98)
            .is_contained_in("category", ["a", "b", "c", "d"])
            .is_positive("qty")
        )
        .run()
    )

    assert result.status == CheckStatus.SUCCESS, result.status
    print("All checks passed over the streamed 500k-row Parquet file.")
    for metric in result.metrics.values():
        print(f"\t{metric.name}({metric.instance}) = {metric.value.get()}")


if __name__ == "__main__":
    main()

"""Observability demo: trace a verification run, print the run report,
and write a Chrome-trace JSON you can load at https://ui.perfetto.dev.

Part two runs a sharded scan across two spawned interpreters, each
writing its own per-process trace, and merges them with
`observe.export.merge_chrome_traces` into one document — the shards'
scan and allgather spans line up side by side under separate process
tracks, which is how a pod-level cold pass is meant to be read.

Run directly or via `make trace-demo`.
"""

import os
import tempfile
import textwrap

import numpy as np

from deequ_tpu import Check, CheckLevel, VerificationSuite
from deequ_tpu.data.table import Table


def main() -> None:
    rng = np.random.default_rng(42)
    n = 500_000
    data = Table.from_numpy(
        {
            "price": rng.lognormal(3.0, 1.0, n),
            "quantity": rng.integers(1, 50, n).astype(np.float64),
            "discount": rng.random(n) * 0.3,
            "in_stock": rng.random(n) < 0.9,
        }
    )

    trace_path = os.path.join(tempfile.gettempdir(), "deequ_tpu_demo_trace.json")
    result = (
        VerificationSuite()
        .on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "inventory sanity")
            .is_complete("price")
            .is_non_negative("price")
            .has_min("quantity", lambda v: v >= 1.0)
            .has_max("discount", lambda v: v <= 0.3)
        )
        .with_tracing(trace_path)  # or DEEQU_TPU_TRACE=1 in the env
        .run()
    )

    trace = result.run_trace
    print(trace.report())
    print()
    phases = trace.phase_seconds()
    print(
        "phase breakdown:",
        ", ".join(f"{k}={phases[k] * 1e3:.1f}ms" for k in sorted(phases)),
    )
    print(f"chrome trace written to: {trace.path}")
    print("load it in https://ui.perfetto.dev (or chrome://tracing)")
    print()
    cross_process_demo()


SHARD_WORKER = textwrap.dedent(
    """
    import json, os, sys, time

    os.environ["JAX_PLATFORMS"] = "cpu"
    rank, _port, tmpdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    data_dir, out_dir = sys.argv[4], sys.argv[5]
    os.environ["DEEQU_TPU_SHARD"] = str(rank)

    from deequ_tpu import observe
    from deequ_tpu.analyzers.scan import Mean, Sum
    from deequ_tpu.data.source import PartitionedParquetSource
    from deequ_tpu.observe.export import write_chrome_trace
    from deequ_tpu.parallel import run_sharded_analysis

    _round = [0]

    def gather(payload):
        r = _round[0]
        _round[0] += 1
        gdir = os.path.join(tmpdir, f"gather-{r}")
        os.makedirs(gdir, exist_ok=True)
        tmp = os.path.join(gdir, f"{rank}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(gdir, f"{rank}.bin"))
        out = []
        for i in range(2):
            p = os.path.join(gdir, f"{i}.bin")
            deadline = time.time() + 120
            while not os.path.exists(p):
                if time.time() > deadline:
                    raise TimeoutError(f"peer {i} missing in round {r}")
                time.sleep(0.01)
            with open(p, "rb") as f:
                out.append(f.read())
        return out

    src = PartitionedParquetSource(
        sorted(
            os.path.join(data_dir, f)
            for f in os.listdir(data_dir)
            if f.endswith(".parquet")
        )
    )
    with observe.traced_run("sharded-scan", enable=True) as handle:
        run_sharded_analysis(
            src, [Mean("price"), Sum("price")],
            shard=rank, num_shards=2, gather=gather,
        )
    trace = handle.trace
    path = write_chrome_trace(
        os.path.join(out_dir, f"trace-p{rank}.json"),
        [trace.root],
        epoch=trace.epoch,
        pid=rank,
    )
    print("RESULT:" + json.dumps({"trace_path": path}), flush=True)
    """
)


def cross_process_demo() -> None:
    """Two real interpreters scan disjoint partition ranges, each writes
    a per-process chrome trace, and the driver merges them."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from deequ_tpu.observe.export import merge_chrome_traces
    from deequ_tpu.parallel.procspawn import (
        WorkerFailure,
        run_worker_processes,
    )

    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as work:
        data_dir = os.path.join(work, "data")
        os.makedirs(data_dir)
        for i in range(4):
            pq.write_table(
                pa.table({"price": rng.lognormal(3.0, 1.0, 2000)}),
                os.path.join(data_dir, f"part-{i}.parquet"),
                row_group_size=1000,
            )
        try:
            results = run_worker_processes(
                SHARD_WORKER, 2, extra_args=[data_dir, work], timeout=240.0
            )
        except WorkerFailure as exc:
            if exc.runtime_unavailable:
                print("cross-process trace demo skipped:", exc)
                return
            raise

        merged_path = os.path.join(
            tempfile.gettempdir(), "deequ_tpu_demo_mesh_trace.json"
        )
        merged = merge_chrome_traces(
            [r["trace_path"] for r in results], out_path=merged_path
        )
        pids = sorted(
            {e["pid"] for e in merged["traceEvents"] if "pid" in e}
        )
        names = {
            e["name"]
            for e in merged["traceEvents"]
            if e.get("ph") == "B"
        }
        print(
            f"merged {len(merged['traceEvents'])} span events from "
            f"{len(results)} shard processes (pids {pids})"
        )
        print(
            "cross-process spans include:",
            ", ".join(
                sorted(n for n in names if n.startswith("shard_"))
            ),
        )
        print(f"merged chrome trace written to: {merged_path}")


if __name__ == "__main__":
    main()

"""Observability demo: trace a verification run, print the run report,
and write a Chrome-trace JSON you can load at https://ui.perfetto.dev.

Run directly or via `make trace-demo`.
"""

import os
import tempfile

import numpy as np

from deequ_tpu import Check, CheckLevel, VerificationSuite
from deequ_tpu.data.table import Table


def main() -> None:
    rng = np.random.default_rng(42)
    n = 500_000
    data = Table.from_numpy(
        {
            "price": rng.lognormal(3.0, 1.0, n),
            "quantity": rng.integers(1, 50, n).astype(np.float64),
            "discount": rng.random(n) * 0.3,
            "in_stock": rng.random(n) < 0.9,
        }
    )

    trace_path = os.path.join(tempfile.gettempdir(), "deequ_tpu_demo_trace.json")
    result = (
        VerificationSuite()
        .on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "inventory sanity")
            .is_complete("price")
            .is_non_negative("price")
            .has_min("quantity", lambda v: v >= 1.0)
            .has_max("discount", lambda v: v <= 0.3)
        )
        .with_tracing(trace_path)  # or DEEQU_TPU_TRACE=1 in the env
        .run()
    )

    trace = result.run_trace
    print(trace.report())
    print()
    phases = trace.phase_seconds()
    print(
        "phase breakdown:",
        ", ".join(f"{k}={phases[k] * 1e3:.1f}ms" for k in sorted(phases)),
    )
    print(f"chrome trace written to: {trace.path}")
    print("load it in https://ui.perfetto.dev (or chrome://tracing)")


if __name__ == "__main__":
    main()

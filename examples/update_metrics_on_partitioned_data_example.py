"""Partitioned-data metrics via per-partition states
(reference: examples/UpdateMetricsOnPartitionedDataExample.scala:30-95).

States are computed per partition; table-level metrics come from merging
the states — no data scan. When one partition changes, only its state is
recomputed and the table metrics re-merged.
"""

from example_utils import Manufacturer, manufacturers_as_table

from deequ_tpu import Check, CheckLevel
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def main() -> None:
    # a manufacturers table partitioned by country code
    de = manufacturers_as_table(
        Manufacturer(1, "ManufacturerA", "DE"),
        Manufacturer(2, "ManufacturerB", "DE"),
    )
    us = manufacturers_as_table(
        Manufacturer(3, "ManufacturerD", "US"),
        Manufacturer(4, "ManufacturerE", "US"),
        Manufacturer(5, "ManufacturerF", "US"),
    )
    cn = manufacturers_as_table(
        Manufacturer(6, "ManufacturerG", "CN"),
        Manufacturer(7, "ManufacturerH", "CN"),
    )

    # constraints over the table as a WHOLE
    check = (
        Check(CheckLevel.WARNING, "a check")
        .is_complete("name")
        .contains_url("name", lambda ratio: ratio == 0.0)
        .is_contained_in("countryCode", ["DE", "US", "CN"])
    )
    analyzers = sorted(check.required_analyzers(), key=repr)

    # compute and store the state per partition
    de_states, us_states, cn_states = (
        InMemoryStateProvider(),
        InMemoryStateProvider(),
        InMemoryStateProvider(),
    )
    AnalysisRunner.do_analysis_run(de, analyzers, save_states_with=de_states)
    AnalysisRunner.do_analysis_run(us, analyzers, save_states_with=us_states)
    AnalysisRunner.do_analysis_run(cn, analyzers, save_states_with=cn_states)

    # table-level metrics purely from the partition states (no data scan)
    table_metrics = AnalysisRunner.run_on_aggregated_states(
        de, analyzers, [de_states, us_states, cn_states]
    )
    print("Metrics for the whole table:\n")
    for analyzer, metric in table_metrics.metric_map.items():
        print(f"\t{analyzer!r}: {metric.value.get()}")

    # a single partition changes: recompute ONLY its state
    updated_us = manufacturers_as_table(
        Manufacturer(3, "ManufacturerDNew", "US"),
        Manufacturer(4, None, "US"),
        Manufacturer(5, "ManufacturerFNew http://clickme.com", "US"),
    )
    updated_us_states = InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(
        updated_us, analyzers, save_states_with=updated_us_states
    )

    updated_table_metrics = AnalysisRunner.run_on_aggregated_states(
        de, analyzers, [de_states, updated_us_states, cn_states]
    )
    print("Metrics for the whole table after updating the US partition:\n")
    for analyzer, metric in updated_table_metrics.metric_map.items():
        print(f"\t{analyzer!r}: {metric.value.get()}")


if __name__ == "__main__":
    main()

"""Test harness: run JAX on a virtual 8-device CPU platform.

The analogue of the reference's SparkContextSpec local-master session
(reference: src/test/scala/com/amazon/deequ/SparkContextSpec.scala:25-95):
everything "distributed" is tested without TPU hardware — the host CPU is
split into 8 XLA devices so mesh/sharding code paths run for real.

Must run before jax is imported anywhere.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"

# isolate the per-user on-disk caches (placement probe results): tests
# must neither read a developer's production cache nor overwrite it
os.environ["DEEQU_TPU_CACHE_DIR"] = tempfile.mkdtemp(prefix="deequ_tpu_test_cache_")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

# jaxtyping's pytest plugin imports jax before this conftest runs, so the
# env vars alone can be too late — on a machine with a real accelerator the
# backend would otherwise initialize with 1 TPU device instead of 8 virtual
# CPU devices. Push platform + device count + x64 through the live config
# (safe post-import: the backend is not initialized until first use).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.4.34 area) has no such option; the
    # xla_force_host_platform_device_count flag above does the same job
    pass
jax.config.update(
    "jax_enable_x64", os.environ["JAX_ENABLE_X64"].lower() in ("1", "true")
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)

"""Canonical toy tables shared across tests.

Mirrors the reference's FixtureSupport corpus
(reference: src/test/scala/com/amazon/deequ/utils/FixtureSupport.scala:24+):
the same ground-truth shapes (missing values, unique columns, numeric
columns, conditionally informative pairs) so analyzer expectations carry
over directly.
"""

from deequ_tpu.data.table import ColumnType, Table


def get_df_missing() -> Table:
    # 12 rows; att1 has 6 non-null, att2 has 9 non-null
    return Table.from_pydict(
        {
            "item": [str(i) for i in range(1, 13)],
            "att1": ["a", "b", None, "a", "a", None, None, "b", "a", None, None, None],
            "att2": ["f", "d", "f", None, "f", "d", "d", None, "f", None, "f", "d"],
        }
    )


def get_df_full() -> Table:
    return Table.from_pydict(
        {
            "item": ["1", "2", "3", "4"],
            "att1": ["a", "a", "a", "b"],
            "att2": ["c", "c", "c", "d"],
        }
    )


def get_df_with_negative_numbers() -> Table:
    return Table.from_pydict(
        {
            "item": ["1", "2", "3", "4"],
            "att1": ["-1", "-2", "-3", "-4"],
            "att2": ["-1", "-2", "-3", "-4"],
        }
    )


def get_df_with_unique_columns() -> Table:
    return Table.from_pydict(
        {
            "unique": ["1", "2", "3", "4", "5", "6"],
            "nonUnique": ["0", "0", "0", "5", "6", "7"],
            "nonUniqueWithNulls": ["3", "3", "3", None, None, None],
            "uniqueWithNulls": ["1", "2", None, "3", "4", "5"],
            "onlyUniqueWithOtherNonUnique": ["5", "6", "7", "0", "0", "0"],
            "halfUniqueCombinedWithNonUnique": ["0", "0", "0", "4", "5", "6"],
        }
    )


def get_df_with_distinct_values() -> Table:
    return Table.from_pydict(
        {
            "att1": ["a", "a", None, "b", "b", "c"],
            "att2": [None, None, "x", "x", "x", "y"],
        }
    )


def get_df_with_numeric_values() -> Table:
    return Table.from_pydict(
        {
            "item": ["1", "2", "3", "4", "5", "6"],
            "att1": [1, 2, 3, 4, 5, 6],
            "att2": [0, 0, 0, 5, 6, 7],
        }
    )


def get_df_with_numeric_fractional_values() -> Table:
    return Table.from_pydict(
        {
            "item": ["1", "2", "3", "4", "5", "6"],
            "att1": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
            "att2": [0.0, 0.0, 0.0, 5.0, 6.0, 7.0],
        }
    )


def get_df_with_conditionally_uninformative_columns() -> Table:
    return Table.from_pydict(
        {"att1": [1, 2, 3], "att2": [0, 0, 0]}
    )


def get_df_with_conditionally_informative_columns() -> Table:
    return Table.from_pydict(
        {"att1": [1, 2, 3], "att2": [4, 5, 6]}
    )


def get_full_nulls() -> Table:
    return Table.from_pydict(
        {
            "item": ["1", "2", "3"],
            "att1": [None, None, None],
        },
        types={"att1": ColumnType.STRING},
    )


def get_basic_example_table() -> Table:
    """The README Item table (reference: examples/BasicExample.scala)."""
    return Table.from_pydict(
        {
            "id": [1, 2, 3, 4, 5],
            "name": ["Thingy A", "Thingy B", None, "Thingy D", "Thingy E"],
            "description": [
                "awesome thing.",
                "available at http://thingb.com",
                None,
                "checkout https://thingd.ca",
                None,
            ],
            "priority": ["high", None, "low", "low", "high"],
            "numViews": [0, 0, 5, 10, 12],
        }
    )

"""Runner tests: scan-sharing as an asserted property (mirrors reference
analyzers/runners/AnalysisRunnerTests.scala job-count assertions) plus
context merge/export semantics."""

from deequ_tpu.analyzers import (
    Completeness,
    Compliance,
    Correlation,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.core.exceptions import NoSuchColumnException
from deequ_tpu.ops import runtime
from deequ_tpu.runners import AnalysisRunner

from fixtures import get_df_with_numeric_values


class TestScanSharing:
    def test_six_analyzers_one_pass(self):
        df = get_df_with_numeric_values()
        analyzers = [
            Size(),
            Completeness("att1"),
            Mean("att1"),
            Minimum("att1"),
            Maximum("att1"),
            Sum("att1"),
        ]
        with runtime.monitored() as separate_stats:
            separate = [a.calculate(df) for a in analyzers]
        assert separate_stats.device_passes == 6

        with runtime.monitored() as fused_stats:
            context = AnalysisRunner.on_data(df).add_analyzers(analyzers).run()
        assert fused_stats.device_passes == 1

        # fused results == separate results (reference: AnalysisRunnerTests.scala:60-75)
        for analyzer, sep_metric in zip(analyzers, separate):
            assert context.metric(analyzer).value.get() == sep_metric.value.get()

    def test_mixed_columns_still_one_pass(self):
        df = get_df_with_numeric_values()
        analyzers = [
            Mean("att1"),
            Mean("att2"),
            StandardDeviation("att1"),
            Correlation("att1", "att2"),
            Compliance("rule", "att2 > att1"),
        ]
        with runtime.monitored() as stats:
            context = AnalysisRunner.on_data(df).add_analyzers(analyzers).run()
        assert stats.device_passes == 1
        assert len(context.metric_map) == 5
        assert all(m.value.is_success for m in context.all_metrics())

    def test_preconditions_fail_without_running_jobs(self):
        df = get_df_with_numeric_values()
        with runtime.monitored() as stats:
            context = (
                AnalysisRunner.on_data(df)
                .add_analyzer(Completeness("nope"))
                .run()
            )
        assert stats.device_passes == 0
        metric = context.metric(Completeness("nope"))
        assert metric.value.is_failure
        assert isinstance(metric.value.exception, NoSuchColumnException)

    def test_failure_does_not_poison_pass(self):
        df = get_df_with_numeric_values()
        context = (
            AnalysisRunner.on_data(df)
            .add_analyzer(Mean("att1"))
            .add_analyzer(Mean("item"))  # string column -> precondition failure
            .run()
        )
        assert context.metric(Mean("att1")).value.is_success
        assert context.metric(Mean("item")).value.is_failure

    def test_duplicate_analyzers_deduped(self):
        df = get_df_with_numeric_values()
        context = (
            AnalysisRunner.on_data(df)
            .add_analyzers([Mean("att1"), Mean("att1"), Mean("att1")])
            .run()
        )
        assert len(context.metric_map) == 1


class TestAnalyzerContext:
    def test_export_rows(self):
        df = get_df_with_numeric_values()
        context = (
            AnalysisRunner.on_data(df)
            .add_analyzers([Size(), Mean("att1"), Completeness("nope")])
            .run()
        )
        rows = context.success_metrics_as_rows()
        assert {
            "entity": "Dataset",
            "instance": "*",
            "name": "Size",
            "value": 6.0,
        } in rows
        assert {
            "entity": "Column",
            "instance": "att1",
            "name": "Mean",
            "value": 3.5,
        } in rows
        assert len(rows) == 2  # failed metric excluded

    def test_context_merge(self):
        df = get_df_with_numeric_values()
        c1 = AnalysisRunner.on_data(df).add_analyzer(Size()).run()
        c2 = AnalysisRunner.on_data(df).add_analyzer(Mean("att1")).run()
        merged = c1 + c2
        assert len(merged.metric_map) == 2


def test_deprecated_analysis_container():
    """reference: analyzers/Analysis.scala:29-63 — the legacy bag of
    analyzers, deprecated in favor of AnalysisRunner.on_data."""
    import warnings

    import numpy as np

    from deequ_tpu.analyzers import Analysis, Mean, Size
    from deequ_tpu.data.table import Table

    analysis = Analysis().add_analyzer(Size()).add_analyzers([Mean("x")])
    assert len(analysis.analyzers) == 2
    table = Table.from_numpy({"x": np.array([1.0, 2.0, 3.0])})
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        ctx = analysis.run(table)
    assert any(issubclass(w.category, DeprecationWarning) for w in caught)
    assert ctx.metric_map[Size()].value.get() == 3.0
    assert ctx.metric_map[Mean("x")].value.get() == 2.0

"""Exact per-analyzer metric values incl. NaN/empty/failure cases — the
depth of the reference's AnalyzerTests.scala (725 LoC) and
NullHandlingTests.scala (144 LoC) on the FixtureSupport corpus."""

from __future__ import annotations

import math

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile
from deequ_tpu.core.exceptions import (
    EmptyStateException,
    NoSuchColumnException,
    WrongColumnTypeException,
)
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from tests.fixtures import (
    get_df_full,
    get_df_missing,
    get_df_with_conditionally_informative_columns,
    get_df_with_conditionally_uninformative_columns,
    get_df_with_distinct_values,
    get_df_with_numeric_values,
    get_df_with_unique_columns,
    get_full_nulls,
)


def value_of(table: Table, analyzer):
    return AnalysisRunner.do_analysis_run(table, [analyzer]).metric_map[
        analyzer
    ].value


class TestSizeAnalyzer:
    """reference: AnalyzerTests.scala:34-44."""

    def test_exact_count(self):
        assert value_of(get_df_missing(), Size()).get() == 12.0
        assert value_of(get_df_full(), Size()).get() == 4.0

    def test_filtered_count(self):
        assert value_of(get_df_full(), Size(where="att1 = 'a'")).get() == 3.0

    def test_empty_table(self):
        t = Table.from_pydict({"x": []})
        assert value_of(t, Size()).get() == 0.0


class TestCompletenessAnalyzer:
    """reference: AnalyzerTests.scala:46-77."""

    def test_exact_fractions(self):
        t = get_df_missing()
        assert value_of(t, Completeness("att1")).get() == 0.5
        assert value_of(t, Completeness("att2")).get() == 0.75

    def test_wrong_column_fails_typed(self):
        v = value_of(get_df_missing(), Completeness("nonExistingColumn"))
        assert v.is_failure
        assert isinstance(v.exception, NoSuchColumnException)

    def test_with_filtering(self):
        # reference :70-77: rows where item in (1,2): att1 = a,b both present
        t = get_df_missing()
        assert value_of(
            t, Completeness("att1", where="item = '1' OR item = '2'")
        ).get() == 1.0

    def test_all_null_column_is_zero(self):
        assert value_of(get_full_nulls(), Completeness("att1")).get() == 0.0


class TestUniquenessAnalyzers:
    """reference: AnalyzerTests.scala:79-132."""

    def test_single_column_values(self):
        t = get_df_with_unique_columns()
        assert value_of(t, Uniqueness(("unique",))).get() == 1.0
        assert value_of(t, Uniqueness(("uniqueWithNulls",))).get() \
            == pytest.approx(5 / 6)
        assert value_of(t, Uniqueness(("nonUnique",))).get() == pytest.approx(3 / 6)

    def test_multi_column_values(self):
        t = get_df_full()
        # (att1, att2) pairs: (a,c)x3, (b,d)x1 -> 1 unique of 4 rows
        assert value_of(t, Uniqueness(("att1", "att2"))).get() == pytest.approx(1 / 4)

    def test_wrong_column_fails(self):
        v = value_of(get_df_full(), Uniqueness(("nonExistent",)))
        assert v.is_failure
        assert isinstance(v.exception, NoSuchColumnException)

    def test_unique_value_ratio(self):
        t = get_df_with_unique_columns()
        # nonUnique groups: {0:3, 5:1, 6:1, 7:1} -> 3 unique / 4 groups
        assert value_of(t, UniqueValueRatio(("nonUnique",))).get() == pytest.approx(0.75)

    def test_distinctness(self):
        t = get_df_with_distinct_values()
        assert value_of(t, Distinctness(("att1",))).get() == pytest.approx(3 / 6)
        assert value_of(t, Distinctness(("att2",))).get() == pytest.approx(2 / 6)

    def test_count_distinct_exact(self):
        t = get_df_with_distinct_values()
        assert value_of(t, CountDistinct(("att1",))).get() == 3.0
        assert value_of(t, CountDistinct(("att2",))).get() == 2.0


class TestEntropyAndMI:
    """reference: AnalyzerTests.scala:134-170."""

    def test_entropy_exact(self):
        t = get_df_full()  # att1: a x3, b x1
        expected = -(0.75 * math.log(0.75) + 0.25 * math.log(0.25))
        assert value_of(t, Entropy("att1")).get() == pytest.approx(expected, rel=1e-12)

    def test_mi_uninformative_is_zero(self):
        t = get_df_with_conditionally_uninformative_columns()
        assert value_of(t, MutualInformation("att1", "att2")).get() \
            == pytest.approx(0.0, abs=1e-12)

    def test_mi_informative_equals_entropy(self):
        # att1 fully determines att2 (both unique): MI == H(att1)
        t = get_df_with_conditionally_informative_columns()
        mi = value_of(t, MutualInformation("att1", "att2")).get()
        h = value_of(t, Entropy("att1")).get()
        assert mi == pytest.approx(h, rel=1e-12)

    def test_mi_of_column_with_itself_is_its_entropy(self):
        """reference: AnalyzerTests.scala:159-170 — MI(X, X) == H(X)."""
        t = get_df_full()
        mi = value_of(t, MutualInformation("att1", "att1")).get()
        h = value_of(t, Entropy("att1")).get()
        assert mi == pytest.approx(h, rel=1e-12)

    def test_mi_requires_two_columns(self):
        v = value_of(
            get_df_with_numeric_values(), MutualInformation(["att1", "att2", "item"])
        )
        assert v.is_failure


class TestComplianceAnalyzer:
    """reference: AnalyzerTests.scala:172-200."""

    def test_exact_fraction(self):
        t = get_df_with_numeric_values()
        assert value_of(t, Compliance("rule1", "att1 > 3")).get() == pytest.approx(0.5)
        assert value_of(t, Compliance("rule2", "att1 > 0")).get() == 1.0

    def test_filtered(self):
        t = get_df_with_numeric_values()
        assert value_of(
            t, Compliance("rule", "att2 > 0", where="att1 > 3")
        ).get() == 1.0

    def test_bad_expression_fails(self):
        v = value_of(get_df_with_numeric_values(), Compliance("bad", "att1 > > 3"))
        assert v.is_failure


class TestHistogramAnalyzer:
    """reference: AnalyzerTests.scala:202-272."""

    def test_exact_distribution(self):
        dist = value_of(get_df_missing(), Histogram("att1")).get()
        assert dist.number_of_bins == 3  # a, b, NullValue
        assert dist.values["a"].absolute == 4
        assert dist.values["b"].absolute == 2
        assert dist.values["NullValue"].absolute == 6
        assert dist.values["a"].ratio == pytest.approx(4 / 12)

    def test_numeric_values_stringified(self):
        dist = value_of(get_df_with_numeric_values(), Histogram("att1")).get()
        assert dist.number_of_bins == 6
        assert dist.values["1"].absolute == 1

    def test_binning_udf(self):
        # reference :229-248 bins by even/odd
        dist = value_of(
            get_df_with_numeric_values(),
            Histogram("att1", binning_udf=lambda v: "even" if v % 2 == 0 else "odd"),
        ).get()
        assert dist.number_of_bins == 2
        assert dist.values["even"].absolute == 3
        assert dist.values["odd"].absolute == 3

    def test_top_n_bins_only(self):
        dist = value_of(
            get_df_missing(), Histogram("att1", max_detail_bins=2)
        ).get()
        # number_of_bins reports ALL groups; details keep top-N
        assert dist.number_of_bins == 3
        assert len(dist.values) == 2
        assert "NullValue" in dist.values and "a" in dist.values

    def test_max_detail_bins_cap(self):
        v = value_of(get_df_missing(), Histogram("att1", max_detail_bins=1001))
        assert v.is_failure


class TestDataTypeAnalyzer:
    """reference: AnalyzerTests.scala:274-421 — the full decision table."""

    def _hist(self, values, types=None):
        t = Table.from_pydict({"v": values}, types=types)
        return value_of(t, DataType("v")).get()

    def test_integral_strings(self):
        d = self._hist(["1", "2", "3"])
        assert d.values["Integral"].absolute == 3
        assert d.values["Integral"].ratio == 1.0

    def test_negative_integrals(self):
        d = self._hist(["-1", "-2", "+3"])
        assert d.values["Integral"].absolute == 3

    def test_fractional_strings(self):
        d = self._hist(["1.0", "-2.0", "+3.5"])
        assert d.values["Fractional"].absolute == 3

    def test_mixed_fractional_and_integral(self):
        d = self._hist(["1", "2.0"])
        assert d.values["Integral"].absolute == 1
        assert d.values["Fractional"].absolute == 1

    def test_booleans(self):
        d = self._hist(["true", "false", "true"])
        assert d.values["Boolean"].absolute == 3

    def test_fallback_to_string(self):
        d = self._hist(["a", "1", "1.0"])
        assert d.values["String"].absolute == 1
        assert d.values["Integral"].absolute == 1
        assert d.values["Fractional"].absolute == 1

    def test_null_class(self):
        d = self._hist(["1", None, "2"])
        assert d.values["Unknown"].absolute == 1
        assert d.values["Integral"].absolute == 2

    def test_typed_numeric_column_is_static(self):
        d = self._hist([1.0, 2.0, 3.0])
        assert d.values["Fractional"].absolute == 3

    def test_where_filtered_rows_are_unknown(self):
        t = Table.from_pydict({"v": ["1", "2", "x"], "k": [1, 2, 3]})
        analyzer = DataType("v", where="k < 3")
        d = value_of(t, analyzer).get()
        assert d.values["Integral"].absolute == 2
        assert d.values["Unknown"].absolute == 1


class TestBasicStatistics:
    """reference: AnalyzerTests.scala:424-506."""

    def test_mean(self):
        assert value_of(get_df_with_numeric_values(), Mean("att1")).get() == 3.5

    def test_mean_with_where(self):
        assert value_of(
            get_df_with_numeric_values(), Mean("att1", where="att2 > 0")
        ).get() == 5.0

    def test_mean_fails_on_non_numeric(self):
        v = value_of(get_df_full(), Mean("att1"))
        assert v.is_failure
        assert isinstance(v.exception, WrongColumnTypeException)

    def test_stddev_population(self):
        expected = float(np.std(np.arange(1, 7)))
        assert value_of(
            get_df_with_numeric_values(), StandardDeviation("att1")
        ).get() == pytest.approx(expected, rel=1e-12)

    def test_stddev_fails_on_non_numeric(self):
        assert value_of(get_df_full(), StandardDeviation("att1")).is_failure

    def test_minimum_maximum_sum(self):
        t = get_df_with_numeric_values()
        assert value_of(t, Minimum("att1")).get() == 1.0
        assert value_of(t, Maximum("att1")).get() == 6.0
        assert value_of(t, Sum("att1")).get() == 21.0

    def test_maximum_with_filtering(self):
        assert value_of(
            get_df_with_numeric_values(), Maximum("att1", where="item <= '3'")
        ).get() == 3.0

    def test_min_max_fail_on_non_numeric(self):
        assert value_of(get_df_full(), Minimum("att1")).is_failure
        assert value_of(get_df_full(), Maximum("att1")).is_failure
        assert value_of(get_df_full(), Sum("att1")).is_failure

    def test_correlation_exact(self):
        t = get_df_with_conditionally_informative_columns()
        assert value_of(t, Correlation("att1", "att2")).get() == pytest.approx(1.0)

    def test_correlation_of_constant_is_nan_or_failure(self):
        t = get_df_with_conditionally_uninformative_columns()
        v = value_of(t, Correlation("att1", "att2"))
        # zero variance in att2: Pearson r undefined
        assert v.is_failure or math.isnan(v.get())

    def test_decimal_columns_work(self):
        t = Table.from_pydict(
            {"v": [1.0, 2.0, 3.0]}, types={"v": ColumnType.DECIMAL}
        )
        assert value_of(t, Sum("v")).get() == 6.0
        assert value_of(t, Mean("v")).get() == 2.0


class TestCountDistinctFamily:
    """reference: AnalyzerTests.scala:508-560."""

    def test_approx_count_distinct_small_exact(self):
        t = get_df_with_numeric_values()
        assert value_of(t, ApproxCountDistinct("att1")).get() == 6.0

    def test_approx_count_distinct_with_filtering(self):
        t = get_df_with_numeric_values()
        assert value_of(
            t, ApproxCountDistinct("att1", where="att2 = 0")
        ).get() == 3.0

    def test_approx_quantile_exact_at_small_n(self):
        t = get_df_with_numeric_values()
        v = value_of(t, ApproxQuantile("att1", 0.5)).get()
        assert 3.0 <= v <= 4.0
        assert value_of(t, ApproxQuantile("att1", 0.0)).get() == 1.0
        assert value_of(t, ApproxQuantile("att1", 1.0)).get() == 6.0

    def test_approx_quantile_rejects_bad_params(self):
        t = get_df_with_numeric_values()
        assert value_of(t, ApproxQuantile("att1", 1.5)).is_failure
        assert value_of(t, ApproxQuantile("att1", -0.1)).is_failure


class TestPatternMatchAnalyzer:
    def test_exact_fraction(self):
        t = Table.from_pydict({"v": ["ab12", "cd34", "xxxx"]})
        assert value_of(t, PatternMatch("v", r"[a-z]{2}\d{2}")).get() \
            == pytest.approx(2 / 3)

    def test_null_values_dont_match(self):
        t = Table.from_pydict({"v": ["12", None, "ab"]})
        assert value_of(t, PatternMatch("v", r"\d+")).get() == pytest.approx(1 / 3)


class TestNullHandling:
    """reference: NullHandlingTests.scala:55-133 — empty states vs zero
    values, and analyzer names in EmptyStateExceptions."""

    def _null_table(self) -> Table:
        return Table.from_pydict(
            {
                "stringCol": [None, None, None],
                "numCol": [None, None, None],
            },
            types={
                "stringCol": ColumnType.STRING,
                "numCol": ColumnType.DOUBLE,
            },
        )

    def test_size_still_counts(self):
        assert value_of(self._null_table(), Size()).get() == 3.0

    def test_completeness_zero_not_failure(self):
        v = value_of(self._null_table(), Completeness("stringCol"))
        assert v.is_success and v.get() == 0.0

    def test_numeric_analyzers_empty_state(self):
        t = self._null_table()
        for analyzer in (
            Mean("numCol"),
            Minimum("numCol"),
            Maximum("numCol"),
            Sum("numCol"),
            StandardDeviation("numCol"),
        ):
            v = value_of(t, analyzer)
            assert v.is_failure, analyzer
            assert isinstance(v.exception, EmptyStateException), analyzer
            # reference :122-133: the exception names the analyzer
            assert analyzer.name in str(v.exception) or repr(analyzer) in str(
                v.exception
            ), analyzer

    def test_approx_count_distinct_of_all_null_is_zero(self):
        assert value_of(self._null_table(), ApproxCountDistinct("stringCol")).get() \
            == 0.0

    def test_compliance_on_all_null_criterion(self):
        # where filter excludes everything -> criterion never non-NULL
        t = get_df_with_numeric_values()
        v = value_of(t, Compliance("none", "att1 > 0", where="att1 > 100"))
        assert v.is_failure
        assert isinstance(v.exception, EmptyStateException)

    def test_grouping_analyzers_on_all_null(self):
        t = self._null_table()
        assert value_of(t, CountDistinct(("stringCol",))).get() == 0.0
        v = value_of(t, Uniqueness(("stringCol",)))
        assert v.is_failure  # SQL sum over empty -> NULL

    def test_incremental_merge_with_all_null_partition(self):
        from deequ_tpu.analyzers.state_provider import InMemoryStateProvider

        full = get_df_with_numeric_values()
        nulls = Table.from_pydict(
            {"item": ["7"], "att1": [None], "att2": [None]},
            types={"att1": ColumnType.LONG, "att2": ColumnType.LONG},
        )
        p1, p2 = InMemoryStateProvider(), InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(full, [Mean("att1")], save_states_with=p1)
        AnalysisRunner.do_analysis_run(nulls, [Mean("att1")], save_states_with=p2)
        analyzer = Mean("att1")
        state1 = p1.load(analyzer)
        assert p2.load(analyzer) is None  # empty contribution
        assert analyzer.compute_metric_from(state1).value.get() == 3.5

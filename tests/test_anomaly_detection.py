"""Anomaly-detection tests (mirrors the reference's 8 pure-function test
files incl. seasonal/HoltWintersTest)."""

import numpy as np
import pytest

from deequ_tpu.anomaly import (
    AnomalyDetector,
    BatchNormalStrategy,
    DataPoint,
    HoltWinters,
    MetricInterval,
    OnlineNormalStrategy,
    RateOfChangeStrategy,
    SeriesSeasonality,
    SimpleThresholdStrategy,
)


class TestSimpleThreshold:
    def test_bounds(self):
        data = [-1.0, 2.0, 3.0, 0.5]
        strategy = SimpleThresholdStrategy(upper_bound=1.0, lower_bound=0.0)
        anomalies = strategy.detect(data, (0, 4))
        assert [i for i, _ in anomalies] == [0, 1, 2]

    def test_interval(self):
        data = [-1.0, 2.0, 3.0, 0.5]
        strategy = SimpleThresholdStrategy(upper_bound=1.0, lower_bound=0.0)
        anomalies = strategy.detect(data, (2, 4))
        assert [i for i, _ in anomalies] == [2]

    def test_invalid_bounds(self):
        with pytest.raises(ValueError):
            SimpleThresholdStrategy(upper_bound=0.0, lower_bound=1.0)


class TestRateOfChange:
    def test_first_order(self):
        data = [1.0, 2.0, 3.0, 10.0, 11.0]
        strategy = RateOfChangeStrategy(max_rate_decrease=-2.0, max_rate_increase=2.0)
        anomalies = strategy.detect(data, (0, 5))
        assert [i for i, _ in anomalies] == [3]

    def test_requires_a_bound(self):
        with pytest.raises(ValueError):
            RateOfChangeStrategy()

    def test_second_order(self):
        data = [1.0, 2.0, 4.0, 8.0, 16.0]
        strategy = RateOfChangeStrategy(max_rate_increase=3.0, order=2)
        anomalies = strategy.detect(data, (0, 5))
        # second differences: 1, 2, 4 -> index 4 (diff 4 > 3)
        assert [i for i, _ in anomalies] == [4]


class TestOnlineNormal:
    def test_detects_outlier(self):
        rng = np.random.default_rng(42)
        data = list(rng.normal(10.0, 1.0, 50))
        data[40] = 100.0
        strategy = OnlineNormalStrategy(ignore_start_percentage=0.2)
        anomalies = strategy.detect(data, (30, 50))
        assert 40 in [i for i, _ in anomalies]

    def test_anomalies_excluded_from_stats(self):
        rng = np.random.default_rng(0)
        data = list(rng.normal(0.0, 1.0, 100))
        data[50] = 500.0
        data[51] = 500.0
        strategy = OnlineNormalStrategy()
        anomalies = strategy.detect(data, (40, 100))
        indices = [i for i, _ in anomalies]
        assert 50 in indices and 51 in indices


class TestBatchNormal:
    def test_excludes_interval_from_stats(self):
        rng = np.random.default_rng(1)
        data = list(rng.normal(5.0, 1.0, 60))
        data[55] = 50.0
        strategy = BatchNormalStrategy()
        anomalies = strategy.detect(data, (50, 60))
        assert [i for i, _ in anomalies] == [55]

    def test_needs_data_outside_interval(self):
        strategy = BatchNormalStrategy()
        with pytest.raises(ValueError):
            strategy.detect([1.0, 2.0], (0, 2))


class TestAnomalyDetector:
    def history(self):
        return [DataPoint(t, float(t % 3 == 0)) for t in range(10)]

    def test_sorts_and_filters(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        points = [
            DataPoint(3, 2.0),
            DataPoint(1, 10.0),
            DataPoint(2, None),  # missing -> dropped
        ]
        result = detector.detect_anomalies_in_history(points)
        assert [(t, a.value) for t, a in result.anomalies] == [(1, 10.0)]

    def test_new_point(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        history = [DataPoint(t, 1.0) for t in range(5)]
        ok = detector.is_new_point_anomalous(history, DataPoint(10, 4.0))
        assert ok.anomalies == []
        bad = detector.is_new_point_anomalous(history, DataPoint(11, 6.0))
        assert len(bad.anomalies) == 1

    def test_new_point_must_be_after_history(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        history = [DataPoint(t, 1.0) for t in range(5)]
        with pytest.raises(ValueError, match="history range"):
            detector.is_new_point_anomalous(history, DataPoint(3, 1.0))

    def test_empty_history_rejected(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        with pytest.raises(ValueError):
            detector.is_new_point_anomalous([], DataPoint(1, 1.0))


class TestHoltWinters:
    def seasonal_series(self, cycles: int, noise: float = 0.0, seed: int = 0):
        rng = np.random.default_rng(seed)
        pattern = np.array([10.0, 12, 14, 16, 14, 12, 10])
        series = np.tile(pattern, cycles) + np.arange(7 * cycles) * 0.1
        return series + rng.normal(0, noise, len(series))

    def test_no_anomaly_on_clean_continuation(self):
        series = self.seasonal_series(5)
        strategy = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        anomalies = strategy.detect(list(series), (28, 35))
        assert anomalies == []

    def test_detects_break(self):
        series = self.seasonal_series(5).copy()
        series[30] += 50.0
        strategy = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        anomalies = strategy.detect(list(series), (28, 35))
        assert 30 in [i for i, _ in anomalies]

    def test_needs_two_cycles(self):
        strategy = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        with pytest.raises(ValueError, match="two full cycles"):
            strategy.detect([1.0] * 20, (10, 20))

    def test_monthly_yearly(self):
        # with only 2 training cycles the 1.96·sd(|residual|) threshold is
        # tight (same formula as the reference) — assert the real break is
        # found and dominates, rather than zero false positives
        rng = np.random.default_rng(7)
        pattern = np.array([5.0, 6, 8, 12, 15, 18, 20, 19, 15, 11, 7, 5])
        series = np.tile(pattern, 3) + rng.normal(0, 0.3, 36)
        series[30] += 40.0
        strategy = HoltWinters(MetricInterval.MONTHLY, SeriesSeasonality.YEARLY)
        anomalies = strategy.detect(list(series), (24, 36))
        indices = [i for i, _ in anomalies]
        assert 30 in indices


class TestAnomalyCheckIntegration:
    def test_verification_with_anomaly_check(self):
        from deequ_tpu import Table, CheckStatus, VerificationSuite
        from deequ_tpu.analyzers import Size
        from deequ_tpu.repository import InMemoryMetricsRepository, ResultKey
        from deequ_tpu.verification.run_builder import AnomalyCheckConfig
        from deequ_tpu.checks.check import CheckLevel

        repo = InMemoryMetricsRepository()
        # build history of sizes ~ 1000
        for day in range(1, 6):
            t = Table.from_pydict({"x": list(range(1000 + day))})
            (
                VerificationSuite.on_data(t)
                .use_repository(repo)
                .add_required_analyzer(Size())
                .save_or_append_result(ResultKey(day, {}))
                .run()
            )

        # normal new value passes
        t_ok = Table.from_pydict({"x": list(range(1010))})
        result = (
            VerificationSuite.on_data(t_ok)
            .use_repository(repo)
            .add_anomaly_check(
                RateOfChangeStrategy(max_rate_decrease=-100.0, max_rate_increase=100.0),
                Size(),
                AnomalyCheckConfig(CheckLevel.ERROR, "size anomaly"),
            )
            .save_or_append_result(ResultKey(6, {}))
            .run()
        )
        assert result.status == CheckStatus.SUCCESS

        # anomalous new value fails
        t_bad = Table.from_pydict({"x": list(range(5000))})
        result = (
            VerificationSuite.on_data(t_bad)
            .use_repository(repo)
            .add_anomaly_check(
                RateOfChangeStrategy(max_rate_decrease=-100.0, max_rate_increase=100.0),
                Size(),
                AnomalyCheckConfig(CheckLevel.ERROR, "size anomaly"),
            )
            .run()
        )
        assert result.status == CheckStatus.ERROR


def test_anomaly_check_does_not_see_current_runs_own_metric():
    """Results are saved AFTER check evaluation: the anomaly assertion's
    history query must not include this run's own metric (reference:
    VerificationSuite.scala:121-139 passes saveOrAppendResultsWithKey=None
    into the runner and saves post-evaluate). With the wrong order, the
    2->5 size jump in AnomalyDetectionExample is invisible (diff 0)."""
    import numpy as np

    from deequ_tpu import CheckStatus, Table, VerificationSuite
    from deequ_tpu.analyzers import Size
    from deequ_tpu.anomaly.strategies import RateOfChangeStrategy
    from deequ_tpu.repository.base import ResultKey
    from deequ_tpu.repository.memory import InMemoryMetricsRepository

    repo = InMemoryMetricsRepository()
    yesterday = Table.from_numpy({"x": np.arange(2.0)})
    today = Table.from_numpy({"x": np.arange(5.0)})

    r1 = (
        VerificationSuite()
        .on_data(yesterday)
        .use_repository(repo)
        .save_or_append_result(ResultKey(1000))
        .add_anomaly_check(RateOfChangeStrategy(max_rate_increase=2.0), Size())
        .run()
    )
    # first run: empty history -> the anomaly constraint fails like the
    # reference's require(dataSeries.nonEmpty); only the SAVE matters here
    assert repo.load_by_key(ResultKey(1000)).metric(Size()).value.get() == 2.0

    r2 = (
        VerificationSuite()
        .on_data(today)
        .use_repository(repo)
        .save_or_append_result(ResultKey(2000))
        .add_anomaly_check(RateOfChangeStrategy(max_rate_increase=2.0), Size())
        .run()
    )
    assert r2.status == CheckStatus.WARNING  # 2 -> 5 is anomalous
    # ... but the metric WAS saved after evaluation
    assert repo.load_by_key(ResultKey(2000)).metric(Size()).value.get() == 5.0

"""Anomaly-strategy depth: boundary conditions, parameter validation and
detail messages per strategy — the coverage of the reference's 8
anomalydetection test files (SimpleThresholdStrategyTest,
RateOfChangeStrategyTest, OnlineNormalStrategyTest,
BatchNormalStrategyTest, AnomalyDetectorTest, HistoryUtilsTest,
seasonal/HoltWintersTest). Complements tests/test_anomaly_detection.py's
scenario tests."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.anomaly.base import Anomaly
from deequ_tpu.anomaly.detector import AnomalyDetector, DataPoint
from deequ_tpu.anomaly.holt_winters import (
    HoltWinters,
    MetricInterval,
    SeriesSeasonality,
)
from deequ_tpu.anomaly.strategies import (
    BatchNormalStrategy,
    OnlineNormalStrategy,
    RateOfChangeStrategy,
    SimpleThresholdStrategy,
)


class TestSimpleThresholdBoundaries:
    def test_bounds_are_inclusive(self):
        s = SimpleThresholdStrategy(lower_bound=-1.0, upper_bound=1.0)
        series = [-1.0, 1.0, -1.0001, 1.0001]
        found = s.detect(series, (0, len(series)))
        assert [i for i, _ in found] == [2, 3]

    def test_search_interval_clamps_to_series(self):
        s = SimpleThresholdStrategy(upper_bound=0.0)
        assert s.detect([1.0, 1.0], (0, 100)) == [
            (0, s.detect([1.0], (0, 1))[0][1]),
            (1, s.detect([1.0], (0, 1))[0][1]),
        ] or len(s.detect([1.0, 1.0], (0, 100))) == 2

    def test_inverted_interval_rejected(self):
        with pytest.raises(ValueError):
            SimpleThresholdStrategy(upper_bound=1.0).detect([1.0], (2, 1))

    def test_inverted_bounds_rejected(self):
        with pytest.raises(ValueError):
            SimpleThresholdStrategy(lower_bound=2.0, upper_bound=1.0)

    def test_detail_message(self):
        s = SimpleThresholdStrategy(lower_bound=0.0, upper_bound=1.0)
        ((_, anomaly),) = s.detect([2.0], (0, 1))
        assert "[SimpleThresholdStrategy]" in anomaly.detail
        assert "2.0" in anomaly.detail

    def test_anomaly_equality_ignores_detail(self):
        """reference: DetectionResult.scala:19-56."""
        assert Anomaly(1.0, 1.0, "left") == Anomaly(1.0, 1.0, "right")
        assert Anomaly(1.0, 1.0, "d") != Anomaly(2.0, 1.0, "d")


class TestRateOfChangeBoundaries:
    def test_only_increase_bound(self):
        s = RateOfChangeStrategy(max_rate_increase=1.0)
        series = [0.0, 0.5, 2.5, 2.0]
        found = s.detect(series, (0, len(series)))
        assert [i for i, _ in found] == [2]

    def test_only_decrease_bound(self):
        s = RateOfChangeStrategy(max_rate_decrease=-1.0)
        series = [2.0, 1.5, 0.0, 0.5]
        found = s.detect(series, (0, len(series)))
        assert [i for i, _ in found] == [2]

    def test_needs_at_least_one_bound(self):
        with pytest.raises(ValueError):
            RateOfChangeStrategy()

    def test_inconsistent_bounds_rejected(self):
        with pytest.raises(ValueError):
            RateOfChangeStrategy(max_rate_decrease=1.0, max_rate_increase=-1.0)

    def test_negative_order_rejected(self):
        with pytest.raises(ValueError):
            RateOfChangeStrategy(max_rate_increase=1.0, order=-1)

    def test_second_order_differences(self):
        # linear growth has zero 2nd difference; the jump breaks it
        s = RateOfChangeStrategy(
            max_rate_decrease=-0.1, max_rate_increase=0.1, order=2
        )
        series = [1.0, 2.0, 3.0, 4.0, 50.0]
        found = s.detect(series, (0, len(series)))
        assert 4 in [i for i, _ in found]

    def test_interval_start_looks_back_for_differences(self):
        # detecting inside (3, 4) still needs series[2] for the diff
        s = RateOfChangeStrategy(max_rate_increase=1.0)
        series = [0.0, 0.0, 0.0, 10.0]
        found = s.detect(series, (3, 4))
        assert [i for i, _ in found] == [3]

    def test_anomaly_carries_value_not_change(self):
        s = RateOfChangeStrategy(max_rate_increase=1.0)
        ((_, anomaly),) = s.detect([0.0, 5.0], (0, 2))
        assert anomaly.value == 5.0
        assert "Change of" in anomaly.detail


class TestOnlineNormalBoundaries:
    def _series(self):
        rng = np.random.default_rng(7)
        series = list(rng.normal(10.0, 1.0, 60))
        series[40] = 30.0
        return series

    def test_detects_spike(self):
        s = OnlineNormalStrategy()
        found = s.detect(self._series(), (0, 60))
        assert 40 in [i for i, _ in found]

    def test_upper_only_ignores_dips(self):
        series = self._series()
        series[50] = -20.0
        s = OnlineNormalStrategy(lower_deviation_factor=None)
        found = [i for i, _ in s.detect(series, (0, 60))]
        assert 40 in found and 50 not in found

    def test_lower_only_ignores_spikes(self):
        series = self._series()
        series[50] = -20.0
        s = OnlineNormalStrategy(upper_deviation_factor=None)
        found = [i for i, _ in s.detect(series, (0, 60))]
        assert 50 in found and 40 not in found

    def test_warmup_fraction_skipped(self):
        s = OnlineNormalStrategy(ignore_start_percentage=0.5)
        series = self._series()
        found = [i for i, _ in s.detect(series, (0, 60)) if i < 30]
        assert found == []

    def test_search_interval_limits_reported_indexes(self):
        s = OnlineNormalStrategy()
        found = [i for i, _ in s.detect(self._series(), (45, 60))]
        assert 40 not in found

    def test_one_sided_constant_series_not_flagged(self):
        # zero variance + a one-sided factor: the missing side's bound
        # is mean ± MaxValue·0 = mean, so an unchanged value stays in
        # bounds (regression: math.inf · 0 = nan flagged every point)
        series = [5.0] * 20
        for s in (
            OnlineNormalStrategy(lower_deviation_factor=None),
            OnlineNormalStrategy(upper_deviation_factor=None),
            OnlineNormalStrategy(),
        ):
            assert s.detect(series, (0, 20)) == []


class TestBatchNormalBoundaries:
    def test_interval_excluded_from_stats(self):
        rng = np.random.default_rng(3)
        series = list(rng.normal(0.0, 1.0, 50)) + [100.0, 101.0]
        s = BatchNormalStrategy()
        found = [i for i, _ in s.detect(series, (50, 52))]
        assert found == [50, 51]

    def test_include_interval_pollutes_stats(self):
        series = [1.0] * 10 + [1000.0] * 40
        s = BatchNormalStrategy(include_interval=True)
        # the outliers dominate mean/stddev when included
        found = s.detect(series, (10, 50))
        assert len(found) < 40

    def test_empty_series_rejected(self):
        with pytest.raises(ValueError):
            BatchNormalStrategy().detect([], (0, 0))

    def test_interval_covering_everything_rejected(self):
        with pytest.raises(ValueError):
            BatchNormalStrategy().detect([1.0, 2.0], (0, 2))

    def test_needs_one_factor(self):
        with pytest.raises(ValueError):
            BatchNormalStrategy(
                lower_deviation_factor=None, upper_deviation_factor=None
            )

    def test_negative_factors_rejected(self):
        with pytest.raises(ValueError):
            BatchNormalStrategy(upper_deviation_factor=-1.0)


class TestAnomalyDetectorPreprocessing:
    """reference: AnomalyDetector.scala:29-102."""

    def test_sorts_by_time_before_detection(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        points = [
            DataPoint(3, 10.0),
            DataPoint(1, 1.0),
            DataPoint(2, 2.0),
        ]
        result = detector.detect_anomalies_in_history(points, (0, 4))
        assert [t for t, _ in result.anomalies] == [3]

    def test_drops_missing_values(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        points = [DataPoint(1, 1.0), DataPoint(2, None), DataPoint(3, 10.0)]
        result = detector.detect_anomalies_in_history(points, (0, 4))
        assert [t for t, _ in result.anomalies] == [3]

    def test_interval_is_time_based(self):
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=5.0))
        points = [DataPoint(t, 10.0) for t in (1, 2, 3)]
        result = detector.detect_anomalies_in_history(points, (2, 3))
        assert [t for t, _ in result.anomalies] == [2]

    def test_is_new_point_anomalous_appends_and_searches_tail(self):
        detector = AnomalyDetector(BatchNormalStrategy())
        history = [DataPoint(t, float(np.sin(t))) for t in range(20)]
        verdict = detector.is_new_point_anomalous(history, DataPoint(20, 50.0))
        assert verdict.anomalies
        ok = detector.is_new_point_anomalous(history, DataPoint(20, 0.5))
        assert not ok.anomalies


class TestDegenerateSeriesRobustness:
    """No strategy may crash (beyond documented ValueErrors) or hang on
    degenerate input: empty, single-point, constant, inf-scaled."""

    SERIES = [
        [],
        [1.0],
        [1.0, 1.0],
        [float("inf")],
        [0.0] * 5,
    ]
    INTERVALS = [(0, 0), (0, 100), (1, 2)]

    @pytest.mark.parametrize(
        "make",
        [
            lambda: SimpleThresholdStrategy(lower_bound=-1.0, upper_bound=1.0),
            lambda: RateOfChangeStrategy(max_rate_increase=1.0, order=1),
            lambda: RateOfChangeStrategy(max_rate_increase=1.0, order=3),
            lambda: OnlineNormalStrategy(),
            lambda: BatchNormalStrategy(),
        ],
        ids=["threshold", "rate1", "rate3", "online", "batch"],
    )
    def test_no_unexpected_exception(self, make):
        for series in self.SERIES:
            for interval in self.INTERVALS:
                try:
                    out = make().detect(list(series), interval)
                except ValueError:
                    continue  # documented parameter/empty errors
                assert isinstance(out, list)


class TestHoltWintersBoundaries:
    """reference: seasonal/HoltWintersTest.scala (224 LoC)."""

    def _weekly_series(self, weeks: int, breakpoint: int = -1):
        # exactly linear trend + additive weekly pattern: ETS(A,A) fits
        # this perfectly, so residual-based thresholds are deterministic
        base = np.array([10, 11, 12, 13, 14, 20, 22], dtype=float)
        series = np.tile(base, weeks) + np.arange(7 * weeks) * 0.1
        if breakpoint >= 0:
            series[breakpoint] += 25
        return list(series)

    def test_clean_continuation_no_anomaly(self):
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        series = self._weekly_series(5)
        found = s.detect(series, (28, 35))
        assert found == []

    def test_seasonal_break_detected(self):
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        series = self._weekly_series(5, breakpoint=31)
        found = [i for i, _ in s.detect(series, (28, 35))]
        assert 31 in found

    def test_two_full_cycles_required(self):
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        with pytest.raises(ValueError):
            s.detect(self._weekly_series(1), (0, 7))

    def test_interval_before_any_training_data_rejected(self):
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        # searching from index 0 leaves no training prefix
        with pytest.raises(ValueError):
            s.detect(self._weekly_series(3), (0, 21))
